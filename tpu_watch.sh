#!/bin/bash
# Probe the TPU tunnel every 10 min; when it answers, run the (resumable)
# round-4 measurement suites. Both suites skip tags already captured in
# bench_suite_r04.jsonl, so a tunnel drop mid-suite just means the next
# probe-cycle picks up the missing configs. Exits when every config has a row.
cd /root/repo
want=16  # 9 suite-a + 7 suite-b tags
for i in $(seq 1 60); do
  have=$(python - <<'EOF'
import json
tags = set()
try:
    for line in open("bench_suite_r04.jsonl"):
        try:
            tags.add(json.loads(line).get("tag"))
        except ValueError:
            pass
except FileNotFoundError:
    pass
print(len(tags))
EOF
)
  if [ "$have" -ge "$want" ]; then
    echo "[watch] all $want configs captured; exiting" >> tpu_watch.log
    exit 0
  fi
  echo "[watch] probe $i at $(date -u +%H:%M:%S) (captured $have/$want)" >> tpu_watch.log
  if timeout 150 python -c "import jax; assert jax.devices()[0].platform=='tpu'; print(jax.devices()[0].device_kind)" >> tpu_watch.log 2>&1; then
    echo "[watch] TPU alive; running suites" >> tpu_watch.log
    python measure_r04.py >> tpu_watch.log 2>&1
    echo "[watch] suite a pass rc=$?" >> tpu_watch.log
    python measure_r04b.py >> tpu_watch.log 2>&1
    echo "[watch] suite b pass rc=$?" >> tpu_watch.log
  fi
  sleep 600
done
echo "[watch] gave up after 60 probes" >> tpu_watch.log

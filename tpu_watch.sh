#!/bin/bash
# Probe the TPU tunnel every 10 min; when it answers, run the (resumable)
# round-4 measurement suites. Both suites skip tags already captured in
# bench_suite_r04.jsonl (measure_r04.captured_tags is the single source of
# truth for the resume key), so a tunnel drop mid-suite just means the next
# probe-cycle picks up the missing configs. Exits when every REQUIRED config
# has a row: "inference gptj-6b" is optional — 6B params + KV cache is ~14 GB
# of the 16 GB chip, and if it can't fit it must not keep the watcher (and the
# tunnel) busy forever after everything else is captured.
cd /root/repo
need=11  # 4 suite-a + 8 suite-b tags, minus the optional gptj-6b
# HARD deadline (epoch seconds, WATCH_DEADLINE env or 14:30 UTC today): the
# chip is a single serialized tunnel, and the round driver runs bench.py at
# round end — a watcher still holding the chip then would starve the official
# capture. Both the loop and in-flight suite runs stop at the deadline.
deadline=${WATCH_DEADLINE:-$(date -u -d "14:30" +%s)}
for i in $(seq 1 60); do
  now=$(date +%s)
  if [ "$now" -ge "$deadline" ]; then
    echo "[watch] deadline reached ($(date -u +%H:%M:%S)); exiting to free the chip for the driver" >> tpu_watch.log
    exit 0
  fi
  have=$(python -c "import measure_r04 as m; t = m.captured_tags(); print(len(t - {'inference gptj-6b'}))")
  if [ "$have" -ge "$need" ]; then
    echo "[watch] all $need required configs captured; exiting" >> tpu_watch.log
    exit 0
  fi
  echo "[watch] probe $i at $(date -u +%H:%M:%S) (captured $have/$need required)" >> tpu_watch.log
  if timeout 150 python -c "import jax; assert jax.devices()[0].platform=='tpu'; print(jax.devices()[0].device_kind)" >> tpu_watch.log 2>&1; then
    echo "[watch] TPU alive; running suites" >> tpu_watch.log
    # The suite runner reaps its own in-flight bench child on SIGTERM
    # (measure_r04._terminate_child), so a deadline timeout here leaves no
    # orphan holding the chip.
    budget=$(( deadline - $(date +%s) ))
    if [ "$budget" -le 60 ]; then
      echo "[watch] deadline imminent; exiting to free the chip for the driver" >> tpu_watch.log
      exit 0
    fi
    timeout "$budget" python measure_r04.py >> tpu_watch.log 2>&1
    echo "[watch] suite a pass rc=$?" >> tpu_watch.log
    budget=$(( deadline - $(date +%s) ))
    if [ "$budget" -le 60 ]; then
      echo "[watch] deadline imminent; exiting to free the chip for the driver" >> tpu_watch.log
      exit 0
    fi
    timeout "$budget" python measure_r04b.py >> tpu_watch.log 2>&1
    echo "[watch] suite b pass rc=$?" >> tpu_watch.log
  fi
  sleep 600
done
echo "[watch] gave up after 60 probes" >> tpu_watch.log

#!/bin/bash
# Probe the TPU tunnel every 10 min; when it answers, run the round-4
# measurement suite once and exit. Log everything to tpu_watch.log.
cd /root/repo
for i in $(seq 1 60); do
  echo "[watch] probe $i at $(date -u +%H:%M:%S)" >> tpu_watch.log
  if timeout 150 python -c "import jax; assert jax.devices()[0].platform=='tpu'; print(jax.devices()[0].device_kind)" >> tpu_watch.log 2>&1; then
    echo "[watch] TPU alive; starting measurement suite" >> tpu_watch.log
    python measure_r04.py >> tpu_watch.log 2>&1
    echo "[watch] suite finished rc=$?" >> tpu_watch.log
    exit 0
  fi
  sleep 600
done
echo "[watch] gave up after 60 probes" >> tpu_watch.log

#!/bin/bash
# Probe the TPU tunnel every 10 min; when it answers, run the resumable
# round-5 measurement suite (measure_r05.py — never-captured configs first).
# Captured tags skip on re-runs, so a tunnel drop mid-suite just means the
# next probe-cycle picks up the missing configs.
#
# Exit contract (round-4 lesson: "captured 3/11" must be LOUD):
#   0  — every required config has a row (MISSING_ROWS_r05.txt removed)
#   1  — deadline/probe budget exhausted with rows missing; the missing tags
#        are written to MISSING_ROWS_r05.txt so an incomplete round is a
#        visible artifact, not a log line.
# The deadline (WATCH_DEADLINE env, epoch seconds; default start+10.5h) frees
# the chip before the round driver's own bench.py capture: the chip is a
# single serialized tunnel, and a watcher still holding it at round end would
# starve the official capture. The suite runner reaps its in-flight bench
# child on SIGTERM (measure_r04._terminate_child), so a deadline timeout
# leaves no orphan holding the chip.
cd /root/repo
# Required-row count comes from the suite itself (round-4 advisor: the
# hand-counted need=11 went stale whenever CONFIGS changed).
need=$(python -c "import measure_r05 as m; print(len(m.required_tags()))")
need=${need:-0}  # a crashed probe flows to finish()'s crash arm, not a syntax error
deadline=${WATCH_DEADLINE:-$(( $(date +%s) + 37800 ))}

finish() {
  missing=$(python measure_r05.py --missing 2>> tpu_watch.log)
  rc=$?
  # --missing exits 0 = complete, 1 = incomplete (tags on stdout). Any other
  # rc (or an empty incomplete list) is a CRASH of the probe itself — which
  # must read as incomplete, not success: deleting the marker on a crashed
  # probe would be the exact silent-failure mode this script exists to ban.
  if [ "$rc" -eq 0 ]; then
    rm -f MISSING_ROWS_r05.txt
    echo "[watch] all $need required configs captured; exiting 0" >> tpu_watch.log
    exit 0
  fi
  if [ "$rc" -ne 1 ] || [ -z "$missing" ]; then
    missing="(missing-row probe crashed rc=$rc; see tpu_watch.log)"
  fi
  n=$(echo "$missing" | grep -c .)
  {
    echo "# Round-5 capture INCOMPLETE: $n of $need required measurement rows missing."
    echo "# The TPU tunnel never stayed up long enough; see tpu_watch.log for probe history."
    echo "$missing"
  } > MISSING_ROWS_r05.txt
  echo "[watch] EXITING INCOMPLETE: $n/$need rows missing (MISSING_ROWS_r05.txt)" >> tpu_watch.log
  exit 1
}

for i in $(seq 1 200); do
  now=$(date +%s)
  if [ "$now" -ge "$deadline" ]; then
    echo "[watch] deadline reached ($(date -u +%H:%M:%S)); freeing the chip for the driver" >> tpu_watch.log
    finish
  fi
  have=$(python -c "import measure_r04 as m4, measure_r05 as m5; print(len(m5.required_tags() & m4.captured_tags(m5.OUT_PATH)))")
  have=${have:-0}
  if [ -n "$have" ] && [ "$have" -ge "$need" ] && [ "$need" -gt 0 ]; then
    finish
  fi
  echo "[watch] probe $i at $(date -u +%H:%M:%S) (captured $have/$need required)" >> tpu_watch.log
  if timeout 150 python -c "import jax; assert jax.devices()[0].platform=='tpu'; print(jax.devices()[0].device_kind)" >> tpu_watch.log 2>&1; then
    echo "[watch] TPU alive; running suite" >> tpu_watch.log
    budget=$(( deadline - $(date +%s) ))
    if [ "$budget" -le 60 ]; then
      echo "[watch] deadline imminent; freeing the chip for the driver" >> tpu_watch.log
      finish
    fi
    timeout "$budget" python measure_r05.py >> tpu_watch.log 2>&1
    echo "[watch] suite pass rc=$?" >> tpu_watch.log
  fi
  sleep 600
done
echo "[watch] gave up after 200 probes" >> tpu_watch.log
finish

"""Round-4 measurement suite (run manually on hardware; the driver contract stays
`bench.py` = one JSON line).

Covers the round-3 verdict's evidence list:
  1. batch-size sweep at EQUAL step counts and steps_per_call=1 (bs 32/64/128,
     500 steps each) — the K=1 baselines for measure_r04b.py's device-loop A/B
  2. second-architecture MFU cross-check + flash-vs-XLA A/B (llama-1b, seq
     1024-4096) — lives in measure_r04b.py (`--remat dots`; the no-remat legs
     OOM, see below)
  3. inference headline (llama-1b latency; gptj-6b — at the end of suite-b —
     when HBM allows)

Each config runs as `python bench.py --no-supervise --_worker ...` in a fresh
process (clean singletons, one backend init per config) with a hard timeout.
Results append to bench_suite_r04.jsonl; summarize into MEASUREMENTS_r04.md.
"""

import json
import signal
import subprocess
import sys
import time

# The in-flight bench child: when the watcher's deadline `timeout` TERMs this
# runner, the child (which is what actually holds the TPU tunnel) must not be
# orphaned — the handler reaps it and exits.
_current_child = None


def _terminate_child(signum, frame):
    child = _current_child
    if child is not None and child.poll() is None:
        child.terminate()
        try:
            child.wait(timeout=10)
        except subprocess.TimeoutExpired:
            child.kill()
    sys.exit(143)


signal.signal(signal.SIGTERM, _terminate_child)

CONFIGS = [
    # (tag, argv, timeout_s)
    # steps_per_call pinned to 1: these are the K=1 baselines for the device-loop
    # A/B in measure_r04b.py (bench.py now auto-defaults bert to K=10 on
    # accelerators, which would silently capture K=10 rows under K=1 tags).
    ("headline bs32", ["--steps", "500", "--trials", "3", "--batch_size", "32", "--steps_per_call", "1"], 2400),
    ("sweep bs64", ["--steps", "500", "--trials", "3", "--batch_size", "64", "--steps_per_call", "1"], 2400),
    ("sweep bs128", ["--steps", "500", "--trials", "3", "--batch_size", "128", "--steps_per_call", "1"], 3000),
    # llama-1b seq1024 WITHOUT remat is unrunnable on the 16 GB chip at bs 4
    # (params + fp32 AdamW moments ~= 15 GB; both the flash and XLA legs OOM'd
    # on hardware), so the flash-vs-XLA A/B runs with `--remat dots` at equal
    # batch in measure_r04b.py — same kernels on the measured path, both legs
    # paying the same remat cost.
    # Long-context scaling (flash kernel at growing seq with --remat dots) lives
    # ONLY in measure_r04b.py ("... seq2048/4096 flash remat" tags) — listing the
    # same argv here under different tags would run each config twice on the chip.
    ("inference llama-1b", ["--mode", "inference", "--model", "llama-1b"], 1800),
    # "inference gptj-6b" runs at the END of suite-b: 6B bf16 params + KV cache
    # is ~14 GB of the 16 GB chip — if it turns out not to fit, it must not
    # stall every watcher cycle ahead of capturable configs (it is also
    # OPTIONAL for tpu_watch.sh's exit condition for the same reason).
]


def captured_tags(out_path="bench_suite_r04.jsonl"):
    """Tags with a persisted result row (the resume key run_suite skips by).
    Error rows are never written, so failed configs are absent and retry."""
    tags = set()
    try:
        with open(out_path) as f:
            for row_line in f:
                try:
                    tags.add(json.loads(row_line).get("tag"))
                except json.JSONDecodeError:
                    pass
    except FileNotFoundError:
        pass
    return tags


def run_suite(configs, prefix="suite", out_path="bench_suite_r04.jsonl"):
    """Shared runner (measure_r04b.py imports this): resumable — the tunnel can
    drop mid-suite; captured tags are skipped so the watcher can just re-run the
    suite until every config has a row. Error rows are never persisted, so
    failed configs retry on the next pass."""
    done = captured_tags(out_path)
    results = []
    for tag, argv, timeout_s in configs:
        if tag in done:
            print(f"[{prefix}] {tag}: already captured, skipping", file=sys.stderr, flush=True)
            continue
        cmd = [sys.executable, "bench.py", "--no-supervise"] + argv
        print(f"[{prefix}] {tag}: {' '.join(cmd)}", file=sys.stderr, flush=True)
        t0 = time.time()
        global _current_child
        _current_child = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True
        )
        try:
            out, err = _current_child.communicate(timeout=timeout_s)
            proc = subprocess.CompletedProcess(cmd, _current_child.returncode, out, err)
        except subprocess.TimeoutExpired:
            _current_child.kill()
            _current_child.communicate()
            print(f"[{prefix}] {tag}: TIMEOUT >{timeout_s}s", file=sys.stderr, flush=True)
            results.append({"tag": tag, "error": f"timeout>{timeout_s}s"})
            continue
        finally:
            _current_child = None
        line = None
        for out_line in (proc.stdout or "").strip().splitlines():
            try:
                parsed = json.loads(out_line)
                if isinstance(parsed, dict) and "metric" in parsed:
                    line = parsed
            except json.JSONDecodeError:
                continue
        if proc.returncode != 0 or line is None:
            print(
                f"[{prefix}] {tag}: FAILED rc={proc.returncode}; stderr tail: "
                f"{(proc.stderr or '')[-600:]!r}",
                file=sys.stderr,
                flush=True,
            )
            results.append({"tag": tag, "error": f"rc={proc.returncode}"})
            continue
        line["tag"] = tag
        line["wall_s"] = round(time.time() - t0, 1)
        results.append(line)
        with open(out_path, "a") as f:
            f.write(json.dumps(line) + "\n")
        print(f"[{prefix}] {tag}: {json.dumps(line)}", flush=True)
    ok = sum(1 for r in results if "error" not in r)
    print(f"[{prefix}] done: {ok}/{len(configs)} configs captured -> {out_path}", flush=True)


if __name__ == "__main__":
    run_suite(CONFIGS)

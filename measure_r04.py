"""Round-4 measurement suite (run manually on hardware; the driver contract stays
`bench.py` = one JSON line).

Covers the round-3 verdict's evidence list:
  1. sustained >= 500-step headline (bert-base, seq 128, bs 32/chip)
  2. batch-size sweep at EQUAL step counts (bs 32/64/128, 500 steps each)
  3. second-architecture MFU cross-check (llama-1b, seq 1024)
  4. flash-vs-XLA A/B where the kernel dispatches (llama-1b @ seq 1024)
  5. inference headline (llama-1b latency; gptj-6b when HBM allows)

Each config runs as `python bench.py --no-supervise --_worker ...` in a fresh
process (clean singletons, one backend init per config) with a hard timeout.
Results append to bench_suite_r04.jsonl; summarize into MEASUREMENTS_r04.md.
"""

import json
import subprocess
import sys
import time

CONFIGS = [
    # (tag, argv, timeout_s)
    ("headline bs32", ["--steps", "500", "--trials", "3", "--batch_size", "32"], 2400),
    ("sweep bs64", ["--steps", "500", "--trials", "3", "--batch_size", "64"], 2400),
    ("sweep bs128", ["--steps", "500", "--trials", "3", "--batch_size", "128"], 3000),
    (
        "llama-1b seq1024 flash",
        ["--model", "llama-1b", "--seq_len", "1024", "--batch_size", "4", "--steps", "100",
         "--trials", "3", "--attention", "flash"],
        3000,
    ),
    (
        "llama-1b seq1024 xla",
        ["--model", "llama-1b", "--seq_len", "1024", "--batch_size", "4", "--steps", "100",
         "--trials", "3", "--attention", "xla"],
        3000,
    ),
    # long-context scaling on the single chip (the per-device block compute the
    # ring path runs at each hop): flash kernel at growing seq, fixed tokens/batch.
    # --remat dots: llama-1b + fp32 AdamW moments is ~15 GB on the 16 GB chip, so
    # 4096-token activation residuals must be rematerialized (the bs-4 seq-1024
    # flash leg without remat OOM'd; measure_r04b.py re-runs it with remat).
    (
        "llama-1b seq2048 flash",
        ["--model", "llama-1b", "--seq_len", "2048", "--batch_size", "2", "--steps", "60",
         "--trials", "2", "--attention", "flash", "--remat", "dots"],
        3000,
    ),
    (
        "llama-1b seq4096 flash",
        ["--model", "llama-1b", "--seq_len", "4096", "--batch_size", "1", "--steps", "40",
         "--trials", "2", "--attention", "flash", "--remat", "dots"],
        3000,
    ),
    ("inference llama-1b", ["--mode", "inference", "--model", "llama-1b"], 1800),
    ("inference gptj-6b", ["--mode", "inference", "--model", "gptj-6b"], 2700),
]


def main():
    out_path = "bench_suite_r04.jsonl"
    # Resumable: the tunnel can drop mid-suite; captured tags are skipped so the
    # watcher can just re-run the suite until every config has a row.
    done = set()
    try:
        with open(out_path) as f:
            for row_line in f:
                try:
                    done.add(json.loads(row_line).get("tag"))
                except json.JSONDecodeError:
                    pass
    except FileNotFoundError:
        pass
    results = []
    for tag, argv, timeout_s in CONFIGS:
        if tag in done:
            print(f"[suite] {tag}: already captured, skipping", file=sys.stderr, flush=True)
            continue
        cmd = [sys.executable, "bench.py", "--no-supervise"] + argv
        print(f"[suite] {tag}: {' '.join(cmd)}", file=sys.stderr, flush=True)
        t0 = time.time()
        try:
            proc = subprocess.run(cmd, timeout=timeout_s, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            print(f"[suite] {tag}: TIMEOUT >{timeout_s}s", file=sys.stderr, flush=True)
            results.append({"tag": tag, "error": f"timeout>{timeout_s}s"})
            continue
        line = None
        for out_line in (proc.stdout or "").strip().splitlines():
            try:
                parsed = json.loads(out_line)
                if isinstance(parsed, dict) and "metric" in parsed:
                    line = parsed
            except json.JSONDecodeError:
                continue
        if proc.returncode != 0 or line is None:
            print(
                f"[suite] {tag}: FAILED rc={proc.returncode}; stderr tail: "
                f"{(proc.stderr or '')[-600:]!r}",
                file=sys.stderr,
                flush=True,
            )
            results.append({"tag": tag, "error": f"rc={proc.returncode}"})
            continue
        line["tag"] = tag
        line["wall_s"] = round(time.time() - t0, 1)
        results.append(line)
        with open(out_path, "a") as f:
            f.write(json.dumps(line) + "\n")
        print(f"[suite] {tag}: {json.dumps(line)}", flush=True)
    ok = sum(1 for r in results if "error" not in r)
    print(f"[suite] done: {ok}/{len(CONFIGS)} configs captured -> {out_path}", flush=True)


if __name__ == "__main__":
    main()

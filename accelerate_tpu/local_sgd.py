"""Local SGD: K independent steps per replica, then parameter averaging.

TPU-native redesign of reference local_sgd.py:19-102. The reference implements Local SGD
at the *process* level: `model.no_sync()` suppresses DDP's gradient all-reduce so each
rank steps on its local gradient, and every `local_sgd_steps` calls the params are
`reduce(mean)`-ed (local_sgd.py:95-102). It explicitly does NOT support XLA/TPU
(local_sgd.py:69-76 raises for anything but CPU/GPU DDP).

Under single-controller SPMD there is no "skip the all-reduce" knob — the gradient of a
global-batch loss w.r.t. replicated params *is* the synced gradient, psum and all. So
local params must be represented explicitly: on `__enter__` every parameter (and the
bound optimizer's state) gains a leading replica axis of size `dp`, sharded over the
`data` mesh axis with `NamedSharding(P("data", ...))` — each device row holds its own
divergent copy at no extra HBM cost versus replication. The model's loss is wrapped in
`jax.vmap` over that axis with the batch reshaped to `(dp, B/dp, ...)`: XLA partitions
the vmapped program along the replica axis, so each replica's gradient depends only on
its own shard and NO inter-replica collective is emitted in the hot path (the only
cross-replica traffic is the scalar loss mean and the every-K parameter average —
exactly Local SGD's communication pattern, riding ICI/DCN once per K steps instead of
every step).

Usage matches the reference:

    with LocalSGD(accelerator=accelerator, model=model, local_sgd_steps=8) as local_sgd:
        for batch in dl:
            loss = accelerator.backward(model.loss, batch)
            optimizer.step(); optimizer.zero_grad()
            local_sgd.step()
"""

from __future__ import annotations

from typing import Optional

from .state import AcceleratorState
from .utils.dataclasses import DistributedType


class LocalSGD:
    """Run `local_sgd_steps` updates independently on each data-parallel replica, then
    average model parameters (reference LocalSGD, local_sgd.py:19)."""

    def __init__(self, accelerator, model, local_sgd_steps: int, enabled: bool = True):
        import jax

        self.accelerator = accelerator
        self.model = model
        self.local_sgd_steps = int(local_sgd_steps)
        self.num_steps = 0
        mesh = model.mesh if model.mesh is not None else AcceleratorState().mesh
        self.mesh = mesh
        dp = 1
        if mesh is not None:
            # Only pure data parallelism is supported, mirroring the reference's
            # restriction to plain DDP (local_sgd.py:69-76): with model/fsdp sharding a
            # "local replica" is not a single device's worth of params.
            for axis in ("fsdp", "model", "seq", "expert", "stage"):
                if axis in mesh.shape and mesh.shape[axis] != 1:
                    raise NotImplementedError(
                        f"LocalSGD supports pure data parallelism only (mesh axis {axis!r} has "
                        f"size {mesh.shape[axis]})"
                    )
            dp = mesh.shape.get("data", 1)
        self.dp = dp
        self.enabled = enabled and accelerator.distributed_type != DistributedType.NO and dp > 1
        self._saved_loss_fn = None
        self._jax = jax

    # ---- context manager -------------------------------------------------------------
    def __enter__(self):
        if self.enabled:
            self._expand()
        return self

    def __exit__(self, exc_type, value, tb):
        if self.enabled:
            self._sync_and_avg_model_params()
            self._collapse()

    def step(self):
        """Count one local step; average params at every `local_sgd_steps` boundary
        (reference local_sgd.py:84-93)."""
        self.num_steps += 1
        if not self.enabled:
            return
        if self.num_steps % self.local_sgd_steps == 0:
            self._sync_and_avg_model_params()

    # ---- replica-axis plumbing -------------------------------------------------------
    def _replica_sharding(self, template):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        def _shard(_):
            return NamedSharding(self.mesh, PartitionSpec("data"))

        return jax.tree_util.tree_map(_shard, template)

    def _expand(self):
        """Give params + opt state a leading replica axis and wrap the loss in vmap."""
        import jax
        import jax.numpy as jnp

        dp = self.dp
        model = self.model

        def _stack(p):
            return jnp.broadcast_to(p[None], (dp,) + p.shape)

        shardings = self._replica_sharding(model.params)
        model.params = jax.jit(
            lambda t: jax.tree_util.tree_map(_stack, t), out_shardings=shardings
        )(model.params)

        opt = self._bound_optimizer()
        if opt is not None and opt.opt_state is not None:
            from .optimizer import DiskOptState

            if isinstance(opt.opt_state, DiskOptState):
                raise NotImplementedError(
                    "LocalSGD stacks a replica axis into device-resident optimizer "
                    "state; offload_optimizer_device='disk' keeps that state on disk. "
                    "Use the pinned-host tier (offload_optimizer_state=True) or no "
                    "offload with LocalSGD."
                )
            from jax.sharding import NamedSharding, PartitionSpec

            # Moments mirror params and get the replica axis; SCALAR leaves (step
            # counts) stay shared — adam's bias correction 1-b^count must broadcast
            # against [dp, ...] moments, and the count is identical per replica anyway.
            def _is_stacked(x):
                return hasattr(x, "ndim") and x.ndim >= 1

            self._opt_stacked_mask = jax.tree_util.tree_map(_is_stacked, opt.opt_state)
            opt_shardings = jax.tree_util.tree_map(
                lambda x: NamedSharding(
                    self.mesh, PartitionSpec("data") if _is_stacked(x) else PartitionSpec()
                ),
                opt.opt_state,
            )
            opt.opt_state = jax.jit(
                lambda t: jax.tree_util.tree_map(
                    lambda x: _stack(x) if _is_stacked(x) else x, t
                ),
                out_shardings=opt_shardings,
            )(opt.opt_state)
            opt.opt_state_sharding = opt_shardings
            opt._jit_cache.clear()

        self._saved_loss_fn = model.loss_fn
        base_loss = model.loss_fn

        def local_loss(params_local, batch, apply_fn):
            def one(params, shard):
                out = base_loss(params, shard, apply_fn)
                return out[0] if isinstance(out, tuple) else out

            shards = jax.tree_util.tree_map(
                lambda x: x.reshape((dp, x.shape[0] // dp) + x.shape[1:]), batch
            )
            losses = jax.vmap(one)(params_local, shards)
            # Value = the global mean (what the user logs); gradient = that of the SUM,
            # so each replica's gradient row is exactly its own local gradient, with no
            # 1/dp attenuation of the effective step size.
            stop = jax.lax.stop_gradient
            return stop(losses.mean()) + losses.sum() - stop(losses.sum())

        model.loss_fn = local_loss

    def _collapse(self):
        """Drop the replica axis (replicas were just averaged, so row 0 == the mean)."""
        import jax

        model = self.model
        take0 = jax.jit(lambda t: jax.tree_util.tree_map(lambda x: x[0], t))
        model.params = take0(model.params)
        if getattr(model, "param_sharding", None) is not None:
            from .parallel.sharding import place_params

            model.params = place_params(model.params, model.param_sharding)
        opt = self._bound_optimizer()
        if opt is not None and opt.opt_state is not None:
            opt.opt_state = jax.tree_util.tree_map(
                lambda x, stacked: x[0] if stacked else x,
                opt.opt_state,
                self._opt_stacked_mask,
            )
            opt.opt_state_sharding = None
            if getattr(opt, "offload_opt_state", False):
                # Collapse loses the derived shardings the host tier needs; keep the
                # state on device rather than silently mis-placing it.
                from .logging import get_logger

                get_logger(__name__).warning(
                    "LocalSGD collapse disables optimizer-state host offload; "
                    "state stays in device memory from here on."
                )
                opt.offload_opt_state = False
            opt._jit_cache.clear()
        model.loss_fn = self._saved_loss_fn
        self._saved_loss_fn = None

    def _bound_optimizer(self):
        for opt in getattr(self.accelerator, "_optimizers", []):
            if opt.model is self.model:
                return opt
        return None

    def _sync_and_avg_model_params(self):
        """Average parameters across replicas (reference local_sgd.py:95-102); one
        all-reduce over the data axis per K steps."""
        import jax
        import jax.numpy as jnp

        self.accelerator.wait_for_everyone()
        shardings = self._replica_sharding(self.model.params)

        def _avg(t):
            return jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x.mean(axis=0, keepdims=True), x.shape), t
            )

        self.model.params = jax.jit(_avg, out_shardings=shardings, donate_argnums=(0,))(
            self.model.params
        )

"""Out-of-process serving workers: one engine per OS process, coordinated over
an explicit IPC protocol.

PR 10's `router.Router` made the serving fleet replicated, but every replica
still shared one Python interpreter: a segfault, a GIL stall, or an OOM in any
engine took down ALL of them. This module moves the engine into a real process
fault domain — the serving analogue of the multi-controller discipline MPMD
training systems use: independent workers, an explicit wire protocol, and a
controller that can lose any worker without losing its own state.

Three layers, bottom up:

  - **Framing** (`send_frame` / `recv_frame`): length-prefixed JSON over a pair
    of pipe/socket file descriptors. A frame is a 4-byte big-endian payload
    length followed by UTF-8 JSON. `recv_frame` always takes a deadline — an
    IPC read with no timeout turns a hung peer into a hung caller, which is
    exactly the failure isolation this module exists to remove (analysis rule
    TPU116 lints that discipline). Torn frames (EOF mid-payload) raise
    `WorkerGone`; oversized or undecodable frames raise `FrameError`.

  - **Worker side** (`python -m accelerate_tpu.worker`): builds a model from a
    JSON spec (a named registry model, or a family+config dict with the params
    loaded from an `.npz` the controller saved — so worker params are
    bit-identical to the controller's, never re-derived), hosts ONE
    `ContinuousBatcher` behind `EngineHost`, optionally pre-warms the insert
    ladder before reporting ready (a restarted worker rejoins WARM: the fleet
    never pays a compile on the serving path), and runs `serve_worker` — a
    recv/dispatch/reply loop with a heartbeat deadline: a controller that goes
    silent past the deadline means the worker is orphaned and exits instead of
    leaking. Fault plans ride the PR 5 env protocol (`ACCELERATE_TPU_FAULT_PLAN`)
    and trace context rides the PR 7 one (`ACCELERATE_TPU_TRACE_DIR`), so chaos
    can SIGKILL a real worker mid-dispatch and the evidence survives.

  - **Controller side** (`SubprocessEngine`): a client proxy exposing the
    engine's EXACT surface (`submit`/`cancel`/`release`/`step`/`run`/`drain`/
    `close`, `results`/`pending`/`load`/`queue_depth`/`stats`/`warm_inserts`,
    assignable `params`), so `router.Router` routes over subprocess workers
    with ZERO routing changes — `make_subprocess_factory` plugs into
    `ReplicaSet.engine_factory` and the health machine's existing
    eject/rebuild/rejoin path becomes real process supervision: a SIGKILLed
    worker surfaces as `WorkerGone` from `step()`, the router ejects it, and
    the rebuild spawns a fresh warm process.

Everything on the wire is host scalars and token ids; params move by file
handoff (`save_pytree` -> path -> worker `load_pytree`, digest-verified
end-to-end), never through frames.

PR 20 lifts the same frame protocol onto TCP sockets (`SocketTransport` +
`python -m accelerate_tpu.worker --listen HOST:PORT`) and makes TRANSPORT
failure a first-class fault distinct from worker death: a torn frame or missed
deadline on a reconnectable transport parks the client proxy in a
`reconnecting` state (capped exponential backoff + jitter, budgeted by
`reconnect_deadline_s`), re-runs the registration handshake under a bumped
epoch, and reconciles in-flight streams against the worker's retained
per-request state — never-streamed requests re-dispatch, streamed requests
resume from the retained tail or surface `finish_reason=replica_lost`. Only an
exhausted reconnect budget escalates to the old behavior: `WorkerGone`, eject,
respawn.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import select
import signal
import socket
import struct
import subprocess
import sys
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .logging import get_logger

logger = get_logger(__name__)

#: Env var carrying the worker's fleet index to the subprocess (the chaos
#: `path_pattern: "worker_N"` targeting token derives from it).
WORKER_ID_ENV = "ACCELERATE_TPU_WORKER_ID"
#: Env var naming the shared append-only chaos journal file workers record
#: injections into BEFORE the damage lands (a SIGKILL must not erase the
#: evidence that it fired) — and read back on restart so a per-process
#: re-armed plan cannot livelock by re-killing at the same trigger.
CHAOS_JOURNAL_ENV = "ACCELERATE_TPU_CHAOS_JOURNAL"

#: Hard ceiling on one frame's payload. Tokens and host scalars only — params
#: move by file handoff — so anything near this is a protocol violation, not a
#: big message.
MAX_FRAME_BYTES = 64 << 20

#: Default worker-side heartbeat: a controller silent for this long means the
#: worker is orphaned (controller crashed without close()) and exits.
DEFAULT_HEARTBEAT_S = 120.0

#: Exit code a worker uses when it terminates itself (orphaned / torn pipe),
#: distinguishing self-termination from a crash in supervision logs.
ORPHANED_EXIT_CODE = 17

#: Frame-protocol version carried in the socket registration handshake; a
#: mismatched controller/worker pair is rejected before any op flows.
PROTOCOL_VERSION = 1


class FrameError(RuntimeError):
    """A malformed frame: oversized length prefix or undecodable payload (a
    protocol bug or corrupted stream, NOT a dead peer)."""


class FrameTimeout(RuntimeError):
    """No complete frame arrived inside the deadline: the peer is hung (or
    stalled past its budget) — the caller decides whether that is fatal."""


class WorkerGone(RuntimeError):
    """The peer's stream ended (EOF / broken pipe), cleanly or mid-frame: the
    process on the other side is dead. Escapes `SubprocessEngine.step()` so the
    router's replica-death handling (eject -> rebuild -> rejoin) takes over."""


def _fileno(stream) -> int:
    return stream if isinstance(stream, int) else stream.fileno()


def _frame_ctx(peer: Optional[str], op: Optional[str]) -> str:
    """Diagnostic suffix naming the peer and the op in flight — a partition
    post-mortem must say WHICH worker's WHICH request tore, not just that
    bytes stopped."""
    parts = []
    if peer:
        parts.append(f"peer={peer}")
    if op:
        parts.append(f"op={op}")
    return f" [{' '.join(parts)}]" if parts else ""


def _read_exact(fd: int, n: int, deadline: Optional[float], what: str,
                ctx: str = "") -> bytes:
    """Read exactly `n` bytes from `fd`, honoring an absolute monotonic
    deadline. EOF before `n` bytes is a dead peer (`WorkerGone`) — torn frames
    included; a deadline miss is `FrameTimeout`. Every message carries the
    bytes-read-so-far plus the peer/op context."""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise FrameTimeout(
                    f"timed out waiting for {what} ({got}/{n} bytes){ctx}"
                )
            ready, _, _ = select.select([fd], [], [], remaining)
            if not ready:
                raise FrameTimeout(
                    f"timed out waiting for {what} ({got}/{n} bytes){ctx}"
                )
        chunk = os.read(fd, n - got)
        if not chunk:
            raise WorkerGone(
                f"peer closed the stream mid-{what} ({got}/{n} bytes){ctx}"
                if got else f"peer closed the stream{ctx}"
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_frame(stream, obj: Dict[str, Any], timeout_s: Optional[float] = None, *,
               peer: Optional[str] = None, op: Optional[str] = None) -> None:
    """Write one length-prefixed JSON frame. Raises `WorkerGone` when the peer
    end of the pipe/socket is closed, `FrameError` for oversized payloads, and
    — when `timeout_s` bounds the write (mandatory on socket transports, where
    a zero-window peer can stall a blocking write forever) — `FrameTimeout`
    on a missed send deadline."""
    ctx = _frame_ctx(peer, op)
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise FrameError(f"frame of {len(payload)} bytes exceeds MAX_FRAME_BYTES{ctx}")
    data = struct.pack(">I", len(payload)) + payload
    fd = _fileno(stream)
    deadline = None if timeout_s is None else time.monotonic() + float(timeout_s)
    view = memoryview(data)
    sent = 0
    while view:
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise FrameTimeout(
                    f"timed out sending frame ({sent}/{len(data)} bytes){ctx}"
                )
            _, writable, _ = select.select([], [fd], [], remaining)
            if not writable:
                raise FrameTimeout(
                    f"timed out sending frame ({sent}/{len(data)} bytes){ctx}"
                )
        try:
            written = os.write(fd, view)
        except (BrokenPipeError, OSError) as exc:
            raise WorkerGone(
                f"peer pipe closed during send ({sent}/{len(data)} bytes){ctx}: {exc!r}"
            ) from exc
        view = view[written:]
        sent += written


def recv_frame(stream, timeout_s: Optional[float], *,
               peer: Optional[str] = None, op: Optional[str] = None) -> Dict[str, Any]:
    """Read one frame. `timeout_s` is the heartbeat deadline for the WHOLE
    frame — pass the peer's liveness budget, never None in a long-lived loop
    (TPU116). Raises `FrameTimeout` / `WorkerGone` / `FrameError`, each
    tagged with the peer identity and op in flight when given."""
    ctx = _frame_ctx(peer, op)
    fd = _fileno(stream)
    deadline = None if timeout_s is None else time.monotonic() + float(timeout_s)
    header = _read_exact(fd, 4, deadline, "frame header", ctx)
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"frame length {length} exceeds MAX_FRAME_BYTES{ctx}")
    payload = _read_exact(fd, length, deadline, "frame payload", ctx)
    try:
        return json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise FrameError(f"undecodable frame payload{ctx}: {exc}") from exc


# ------------------------------------------------------------------ wire codecs
def request_to_wire(request) -> Dict[str, Any]:
    return {
        "request_id": int(request.request_id),
        "input_ids": [int(t) for t in np.asarray(request.input_ids).reshape(-1)],
        "max_new_tokens": int(request.max_new_tokens),
        "temperature": float(request.temperature),
        "repetition_penalty": float(request.repetition_penalty),
        "eos_token_id": None if request.eos_token_id is None else int(request.eos_token_id),
        "arrival_time": float(request.arrival_time),
        "deadline_s": None if request.deadline_s is None else float(request.deadline_s),
        "tenant": getattr(request, "tenant", None),
        "priority": int(getattr(request, "priority", 0)),
    }


def request_from_wire(data: Dict[str, Any]):
    from .serving import Request

    return Request(
        request_id=int(data["request_id"]),
        input_ids=np.asarray(data["input_ids"], np.int32),
        max_new_tokens=int(data["max_new_tokens"]),
        temperature=float(data.get("temperature", 1.0)),
        repetition_penalty=float(data.get("repetition_penalty", 1.0)),
        eos_token_id=data.get("eos_token_id"),
        arrival_time=float(data.get("arrival_time", 0.0)),
        deadline_s=data.get("deadline_s"),
        tenant=data.get("tenant"),
        priority=int(data.get("priority", 0)),
    )


def result_to_wire(result) -> Dict[str, Any]:
    return {
        "request_id": int(result.request_id),
        "tokens": [int(t) for t in result.tokens],
        "finished": bool(result.finished),
        "finish_reason": result.finish_reason,
        "error": result.error,
    }


#: Engine exception -> wire kind; the client re-raises the same type, so the
#: router's QueueFull/EngineClosed handling works unchanged out of process.
_ERROR_KINDS = ("queue_full", "engine_closed", "value_error", "key_error", "runtime_error")


def _error_reply(exc: BaseException) -> Dict[str, Any]:
    from .serving import EngineClosed, QueueFull

    if isinstance(exc, QueueFull):
        kind = "queue_full"
    elif isinstance(exc, EngineClosed):
        kind = "engine_closed"
    elif isinstance(exc, ValueError):
        kind = "value_error"
    elif isinstance(exc, KeyError):
        kind = "key_error"
    else:
        kind = "runtime_error"
    return {"ok": False, "kind": kind, "error": str(exc) or repr(exc)}


def _raise_from_reply(reply: Dict[str, Any]):
    from .serving import EngineClosed, QueueFull

    kind = reply.get("kind", "runtime_error")
    message = reply.get("error", "worker error")
    if kind == "queue_full":
        raise QueueFull(message)
    if kind == "engine_closed":
        raise EngineClosed(message)
    if kind == "value_error":
        raise ValueError(message)
    if kind == "key_error":
        raise KeyError(message)
    raise RuntimeError(message)


# ------------------------------------------------------------------ model specs
#: Flax module class name -> model-family key (`models.CREATE_BY_FAMILY`).
#: Serving needs `decode_slot_cache`, so only the slot-cache families appear.
_FAMILY_BY_MODULE = {
    "LlamaForCausalLM": "llama",
    "GPTNeoXForCausalLM": "gpt_neox",
}


def spec_for_model(model, params_path: Optional[str] = None,
                   params_digest: Optional[str] = None) -> Dict[str, Any]:
    """Serialize a live Model bundle into a worker-buildable JSON spec: the
    family + config dataclass fields, plus the path of a `save_pytree`'d params
    file. Params ALWAYS move by file — a worker must serve the controller's
    exact weights (token parity), never a re-derived init. `params_digest`
    (the file's SHA-256, PR 2 manifest machinery) makes the handoff safe
    across hosts: a worker on another machine verifies it read the exact
    bytes the controller wrote, not a torn or stale object at the same path."""
    family = _FAMILY_BY_MODULE.get(type(model.module).__name__)
    if family is None:
        raise ValueError(
            f"{type(model.module).__name__} has no subprocess-worker family mapping; "
            f"known: {sorted(_FAMILY_BY_MODULE)}"
        )
    return {
        "family": family,
        "config": dataclasses.asdict(model.module.config),
        "params_path": params_path,
        "params_digest": params_digest,
    }


def build_model_from_spec(spec: Dict[str, Any]):
    """Worker-side model construction. Accepts either a named registry model
    (`{"name": "llama-tiny"}`) or a family+config spec from `spec_for_model`;
    a `params_path` (when present) replaces the init params wholesale."""
    from . import models

    if "name" in spec:
        model = models.create_named_model(spec["name"], seq_len=int(spec.get("seq_len", 8)))
    else:
        family = spec["family"]
        create = models.CREATE_BY_FAMILY.get(family)
        if create is None:
            raise ValueError(f"unknown model family {family!r} in worker spec")
        config_cls = type(models.MODEL_REGISTRY[f"{family.replace('_', '-')}-tiny"][1]())
        config = config_cls(**spec["config"])
        # Tiny init seq_len: the real params arrive via params_path below, so
        # the throwaway init should cost as little as possible.
        seq_len = int(spec.get("seq_len", 8))
        model = create(config, seq_len=seq_len)
    params_path = spec.get("params_path")
    if params_path:
        _verify_params_digest(params_path, spec.get("params_digest"))
        model.params = _load_params_on_device(params_path)
    return model


def _verify_params_digest(path: str, digest: Optional[str]) -> None:
    """End-to-end digest check for the params file handoff: the controller
    names the SHA-256 it wrote, the worker refuses to serve anything else.
    (`load_pytree` already verifies payload-vs-manifest; this closes the
    cross-host gap where the PATH resolves to different bytes.)"""
    if not digest:
        return
    from .checkpointing import file_sha256

    actual = file_sha256(path)
    if actual != digest:
        raise ValueError(
            f"params digest mismatch for {path}: controller expects "
            f"{digest[:12]}..., file hashes to {actual[:12]}... — refusing to "
            "serve unverified weights"
        )


def _load_params_on_device(path: str):
    """`load_pytree` returns numpy leaves "placed by the caller" — place them
    NOW: params left as numpy would ride every dispatch as an implicit
    host-to-device transfer (a per-step re-upload the worker's own armed
    TraceGuard rightly rejects)."""
    import jax

    from .checkpointing import load_pytree

    return jax.tree_util.tree_map(jax.device_put, load_pytree(path))


# ------------------------------------------------------------------ worker side
class EngineHost:
    """Executes protocol ops against one `ContinuousBatcher`. Pure translation:
    every engine exception maps to a typed error reply, every reply carries the
    load/queue-depth scalars the controller mirrors for routing."""

    def __init__(self, engine, worker_id: int = 0, guard=None):
        self.engine = engine
        self.worker_id = int(worker_id)
        self.guard = guard
        #: Result ids already shipped in a `finished` list (step/drain replies
        #: carry only the delta; release forgets).
        self._reported: set = set()

    # ---- op implementations ----
    def _load_view(self) -> Dict[str, Any]:
        return {
            "load": int(self.engine.load),
            "queue_depth": int(self.engine.queue_depth),
            "pending": bool(self.engine.pending),
        }

    def _finished_delta(self) -> List[Dict[str, Any]]:
        out = []
        for rid, result in self.engine.results.items():
            if result.finished and rid not in self._reported:
                self._reported.add(rid)
                out.append(result_to_wire(result))
        return out

    def _worker_stats(self) -> Dict[str, Any]:
        stats = dict(self.engine.stats)
        stats["worker"] = {
            "pid": os.getpid(),
            "worker_id": self.worker_id,
            "trace_counts": dict(self.engine.trace_counts),
            "guard": None if self.guard is None else {
                "recompiles": int(self.guard.total_recompiles),
                "host_transfers": int(self.guard.host_transfers),
            },
        }
        return stats

    def handle(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        op = msg.get("op")
        try:
            if op == "ping":
                return {"ok": True, "pid": os.getpid(), **self._load_view()}
            if op == "submit":
                request = request_from_wire(msg["request"])
                self.engine.submit(request)
                return {"ok": True, **self._load_view()}
            if op == "cancel":
                rid = int(msg["request_id"])
                cancelled = self.engine.cancel(rid)
                return {
                    "ok": True,
                    "cancelled": bool(cancelled),
                    "result": result_to_wire(self.engine.results[rid]),
                    **self._load_view(),
                }
            if op == "release":
                rid = int(msg["request_id"])
                result = self.engine.release(rid)
                self._reported.discard(rid)
                return {"ok": True, "result": result_to_wire(result)}
            if op == "step":
                events = self.engine.step()
                return {
                    "ok": True,
                    "events": [[int(rid), [int(t) for t in toks]] for rid, toks in events],
                    "finished": self._finished_delta(),
                    **self._load_view(),
                }
            if op == "drain":
                self.engine.drain()
                return {"ok": True, "finished": self._finished_delta(), **self._load_view()}
            if op == "warm":
                # Warmup pushes throwaway donated operands host->device by
                # design — suspend the armed guard (the 0/0 gate covers the
                # SERVING path, warm windows are excluded exactly like the
                # in-process benches arm after warm_inserts()).
                if self.guard is not None:
                    self.guard.__exit__(None, None, None)
                try:
                    buckets = self.engine.warm_inserts()
                finally:
                    if self.guard is not None:
                        self.guard.__enter__()
                return {"ok": True, "buckets": [int(b) for b in buckets]}
            if op == "stats":
                return {"ok": True, "stats": self._worker_stats(), **self._load_view()}
            if op == "guard_reset":
                # Benches warm the serving path first, then zero the guard so
                # the timed window's 0-recompile/0-transfer gate is exact.
                if self.guard is not None:
                    self.guard.reset()
                return {"ok": True, "armed": self.guard is not None}
            if op == "reconcile":
                # The stream-reconciliation snapshot a reconnecting controller
                # diffs its mirrors against: every request this engine knows,
                # with the FULL retained token tail (step replies ship deltas;
                # a reply lost in a partition is recovered from here).
                return {
                    "ok": True,
                    "pid": os.getpid(),
                    "worker_id": self.worker_id,
                    "requests": {
                        str(rid): result_to_wire(result)
                        for rid, result in self.engine.results.items()
                    },
                    **self._load_view(),
                }
            if op == "set_params":
                # The file handoff always carries RAW params; a quantized
                # engine (weight_dtype="int8" via engine_kwargs) re-quantizes
                # in its params setter — same seam as an in-process swap.
                # A digest (mandatory for cross-host swaps) is verified
                # against the actual file bytes before anything is served.
                _verify_params_digest(msg["path"], msg.get("digest"))
                self.engine.params = _load_params_on_device(msg["path"])
                return {"ok": True, "digest_verified": bool(msg.get("digest"))}
            if op == "close":
                self.engine.close()
                return {"ok": True, "finished": self._finished_delta()}
            return {"ok": False, "kind": "value_error", "error": f"unknown op {op!r}"}
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as exc:  # noqa: BLE001 — typed error replies, worker stays up
            return _error_reply(exc)


def _journal_line(path: str, entry: Dict[str, Any]) -> None:
    """Durably append one JSON line to the shared chaos/fleet journal.
    O_APPEND single-write + fsync: atomic against concurrent workers, durable
    against the SIGKILL that may follow immediately."""
    record = json.dumps(entry)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        os.write(fd, (record + "\n").encode())
        os.fsync(fd)
    finally:
        os.close(fd)


class WorkerChaos:
    """Worker-side fault injection (the env-propagated half of the fleet
    sweeps): `fleet.worker_kill` delivers a REAL ``SIGKILL`` to this process at
    a matching step op, `fleet.worker_stall` sleeps past the controller's step
    timeout so the heartbeat machinery — not cooperation — detects the hang.

    Every firing is journaled (append + fsync) to the shared
    ``ACCELERATE_TPU_CHAOS_JOURNAL`` file BEFORE the damage lands, and the
    journal is read back at startup to pre-consume already-fired events — a
    restarted worker re-arms the same plan from env but must not re-kill
    itself at the same trigger (the PR 9 livelock lesson)."""

    def __init__(self, plan, worker_id: int, journal_path: Optional[str] = None,
                 tracer=None):
        from .chaos.injectors import ChaosSession

        self.session = ChaosSession(plan, tracer=tracer)
        self.token = f"worker_{int(worker_id)}"
        self.journal_path = journal_path
        if journal_path and os.path.exists(journal_path):
            for kind, count in self._journaled_counts(journal_path).items():
                self.session.preconsume(kind, count, path=self.token)
        if journal_path:
            self.session.on_inject = self._journal

    def _journaled_counts(self, path: str) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail of a killed writer
                if entry.get("worker") == self.token:
                    counts[entry["kind"]] = counts.get(entry["kind"], 0) + 1
        return counts

    def _journal(self, entry: Dict[str, Any]):
        _journal_line(
            self.journal_path, {**entry, "worker": self.token, "pid": os.getpid()}
        )

    def arm(self, engine):
        from .chaos.injectors import ServingInjector

        ServingInjector(self.session).arm(engine)
        return self

    def poll(self, op: str):
        if op != "step":
            return
        for ev in self.session.fire("fleet.worker_stall", path=self.token):
            self.session.clock.sleep(float(ev.args.get("delay_s", 1.0)))
        for _ev in self.session.fire("fleet.worker_kill", path=self.token):
            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(5)  # unreachable — SIGKILL is unmaskable; belt for exotic platforms


def serve_worker(host: EngineHost, rstream, wstream, *,
                 heartbeat_deadline_s: Optional[float] = DEFAULT_HEARTBEAT_S,
                 chaos: Optional[WorkerChaos] = None) -> int:
    """The worker main loop: recv one frame, dispatch, reply. The heartbeat
    deadline bounds EVERY recv — a controller silent past it means this worker
    is orphaned (controller crashed without `close`), and the worker exits
    rather than leaking a process + device memory (analysis rule TPU116 flags
    loops built without this bound). Returns the process exit code."""
    while True:
        try:
            msg = recv_frame(rstream, timeout_s=heartbeat_deadline_s)
        except FrameTimeout:
            logger.warning(
                "worker %d: controller silent for %.1fs — exiting as orphaned",
                host.worker_id, heartbeat_deadline_s,
            )
            return ORPHANED_EXIT_CODE
        except (WorkerGone, FrameError) as exc:
            logger.warning("worker %d: control stream died: %r", host.worker_id, exc)
            return ORPHANED_EXIT_CODE
        if chaos is not None:
            chaos.poll(msg.get("op"))
        reply = host.handle(msg)
        try:
            send_frame(wstream, reply)
        except WorkerGone:
            return ORPHANED_EXIT_CODE
        if msg.get("op") == "close":
            return 0


def _parse_hostport(text: str) -> Tuple[str, int]:
    host, _, port = str(text).rpartition(":")
    if not host or not port.lstrip("-").isdigit() or int(port) < 0:
        raise ValueError(f"expected HOST:PORT, got {text!r}")
    return host, int(port)


def _accept_registration(host: EngineHost, conn, addr, current_epoch: int,
                         deadline_s: Optional[float],
                         ready_extra: Optional[Dict[str, Any]] = None):
    """One registration handshake on a freshly accepted connection. The
    controller opens with ``{"op": "register", "protocol", "epoch", ...}``;
    the worker validates the protocol version, rejects epochs that are not
    newer than the highest it has served (a stale controller link — e.g. a
    half-open socket's owner waking up after a reconnect — must not steal the
    stream), and replies with the ready frame: identity, protocol version,
    and the warm-state attestation. Returns ``(conn, epoch, peer)`` on
    success, None after closing a rejected connection."""
    peer = "%s:%s" % (addr[0], addr[1]) if addr else "?"
    budget = min(deadline_s, 30.0) if deadline_s is not None else 30.0
    try:
        msg = recv_frame(conn, timeout_s=budget, peer=peer, op="register")
    except (WorkerGone, FrameError, FrameTimeout) as exc:
        logger.warning("worker %d: registration from %s died: %r",
                       host.worker_id, peer, exc)
        conn.close()
        return None
    epoch = int(msg.get("epoch", 0))
    problem = None
    if msg.get("op") != "register":
        problem = ("value_error", f"expected a register frame, got op={msg.get('op')!r}")
    elif int(msg.get("protocol", -1)) != PROTOCOL_VERSION:
        problem = (
            "protocol_mismatch",
            f"protocol version {msg.get('protocol')!r} != worker's {PROTOCOL_VERSION}",
        )
    elif epoch <= current_epoch:
        problem = (
            "stale_epoch",
            f"registration epoch {epoch} is not newer than the served epoch "
            f"{current_epoch} — a stale controller link cannot steal the stream",
        )
    if problem is not None:
        kind, error = problem
        try:
            send_frame(conn, {"ok": False, "kind": kind, "error": error},
                       timeout_s=5.0, peer=peer, op="register")
        except (WorkerGone, FrameTimeout, FrameError):
            pass
        conn.close()
        logger.warning("worker %d: rejected registration from %s: %s",
                       host.worker_id, peer, error)
        return None
    ready = {
        "ok": True, "ready": True, "registered": True, "pid": os.getpid(),
        "worker_id": host.worker_id, "protocol": PROTOCOL_VERSION,
        "epoch": epoch, **(ready_extra or {}),
    }
    try:
        send_frame(conn, ready, timeout_s=budget, peer=peer, op="register")
    except (WorkerGone, FrameTimeout, FrameError) as exc:
        logger.warning("worker %d: ready frame to %s died: %r",
                       host.worker_id, peer, exc)
        conn.close()
        return None
    logger.info("worker %d: controller registered from %s (reconnect epoch %d)",
                host.worker_id, peer, epoch)
    return conn, epoch, peer


def serve_listener(host: EngineHost, listener, *,
                   heartbeat_deadline_s: Optional[float] = DEFAULT_HEARTBEAT_S,
                   chaos: Optional[WorkerChaos] = None,
                   journal_path: Optional[str] = None,
                   ready_extra: Optional[Dict[str, Any]] = None) -> int:
    """The socket-mode worker loop: accept a registration, then
    recv/dispatch/reply like `serve_worker` — but the ENGINE outlives any one
    connection. A torn link parks the worker back in accept-wait with its warm
    state, in-flight requests, and retained results intact; the controller
    re-registers under a bumped epoch and reconciles streams via the
    `reconcile` op. A registration arriving while a (possibly half-open)
    connection is live wins if and only if its epoch is newer — the select
    loop watches the listener alongside the active connection precisely so a
    reconnecting controller is never blocked behind a dead socket that the
    kernel still calls established. The heartbeat deadline spans connected
    AND disconnected time: a worker nobody has talked to for the whole window
    exits as orphaned (TPU116 discipline), never leaks. Re-registrations
    beyond the first epoch are journaled (``net.reregister``) so chaos
    invariants can reconcile controller reconnect counters against
    worker-side evidence."""
    epoch = 0
    conn = None
    peer = "unregistered"
    last_frame = time.monotonic()
    token = f"worker_{host.worker_id}"

    def _drop_conn(why: str):
        nonlocal conn
        if conn is not None:
            logger.warning(
                "worker %d: link to %s tore at reconnect epoch %d (%s) — "
                "awaiting re-registration", host.worker_id, peer, epoch, why,
            )
            try:
                conn.close()
            except OSError:
                pass
            conn = None

    while True:
        if heartbeat_deadline_s is not None:
            budget = heartbeat_deadline_s - (time.monotonic() - last_frame)
            if budget <= 0:
                logger.warning(
                    "worker %d: no controller traffic for %.1fs — exiting as orphaned",
                    host.worker_id, heartbeat_deadline_s,
                )
                return ORPHANED_EXIT_CODE
        else:
            budget = 1.0
        watch = [listener] if conn is None else [listener, conn]
        try:
            ready, _, _ = select.select(watch, [], [], min(budget, 1.0))
        except OSError:
            _drop_conn("select failed on the connection")
            continue
        if listener in ready:
            try:
                candidate, cand_addr = listener.accept()
            except OSError:
                continue
            candidate.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            accepted = _accept_registration(
                host, candidate, cand_addr, epoch, heartbeat_deadline_s,
                ready_extra=ready_extra,
            )
            if accepted is not None:
                _drop_conn("superseded by a newer registration epoch")
                conn, epoch, peer = accepted
                last_frame = time.monotonic()
                if epoch > 1 and journal_path:
                    _journal_line(journal_path, {
                        "kind": "net.reregister", "worker": token,
                        "epoch": epoch, "pid": os.getpid(),
                    })
            continue  # buffered op frames (if any) surface on the next select
        if conn is None or conn not in ready:
            continue
        try:
            msg = recv_frame(conn, timeout_s=heartbeat_deadline_s, peer=peer)
        except (WorkerGone, FrameError, FrameTimeout) as exc:
            _drop_conn(repr(exc))
            continue
        last_frame = time.monotonic()
        if chaos is not None:
            chaos.poll(msg.get("op"))
        reply = host.handle(msg)
        try:
            send_frame(conn, reply, timeout_s=heartbeat_deadline_s,
                       peer=peer, op=msg.get("op"))
        except (WorkerGone, FrameTimeout) as exc:
            _drop_conn(repr(exc))
            continue
        if msg.get("op") == "close":
            return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser("accelerate-tpu serving worker")
    parser.add_argument("--spec-json", required=True,
                        help="model spec JSON (spec_for_model / {'name': ...})")
    parser.add_argument("--engine-json", default="{}",
                        help="ContinuousBatcher kwargs as JSON")
    parser.add_argument("--worker-id", type=int,
                        default=int(os.environ.get(WORKER_ID_ENV, "0")))
    parser.add_argument("--heartbeat-deadline-s", type=float, default=DEFAULT_HEARTBEAT_S)
    parser.add_argument("--no-warm", action="store_true",
                        help="skip pre-warming the insert ladder before reporting ready")
    parser.add_argument("--guard", action="store_true",
                        help="arm a record-mode TraceGuard post-warmup and report its "
                        "recompile/host-transfer counters in stats")
    parser.add_argument("--listen", default=None, metavar="HOST:PORT",
                        help="socket mode: bind HOST:PORT (port 0 = ephemeral), announce "
                        "the bound address on stdout, then serve registered controllers "
                        "over TCP instead of the stdio pipes")
    args = parser.parse_args(argv)

    # fd 1 belongs to the PROTOCOL: anything else printing to stdout (jax
    # warnings, user prints) would corrupt frames. Keep a private dup for
    # frames and point fd 1 (and sys.stdout) at stderr.
    ipc_out = os.dup(1)
    os.dup2(2, 1)
    sys.stdout = sys.stderr
    ipc_in = 0

    from .serving import ContinuousBatcher
    from .telemetry.tracing import default_tracer

    tracer = default_tracer()
    spec = json.loads(args.spec_json)
    engine_kwargs = json.loads(args.engine_json)
    span = tracer.start_span(
        "worker.lifetime", category="worker",
        worker_id=args.worker_id, pid=os.getpid(),
    )
    model = build_model_from_spec(spec)
    # The controller always threads its own max_queue through engine_kwargs;
    # a hand-launched worker still gets a bounded queue (TPU114 discipline).
    max_queue = engine_kwargs.pop("max_queue", 64)
    engine = ContinuousBatcher(model, tracer=tracer, max_queue=max_queue, **engine_kwargs)

    chaos = None
    from .chaos.plan import FaultPlan

    plan = FaultPlan.from_env()
    if plan is not None:
        chaos = WorkerChaos(
            plan, args.worker_id,
            journal_path=os.environ.get(CHAOS_JOURNAL_ENV), tracer=tracer,
        )
        chaos.arm(engine)

    warmed: List[int] = []
    if not args.no_warm:
        warmed = [int(b) for b in engine.warm_inserts()]

    guard = None
    if args.guard:
        from .analysis import TraceGuard

        guard = TraceGuard(
            transfer_guard="disallow", on_violation="record",
            name=f"worker-{args.worker_id}",
        )
        guard.__enter__()

    host = EngineHost(engine, worker_id=args.worker_id, guard=guard)
    if args.listen is not None:
        bind_host, bind_port = _parse_hostport(args.listen)
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((bind_host, bind_port))
        listener.listen(4)
        got_host, got_port = listener.getsockname()[:2]
        warm_attest = {"warm": not args.no_warm, "warmed": warmed}
        # The announce frame rides the original stdout pipe (or a terminal, for
        # a hand-launched worker): the controller — or the operator — learns the
        # bound address, then all protocol traffic moves to the socket.
        send_frame(ipc_out, {
            "ok": True, "listening": True, "host": got_host, "port": int(got_port),
            "pid": os.getpid(), "worker_id": args.worker_id,
            "protocol": PROTOCOL_VERSION, **warm_attest,
        })
        span.event("listening", host=got_host, port=int(got_port),
                   warmed_buckets=len(warmed))
        code = serve_listener(
            host, listener,
            heartbeat_deadline_s=args.heartbeat_deadline_s, chaos=chaos,
            journal_path=os.environ.get(CHAOS_JOURNAL_ENV),
            ready_extra=warm_attest,
        )
        listener.close()
        if guard is not None:
            guard.__exit__(None, None, None)
        span.annotate(exit_code=code).end()
        return code
    send_frame(ipc_out, {
        "ok": True, "ready": True, "pid": os.getpid(),
        "worker_id": args.worker_id, "warm": not args.no_warm, "warmed": warmed,
    })
    span.event("ready", warmed_buckets=len(warmed))
    code = serve_worker(
        host, ipc_in, ipc_out,
        heartbeat_deadline_s=args.heartbeat_deadline_s, chaos=chaos,
    )
    if guard is not None:
        guard.__exit__(None, None, None)
    span.annotate(exit_code=code).end()
    return code


# ------------------------------------------------------------------ controller side
class _PipeTransport:
    """The real transport: a spawned worker process with frame streams over
    its stdin/stdout pipes. Tests substitute a duck-typed fake."""

    def __init__(self, cmd: List[str], env: Dict[str, str], stderr=None,
                 worker_id: int = 0):
        self.peer = f"worker_{worker_id}/pipe"
        self._last_op: Optional[str] = None
        self.proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=stderr, env=env, bufsize=0,
        )

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def send(self, obj: Dict[str, Any]):
        self._last_op = obj.get("op")
        send_frame(self.proc.stdin, obj, peer=self.peer, op=self._last_op)

    def recv(self, timeout_s: Optional[float]) -> Dict[str, Any]:
        return recv_frame(self.proc.stdout, timeout_s=timeout_s,
                          peer=self.peer, op=self._last_op)

    def kill(self):
        if self.alive():
            self.proc.kill()

    def close(self, timeout_s: float = 10.0):
        try:
            self.proc.stdin.close()
        except OSError:
            pass
        try:
            self.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()
        try:
            self.proc.stdout.close()
        except OSError:
            pass


class SocketTransport:
    """Frame transport over TCP to a listening worker (`--listen HOST:PORT`).

    Duck-types `_PipeTransport` (pid/alive/send/recv/kill/close) so
    `SubprocessEngine` and every test fake stay interchangeable, and adds the
    transport-level verbs the reconnect machinery needs:

    - `handshake(timeout_s, resume=)` — dial, send a `register` frame carrying
      the protocol version and a monotonically increasing reconnect *epoch*,
      and validate the worker's ready/attestation reply. The epoch is what
      lets the worker reject a stale controller link (an older socket waking
      up after we already re-registered) without guessing from timing.
    - `reconnect(timeout_s)` — `handshake(resume=True)`: same wire exchange,
      but the caller treats the worker's retained state as authoritative and
      reconciles streams afterwards instead of assuming a fresh engine.
    - `sever()` — drop the socket WITHOUT touching the worker process. This is
      the partition seam: chaos injectors and the reconnect path both cut the
      link here, and worker death stays a separate, deliberate act (`kill`).

    `proc` is optional: a controller can adopt a worker it never spawned
    (cross-host fleet) — then pid/alive reflect the remote identity from the
    handshake and kill() can only sever the link."""

    def __init__(self, address: Tuple[str, int], proc=None, worker_id: int = 0):
        self.address = (str(address[0]), int(address[1]))
        self.proc = proc
        self.peer = "%s:%d/worker_%d" % (self.address[0], self.address[1], worker_id)
        self.epoch = 0
        self.sock = None
        self.ready_info: Dict[str, Any] = {}
        self._remote_pid: Optional[int] = None
        self._last_op: Optional[str] = None
        self._killed = False

    # ---- lifecycle ----
    def handshake(self, timeout_s: Optional[float], resume: bool = False) -> Dict[str, Any]:
        self.sever()
        self.epoch += 1
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        try:
            sock = socket.create_connection(self.address, timeout=timeout_s or 30.0)
        except OSError as exc:
            raise WorkerGone(
                f"dial {self.address[0]}:{self.address[1]} failed"
                f"{_frame_ctx(self.peer, 'register')}: {exc!r}"
            ) from exc
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Framing owns all deadlines via select(); a lingering socket-level
        # timeout would race it and surface as spurious BlockingIOError.
        sock.settimeout(None)
        remaining = (None if deadline is None
                     else max(0.001, deadline - time.monotonic()))
        try:
            send_frame(sock, {
                "op": "register", "protocol": PROTOCOL_VERSION,
                "epoch": self.epoch, "resume": bool(resume),
                "controller_pid": os.getpid(),
            }, timeout_s=remaining, peer=self.peer, op="register")
            ready = recv_frame(sock, timeout_s=remaining,
                               peer=self.peer, op="register")
        except (WorkerGone, FrameError, FrameTimeout):
            sock.close()
            raise
        if not ready.get("ok") or not ready.get("registered"):
            sock.close()
            raise WorkerGone(
                f"worker at {self.peer} refused registration "
                f"(epoch {self.epoch}): {ready.get('error', ready)!r}"
            )
        self.sock = sock
        self.ready_info = ready
        self._remote_pid = int(ready.get("pid", 0)) or None
        return ready

    def reconnect(self, timeout_s: Optional[float]) -> Dict[str, Any]:
        return self.handshake(timeout_s, resume=True)

    def sever(self):
        sock, self.sock = self.sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    # ---- _PipeTransport surface ----
    @property
    def pid(self) -> Optional[int]:
        if self.proc is not None:
            return self.proc.pid
        return self._remote_pid

    def alive(self) -> bool:
        if self.proc is not None:
            return self.proc.poll() is None
        return not self._killed

    def send(self, obj: Dict[str, Any]):
        if self.sock is None:
            raise WorkerGone(
                f"transport link is severed{_frame_ctx(self.peer, obj.get('op'))}"
            )
        self._last_op = obj.get("op")
        send_frame(self.sock, obj, timeout_s=30.0, peer=self.peer, op=self._last_op)

    def recv(self, timeout_s: Optional[float]) -> Dict[str, Any]:
        if self.sock is None:
            raise WorkerGone(
                f"transport link is severed{_frame_ctx(self.peer, self._last_op)}"
            )
        return recv_frame(self.sock, timeout_s=timeout_s,
                          peer=self.peer, op=self._last_op)

    def kill(self):
        self._killed = True
        self.sever()
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()

    def close(self, timeout_s: float = 10.0):
        self.sever()
        if self.proc is not None:
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
            for stream in (self.proc.stdout, self.proc.stdin):
                if stream is not None:
                    try:
                        stream.close()
                    except OSError:
                        pass


class _TransportDown(WorkerGone):
    """Internal: the transport tore but the engine entered `reconnecting`
    instead of dying. Subclasses WorkerGone so callers that only know the old
    failure language (submit -> EngineClosed, release swallows) keep working;
    `step()` catches it specifically to drive the reconnect loop."""


class SubprocessEngine:
    """Client proxy for one out-of-process engine worker, exposing the exact
    `ContinuousBatcher` surface so `Router` needs no routing changes.

    The proxy mirrors request results locally (`results` holds real
    `RequestResult`s updated from step replies), mirrors the worker's
    load/queue-depth scalars for least-loaded routing, and converts transport
    death into the router's existing failure language: a dead/hung worker makes
    `step()` raise `WorkerGone` (-> `fail_replica` -> factory rebuild -> warm
    rejoin) and `submit()` raise `EngineClosed` (-> the router tries the next
    candidate replica).

    With `transport="socket"` (or `connect=` to adopt an already-listening
    worker on another host), a torn frame is a TRANSPORT fault, not a worker
    death: the proxy enters `reconnecting`, re-handshakes under capped
    exponential backoff + jitter budgeted by `reconnect_deadline_s`, and
    reconciles in-flight streams against the worker's retained per-request
    state — never-streamed requests re-dispatch, streamed requests resume from
    the worker's tail or finish `replica_lost`; only an exhausted budget
    escalates to the old WorkerGone/respawn path."""

    def __init__(
        self,
        spec: Dict[str, Any],
        engine_kwargs: Optional[Dict[str, Any]] = None,
        worker_id: int = 0,
        *,
        warm: bool = True,
        guard: bool = False,
        heartbeat_deadline_s: float = DEFAULT_HEARTBEAT_S,
        step_timeout_s: float = 120.0,
        start_timeout_s: float = 600.0,
        env: Optional[Dict[str, str]] = None,
        stderr=None,
        python: Optional[str] = None,
        transport: str = "pipe",
        connect: Optional[str] = None,
        reconnect_deadline_s: Optional[float] = None,
        reconnect_backoff_s: float = 0.05,
        reconnect_backoff_cap_s: float = 2.0,
        _transport=None,
    ):
        from .serving import RequestResult  # noqa: F401 — re-exported surface

        if transport not in ("pipe", "socket"):
            raise ValueError(f"transport must be 'pipe' or 'socket', got {transport!r}")
        if connect is not None:
            transport = "socket"
        self.spec = dict(spec)
        self.engine_kwargs = dict(engine_kwargs or {})
        self.worker_id = int(worker_id)
        self.max_queue = self.engine_kwargs.get("max_queue")
        self.step_timeout_s = float(step_timeout_s)
        self.transport_kind = transport
        if reconnect_deadline_s is None and transport == "socket":
            reconnect_deadline_s = 10.0
        self.reconnect_deadline_s = reconnect_deadline_s
        self.reconnect_backoff_s = float(reconnect_backoff_s)
        self.reconnect_backoff_cap_s = float(reconnect_backoff_cap_s)
        self.results: Dict[int, Any] = {}
        self.trace_guard = None  # surface parity; guards run worker-side
        self._dead = False
        self._closed = False
        self._load = 0
        self._queue_depth = 0
        self._worker_pending = False
        self._stats_cache: Dict[str, Any] = {}
        self._params_dir: Optional[str] = None
        self._params_seq = 0
        # --- reconnect state machine ---
        self.reconnects = 0  # successful re-handshakes over this proxy's life
        self._reconnecting = False
        self._in_reconcile = False
        self._rc_since = 0.0
        self._rc_attempts = 0
        self._rc_next = 0.0
        self._rc_cause: Optional[str] = None
        self._rc_last_err: Optional[str] = None
        self._rc_pending_events: List[Tuple[int, List[int]]] = []
        self._requests_wire: Dict[int, Dict[str, Any]] = {}
        self._cancel_after_reconnect: set = set()
        # --- telemetry (wired lazily via attach_telemetry) ---
        self._registry = None
        self._tracer = None
        self._replica_label = str(self.worker_id)
        self._m_reconnects = None
        self._m_rtt = None
        self._m_reconnecting = None
        self._rc_span = None
        if _transport is not None:
            self.transport = _transport
        elif connect is not None:
            self.transport = SocketTransport(
                _parse_hostport(connect), proc=None, worker_id=self.worker_id
            )
        else:
            run_env = dict(os.environ if env is None else env)
            run_env[WORKER_ID_ENV] = str(self.worker_id)
            pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            run_env["PYTHONPATH"] = pkg_parent + os.pathsep + run_env.get("PYTHONPATH", "")
            cmd = [
                python or sys.executable, "-m", "accelerate_tpu.worker",
                "--spec-json", json.dumps(self.spec),
                "--engine-json", json.dumps(self.engine_kwargs),
                "--worker-id", str(self.worker_id),
                "--heartbeat-deadline-s", str(heartbeat_deadline_s),
            ]
            if not warm:
                cmd.append("--no-warm")
            if guard:
                cmd.append("--guard")
            if transport == "socket":
                cmd += ["--listen", "127.0.0.1:0"]
                proc = subprocess.Popen(
                    cmd, stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
                    stderr=stderr, env=run_env, bufsize=0,
                )
                try:
                    announce = recv_frame(
                        proc.stdout, timeout_s=start_timeout_s,
                        peer=f"worker_{self.worker_id}/announce", op="announce",
                    )
                except (WorkerGone, FrameTimeout, FrameError) as exc:
                    proc.kill()
                    proc.wait()
                    raise WorkerGone(
                        f"worker {self.worker_id} never announced a listen address: {exc}"
                    ) from exc
                if not announce.get("listening"):
                    proc.kill()
                    proc.wait()
                    raise WorkerGone(
                        f"worker {self.worker_id} announce frame malformed: {announce}"
                    )
                self.transport = SocketTransport(
                    (announce["host"], int(announce["port"])),
                    proc=proc, worker_id=self.worker_id,
                )
            else:
                self.transport = _PipeTransport(
                    cmd, env=run_env, stderr=stderr, worker_id=self.worker_id
                )
        handshake = getattr(self.transport, "handshake", None)
        try:
            if handshake is not None:
                self.ready_info = handshake(timeout_s=start_timeout_s)
            else:
                self.ready_info = self.transport.recv(timeout_s=start_timeout_s)
        except (WorkerGone, FrameTimeout, FrameError) as exc:
            self._mark_dead()
            raise WorkerGone(f"worker {self.worker_id} never became ready: {exc}") from exc
        if not (self.ready_info.get("ready") or self.ready_info.get("registered")):
            self._mark_dead()
            raise WorkerGone(f"worker {self.worker_id} handshake failed: {self.ready_info}")

    # ---- transport plumbing ----
    @property
    def pid(self) -> Optional[int]:
        return getattr(self.transport, "pid", None)

    def _mark_dead(self):
        self._dead = True
        kill = getattr(self.transport, "kill", None)
        if kill is not None:
            try:
                kill()
            except OSError:
                pass

    def _call(self, msg: Dict[str, Any], timeout_s: Optional[float] = None) -> Dict[str, Any]:
        if self._dead:
            raise WorkerGone(f"worker {self.worker_id} process is gone")
        if self._reconnecting and not self._in_reconcile:
            raise _TransportDown(
                f"worker {self.worker_id} transport is reconnecting "
                f"(attempt {self._rc_attempts}, cause: {self._rc_cause})"
            )
        op = msg.get("op")
        t0 = time.perf_counter()
        try:
            self.transport.send(msg)
            reply = self.transport.recv(
                timeout_s=self.step_timeout_s if timeout_s is None else timeout_s
            )
        except FrameTimeout as exc:
            self._count_frame_error("timeout")
            if self._maybe_enter_reconnecting(exc, op):
                raise _TransportDown(str(exc)) from exc
            # A hung worker is indistinguishable from a dead one from the
            # controller's side — kill it so the rebuild path can take over.
            self._mark_dead()
            raise WorkerGone(
                f"worker {self.worker_id} missed its step deadline: {exc}"
            ) from exc
        except (WorkerGone, FrameError) as exc:
            self._count_frame_error(
                "torn" if isinstance(exc, WorkerGone) else "frame_error"
            )
            if self._maybe_enter_reconnecting(exc, op):
                raise _TransportDown(str(exc)) from exc
            self._mark_dead()
            raise WorkerGone(f"worker {self.worker_id} died: {exc}") from exc
        if self._m_rtt is not None:
            self._m_rtt.observe(time.perf_counter() - t0)
        if not reply.get("ok"):
            _raise_from_reply(reply)
        self._load = int(reply.get("load", self._load))
        self._queue_depth = int(reply.get("queue_depth", self._queue_depth))
        self._worker_pending = bool(reply.get("pending", self._worker_pending))
        return reply

    # ---- reconnect state machine ----
    @property
    def reconnecting(self) -> bool:
        return self._reconnecting

    def _can_reconnect(self) -> bool:
        if self.reconnect_deadline_s is None or self._closed or self._dead:
            return False
        if not hasattr(self.transport, "reconnect"):
            return False
        alive = getattr(self.transport, "alive", None)
        # A locally spawned worker whose PROCESS exited cannot be re-dialed —
        # that is genuine death, not a transport fault.
        return alive() if alive is not None else True

    def _maybe_enter_reconnecting(self, exc: BaseException, op: Optional[str]) -> bool:
        if not self._can_reconnect():
            return False
        self._enter_reconnecting(exc, op)
        return True

    def _enter_reconnecting(self, exc: BaseException, op: Optional[str]):
        sever = getattr(self.transport, "sever", None)
        if sever is not None:
            sever()
        if self._reconnecting:
            return  # a tear mid-reconcile keeps the ORIGINAL budget anchor
        now = time.monotonic()
        self._reconnecting = True
        self._rc_since = now
        self._rc_attempts = 0
        self._rc_next = now  # first attempt fires immediately
        self._rc_cause = f"{type(exc).__name__} during op={op}: {exc}"
        self._rc_last_err = None
        if self._m_reconnecting is not None:
            self._m_reconnecting.set(1.0)
        if self._tracer is not None:
            self._rc_span = self._tracer.start_span(
                "serve.reconnect", category="serve",
                replica=self._replica_label, worker_id=self.worker_id,
                cause=self._rc_cause,
            )
        logger.warning(
            "worker %d: transport tore (%s) — entering reconnecting "
            "(deadline %.1fs)", self.worker_id, self._rc_cause,
            self.reconnect_deadline_s,
        )

    def _finish_reconnect(self, outcome: str):
        self._reconnecting = False
        if outcome == "reconnected":
            self.reconnects += 1
            if self._m_reconnects is not None:
                self._m_reconnects.inc()
        if self._m_reconnecting is not None:
            self._m_reconnecting.set(0.0)
        if self._rc_span is not None:
            self._rc_span.annotate(
                outcome=outcome, attempts=self._rc_attempts,
                waited_s=round(time.monotonic() - self._rc_since, 3),
            ).end()
            self._rc_span = None
        logger.warning(
            "worker %d: reconnect %s after %d attempt(s)",
            self.worker_id, outcome, self._rc_attempts,
        )

    def _reconnect_step(self) -> List[Tuple[int, List[int]]]:
        """One non-blocking tick of the reconnect loop, driven by `step()`.
        Returns resumed stream events on success, [] while backing off; raises
        WorkerGone only when the reconnect budget is exhausted (escalating to
        the router's existing death/respawn path)."""
        now = time.monotonic()
        # Exhaustion requires at least one REAL attempt: a controller that
        # blocked past the whole budget (e.g. a synchronous respawn elsewhere
        # in the fleet) must not condemn a healthy link it never re-dialed.
        if self._rc_attempts >= 1 and now - self._rc_since > self.reconnect_deadline_s:
            self._finish_reconnect("exhausted")
            self._mark_dead()
            raise WorkerGone(
                f"worker {self.worker_id} reconnect budget exhausted: "
                f"{self._rc_attempts} attempt(s) over {self.reconnect_deadline_s:.1f}s "
                f"(cause: {self._rc_cause}; last error: {self._rc_last_err})"
            )
        if now < self._rc_next:
            return []
        self._rc_attempts += 1
        budget_left = self.reconnect_deadline_s - (now - self._rc_since)
        try:
            ready = self.transport.reconnect(
                timeout_s=max(0.05, min(5.0, budget_left))
            )
            self._in_reconcile = True
            try:
                self._reconcile_streams(ready)
            finally:
                self._in_reconcile = False
        except (WorkerGone, FrameError, FrameTimeout, OSError) as exc:
            backoff = min(
                self.reconnect_backoff_cap_s,
                self.reconnect_backoff_s * (2 ** (self._rc_attempts - 1)),
            ) * (0.5 + random.random() / 2)  # jitter: avoid fleet-wide lockstep
            self._rc_next = time.monotonic() + backoff
            self._rc_last_err = repr(exc)
            return []
        self._finish_reconnect("reconnected")
        events, self._rc_pending_events = self._rc_pending_events, []
        return events

    def _reconcile_streams(self, ready: Dict[str, Any]):
        """Reconcile local mirrors against the worker's retained per-request
        journal after a re-handshake. The contract: a stream is never
        duplicated and never silently truncated — requests the worker never
        saw (lost in a torn submit) re-dispatch verbatim IF nothing streamed
        yet; anything already streamed either resumes from the worker's
        retained tail (prefix-verified) or finishes `replica_lost`.

        Resumed tails accumulate in `_rc_pending_events` (not returned here):
        mirror extension is idempotent across a tear-during-reconcile retry,
        and `_reconnect_step` releases the events exactly once, on full
        success, so the router streams each token exactly once."""
        reply = self._call({"op": "reconcile"}, timeout_s=self.step_timeout_s)
        worker_view = {
            int(rid): rec for rid, rec in reply.get("requests", {}).items()
        }
        for rid, result in list(self.results.items()):
            queued_cancel = rid in self._cancel_after_reconnect
            if result.finished and not queued_cancel:
                continue
            rec = worker_view.get(rid)
            if rec is None:
                if result.finished:
                    continue  # locally cancelled; the worker never knew it
                wire = self._requests_wire.get(rid)
                if not result.tokens and wire is not None:
                    # Never streamed and unknown worker-side: the submit frame
                    # died in the partition — safe to re-dispatch.
                    try:
                        self._call({"op": "submit", "request": wire})
                    except (WorkerGone, FrameError, FrameTimeout):
                        raise  # transport tore again: retry the whole reconcile
                    except RuntimeError:
                        # Engine-side rejection (queue full, bad request): the
                        # request can't ride this replica anymore.
                        result.finished = True
                        result.finish_reason = "replica_lost"
                        result.finish_time = time.perf_counter()
                else:
                    result.finished = True
                    result.finish_reason = "replica_lost"
                    result.finish_time = time.perf_counter()
                continue
            worker_tokens = [int(t) for t in rec.get("tokens", ())]
            mine = [int(t) for t in result.tokens]
            if worker_tokens[: len(mine)] != mine:
                # The worker's journal does not extend what we streamed:
                # resuming would corrupt the stream — surface the loss.
                if not result.finished:
                    result.finished = True
                    result.finish_reason = "replica_lost"
                    result.finish_time = time.perf_counter()
                continue
            tail = worker_tokens[len(mine):]
            if tail and not result.finished:
                result.tokens.extend(tail)
                if result.first_token_time is None:
                    result.first_token_time = time.perf_counter()
                self._rc_pending_events.append((rid, tail))
            if rec.get("finished") and not result.finished:
                self._apply_finished([rec])
        # Cancels issued while the link was down: the mirrors already finished
        # "cancelled" locally; now actually stop the worker-side generation.
        for rid in sorted(self._cancel_after_reconnect):
            if rid in worker_view and not worker_view[rid].get("finished"):
                try:
                    self._call({"op": "cancel", "request_id": int(rid)})
                except (KeyError, ValueError):
                    pass
        self._cancel_after_reconnect.clear()

    def _count_frame_error(self, kind: str):
        if self._registry is not None:
            self._registry.counter(
                "transport_frame_errors_total",
                help="transport frame faults by kind (timeout/torn/frame_error)",
                labels={"kind": kind},
            ).inc()

    def attach_telemetry(self, registry, tracer=None, replica=None):
        """Wire the reconnect/transport instruments into a shared registry.
        Idempotent (the registry memoizes on (name, labels)); the router calls
        this for every engine it builds so cross-host replicas report
        `router_reconnects_total`, frame-error counts, RTTs, and the
        per-replica reconnecting gauge under one scrape."""
        self._registry = registry
        if tracer is not None:
            self._tracer = tracer
        if replica is not None:
            self._replica_label = str(replica)
        labels = {"replica": self._replica_label}
        if registry is not None:
            self._m_reconnects = registry.counter(
                "router_reconnects_total",
                help="successful transport re-handshakes (reconnect, not respawn)",
                labels=labels,
            )
            self._m_rtt = registry.histogram(
                "transport_rtt_seconds",
                help="frame round-trip time per protocol call", labels=labels,
            )
            self._m_reconnecting = registry.gauge(
                "router_replica_reconnecting",
                help="1 while the replica's transport is in the reconnecting state",
                labels=labels,
            )

    # ---- mirror maintenance ----
    def _apply_finished(self, records: List[Dict[str, Any]]):
        for record in records:
            result = self.results.get(int(record["request_id"]))
            if result is None or result.finished:
                continue
            result.tokens[:] = [int(t) for t in record["tokens"]]
            result.finished = True
            result.finish_reason = record.get("finish_reason")
            result.error = record.get("error")
            result.finish_time = time.perf_counter()

    # ---- engine surface ----
    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def pending(self) -> bool:
        # A dead worker with unfinished mirrors must look pending: the router
        # only discovers replica death by stepping it.
        unfinished = any(not r.finished for r in self.results.values())
        return unfinished or (self._worker_pending and not self._dead)

    @property
    def load(self) -> int:
        return self._load

    @property
    def queue_depth(self) -> int:
        return self._queue_depth

    @property
    def stats(self) -> Dict[str, Any]:
        if not self._dead and not self._closed:
            try:
                self._stats_cache = self._call({"op": "stats"})["stats"]
            except (WorkerGone, RuntimeError):
                pass
        return self._stats_cache

    @property
    def params(self):
        return None  # live params stay worker-side; the setter ships new ones

    @params.setter
    def params(self, value):
        if value is None:
            return
        from .checkpointing import save_pytree

        if self._params_dir is None:
            self._params_dir = tempfile.mkdtemp(prefix="accelerate_tpu_worker_params_")
        self._params_seq += 1
        path = os.path.join(self._params_dir, f"params_{self._params_seq}.npz")
        save_pytree(value, path)
        from .checkpointing import file_sha256

        # Digest-verified path handoff: across hosts the params file travels
        # by shared filesystem/object store, and the worker refuses to load
        # bytes that don't hash to what the controller shipped.
        self._call({"op": "set_params", "path": path,
                    "digest": file_sha256(path)})

    def submit(self, request) -> int:
        from .serving import EngineClosed, RequestResult

        if self._closed:
            raise EngineClosed("engine is closed")
        if self._dead:
            raise EngineClosed(f"worker {self.worker_id} process is gone")
        wire = request_to_wire(request)
        try:
            self._call({"op": "submit", "request": wire})
        except WorkerGone as exc:
            # The router's dispatch loop treats EngineClosed as "try the next
            # replica" (a reconnecting transport included — _TransportDown is
            # a WorkerGone); the death itself surfaces from the next step().
            raise EngineClosed(str(exc)) from exc
        self.results[request.request_id] = RequestResult(
            request.request_id, arrival_time=request.arrival_time
        )
        # Retained verbatim so a submit that streamed nothing before a
        # partition can safely re-dispatch during stream reconciliation.
        self._requests_wire[request.request_id] = wire
        return request.request_id

    def cancel(self, request_id: int) -> bool:
        result = self.results[request_id]  # KeyError for unknown ids, like the engine
        if result.finished:
            return False
        try:
            reply = self._call({"op": "cancel", "request_id": int(request_id)})
        except _TransportDown:
            # Link is down but the worker lives: finish the mirror cancelled
            # NOW (the caller's intent is immediate) and queue the worker-side
            # cancel for delivery right after stream reconciliation.
            self._cancel_after_reconnect.add(int(request_id))
            result.finished = True
            result.finish_reason = "cancelled"
            result.finish_time = time.perf_counter()
            return True
        except WorkerGone:
            # Worker died under the cancel: the mirror finishes cancelled
            # locally (partial tokens kept) — nothing can stream anymore.
            result.finished = True
            result.finish_reason = "cancelled"
            result.finish_time = time.perf_counter()
            return True
        # `cancelled: false` means the worker finished it first (a terminal
        # token raced our cancel out): adopt the worker's record verbatim.
        self._apply_finished([reply["result"]])
        return bool(reply["cancelled"])

    def release(self, request_id: int):
        result = self.results[request_id]
        if not result.finished:
            raise ValueError(f"request {request_id} is still in flight")
        if not self._dead and not self._closed:
            try:
                self._call({"op": "release", "request_id": int(request_id)})
            except (WorkerGone, KeyError, ValueError):
                pass
        del self.results[request_id]
        self._requests_wire.pop(request_id, None)
        self._cancel_after_reconnect.discard(request_id)
        return result

    def step(self) -> List[Tuple[int, List[int]]]:
        if self._closed:
            return []
        if self._reconnecting:
            return self._reconnect_step()
        try:
            reply = self._call({"op": "step"})
        except _TransportDown:
            # The tear happened on THIS call — drive the first reconnect
            # attempt immediately instead of burning a router cycle.
            return self._reconnect_step()
        events: List[Tuple[int, List[int]]] = []
        for rid, toks in reply.get("events", ()):
            rid = int(rid)
            toks = [int(t) for t in toks]
            result = self.results.get(rid)
            if result is not None and not result.finished:
                result.tokens.extend(toks)
                if result.first_token_time is None:
                    result.first_token_time = time.perf_counter()
            events.append((rid, toks))
        self._apply_finished(reply.get("finished", ()))
        return events

    def run(self, requests=None) -> Dict[int, np.ndarray]:
        for request in requests or ():
            self.submit(request)
        while self.pending:
            self.step()
            if self._reconnecting:
                time.sleep(0.005)  # pace the backoff wait instead of spinning
        return {rid: np.asarray(r.tokens, np.int32) for rid, r in self.results.items()}

    def drain(self) -> Dict[int, Any]:
        while self._reconnecting:
            self._reconnect_step()
            if self._reconnecting:
                time.sleep(min(0.05, max(0.0, self._rc_next - time.monotonic())) or 0.005)
        reply = self._call({"op": "drain"}, timeout_s=self.step_timeout_s * 10)
        self._apply_finished(reply.get("finished", ()))
        return self.results

    def warm_inserts(self) -> List[int]:
        return [int(b) for b in self._call({"op": "warm"})["buckets"]]

    def reset_guard(self) -> bool:
        """Zero the worker-side TraceGuard counters (spawned with guard=True):
        benches call this after warmup so the timed window's 0/0 gate is
        exact. Returns whether a guard is armed at all."""
        return bool(self._call({"op": "guard_reset"})["armed"])

    def terminate(self):
        """Hard shutdown for a replica being ejected: kill the worker process
        and reap it WITHOUT the cooperative close RPC (the worker may be the
        reason we are here — hung, or erroring every dispatch). The router's
        eject path calls this so a worker that failed via error replies (its
        transport still alive) can never linger as an orphan next to its
        replacement, holding device memory."""
        self._mark_dead()
        close = getattr(self.transport, "close", None)
        if close is not None:
            close()

    def close(self) -> Dict[int, Any]:
        if self._closed:
            return self.results
        if not self._dead:
            try:
                reply = self._call({"op": "close"})
                self._apply_finished(reply.get("finished", ()))
            except (WorkerGone, RuntimeError):
                pass
        for result in self.results.values():
            if not result.finished:
                result.finished = True
                result.finish_reason = "cancelled"
                result.finish_time = time.perf_counter()
        close = getattr(self.transport, "close", None)
        if close is not None:
            close()
        self._closed = True
        return self.results


def make_subprocess_factory(
    model=None,
    spec: Optional[Dict[str, Any]] = None,
    engine_kwargs: Optional[Dict[str, Any]] = None,
    *,
    workdir: Optional[str] = None,
    warm: bool = True,
    guard: bool = False,
    env: Optional[Dict[str, str]] = None,
    heartbeat_deadline_s: float = DEFAULT_HEARTBEAT_S,
    step_timeout_s: float = 120.0,
    start_timeout_s: float = 600.0,
    stderr_dir: Optional[str] = None,
    transport: str = "pipe",
    reconnect_deadline_s: Optional[float] = None,
    connect: Optional[Sequence[str]] = None,
) -> Callable[[int], SubprocessEngine]:
    """Build a `ReplicaSet.engine_factory` that spawns one warm subprocess
    worker per replica index. When a live `model` is given, its params are
    saved ONCE to `<workdir>/params.npz` and every worker (including restarts)
    loads that exact file — subprocess fleets are token-identical to in-process
    ones by construction. `stderr_dir` (default: the workdir) collects one
    append-mode `worker_<i>.stderr.log` per index, so restarted workers extend
    their predecessor's log instead of interleaving on the controller's tty.

    `connect=["HOST:PORT", ...]` adopts EXTERNALLY launched listener workers
    (`python -m accelerate_tpu.worker --listen HOST:PORT`) instead of spawning:
    replica `i` dials `connect[i % len(connect)]`, and a factory rebuild after
    worker death re-dials the same address — respawning the remote process is
    its own supervisor's job. Implies the socket transport; the spec's params
    path must be reachable on the worker's host (digest-verified on load)."""
    if (model is None) == (spec is None):
        raise ValueError("pass exactly one of model= or spec=")
    workdir = workdir or tempfile.mkdtemp(prefix="accelerate_tpu_fleet_")
    os.makedirs(workdir, exist_ok=True)
    if model is not None:
        from .checkpointing import file_sha256, save_pytree

        params_path = os.path.join(workdir, "params.npz")
        save_pytree(model.params, params_path)
        spec = spec_for_model(
            model, params_path=params_path,
            params_digest=file_sha256(params_path),
        )
    engine_kwargs = dict(engine_kwargs or {})
    stderr_dir = stderr_dir or workdir

    addresses = list(connect) if connect else None
    if addresses is not None:
        transport = "socket"

    def factory(index: int) -> SubprocessEngine:
        if addresses is not None:
            return SubprocessEngine(
                spec, engine_kwargs, worker_id=index,
                connect=addresses[index % len(addresses)],
                heartbeat_deadline_s=heartbeat_deadline_s,
                step_timeout_s=step_timeout_s,
                start_timeout_s=start_timeout_s,
                reconnect_deadline_s=reconnect_deadline_s,
            )
        log_path = os.path.join(stderr_dir, f"worker_{index}.stderr.log")
        stderr = open(log_path, "ab")
        try:
            return SubprocessEngine(
                spec, engine_kwargs, worker_id=index,
                warm=warm, guard=guard,
                heartbeat_deadline_s=heartbeat_deadline_s,
                step_timeout_s=step_timeout_s,
                start_timeout_s=start_timeout_s,
                env=env, stderr=stderr,
                transport=transport,
                reconnect_deadline_s=reconnect_deadline_s,
            )
        finally:
            stderr.close()  # the child holds its own copy of the fd

    factory.workdir = workdir
    factory.spec = spec
    factory.transport = transport
    factory.connect = addresses
    return factory


if __name__ == "__main__":
    sys.exit(main())

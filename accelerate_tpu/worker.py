"""Out-of-process serving workers: one engine per OS process, coordinated over
an explicit IPC protocol.

PR 10's `router.Router` made the serving fleet replicated, but every replica
still shared one Python interpreter: a segfault, a GIL stall, or an OOM in any
engine took down ALL of them. This module moves the engine into a real process
fault domain — the serving analogue of the multi-controller discipline MPMD
training systems use: independent workers, an explicit wire protocol, and a
controller that can lose any worker without losing its own state.

Three layers, bottom up:

  - **Framing** (`send_frame` / `recv_frame`): length-prefixed JSON over a pair
    of pipe/socket file descriptors. A frame is a 4-byte big-endian payload
    length followed by UTF-8 JSON. `recv_frame` always takes a deadline — an
    IPC read with no timeout turns a hung peer into a hung caller, which is
    exactly the failure isolation this module exists to remove (analysis rule
    TPU116 lints that discipline). Torn frames (EOF mid-payload) raise
    `WorkerGone`; oversized or undecodable frames raise `FrameError`.

  - **Worker side** (`python -m accelerate_tpu.worker`): builds a model from a
    JSON spec (a named registry model, or a family+config dict with the params
    loaded from an `.npz` the controller saved — so worker params are
    bit-identical to the controller's, never re-derived), hosts ONE
    `ContinuousBatcher` behind `EngineHost`, optionally pre-warms the insert
    ladder before reporting ready (a restarted worker rejoins WARM: the fleet
    never pays a compile on the serving path), and runs `serve_worker` — a
    recv/dispatch/reply loop with a heartbeat deadline: a controller that goes
    silent past the deadline means the worker is orphaned and exits instead of
    leaking. Fault plans ride the PR 5 env protocol (`ACCELERATE_TPU_FAULT_PLAN`)
    and trace context rides the PR 7 one (`ACCELERATE_TPU_TRACE_DIR`), so chaos
    can SIGKILL a real worker mid-dispatch and the evidence survives.

  - **Controller side** (`SubprocessEngine`): a client proxy exposing the
    engine's EXACT surface (`submit`/`cancel`/`release`/`step`/`run`/`drain`/
    `close`, `results`/`pending`/`load`/`queue_depth`/`stats`/`warm_inserts`,
    assignable `params`), so `router.Router` routes over subprocess workers
    with ZERO routing changes — `make_subprocess_factory` plugs into
    `ReplicaSet.engine_factory` and the health machine's existing
    eject/rebuild/rejoin path becomes real process supervision: a SIGKILLed
    worker surfaces as `WorkerGone` from `step()`, the router ejects it, and
    the rebuild spawns a fresh warm process.

Everything on the wire is host scalars and token ids; params move by file
handoff (`save_pytree` -> path -> worker `load_pytree`), never through frames.
"""

from __future__ import annotations

import dataclasses
import json
import os
import select
import signal
import struct
import subprocess
import sys
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .logging import get_logger

logger = get_logger(__name__)

#: Env var carrying the worker's fleet index to the subprocess (the chaos
#: `path_pattern: "worker_N"` targeting token derives from it).
WORKER_ID_ENV = "ACCELERATE_TPU_WORKER_ID"
#: Env var naming the shared append-only chaos journal file workers record
#: injections into BEFORE the damage lands (a SIGKILL must not erase the
#: evidence that it fired) — and read back on restart so a per-process
#: re-armed plan cannot livelock by re-killing at the same trigger.
CHAOS_JOURNAL_ENV = "ACCELERATE_TPU_CHAOS_JOURNAL"

#: Hard ceiling on one frame's payload. Tokens and host scalars only — params
#: move by file handoff — so anything near this is a protocol violation, not a
#: big message.
MAX_FRAME_BYTES = 64 << 20

#: Default worker-side heartbeat: a controller silent for this long means the
#: worker is orphaned (controller crashed without close()) and exits.
DEFAULT_HEARTBEAT_S = 120.0

#: Exit code a worker uses when it terminates itself (orphaned / torn pipe),
#: distinguishing self-termination from a crash in supervision logs.
ORPHANED_EXIT_CODE = 17


class FrameError(RuntimeError):
    """A malformed frame: oversized length prefix or undecodable payload (a
    protocol bug or corrupted stream, NOT a dead peer)."""


class FrameTimeout(RuntimeError):
    """No complete frame arrived inside the deadline: the peer is hung (or
    stalled past its budget) — the caller decides whether that is fatal."""


class WorkerGone(RuntimeError):
    """The peer's stream ended (EOF / broken pipe), cleanly or mid-frame: the
    process on the other side is dead. Escapes `SubprocessEngine.step()` so the
    router's replica-death handling (eject -> rebuild -> rejoin) takes over."""


def _fileno(stream) -> int:
    return stream if isinstance(stream, int) else stream.fileno()


def _read_exact(fd: int, n: int, deadline: Optional[float], what: str) -> bytes:
    """Read exactly `n` bytes from `fd`, honoring an absolute monotonic
    deadline. EOF before `n` bytes is a dead peer (`WorkerGone`) — torn frames
    included; a deadline miss is `FrameTimeout`."""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise FrameTimeout(f"timed out waiting for {what} ({got}/{n} bytes)")
            ready, _, _ = select.select([fd], [], [], remaining)
            if not ready:
                raise FrameTimeout(f"timed out waiting for {what} ({got}/{n} bytes)")
        chunk = os.read(fd, n - got)
        if not chunk:
            raise WorkerGone(
                f"peer closed the stream mid-{what} ({got}/{n} bytes)"
                if got else "peer closed the stream"
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_frame(stream, obj: Dict[str, Any]) -> None:
    """Write one length-prefixed JSON frame. Raises `WorkerGone` when the peer
    end of the pipe is closed, `FrameError` for oversized payloads."""
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise FrameError(f"frame of {len(payload)} bytes exceeds MAX_FRAME_BYTES")
    data = struct.pack(">I", len(payload)) + payload
    fd = _fileno(stream)
    view = memoryview(data)
    while view:
        try:
            written = os.write(fd, view)
        except (BrokenPipeError, OSError) as exc:
            raise WorkerGone(f"peer pipe closed during send: {exc!r}") from exc
        view = view[written:]


def recv_frame(stream, timeout_s: Optional[float]) -> Dict[str, Any]:
    """Read one frame. `timeout_s` is the heartbeat deadline for the WHOLE
    frame — pass the peer's liveness budget, never None in a long-lived loop
    (TPU116). Raises `FrameTimeout` / `WorkerGone` / `FrameError`."""
    fd = _fileno(stream)
    deadline = None if timeout_s is None else time.monotonic() + float(timeout_s)
    header = _read_exact(fd, 4, deadline, "frame header")
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"frame length {length} exceeds MAX_FRAME_BYTES")
    payload = _read_exact(fd, length, deadline, "frame payload")
    try:
        return json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise FrameError(f"undecodable frame payload: {exc}") from exc


# ------------------------------------------------------------------ wire codecs
def request_to_wire(request) -> Dict[str, Any]:
    return {
        "request_id": int(request.request_id),
        "input_ids": [int(t) for t in np.asarray(request.input_ids).reshape(-1)],
        "max_new_tokens": int(request.max_new_tokens),
        "temperature": float(request.temperature),
        "repetition_penalty": float(request.repetition_penalty),
        "eos_token_id": None if request.eos_token_id is None else int(request.eos_token_id),
        "arrival_time": float(request.arrival_time),
        "deadline_s": None if request.deadline_s is None else float(request.deadline_s),
        "tenant": getattr(request, "tenant", None),
        "priority": int(getattr(request, "priority", 0)),
    }


def request_from_wire(data: Dict[str, Any]):
    from .serving import Request

    return Request(
        request_id=int(data["request_id"]),
        input_ids=np.asarray(data["input_ids"], np.int32),
        max_new_tokens=int(data["max_new_tokens"]),
        temperature=float(data.get("temperature", 1.0)),
        repetition_penalty=float(data.get("repetition_penalty", 1.0)),
        eos_token_id=data.get("eos_token_id"),
        arrival_time=float(data.get("arrival_time", 0.0)),
        deadline_s=data.get("deadline_s"),
        tenant=data.get("tenant"),
        priority=int(data.get("priority", 0)),
    )


def result_to_wire(result) -> Dict[str, Any]:
    return {
        "request_id": int(result.request_id),
        "tokens": [int(t) for t in result.tokens],
        "finished": bool(result.finished),
        "finish_reason": result.finish_reason,
        "error": result.error,
    }


#: Engine exception -> wire kind; the client re-raises the same type, so the
#: router's QueueFull/EngineClosed handling works unchanged out of process.
_ERROR_KINDS = ("queue_full", "engine_closed", "value_error", "key_error", "runtime_error")


def _error_reply(exc: BaseException) -> Dict[str, Any]:
    from .serving import EngineClosed, QueueFull

    if isinstance(exc, QueueFull):
        kind = "queue_full"
    elif isinstance(exc, EngineClosed):
        kind = "engine_closed"
    elif isinstance(exc, ValueError):
        kind = "value_error"
    elif isinstance(exc, KeyError):
        kind = "key_error"
    else:
        kind = "runtime_error"
    return {"ok": False, "kind": kind, "error": str(exc) or repr(exc)}


def _raise_from_reply(reply: Dict[str, Any]):
    from .serving import EngineClosed, QueueFull

    kind = reply.get("kind", "runtime_error")
    message = reply.get("error", "worker error")
    if kind == "queue_full":
        raise QueueFull(message)
    if kind == "engine_closed":
        raise EngineClosed(message)
    if kind == "value_error":
        raise ValueError(message)
    if kind == "key_error":
        raise KeyError(message)
    raise RuntimeError(message)


# ------------------------------------------------------------------ model specs
#: Flax module class name -> model-family key (`models.CREATE_BY_FAMILY`).
#: Serving needs `decode_slot_cache`, so only the slot-cache families appear.
_FAMILY_BY_MODULE = {
    "LlamaForCausalLM": "llama",
    "GPTNeoXForCausalLM": "gpt_neox",
}


def spec_for_model(model, params_path: Optional[str] = None) -> Dict[str, Any]:
    """Serialize a live Model bundle into a worker-buildable JSON spec: the
    family + config dataclass fields, plus the path of a `save_pytree`'d params
    file. Params ALWAYS move by file — a worker must serve the controller's
    exact weights (token parity), never a re-derived init."""
    family = _FAMILY_BY_MODULE.get(type(model.module).__name__)
    if family is None:
        raise ValueError(
            f"{type(model.module).__name__} has no subprocess-worker family mapping; "
            f"known: {sorted(_FAMILY_BY_MODULE)}"
        )
    return {
        "family": family,
        "config": dataclasses.asdict(model.module.config),
        "params_path": params_path,
    }


def build_model_from_spec(spec: Dict[str, Any]):
    """Worker-side model construction. Accepts either a named registry model
    (`{"name": "llama-tiny"}`) or a family+config spec from `spec_for_model`;
    a `params_path` (when present) replaces the init params wholesale."""
    from . import models

    if "name" in spec:
        model = models.create_named_model(spec["name"], seq_len=int(spec.get("seq_len", 8)))
    else:
        family = spec["family"]
        create = models.CREATE_BY_FAMILY.get(family)
        if create is None:
            raise ValueError(f"unknown model family {family!r} in worker spec")
        config_cls = type(models.MODEL_REGISTRY[f"{family.replace('_', '-')}-tiny"][1]())
        config = config_cls(**spec["config"])
        # Tiny init seq_len: the real params arrive via params_path below, so
        # the throwaway init should cost as little as possible.
        seq_len = int(spec.get("seq_len", 8))
        model = create(config, seq_len=seq_len)
    params_path = spec.get("params_path")
    if params_path:
        model.params = _load_params_on_device(params_path)
    return model


def _load_params_on_device(path: str):
    """`load_pytree` returns numpy leaves "placed by the caller" — place them
    NOW: params left as numpy would ride every dispatch as an implicit
    host-to-device transfer (a per-step re-upload the worker's own armed
    TraceGuard rightly rejects)."""
    import jax

    from .checkpointing import load_pytree

    return jax.tree_util.tree_map(jax.device_put, load_pytree(path))


# ------------------------------------------------------------------ worker side
class EngineHost:
    """Executes protocol ops against one `ContinuousBatcher`. Pure translation:
    every engine exception maps to a typed error reply, every reply carries the
    load/queue-depth scalars the controller mirrors for routing."""

    def __init__(self, engine, worker_id: int = 0, guard=None):
        self.engine = engine
        self.worker_id = int(worker_id)
        self.guard = guard
        #: Result ids already shipped in a `finished` list (step/drain replies
        #: carry only the delta; release forgets).
        self._reported: set = set()

    # ---- op implementations ----
    def _load_view(self) -> Dict[str, Any]:
        return {
            "load": int(self.engine.load),
            "queue_depth": int(self.engine.queue_depth),
            "pending": bool(self.engine.pending),
        }

    def _finished_delta(self) -> List[Dict[str, Any]]:
        out = []
        for rid, result in self.engine.results.items():
            if result.finished and rid not in self._reported:
                self._reported.add(rid)
                out.append(result_to_wire(result))
        return out

    def _worker_stats(self) -> Dict[str, Any]:
        stats = dict(self.engine.stats)
        stats["worker"] = {
            "pid": os.getpid(),
            "worker_id": self.worker_id,
            "trace_counts": dict(self.engine.trace_counts),
            "guard": None if self.guard is None else {
                "recompiles": int(self.guard.total_recompiles),
                "host_transfers": int(self.guard.host_transfers),
            },
        }
        return stats

    def handle(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        op = msg.get("op")
        try:
            if op == "ping":
                return {"ok": True, "pid": os.getpid(), **self._load_view()}
            if op == "submit":
                request = request_from_wire(msg["request"])
                self.engine.submit(request)
                return {"ok": True, **self._load_view()}
            if op == "cancel":
                rid = int(msg["request_id"])
                cancelled = self.engine.cancel(rid)
                return {
                    "ok": True,
                    "cancelled": bool(cancelled),
                    "result": result_to_wire(self.engine.results[rid]),
                    **self._load_view(),
                }
            if op == "release":
                rid = int(msg["request_id"])
                result = self.engine.release(rid)
                self._reported.discard(rid)
                return {"ok": True, "result": result_to_wire(result)}
            if op == "step":
                events = self.engine.step()
                return {
                    "ok": True,
                    "events": [[int(rid), [int(t) for t in toks]] for rid, toks in events],
                    "finished": self._finished_delta(),
                    **self._load_view(),
                }
            if op == "drain":
                self.engine.drain()
                return {"ok": True, "finished": self._finished_delta(), **self._load_view()}
            if op == "warm":
                # Warmup pushes throwaway donated operands host->device by
                # design — suspend the armed guard (the 0/0 gate covers the
                # SERVING path, warm windows are excluded exactly like the
                # in-process benches arm after warm_inserts()).
                if self.guard is not None:
                    self.guard.__exit__(None, None, None)
                try:
                    buckets = self.engine.warm_inserts()
                finally:
                    if self.guard is not None:
                        self.guard.__enter__()
                return {"ok": True, "buckets": [int(b) for b in buckets]}
            if op == "stats":
                return {"ok": True, "stats": self._worker_stats(), **self._load_view()}
            if op == "guard_reset":
                # Benches warm the serving path first, then zero the guard so
                # the timed window's 0-recompile/0-transfer gate is exact.
                if self.guard is not None:
                    self.guard.reset()
                return {"ok": True, "armed": self.guard is not None}
            if op == "set_params":
                # The file handoff always carries RAW params; a quantized
                # engine (weight_dtype="int8" via engine_kwargs) re-quantizes
                # in its params setter — same seam as an in-process swap.
                self.engine.params = _load_params_on_device(msg["path"])
                return {"ok": True}
            if op == "close":
                self.engine.close()
                return {"ok": True, "finished": self._finished_delta()}
            return {"ok": False, "kind": "value_error", "error": f"unknown op {op!r}"}
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as exc:  # noqa: BLE001 — typed error replies, worker stays up
            return _error_reply(exc)


class WorkerChaos:
    """Worker-side fault injection (the env-propagated half of the fleet
    sweeps): `fleet.worker_kill` delivers a REAL ``SIGKILL`` to this process at
    a matching step op, `fleet.worker_stall` sleeps past the controller's step
    timeout so the heartbeat machinery — not cooperation — detects the hang.

    Every firing is journaled (append + fsync) to the shared
    ``ACCELERATE_TPU_CHAOS_JOURNAL`` file BEFORE the damage lands, and the
    journal is read back at startup to pre-consume already-fired events — a
    restarted worker re-arms the same plan from env but must not re-kill
    itself at the same trigger (the PR 9 livelock lesson)."""

    def __init__(self, plan, worker_id: int, journal_path: Optional[str] = None,
                 tracer=None):
        from .chaos.injectors import ChaosSession

        self.session = ChaosSession(plan, tracer=tracer)
        self.token = f"worker_{int(worker_id)}"
        self.journal_path = journal_path
        if journal_path and os.path.exists(journal_path):
            for kind, count in self._journaled_counts(journal_path).items():
                self.session.preconsume(kind, count, path=self.token)
        if journal_path:
            self.session.on_inject = self._journal

    def _journaled_counts(self, path: str) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail of a killed writer
                if entry.get("worker") == self.token:
                    counts[entry["kind"]] = counts.get(entry["kind"], 0) + 1
        return counts

    def _journal(self, entry: Dict[str, Any]):
        record = json.dumps({**entry, "worker": self.token, "pid": os.getpid()})
        # O_APPEND single-write + fsync: atomic against concurrent workers,
        # durable against the SIGKILL that may follow immediately.
        fd = os.open(self.journal_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, (record + "\n").encode())
            os.fsync(fd)
        finally:
            os.close(fd)

    def arm(self, engine):
        from .chaos.injectors import ServingInjector

        ServingInjector(self.session).arm(engine)
        return self

    def poll(self, op: str):
        if op != "step":
            return
        for ev in self.session.fire("fleet.worker_stall", path=self.token):
            self.session.clock.sleep(float(ev.args.get("delay_s", 1.0)))
        for _ev in self.session.fire("fleet.worker_kill", path=self.token):
            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(5)  # unreachable — SIGKILL is unmaskable; belt for exotic platforms


def serve_worker(host: EngineHost, rstream, wstream, *,
                 heartbeat_deadline_s: Optional[float] = DEFAULT_HEARTBEAT_S,
                 chaos: Optional[WorkerChaos] = None) -> int:
    """The worker main loop: recv one frame, dispatch, reply. The heartbeat
    deadline bounds EVERY recv — a controller silent past it means this worker
    is orphaned (controller crashed without `close`), and the worker exits
    rather than leaking a process + device memory (analysis rule TPU116 flags
    loops built without this bound). Returns the process exit code."""
    while True:
        try:
            msg = recv_frame(rstream, timeout_s=heartbeat_deadline_s)
        except FrameTimeout:
            logger.warning(
                "worker %d: controller silent for %.1fs — exiting as orphaned",
                host.worker_id, heartbeat_deadline_s,
            )
            return ORPHANED_EXIT_CODE
        except (WorkerGone, FrameError) as exc:
            logger.warning("worker %d: control stream died: %r", host.worker_id, exc)
            return ORPHANED_EXIT_CODE
        if chaos is not None:
            chaos.poll(msg.get("op"))
        reply = host.handle(msg)
        try:
            send_frame(wstream, reply)
        except WorkerGone:
            return ORPHANED_EXIT_CODE
        if msg.get("op") == "close":
            return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser("accelerate-tpu serving worker")
    parser.add_argument("--spec-json", required=True,
                        help="model spec JSON (spec_for_model / {'name': ...})")
    parser.add_argument("--engine-json", default="{}",
                        help="ContinuousBatcher kwargs as JSON")
    parser.add_argument("--worker-id", type=int,
                        default=int(os.environ.get(WORKER_ID_ENV, "0")))
    parser.add_argument("--heartbeat-deadline-s", type=float, default=DEFAULT_HEARTBEAT_S)
    parser.add_argument("--no-warm", action="store_true",
                        help="skip pre-warming the insert ladder before reporting ready")
    parser.add_argument("--guard", action="store_true",
                        help="arm a record-mode TraceGuard post-warmup and report its "
                        "recompile/host-transfer counters in stats")
    args = parser.parse_args(argv)

    # fd 1 belongs to the PROTOCOL: anything else printing to stdout (jax
    # warnings, user prints) would corrupt frames. Keep a private dup for
    # frames and point fd 1 (and sys.stdout) at stderr.
    ipc_out = os.dup(1)
    os.dup2(2, 1)
    sys.stdout = sys.stderr
    ipc_in = 0

    from .serving import ContinuousBatcher
    from .telemetry.tracing import default_tracer

    tracer = default_tracer()
    spec = json.loads(args.spec_json)
    engine_kwargs = json.loads(args.engine_json)
    span = tracer.start_span(
        "worker.lifetime", category="worker",
        worker_id=args.worker_id, pid=os.getpid(),
    )
    model = build_model_from_spec(spec)
    # The controller always threads its own max_queue through engine_kwargs;
    # a hand-launched worker still gets a bounded queue (TPU114 discipline).
    max_queue = engine_kwargs.pop("max_queue", 64)
    engine = ContinuousBatcher(model, tracer=tracer, max_queue=max_queue, **engine_kwargs)

    chaos = None
    from .chaos.plan import FaultPlan

    plan = FaultPlan.from_env()
    if plan is not None:
        chaos = WorkerChaos(
            plan, args.worker_id,
            journal_path=os.environ.get(CHAOS_JOURNAL_ENV), tracer=tracer,
        )
        chaos.arm(engine)

    warmed: List[int] = []
    if not args.no_warm:
        warmed = [int(b) for b in engine.warm_inserts()]

    guard = None
    if args.guard:
        from .analysis import TraceGuard

        guard = TraceGuard(
            transfer_guard="disallow", on_violation="record",
            name=f"worker-{args.worker_id}",
        )
        guard.__enter__()

    host = EngineHost(engine, worker_id=args.worker_id, guard=guard)
    send_frame(ipc_out, {
        "ok": True, "ready": True, "pid": os.getpid(),
        "worker_id": args.worker_id, "warm": not args.no_warm, "warmed": warmed,
    })
    span.event("ready", warmed_buckets=len(warmed))
    code = serve_worker(
        host, ipc_in, ipc_out,
        heartbeat_deadline_s=args.heartbeat_deadline_s, chaos=chaos,
    )
    if guard is not None:
        guard.__exit__(None, None, None)
    span.annotate(exit_code=code).end()
    return code


# ------------------------------------------------------------------ controller side
class _PipeTransport:
    """The real transport: a spawned worker process with frame streams over
    its stdin/stdout pipes. Tests substitute a duck-typed fake."""

    def __init__(self, cmd: List[str], env: Dict[str, str], stderr=None):
        self.proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=stderr, env=env, bufsize=0,
        )

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def send(self, obj: Dict[str, Any]):
        send_frame(self.proc.stdin, obj)

    def recv(self, timeout_s: Optional[float]) -> Dict[str, Any]:
        return recv_frame(self.proc.stdout, timeout_s=timeout_s)

    def kill(self):
        if self.alive():
            self.proc.kill()

    def close(self, timeout_s: float = 10.0):
        try:
            self.proc.stdin.close()
        except OSError:
            pass
        try:
            self.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()
        try:
            self.proc.stdout.close()
        except OSError:
            pass


class SubprocessEngine:
    """Client proxy for one out-of-process engine worker, exposing the exact
    `ContinuousBatcher` surface so `Router` needs no routing changes.

    The proxy mirrors request results locally (`results` holds real
    `RequestResult`s updated from step replies), mirrors the worker's
    load/queue-depth scalars for least-loaded routing, and converts transport
    death into the router's existing failure language: a dead/hung worker makes
    `step()` raise `WorkerGone` (-> `fail_replica` -> factory rebuild -> warm
    rejoin) and `submit()` raise `EngineClosed` (-> the router tries the next
    candidate replica)."""

    def __init__(
        self,
        spec: Dict[str, Any],
        engine_kwargs: Optional[Dict[str, Any]] = None,
        worker_id: int = 0,
        *,
        warm: bool = True,
        guard: bool = False,
        heartbeat_deadline_s: float = DEFAULT_HEARTBEAT_S,
        step_timeout_s: float = 120.0,
        start_timeout_s: float = 600.0,
        env: Optional[Dict[str, str]] = None,
        stderr=None,
        python: Optional[str] = None,
        _transport=None,
    ):
        from .serving import RequestResult  # noqa: F401 — re-exported surface

        self.spec = dict(spec)
        self.engine_kwargs = dict(engine_kwargs or {})
        self.worker_id = int(worker_id)
        self.max_queue = self.engine_kwargs.get("max_queue")
        self.step_timeout_s = float(step_timeout_s)
        self.results: Dict[int, Any] = {}
        self.trace_guard = None  # surface parity; guards run worker-side
        self._dead = False
        self._closed = False
        self._load = 0
        self._queue_depth = 0
        self._worker_pending = False
        self._stats_cache: Dict[str, Any] = {}
        self._params_dir: Optional[str] = None
        self._params_seq = 0
        if _transport is not None:
            self.transport = _transport
        else:
            run_env = dict(os.environ if env is None else env)
            run_env[WORKER_ID_ENV] = str(self.worker_id)
            pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            run_env["PYTHONPATH"] = pkg_parent + os.pathsep + run_env.get("PYTHONPATH", "")
            cmd = [
                python or sys.executable, "-m", "accelerate_tpu.worker",
                "--spec-json", json.dumps(self.spec),
                "--engine-json", json.dumps(self.engine_kwargs),
                "--worker-id", str(self.worker_id),
                "--heartbeat-deadline-s", str(heartbeat_deadline_s),
            ]
            if not warm:
                cmd.append("--no-warm")
            if guard:
                cmd.append("--guard")
            self.transport = _PipeTransport(cmd, env=run_env, stderr=stderr)
        try:
            self.ready_info = self.transport.recv(timeout_s=start_timeout_s)
        except (WorkerGone, FrameTimeout, FrameError) as exc:
            self._mark_dead()
            raise WorkerGone(f"worker {self.worker_id} never became ready: {exc}") from exc
        if not self.ready_info.get("ready"):
            self._mark_dead()
            raise WorkerGone(f"worker {self.worker_id} handshake failed: {self.ready_info}")

    # ---- transport plumbing ----
    @property
    def pid(self) -> Optional[int]:
        return getattr(self.transport, "pid", None)

    def _mark_dead(self):
        self._dead = True
        kill = getattr(self.transport, "kill", None)
        if kill is not None:
            try:
                kill()
            except OSError:
                pass

    def _call(self, msg: Dict[str, Any], timeout_s: Optional[float] = None) -> Dict[str, Any]:
        if self._dead:
            raise WorkerGone(f"worker {self.worker_id} process is gone")
        try:
            self.transport.send(msg)
            reply = self.transport.recv(
                timeout_s=self.step_timeout_s if timeout_s is None else timeout_s
            )
        except FrameTimeout as exc:
            # A hung worker is indistinguishable from a dead one from the
            # controller's side — kill it so the rebuild path can take over.
            self._mark_dead()
            raise WorkerGone(
                f"worker {self.worker_id} missed its step deadline: {exc}"
            ) from exc
        except (WorkerGone, FrameError) as exc:
            self._mark_dead()
            raise WorkerGone(f"worker {self.worker_id} died: {exc}") from exc
        if not reply.get("ok"):
            _raise_from_reply(reply)
        self._load = int(reply.get("load", self._load))
        self._queue_depth = int(reply.get("queue_depth", self._queue_depth))
        self._worker_pending = bool(reply.get("pending", self._worker_pending))
        return reply

    # ---- mirror maintenance ----
    def _apply_finished(self, records: List[Dict[str, Any]]):
        for record in records:
            result = self.results.get(int(record["request_id"]))
            if result is None or result.finished:
                continue
            result.tokens[:] = [int(t) for t in record["tokens"]]
            result.finished = True
            result.finish_reason = record.get("finish_reason")
            result.error = record.get("error")
            result.finish_time = time.perf_counter()

    # ---- engine surface ----
    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def pending(self) -> bool:
        # A dead worker with unfinished mirrors must look pending: the router
        # only discovers replica death by stepping it.
        unfinished = any(not r.finished for r in self.results.values())
        return unfinished or (self._worker_pending and not self._dead)

    @property
    def load(self) -> int:
        return self._load

    @property
    def queue_depth(self) -> int:
        return self._queue_depth

    @property
    def stats(self) -> Dict[str, Any]:
        if not self._dead and not self._closed:
            try:
                self._stats_cache = self._call({"op": "stats"})["stats"]
            except (WorkerGone, RuntimeError):
                pass
        return self._stats_cache

    @property
    def params(self):
        return None  # live params stay worker-side; the setter ships new ones

    @params.setter
    def params(self, value):
        if value is None:
            return
        from .checkpointing import save_pytree

        if self._params_dir is None:
            self._params_dir = tempfile.mkdtemp(prefix="accelerate_tpu_worker_params_")
        self._params_seq += 1
        path = os.path.join(self._params_dir, f"params_{self._params_seq}.npz")
        save_pytree(value, path)
        self._call({"op": "set_params", "path": path})

    def submit(self, request) -> int:
        from .serving import EngineClosed, RequestResult

        if self._closed:
            raise EngineClosed("engine is closed")
        if self._dead:
            raise EngineClosed(f"worker {self.worker_id} process is gone")
        try:
            self._call({"op": "submit", "request": request_to_wire(request)})
        except WorkerGone as exc:
            # The router's dispatch loop treats EngineClosed as "try the next
            # replica"; the death itself surfaces from the next step().
            raise EngineClosed(str(exc)) from exc
        self.results[request.request_id] = RequestResult(
            request.request_id, arrival_time=request.arrival_time
        )
        return request.request_id

    def cancel(self, request_id: int) -> bool:
        result = self.results[request_id]  # KeyError for unknown ids, like the engine
        if result.finished:
            return False
        try:
            reply = self._call({"op": "cancel", "request_id": int(request_id)})
        except WorkerGone:
            # Worker died under the cancel: the mirror finishes cancelled
            # locally (partial tokens kept) — nothing can stream anymore.
            result.finished = True
            result.finish_reason = "cancelled"
            result.finish_time = time.perf_counter()
            return True
        # `cancelled: false` means the worker finished it first (a terminal
        # token raced our cancel out): adopt the worker's record verbatim.
        self._apply_finished([reply["result"]])
        return bool(reply["cancelled"])

    def release(self, request_id: int):
        result = self.results[request_id]
        if not result.finished:
            raise ValueError(f"request {request_id} is still in flight")
        if not self._dead and not self._closed:
            try:
                self._call({"op": "release", "request_id": int(request_id)})
            except (WorkerGone, KeyError, ValueError):
                pass
        del self.results[request_id]
        return result

    def step(self) -> List[Tuple[int, List[int]]]:
        if self._closed:
            return []
        reply = self._call({"op": "step"})
        events: List[Tuple[int, List[int]]] = []
        for rid, toks in reply.get("events", ()):
            rid = int(rid)
            toks = [int(t) for t in toks]
            result = self.results.get(rid)
            if result is not None and not result.finished:
                result.tokens.extend(toks)
                if result.first_token_time is None:
                    result.first_token_time = time.perf_counter()
            events.append((rid, toks))
        self._apply_finished(reply.get("finished", ()))
        return events

    def run(self, requests=None) -> Dict[int, np.ndarray]:
        for request in requests or ():
            self.submit(request)
        while self.pending:
            self.step()
        return {rid: np.asarray(r.tokens, np.int32) for rid, r in self.results.items()}

    def drain(self) -> Dict[int, Any]:
        reply = self._call({"op": "drain"}, timeout_s=self.step_timeout_s * 10)
        self._apply_finished(reply.get("finished", ()))
        return self.results

    def warm_inserts(self) -> List[int]:
        return [int(b) for b in self._call({"op": "warm"})["buckets"]]

    def reset_guard(self) -> bool:
        """Zero the worker-side TraceGuard counters (spawned with guard=True):
        benches call this after warmup so the timed window's 0/0 gate is
        exact. Returns whether a guard is armed at all."""
        return bool(self._call({"op": "guard_reset"})["armed"])

    def terminate(self):
        """Hard shutdown for a replica being ejected: kill the worker process
        and reap it WITHOUT the cooperative close RPC (the worker may be the
        reason we are here — hung, or erroring every dispatch). The router's
        eject path calls this so a worker that failed via error replies (its
        transport still alive) can never linger as an orphan next to its
        replacement, holding device memory."""
        self._mark_dead()
        close = getattr(self.transport, "close", None)
        if close is not None:
            close()

    def close(self) -> Dict[int, Any]:
        if self._closed:
            return self.results
        if not self._dead:
            try:
                reply = self._call({"op": "close"})
                self._apply_finished(reply.get("finished", ()))
            except (WorkerGone, RuntimeError):
                pass
        for result in self.results.values():
            if not result.finished:
                result.finished = True
                result.finish_reason = "cancelled"
                result.finish_time = time.perf_counter()
        close = getattr(self.transport, "close", None)
        if close is not None:
            close()
        self._closed = True
        return self.results


def make_subprocess_factory(
    model=None,
    spec: Optional[Dict[str, Any]] = None,
    engine_kwargs: Optional[Dict[str, Any]] = None,
    *,
    workdir: Optional[str] = None,
    warm: bool = True,
    guard: bool = False,
    env: Optional[Dict[str, str]] = None,
    heartbeat_deadline_s: float = DEFAULT_HEARTBEAT_S,
    step_timeout_s: float = 120.0,
    start_timeout_s: float = 600.0,
    stderr_dir: Optional[str] = None,
) -> Callable[[int], SubprocessEngine]:
    """Build a `ReplicaSet.engine_factory` that spawns one warm subprocess
    worker per replica index. When a live `model` is given, its params are
    saved ONCE to `<workdir>/params.npz` and every worker (including restarts)
    loads that exact file — subprocess fleets are token-identical to in-process
    ones by construction. `stderr_dir` (default: the workdir) collects one
    append-mode `worker_<i>.stderr.log` per index, so restarted workers extend
    their predecessor's log instead of interleaving on the controller's tty."""
    if (model is None) == (spec is None):
        raise ValueError("pass exactly one of model= or spec=")
    workdir = workdir or tempfile.mkdtemp(prefix="accelerate_tpu_fleet_")
    os.makedirs(workdir, exist_ok=True)
    if model is not None:
        from .checkpointing import save_pytree

        params_path = os.path.join(workdir, "params.npz")
        save_pytree(model.params, params_path)
        spec = spec_for_model(model, params_path=params_path)
    engine_kwargs = dict(engine_kwargs or {})
    stderr_dir = stderr_dir or workdir

    def factory(index: int) -> SubprocessEngine:
        log_path = os.path.join(stderr_dir, f"worker_{index}.stderr.log")
        stderr = open(log_path, "ab")
        try:
            return SubprocessEngine(
                spec, engine_kwargs, worker_id=index,
                warm=warm, guard=guard,
                heartbeat_deadline_s=heartbeat_deadline_s,
                step_timeout_s=step_timeout_s,
                start_timeout_s=start_timeout_s,
                env=env, stderr=stderr,
            )
        finally:
            stderr.close()  # the child holds its own copy of the fd

    factory.workdir = workdir
    factory.spec = spec
    return factory


if __name__ == "__main__":
    sys.exit(main())

"""Pallas TPU kernels for serving decode: paged single-query attention and the
speculative block-verify variant, with the page-table gather FUSED into the
attention walk.

The XLA paged path (`ops/attention.update_slot_cache`) gathers every slot's
pages back into a logical ``[B, L, h, d]`` K/V buffer before attending — a
full materialized copy of the cache per decode dispatch, which is exactly the
HBM traffic that bounds decode throughput. These kernels never materialize
that buffer: the grid walks each slot's ``page_table`` directly (the table
rides as a SCALAR-PREFETCH operand, so the BlockSpec index maps pick which
pool page to stream into VMEM for each grid step) and folds every page into
the shared online-softmax accumulator (`ops/flash_common.py`). HBM traffic
per dispatch drops from "the whole logical cache, written then read" to "each
live page, read once".

Page-walk contract (mirrors the engine's host-side conventions, paging.py):

  - ``page_table`` entries past a slot's reservation point at the scratch
    page (page 0). Consecutive grid steps that map to the SAME pool page skip
    the re-fetch (Pallas pipelines dedupe identical block indices), so the
    tail of a short slot's walk costs one scratch-page read, not P of them.
  - Masking is positional, not structural: query j of row i attends exactly
    ``cols <= positions[i, j]``, the same per-query mask the XLA oracle
    builds — scratch-page rows sit above every live position and contribute
    exact zeros, so prefix-shared pages, ragged lengths, and freed slots all
    come out token-identical to the gather path.
  - Rows whose every lane is masked normalize against a tiny floor
    (`finalize_softmax`), never NaN — inactive slots ride the same dispatch.

Both kernels are single-program-multiple-rows: grid ``(B, Hkv, pages)``, GQA
handled by grouping the ``G = Hq // Hkv`` query heads of each KV head into the
kernel's row axis (the pool is shared per KV head; repeating it like the XLA
path does would multiply the very HBM traffic this kernel exists to remove).

QUANTIZED pools (``k_scale``/``v_scale`` operands, `ops/quantization.py`):
int8/fp8 pages stream through the same BlockSpec walk at 1 byte/value, their
per-page-per-head scales ride (1, 1) SMEM blocks picked by the SAME
``tbl[b, p]`` index map, and the dequant is one fused multiply on the
VMEM-resident block before the score dot — the cache crosses HBM quantized,
fp32 exists only inside the accumulator. Token-identical to the XLA
dequantize-on-read oracle (`tests/test_quantization.py`).

Interpret mode (`interpret=None` auto-enables off-TPU) runs the same kernels
on CPU for the tier-1 parity sweeps (`tests/test_paged_kernel.py`), the
`ring_attention.py` testing pattern. All accumulation is fp32.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from .flash_common import (
    LANE,
    NEG_INF,
    finalize_softmax,
    init_softmax_state,
    online_softmax_update,
)


def _decode_kernel(
    tbl_ref, q_ref, k_ref, v_ref, *rest,
    scale, page_size, quantized,
):
    """Single-query paged decode: one [G, D] query group per (batch, kv head),
    streaming that row's pages through the online-softmax accumulator.

    Quantized pools (`quantized=True`) thread two extra refs — the page's
    per-head K/V scales ((1, 1) SMEM scalars picked by the same
    ``tbl[b, p]`` index map that streams the page) — and the dequant is one
    fused multiply on the VMEM-resident block: the page crosses HBM at
    int8/fp8 width, fp32 exists only inside the accumulator."""
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, pos_ref, len_ref, o_ref, acc, m_scr, l_scr = rest
    else:
        pos_ref, len_ref, o_ref, acc, m_scr, l_scr = rest

    pi = pl.program_id(2)
    n_pages = pl.num_programs(2)

    @pl.when(pi == 0)
    def _init():
        init_softmax_state(acc, m_scr, l_scr)

    length = len_ref[0, 0]  # row's valid cache length (pos + 1)
    base = pi * page_size

    @pl.when(base < length)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)  # [G, D]
        k = k_ref[0, :, 0, :].astype(jnp.float32)  # [page_size, D]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0, 0]
            v = v * vs_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [G, page_size]
        cols = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols < length, s, NEG_INF)
        online_softmax_update(s, v, acc, m_scr, l_scr)

    @pl.when(pi == n_pages - 1)
    def _finish():
        out, _ = finalize_softmax(acc, m_scr, l_scr)
        o_ref[0, 0] = out.astype(o_ref.dtype)


def _verify_kernel(
    tbl_ref, q_ref, k_ref, v_ref, *rest,
    scale, page_size, s_block, gsize, quantized,
):
    """Block-verify paged attention: the [B, s] multi-token twin. Rows are the
    s*G (query position, GQA group) pairs of one (batch, kv head); query j
    attends ``cols <= positions[b, j]`` — the accepted prefix plus the block
    tokens at or before it, exactly the per-query mask of the XLA verify
    path, so the speculative accept loop sees identical greedy tokens.
    Quantized pools dequant the streamed page in VMEM exactly like
    `_decode_kernel`."""
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, pos_ref, len_ref, o_ref, acc, m_scr, l_scr = rest
    else:
        pos_ref, len_ref, o_ref, acc, m_scr, l_scr = rest

    pi = pl.program_id(2)
    n_pages = pl.num_programs(2)

    @pl.when(pi == 0)
    def _init():
        init_softmax_state(acc, m_scr, l_scr)

    length = len_ref[0, 0]  # max block position + 1: pages past it hold no query's keys
    base = pi * page_size

    @pl.when(base < length)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)  # [s*G, D]
        k = k_ref[0, :, 0, :].astype(jnp.float32)  # [page_size, D]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0, 0]
            v = v * vs_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [s*G, page_size]
        pos = pos_ref[0]  # [s] int32 per-query attend limits
        s3 = s.reshape(s_block, gsize, page_size)
        cols = base + jax.lax.broadcasted_iota(jnp.int32, s3.shape, 2)
        s3 = jnp.where(cols <= pos[:, None, None], s3, NEG_INF)
        online_softmax_update(s3.reshape(s_block * gsize, page_size), v, acc, m_scr, l_scr)

    @pl.when(pi == n_pages - 1)
    def _finish():
        out, _ = finalize_softmax(acc, m_scr, l_scr)
        o_ref[0, 0] = out.astype(o_ref.dtype)


def _paged_call(
    q, k_pool, v_pool, page_table, positions, scale, interpret, kernel_for,
    k_scale=None, v_scale=None,
):
    """Shared wrapper: layout transforms, prefetch grid spec, pallas_call.
    `k_scale`/`v_scale` ([num_pages, Hkv] f32 traced operands, never Python
    scalars — TPU117) switch the kernels into fused-dequant mode."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s, hq, d = q.shape
    n_pages_pool, page_size, hkv, _ = k_pool.shape
    if hq % hkv:
        raise ValueError(f"GQA requires Hq % Hkv == 0, got {hq}, {hkv}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("quantized pools need BOTH k_scale and v_scale (or neither)")
    quantized = k_scale is not None
    if quantized:
        for name, sc in (("k_scale", k_scale), ("v_scale", v_scale)):
            if sc.shape != (n_pages_pool, hkv):
                raise ValueError(
                    f"per-page-per-head {name} must be [num_pages, Hkv] = "
                    f"{(n_pages_pool, hkv)}, got {sc.shape}"
                )
    gsize = hq // hkv
    rows = s * gsize
    pages_per_slot = page_table.shape[-1]

    # [B, s, Hq, D] -> [B, Hkv, s*G, D]: query head h*G+g rides kv head h's
    # walk (the row ordering the kernels' reshape masks assume).
    qt = (
        q.reshape(b, s, hkv, gsize, d)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b, hkv, rows, d)
    )
    table = jnp.clip(jnp.asarray(page_table, jnp.int32), 0, n_pages_pool - 1)
    pos = jnp.asarray(positions, jnp.int32).reshape(b, s)
    # Scalar page-skip bound per row, SMEM-friendly [B, 1].
    lengths = (jnp.max(pos, axis=1, keepdims=True) + 1).astype(jnp.int32)

    kernel = kernel_for(
        scale=float(scale), page_size=page_size, s_block=s, gsize=gsize,
        quantized=quantized,
    )
    in_specs = [
        pl.BlockSpec((1, 1, rows, d), lambda bi, hi, pi, tbl: (bi, hi, 0, 0)),  # q
        # THE fused page-table gather: grid step (b, h, p) streams pool page
        # table[b, p] for kv head h. Table entries past a slot's reservation
        # are the scratch page — identical consecutive block indices, which
        # the Pallas pipeline fetches once, not P times.
        pl.BlockSpec((1, page_size, 1, d), lambda bi, hi, pi, tbl: (tbl[bi, pi], 0, hi, 0)),
        pl.BlockSpec((1, page_size, 1, d), lambda bi, hi, pi, tbl: (tbl[bi, pi], 0, hi, 0)),
    ]
    operands = [qt, k_pool, v_pool]
    if quantized:
        # The streamed page's per-head scales ride the SAME tbl[b, p] walk as
        # the page itself — the dequant is fused, not a second gather.
        scale_spec = pl.BlockSpec(
            (1, 1), lambda bi, hi, pi, tbl: (tbl[bi, pi], hi), memory_space=pltpu.SMEM
        )
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    in_specs += [
        pl.BlockSpec((1, s), lambda bi, hi, pi, tbl: (bi, 0)),  # per-query limits
        pl.BlockSpec((1, 1), lambda bi, hi, pi, tbl: (bi, 0), memory_space=pltpu.SMEM),
    ]
    operands += [pos, lengths]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, hkv, pages_per_slot),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rows, d), lambda bi, hi, pi, tbl: (bi, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rows, d), jnp.float32),
            pltpu.VMEM((rows, LANE), jnp.float32),
            pltpu.VMEM((rows, LANE), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, hkv, rows, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(table, *operands)
    return (
        out.reshape(b, hkv, s, gsize, d).transpose(0, 2, 1, 3, 4).reshape(b, s, hq, d)
    )


def _auto_interpret(interpret: Optional[bool]) -> bool:
    if interpret is None:
        return jax.default_backend() != "tpu"
    return bool(interpret)


def paged_decode_attention(
    q, k_pool, v_pool, page_table, positions, *, scale=None, interpret=None,
    k_scale=None, v_scale=None,
):
    """Single-query paged decode attention over a pool-resident KV cache.

    Args:
        q: [B, 1, Hq, D] this step's queries (one per slot).
        k_pool / v_pool: [num_pages, page_size, Hkv, D] page pools, ALREADY
            holding this dispatch's K/V writes (the caller scatters first —
            query i attends its own new row via ``cols <= positions[i]``).
        page_table: [B, pages_per_slot] int32 pool-page ids (traced operand);
            unused entries point at the scratch page.
        positions: [B, 1] (or [B]) int32 — row i attends ``cols <= positions[i]``.
        scale: defaults to 1/sqrt(D).
        interpret: None = auto (Pallas interpreter off-TPU, compiled on TPU).
        k_scale / v_scale: [num_pages, Hkv] f32 per-page-per-head scale pools
            for int8/fp8 page pools (traced operands, never Python scalars —
            TPU117); the dequant fuses into the page-streaming loop. Both or
            neither.

    Returns [B, 1, Hq, D], token-identical to the XLA gather oracle
    (dequantize-on-read for quantized pools).
    """
    b = q.shape[0]
    if q.ndim != 4 or q.shape[1] != 1:
        raise ValueError(f"paged_decode_attention takes [B, 1, Hq, D] queries, got {q.shape}")
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    pos = jnp.asarray(positions, jnp.int32).reshape(b, 1)

    def kernel_for(scale, page_size, s_block, gsize, quantized):
        return functools.partial(
            _decode_kernel, scale=scale, page_size=page_size, quantized=quantized
        )

    return _paged_call(
        q, k_pool, v_pool, page_table, pos, scale, _auto_interpret(interpret), kernel_for,
        k_scale=k_scale, v_scale=v_scale,
    )


def paged_verify_attention(
    q, k_pool, v_pool, page_table, positions, *, scale=None, interpret=None,
    k_scale=None, v_scale=None,
):
    """Block-verify paged attention: the [B, s] multi-token variant used by
    speculative decoding's verify step (s = draft_tokens + 1).

    Args:
        q: [B, s, Hq, D] the block's queries.
        k_pool / v_pool / page_table: as `paged_decode_attention` — the pools
            already hold the block's K/V writes.
        positions: [B, s] int32 — query j of row i attends
            ``cols <= positions[i, j]`` (its accepted prefix plus the block
            tokens at or before it, all written by this same dispatch).
        k_scale / v_scale: as `paged_decode_attention` (quantized pools).

    Returns [B, s, Hq, D].
    """
    if q.ndim != 4:
        raise ValueError(f"paged_verify_attention takes [B, s, Hq, D] queries, got {q.shape}")
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])

    def kernel_for(scale, page_size, s_block, gsize, quantized):
        return functools.partial(
            _verify_kernel, scale=scale, page_size=page_size, s_block=s_block,
            gsize=gsize, quantized=quantized,
        )

    return _paged_call(
        q, k_pool, v_pool, page_table, positions, scale, _auto_interpret(interpret), kernel_for,
        k_scale=k_scale, v_scale=v_scale,
    )

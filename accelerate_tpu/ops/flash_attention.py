"""Pallas TPU flash attention (forward + backward), the hot-op kernel behind
`ops.attention.dot_product_attention`.

FlashAttention-2 style: online-softmax over KV blocks in the forward (O(S) memory, no
[S,S] materialization), saved logsumexp + recompute in the backward. Layout inside the
kernels is [B*H, S, D] with a 3-D grid; the innermost grid axis streams KV (forward,
dq) or Q (dk/dv) blocks through VMEM scratch accumulators, so HBM traffic per block is
one read of each operand tile — the MXU sees back-to-back (Bq×D)@(D×Bk) matmuls.

Interpret mode (`interpret=True`) runs the same kernels on CPU for tests; real runs
compile for TPU. All accumulation is fp32 regardless of input dtype.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

# The online-softmax accumulator math is shared with the serving paged
# kernels (ops/paged_attention.py) — one implementation, one parity contract.
from .flash_common import (
    LANE,
    NEG_INF,
    finalize_softmax,
    init_softmax_state,
    online_softmax_update,
)


def _causal_block_visible(iq, ik, block_q: int, block_k: int, offset: int) -> "jnp.ndarray":
    """Whether KV block ik has any unmasked position for Q block iq.

    `offset = Skv - Sq` gives bottom-right alignment (query i attends keys
    j <= i + offset), matching `ops.attention.make_causal_mask`."""
    q_last = (iq + 1) * block_q - 1
    k_first = ik * block_k
    return k_first <= q_last + offset


def _block_mask(iq, ik, block_q: int, block_k: int, offset: int):
    """[Bq, Bk] bottom-right-aligned causal mask for the (iq, ik) tile (True = attend)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + iq * block_q
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + ik * block_k
    return cols <= rows + offset


# ---------------------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr, *, scale, causal, block_q, block_k, offset):
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    ik = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        init_softmax_state(acc, m_scr, l_scr)

    run = _causal_block_visible(iq, ik, block_q, block_k, offset) if causal else True

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)  # [Bq, D]
        k = k_ref[0].astype(jnp.float32)  # [Bk, D]
        v = v_ref[0].astype(jnp.float32)  # [Bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [Bq, Bk]
        if causal:
            s = jnp.where(_block_mask(iq, ik, block_q, block_k, offset), s, NEG_INF)
        online_softmax_update(s, v, acc, m_scr, l_scr)

    @pl.when(ik == n_k - 1)
    def _finish():
        out, lse = finalize_softmax(acc, m_scr, l_scr)
        o_ref[0] = out.astype(o_ref.dtype)
        # lse carries a broadcast 128-lane trailing dim: Mosaic requires the last
        # two block dims to be (8k, 128k) or match the array, so a [BH, S] layout
        # cannot be blocked (1, block_q). Same workaround as jax's in-tree TPU
        # flash kernel (l/m stored [B, H, S, MIN_BLOCK_SIZE]).
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref[0].shape)


def _fwd_call(q, k, v, scale, causal, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape
    Sk = k.shape[1]
    grid = (BH, S // block_q, Sk // block_k)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k, offset=Sk - S
    )
    o, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, LANE), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, LANE), lambda b, i, j: (b, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------------- backward
def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, block_q, block_k, offset):
    from jax.experimental import pallas as pl

    ik = pl.program_id(1)
    iq = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = _causal_block_visible(iq, ik, block_q, block_k, offset) if causal else True

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)  # [Bq, D]
        k = k_ref[0].astype(jnp.float32)  # [Bk, D]
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)  # [Bq, D]
        lse = lse_ref[0][:, 0:1]  # [Bq, 1] (lane dim is broadcast)
        delta = delta_ref[0][:, 0:1]  # [Bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = jnp.where(_block_mask(iq, ik, block_q, block_k, offset), s, NEG_INF)
        p = jnp.exp(s - lse)  # [Bq, Bk]
        # dv += p^T @ do
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        # dp = do @ v^T ; ds = p * (dp - delta) * scale
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale  # [Bq, Bk]
        # dk += ds^T @ q
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(iq == n_q - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc, *, scale, causal, block_q, block_k, offset):
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    ik = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = _causal_block_visible(iq, ik, block_q, block_k, offset) if causal else True

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0:1]  # [Bq, 1] (lane dim is broadcast)
        delta = delta_ref[0][:, 0:1]  # [Bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = jnp.where(_block_mask(iq, ik, block_q, block_k, offset), s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale  # [Bq, Bk]
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ik == n_k - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_call(q, k, v, o, lse, do, scale, causal, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, S, D = q.shape
    Sk = k.shape[1]
    # [BH, S, LANE] — broadcast lane dim for the same Mosaic tiling reason as lse.
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)[..., None], (BH, S, LANE)
    )

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k, offset=Sk - S
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((BH, Sk, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Sk, D), q.dtype),
        ),
        grid=(BH, Sk // block_k, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),  # q
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),  # k
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),  # v
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),  # do
            pl.BlockSpec((1, block_q, LANE), lambda b, j, i: (b, i, 0)),  # lse
            pl.BlockSpec((1, block_q, LANE), lambda b, j, i: (b, i, 0)),  # delta
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k, offset=Sk - S
    )
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        grid=(BH, S // block_q, Sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, LANE), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, LANE), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------------------ public API
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhsd(q, k, v, scale, causal, block_q, block_k, interpret):
    o, _ = _fwd_call(q, k, v, scale, causal, block_q, block_k, interpret)
    return o


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    o, lse = _fwd_call(q, k, v, scale, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _bwd_call(q, k, v, o, lse, do, scale, causal, block_q, block_k, interpret)
    return dq, dk, dv


_flash_bhsd.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
):
    """Flash attention on [B, S, H, D] (BSHD) inputs; supports GQA by KV-head repeat.

    Requires Sq % block_q == 0 and Skv % block_k == 0 (callers pad or fall back to the
    XLA path via `dot_product_attention`). `interpret=None` auto-enables the Pallas
    interpreter off-TPU (CPU tests) and compiles on TPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    block_q = min(block_q, sq)
    block_k = min(block_k, skv)
    if sq % block_q or skv % block_k:
        raise ValueError(f"Sequence lengths ({sq}, {skv}) must divide blocks ({block_q}, {block_k})")
    if causal and sq > skv:
        # Bottom-right alignment would leave the first (sq - skv) query rows with no
        # visible keys — a degenerate mask the XLA path also can't represent sensibly.
        raise ValueError(f"causal flash attention requires Sq <= Skv, got ({sq}, {skv})")
    if hq != hkv:
        if hq % hkv:
            raise ValueError(f"GQA requires Hq % Hkv == 0, got {hq}, {hkv}")
        reps = hq // hkv
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
    # BSHD -> [B*H, S, D]
    qt = q.transpose(0, 2, 1, 3).reshape(b * hq, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hq, skv, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hq, skv, d)
    o = _flash_bhsd(qt, kt, vt, float(scale), bool(causal), block_q, block_k, interpret)
    return o.reshape(b, hq, sq, d).transpose(0, 2, 1, 3)

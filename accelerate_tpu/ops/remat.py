"""Per-layer activation checkpointing (rematerialization).

The reference applies torch's `checkpoint_wrapper` to each FSDP-wrapped block
(reference accelerator.py:1460-1474). The TPU-native equivalent is flax
`nn.remat` (jax.checkpoint) around each transformer layer: the backward pass
recomputes one layer's internals at a time, so peak memory holds only layer
-boundary activations instead of every intermediate.

Models cannot be rewrapped after construction (flax modules bind structure at
trace time), so the seam is a trace-time contextvar scope — the exact pattern
`activation_sharding_scope` uses: model families route their layer classes
through `maybe_remat`, which is the identity unless a `remat_scope` is active.
`PreparedModel` enters the scope when
`FullyShardedDataParallelPlugin.activation_checkpointing` is set (or a
`CompilationConfig.remat_policy` asks for it), so the knob acts on any in-tree
model with zero per-model configuration.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Optional

# Active remat policy name, or None (no remat). Set at trace time.
_REMAT_POLICY: contextvars.ContextVar = contextvars.ContextVar("remat_policy", default=None)

#: CompilationConfig.remat_policy / plugin values -> jax.checkpoint policies.
#: "full" saves nothing (classic activation checkpointing: only layer inputs
#: survive); "dots" keeps MXU outputs and recomputes the elementwise chain —
#: cheaper recompute, smaller saving.
POLICY_NAMES = ("full", "nothing_saveable", "dots", "dots_saveable", "dots_with_no_batch_dims")


def _resolve_policy(name: str):
    import jax

    cp = jax.checkpoint_policies
    return {
        "full": None,  # jax.checkpoint default: save nothing
        "nothing_saveable": cp.nothing_saveable,
        "dots": cp.dots_saveable,
        "dots_saveable": cp.dots_saveable,
        "dots_with_no_batch_dims": cp.dots_with_no_batch_dims_saveable,
    }[name]


@contextlib.contextmanager
def remat_scope(policy: Optional[str] = "full"):
    """Enable per-layer remat for models traced inside this scope.

    `policy` is one of POLICY_NAMES (None disables — convenient for callers
    threading a config value straight through)."""
    if policy is not None and policy not in POLICY_NAMES:
        raise ValueError(f"remat policy must be one of {POLICY_NAMES}, got {policy!r}")
    token = _REMAT_POLICY.set(policy)
    try:
        yield
    finally:
        _REMAT_POLICY.reset(token)


def active_remat_policy() -> Optional[str]:
    return _REMAT_POLICY.get()


def maybe_remat(module_cls):
    """Layer-class wrapper used by every in-tree model at its stack loop:
    `Layer = maybe_remat(LlamaLayer)` — identity unless a remat_scope is active.

    Called at trace time (inside @nn.compact), so the same model object honors
    whatever scope each forward runs under; lifted `nn.remat` preserves the
    parameter structure, so checkpoints and shardings are unaffected.
    """
    name = _REMAT_POLICY.get()
    if name is None:
        return module_cls
    import flax.linen as nn

    policy = _resolve_policy(name)
    if policy is None:
        return nn.remat(module_cls)
    return nn.remat(module_cls, policy=policy)

"""Attention ops: the single seam all in-tree models call.

`dot_product_attention` dispatches to the best available implementation:
  - XLA einsum-softmax (always; XLA fuses the elementwise chain into the matmuls and
    tiles onto the MXU),
  - a Pallas flash-attention kernel on TPU for long sequences (ops/flash_attention.py),
  - ring attention across the "seq" mesh axis (parallel/ring_attention.py) when
    activations are sequence-sharded.

`slot_cache_attention` is the SERVING twin: the fused cache-write + attend seam
for slot-batched decode, with its own `attention_impl` dispatch — the XLA
gather oracle, or the Pallas paged-decode / block-verify kernels
(ops/paged_attention.py) that walk the page table without materializing the
gathered cache.

Shapes follow the [batch, seq, heads, head_dim] convention (BSHD) throughout.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

# Trace-time record of the implementation the last dispatch chose ("xla" | "flash"
# | "ring" | "allgather" | "pallas_paged"). Benchmarks read it to PROVE the kernel
# they claim to measure actually ran (round-2 verdict weak #5: flash was dead code
# on every benchmarked path and nothing would have noticed).
LAST_DISPATCH: Optional[str] = None

#: The serving-decode attention implementations `slot_cache_attention` accepts.
SLOT_ATTENTION_IMPLS = ("xla", "pallas_paged")

# Once-per-reason guard for the SP-bypass warning (see below).
_SP_BYPASS_WARNED: set = set()


def make_causal_mask(q_len: int, kv_len: int, dtype=None):
    import jax.numpy as jnp

    i = jnp.arange(q_len)[:, None]
    j = jnp.arange(kv_len)[None, :]
    return (j <= i + (kv_len - q_len)).astype(dtype or jnp.bool_)


def update_decode_cache(module, k, v, cache_length: int, pad_mask=None):
    """The KV-cache write path shared by every decoder family (llama/gptj/
    gpt_neox/opt): persist K/V in the flax "cache" collection with static capacity
    `cache_length`. ONE write path covers prefill (s = prompt_len at index 0) and
    decode (s = 1 at the running index); the returned mask is causal over absolute
    positions and masks unwritten slots.

    `pad_mask` ([B, s] 1/0, usually the prompt's attention_mask at prefill):
    left-padded batch prompts persist their pad slots in the cache collection, so
    every LATER decode step keeps masking them without re-threading the mask —
    ragged prompts batch-generate like HF's left-pad convention.

    Call from inside the attention module's `__call__` (needs `module.variable`).
    Returns `(k_full, v_full, decode_mask)` — feed to
    `dot_product_attention(..., mask=decode_mask, causal=False)`.
    """
    import jax
    import jax.numpy as jnp

    b, s, h, d = k.shape
    L = cache_length
    cached_k = module.variable("cache", "cached_key", jnp.zeros, (b, L, h, d), k.dtype)
    cached_v = module.variable("cache", "cached_value", jnp.zeros, (b, L, h, d), v.dtype)
    cache_index = module.variable("cache", "cache_index", lambda: jnp.zeros((), jnp.int32))
    cur = cache_index.value
    cached_k.value = jax.lax.dynamic_update_slice(cached_k.value, k, (0, cur, 0, 0))
    cached_v.value = jax.lax.dynamic_update_slice(cached_v.value, v, (0, cur, 0, 0))
    cache_index.value = cur + s
    # causal over absolute positions: query row i (absolute cur+i) sees cache
    # slots j <= cur+i and only written slots (j < cur+s).
    rows = cur + jnp.arange(s)[:, None]
    cols = jnp.arange(L)[None, :]
    attend = (cols <= rows) & (cols < cur + s)
    decode_mask = jnp.broadcast_to(attend[None, None, :, :], (b, 1, s, L))
    valid = None
    if pad_mask is not None:
        if pad_mask.ndim != 2:
            # Pre-pad-support this arg was silently IGNORED on the cached path
            # (4D callers got no masking at all); be loud rather than wrong.
            raise ValueError(
                f"the decode-cache path persists a [B, S] key-padding mask; got a "
                f"rank-{pad_mask.ndim} mask. Pass attention_mask as [batch, seq] "
                f"(1 = real token), the HF padding-mask shape."
            )
        pad_var = module.variable("cache", "pad_mask", jnp.ones, (b, L), bool)
        pad_var.value = jax.lax.dynamic_update_slice(
            pad_var.value, pad_mask.astype(bool), (0, cur)
        )
        valid = pad_var.value
    elif module.has_variable("cache", "pad_mask"):
        valid = module.get_variable("cache", "pad_mask")
    if valid is not None:
        decode_mask = decode_mask & valid[:, None, None, :]
    return cached_k.value, cached_v.value, decode_mask


def update_slot_cache(
    module, k, v, cache_length: int, positions, page_table=None, page_size: int = 0,
    num_pages: int = 0, kv_cache_dtype: str = "bf16",
):
    """Per-ROW cache writes for slot-based continuous batching (serving.py):
    every batch row is an independent request slot with its OWN running position,
    so the new K/V of row i lands at `positions[i]` instead of a shared
    scalar `cache_index`. The scatter (`.at[rows, pos].set`) is the per-slot twin
    of `update_decode_cache`'s `dynamic_update_slice`; the returned mask lets each
    query attend exactly to its written prefix `cols <= its position` — stale K/V
    from a previous slot occupant above the current position is never visible,
    which is what makes slot reuse sound without ever clearing the cache.

    Decode (s == 1) and speculative VERIFY BLOCKS (s == draft_tokens + 1,
    positions[i] = pos_i + [0..s)): the s > 1 path writes every block token's
    K/V at its own position and returns a per-query causal mask, so one
    dispatch scores all s positions — query j of row i attends
    `cols <= positions[i, j]`, i.e. the accepted prefix plus the block tokens
    at or before it, every one of which this same dispatch just wrote. Rejected
    draft positions need no rollback: the engine simply does not advance the
    slot's position past the accepted prefix, the mask keeps the stale K/V
    invisible, and the next dispatch overwrites it before anything attends it.
    Positions past the cache capacity (a draft window overrunning a finishing
    request) clip to the last cell, which is never attended — the final token
    of a capacity-exact request is emitted without ever being dispatched.

    Slot PREFILL goes through the ordinary `update_decode_cache` path on a
    batch-1 cache that the serving engine scatters into the slot row
    (utils/operations.tree_scatter_rows) — or, paged, into the slot's pool
    pages (tree_scatter_pages) — so one attention code path covers both
    programs.

    PAGED mode (`page_size > 0`): the cache collection holds one POOL of
    `num_pages` fixed-size pages ([num_pages, page_size, h, d]) instead of one
    `cache_length` row per slot, and `page_table` ([B, pages_per_slot] int32, a
    traced operand — admissions never recompile) maps each slot's logical
    positions onto pool pages. Row i's new K/V lands at
    `pool[page_table[i, pos_i // page_size], pos_i % page_size]`; the read
    gathers the row's pages back into logical order and applies the same
    `cols <= pos` mask, so decode is token-identical to the contiguous layout.
    Page 0 is the engine's reserved scratch page: the host points inactive
    slots' table rows at it, so their (discarded) writes can never land in a
    page owned by a live request or a shared read-only prefix page.

    QUANTIZED pool (`kv_cache_dtype` "int8" / "fp8_e4m3", paged only): pages
    are stored in the quantized dtype with per-page-per-head scales in
    parallel `key_scale`/`value_scale` pool arrays ([num_pages, h] f32, same
    cache collection — traced operands, never Python scalars), maintained by
    `ops.quantization.quantized_pool_write` (offset-0 scale reset, scatter-max
    growth, in-dispatch requant of touched pages). This XLA read path
    dequantizes the gathered pages — the parity oracle the fused-dequant
    Pallas kernels are pinned against.

    Args:
        positions: [B, s] int32 — each token's absolute write/attend position.
        page_table: [B, pages_per_slot] int32 pool-page ids per slot (paged only).
        page_size / num_pages: static pool geometry (paged only).
        kv_cache_dtype: "bf16" (unquantized, the model compute dtype) |
            "int8" | "fp8_e4m3" — pool storage dtype (paged only).

    Returns `(k_full, v_full, decode_mask)` like `update_decode_cache`.
    """
    import jax.numpy as jnp

    b, s, h, d = k.shape
    if positions.shape != (b, s):
        raise ValueError(
            f"update_slot_cache needs per-token positions [B, S] = {(b, s)}, "
            f"got {positions.shape}; slot prefill goes through "
            "update_decode_cache on a batch-1 cache (tree_scatter_rows)"
        )
    if page_size:
        pool_k, pool_v, pos, table, scales = _write_slot_pool(
            module, k, v, positions, page_table, page_size, num_pages,
            kv_cache_dtype=kv_cache_dtype,
        )
        pages_per_slot = table.shape[-1]
        L = pages_per_slot * page_size
        # Logical-order read: [B, P, ps, h, d] -> [B, P*ps, h, d]. Same masked
        # attention as the contiguous layout — pool order never leaks. This
        # materialized gather is the HBM cost `slot_cache_attention`'s
        # "pallas_paged" path exists to remove; it stays as the parity oracle.
        k_pages = jnp.take(pool_k, table, axis=0)  # [B, P, ps, h, d]
        v_pages = jnp.take(pool_v, table, axis=0)
        if scales is not None:
            # Dequantize-on-read: scale[table] broadcasts per page per head.
            from .quantization import dequantize_kv_pages

            k_scale, v_scale = scales
            k_pages = dequantize_kv_pages(k_pages, jnp.take(k_scale, table, axis=0), k.dtype)
            v_pages = dequantize_kv_pages(v_pages, jnp.take(v_scale, table, axis=0), v.dtype)
        k_full = k_pages.reshape(b, L, h, d)
        v_full = v_pages.reshape(b, L, h, d)
        cols = jnp.arange(L)[None, None, :]
        decode_mask = (cols <= pos[:, :, None])[:, None, :, :]  # [B, 1, s, L]
        return k_full, v_full, decode_mask
    if kv_cache_dtype != "bf16":
        raise ValueError(
            f"kv_cache_dtype={kv_cache_dtype!r} requires the paged slot cache "
            "(page_size > 0); the contiguous layout has no page-scale pool"
        )
    L = cache_length
    cached_k = module.variable("cache", "cached_key", jnp.zeros, (b, L, h, d), k.dtype)
    cached_v = module.variable("cache", "cached_value", jnp.zeros, (b, L, h, d), v.dtype)
    pos = jnp.clip(positions, 0, L - 1).astype(jnp.int32)  # [B, s]
    rows = jnp.arange(b)[:, None]
    cached_k.value = cached_k.value.at[rows, pos].set(k)
    cached_v.value = cached_v.value.at[rows, pos].set(v)
    cols = jnp.arange(L)[None, None, :]
    decode_mask = (cols <= pos[:, :, None])[:, None, :, :]  # [B, 1, s, L]
    return cached_k.value, cached_v.value, decode_mask


def _write_slot_pool(
    module, k, v, positions, page_table, page_size: int, num_pages: int,
    kv_cache_dtype: str = "bf16",
):
    """The paged slot cache's WRITE half: scatter this dispatch's [B, s] K/V
    into the page pool through the slot page tables, and return the updated
    pools plus the clipped positions/table and (quantized pools only) the
    `(key_scale, value_scale)` parallel scale pools. Shared by the XLA gather
    path (`update_slot_cache`) and the fused kernel path
    (`slot_cache_attention`) so the two implementations can never disagree
    about where K/V lives — or what scale it was stored under."""
    import jax.numpy as jnp

    from .quantization import kv_quant_spec, quantized_pool_write

    if page_table is None:
        raise ValueError("paged slot cache needs a [B, pages_per_slot] page_table operand")
    b, s, h, d = k.shape
    pages_per_slot = page_table.shape[-1]
    L = pages_per_slot * page_size
    spec = kv_quant_spec(kv_cache_dtype)
    pool_dtype = k.dtype if spec is None else spec[0]
    pool_k = module.variable(
        "cache", "cached_key", jnp.zeros, (num_pages, page_size, h, d), pool_dtype
    )
    pool_v = module.variable(
        "cache", "cached_value", jnp.zeros, (num_pages, page_size, h, d), pool_dtype
    )
    pos = jnp.clip(positions, 0, L - 1).astype(jnp.int32)  # [B, s]
    table = jnp.asarray(page_table, jnp.int32)
    page_slot = jnp.clip(pos // page_size, 0, pages_per_slot - 1)
    pid = jnp.take_along_axis(table, page_slot, axis=1)  # [B, s]
    off = pos % page_size
    if spec is None:
        pool_k.value = pool_k.value.at[pid, off].set(k)
        pool_v.value = pool_v.value.at[pid, off].set(v)
        return pool_k.value, pool_v.value, pos, table, None
    k_scale = module.variable("cache", "key_scale", jnp.zeros, (num_pages, h), jnp.float32)
    v_scale = module.variable("cache", "value_scale", jnp.zeros, (num_pages, h), jnp.float32)
    pool_k.value, k_scale.value = quantized_pool_write(
        pool_k.value, k_scale.value, k, pid, off, spec
    )
    pool_v.value, v_scale.value = quantized_pool_write(
        pool_v.value, v_scale.value, v, pid, off, spec
    )
    return pool_k.value, pool_v.value, pos, table, (k_scale.value, v_scale.value)


def _tp_paged_attention(fn, q, pool_k, pool_v, table, positions, k_scale, v_scale, mesh):
    """`shard_map` the fused page-walk kernels over the "model" axis: each
    device runs the kernel on its OWN KV-head shard of the pool. `pallas_call`
    has no GSPMD partitioning rule, so without the manual map the compiler
    would all-gather the whole pool to every chip per dispatch — exactly the
    HBM/ICI traffic the kernel exists to remove. GQA grouping survives the
    split because heads shard in contiguous chunks: device i holds query
    heads [i*Hq/tp, (i+1)*Hq/tp) and their kv heads [i*Hkv/tp, (i+1)*Hkv/tp),
    so every local query head's kv head is local too. Page tables, positions
    and the output's batch dims stay replicated traced operands."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.sharding import compat_shard_map

    head = P(None, None, "model", None)  # q/pools: [.., heads, head_dim]
    repl = P(None, None)  # page tables / positions: replicated operands

    if k_scale is not None:
        def inner(q_, pk, pv, tbl, pos_, ks, vs):
            return fn(q_, pk, pv, tbl, pos_, k_scale=ks, v_scale=vs)

        in_specs = (head, head, head, repl, repl, P(None, "model"), P(None, "model"))
        args = (q, pool_k, pool_v, table, positions, k_scale, v_scale)
    else:
        def inner(q_, pk, pv, tbl, pos_):
            return fn(q_, pk, pv, tbl, pos_)

        in_specs = (head, head, head, repl, repl)
        args = (q, pool_k, pool_v, table, positions)
    # Replication checking off: pallas_call can't annotate its outputs (the
    # same dispensation ring_attention's flash path uses); numerics are
    # covered by the tp-parity pins.
    wrapped = compat_shard_map(
        inner, mesh=mesh, in_specs=in_specs, out_specs=head, check_vma=False
    )
    return wrapped(*args)


def slot_cache_attention(
    module, q, k, v, cache_length: int, positions, page_table=None,
    page_size: int = 0, num_pages: int = 0, attention_impl: str = "xla",
    kv_cache_dtype: str = "bf16", mesh=None,
):
    """Write this dispatch's K/V into the slot cache AND attend — the fused
    serving-decode seam every slot-cache model family calls (llama, gpt_neox).
    One function covers decode steps (s == 1) and speculative verify blocks
    (s == draft_tokens + 1); `attention_impl` picks the read-side engine:

      - ``"xla"`` (default, and the only option for the contiguous layout):
        `update_slot_cache`'s gather-then-mask read + `dot_product_attention`.
        Paged mode pays a full materialized copy of the logical cache per
        dispatch — this path is the PARITY ORACLE the kernels are pinned
        against, not the serving hot path.
      - ``"pallas_paged"`` (paged mode only): the pool write plus the
        `ops/paged_attention` kernels, which walk each slot's page table
        directly and never materialize the gathered cache. Greedy decode is
        token-identical to the oracle (`tests/test_paged_kernel.py`).

    `kv_cache_dtype` "int8"/"fp8_e4m3" stores the pool quantized with
    per-page-per-head scale pools (see `update_slot_cache`); the kernels
    receive the scale pools as operands and fuse the dequant into the
    page-streaming loop, so quantized decode moves int8/fp8 bytes.

    `mesh` (a 1-axis ("model",) Mesh, threaded from the model config's
    `decode_tp_mesh` by a tensor-parallel `ContinuousBatcher(tp=N)`) makes
    the kernel path `shard_map` over the KV-head grid so each device walks
    only its own pool shard; the XLA paths ignore it — GSPMD partitions them
    automatically from the sharded pool/param operands.

    Args and cache semantics match `update_slot_cache`; returns the attention
    output [B, s, Hq, D]."""
    global LAST_DISPATCH
    if attention_impl not in SLOT_ATTENTION_IMPLS:
        raise ValueError(
            f"unknown attention_impl {attention_impl!r}; expected one of {SLOT_ATTENTION_IMPLS}"
        )
    if attention_impl == "pallas_paged":
        if not page_size:
            raise ValueError(
                "attention_impl='pallas_paged' requires the paged slot cache "
                "(page_size > 0); the contiguous layout has no page table to walk"
            )
        from .paged_attention import paged_decode_attention, paged_verify_attention

        pool_k, pool_v, pos, table, scales = _write_slot_pool(
            module, k, v, positions, page_table, page_size, num_pages,
            kv_cache_dtype=kv_cache_dtype,
        )
        k_scale, v_scale = scales if scales is not None else (None, None)
        LAST_DISPATCH = "pallas_paged"
        fn = paged_decode_attention if q.shape[1] == 1 else paged_verify_attention
        if mesh is not None and mesh.shape.get("model", 1) > 1:
            return _tp_paged_attention(
                fn, q, pool_k, pool_v, table, pos, k_scale, v_scale, mesh
            )
        return fn(q, pool_k, pool_v, table, pos, k_scale=k_scale, v_scale=v_scale)
    k_all, v_all, decode_mask = update_slot_cache(
        module, k, v, cache_length, positions,
        page_table=page_table, page_size=page_size, num_pages=num_pages,
        kv_cache_dtype=kv_cache_dtype,
    )
    return dot_product_attention(q, k_all, v_all, mask=decode_mask, causal=False)


def _auto_sequence_parallel(batch: int, seq_len: int):
    """(mesh, mode) when an already-built mesh has a real "seq" axis and the shapes
    divide cleanly — models then get ring attention with zero code changes. None
    otherwise (no Accelerator yet, module.init's batch-1 trace, tiny eval batches).

    Deliberately side-effect free: inspects the Borg storage directly (constructing
    AcceleratorState() would *initialize* it) and never builds the mesh lazily — a
    forward pass must not create global state or raise mesh-shape errors."""
    from ..state import AcceleratorState

    shared = AcceleratorState._shared_state
    if not shared:
        return None
    mesh = shared.get("_mesh")
    if mesh is None:
        return None
    seq_size = mesh.shape.get("seq", 1)
    batch_size_div = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
    if seq_size <= 1 or seq_len % seq_size != 0 or batch % batch_size_div != 0:
        return None
    mode = "ring"
    sp_plugin = shared.get("sequence_parallel_plugin")
    if sp_plugin is not None:
        mode = sp_plugin.mode
    return mesh, mode


def dot_product_attention(
    q,
    k,
    v,
    mask=None,
    *,
    bias=None,
    causal: bool = False,
    scale: Optional[float] = None,
    implementation: Optional[str] = None,
    segment_ids=None,
):
    """Multi-head (optionally grouped-query) scaled dot-product attention.

    Args:
        q: [B, Sq, Hq, D]
        k/v: [B, Skv, Hkv, D] with Hq % Hkv == 0 (GQA broadcast)
        mask: optional [B, 1|Hq, Sq, Skv] or [B, Skv] boolean; True = attend.
        bias: optional additive [1|B, Hq, Sq, Skv] score bias (T5-style relative
            positions), applied after scaling and before masking. Bias forces the
            XLA path — the flash/ring kernels don't thread it.
        causal: apply a causal mask.
        scale: defaults to 1/sqrt(D).
        implementation: force "xla" (default) — the seam where flash/ring kernels hook in.
        segment_ids: optional [B, S] int ids for packed sequences (requires
            Sq == Skv); attention is restricted to equal ids. Unlike `mask`, this
            RIDES the sequence-parallel dispatch — the ring rotates the id blocks
            — so packed long-context batches still run distributed.
    """
    import jax.numpy as jnp

    if implementation is None:
        # Benchmark/debug override (bench.py --attention): force one backend for
        # every model-internal call without touching model code. "xla" also
        # bypasses the sequence-parallel auto-dispatch (it requires an
        # unconstrained call), so A/B runs compare exactly the two kernels.
        import os

        forced = os.environ.get("ACCELERATE_TPU_ATTENTION_IMPL")
        if forced in ("xla", "flash"):
            implementation = forced

    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    if hq % hkv != 0:
        raise ValueError(f"GQA requires query heads ({hq}) divisible by kv heads ({hkv})")

    if segment_ids is not None and sq != skv:
        raise ValueError(f"segment_ids requires Sq == Skv (self-attention packing), got ({sq}, {skv})")

    # Sequence-parallel dispatch happens BEFORE GQA expansion so the ring rotates the
    # small hkv-sized K/V blocks (expansion is done per-block inside the ring).
    global LAST_DISPATCH
    if implementation is None and sq == skv:
        impl = _auto_sequence_parallel(b, sq)
        if impl is not None and (mask is not None or bias is not None):
            # A seq-parallel mesh is ACTIVE but a dense mask/bias can't ride the
            # ring (only segment_ids and causal do) — the call silently falling
            # back to replicated XLA attention was round-4 verdict weak #4: at
            # the lengths SP exists for, that is an O(S^2) memory surprise.
            # Loud, but ONCE per blocking reason per process: a 24-layer T5
            # passes bias= on every layer and would otherwise warn ~72x per
            # compilation (and per call in eager eval).
            global _SP_BYPASS_WARNED
            reason = "mask" if mask is not None else "bias"
            if reason not in _SP_BYPASS_WARNED:
                _SP_BYPASS_WARNED.add(reason)
                from ..logging import get_logger

                advice = (
                    "Use segment_ids= (rotates with K/V) or causal= for "
                    "distributed long-context attention."
                    if reason == "mask"
                    else "Score biases (e.g. T5 relative positions) cannot ride "
                    "the ring; drop the 'seq' mesh axis for this model, or use a "
                    "bias-free architecture for sequence parallelism."
                )
                get_logger(__name__).warning(
                    "sequence-parallel attention (axis 'seq', %d-way) is configured, "
                    "but a dense %s= argument cannot ride the ring: such calls run "
                    "REPLICATED XLA attention instead. %s",
                    impl[0].shape.get("seq", 0) if hasattr(impl[0], "shape") else 0,
                    reason,
                    advice,
                )
        elif impl is not None:
            from ..parallel.ring_attention import sequence_parallel_attention

            mesh, mode = impl
            out = sequence_parallel_attention(
                q, k, v, mesh=mesh, causal=causal, scale=scale, mode=mode, segment_ids=segment_ids
            )
            # Record AFTER the call: allgather mode re-enters this function with
            # implementation="xla" internally, which would overwrite the record.
            LAST_DISPATCH = mode
            return out

    # Flash kernel: explicit, or automatic on TPU for long unmasked sequences where
    # the [S,S] score materialization would dominate HBM traffic.
    if implementation == "flash" and (bias is not None or mask is not None or segment_ids is not None):
        blocked = "bias" if bias is not None else ("mask" if mask is not None else "segment_ids")
        raise ValueError(
            f"implementation='flash' cannot honor a {blocked} argument — the Pallas "
            "kernel threads only `causal`. Drop implementation= to let the dispatcher "
            "pick the XLA path, or pass implementation='xla'."
        )
    use_flash = implementation == "flash"
    if (
        implementation is None
        and mask is None
        and bias is None
        and segment_ids is None
        and sq >= 1024
        and sq % 128 == 0
        and skv % 128 == 0
    ):
        import jax

        use_flash = jax.default_backend() == "tpu"
    if use_flash and causal and sq > skv and implementation is None:
        use_flash = False  # degenerate mask shape the kernel rejects; use XLA path
    if use_flash:
        from .flash_attention import flash_attention

        LAST_DISPATCH = "flash"
        return flash_attention(q, k, v, causal=causal, scale=scale)
    LAST_DISPATCH = "xla"

    if hq != hkv:
        reps = hq // hkv
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)

    # [B, H, Sq, Skv]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        scores = scores + bias.astype(scores.dtype)
    neg = jnp.finfo(scores.dtype).min
    if causal:
        cm = make_causal_mask(sq, skv)
        scores = jnp.where(cm[None, None, :, :], scores, neg)
    if mask is not None:
        if mask.ndim == 2:  # [B, Skv] padding mask
            mask = mask[:, None, None, :]
        scores = jnp.where(mask.astype(bool), scores, neg)
    if segment_ids is not None:
        from ..parallel.ring_attention import segment_mask

        scores = jnp.where(segment_mask(segment_ids, segment_ids), scores, neg)
    # Softmax in fp32 for stability under bf16 compute.
    probs = jnp.asarray(
        jnp.exp(
            scores.astype(jnp.float32)
            - jnp.max(scores.astype(jnp.float32), axis=-1, keepdims=True)
        )
    )
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

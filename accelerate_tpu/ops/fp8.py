"""fp8 matmuls: XLA-native replacement for TransformerEngine / MS-AMP
(reference utils/transformer_engine.py:24-80, accelerator.py:1922-1956).

The reference converts `nn.Linear` → TE modules whose CUDA kernels run fp8 GEMMs with a
*delayed* scaling recipe (amax history). On TPU, XLA exposes fp8 dtypes
(`float8_e4m3fn`, `float8_e5m2`) directly to `dot_general`, so fp8 needs no kernel
library — just scaled casts around the dot. Scaling here is *dynamic* (per-tensor amax
computed in-graph): the amax reduction fuses into the preceding producer, which costs
almost nothing on TPU and is strictly more accurate than TE's history heuristic; the
`amax_history_len` field of `FP8RecipeKwargs` is accepted for config parity and unused.

Format policy follows the recipe: "E4M3" uses e4m3 everywhere; "HYBRID" (default, TE
parity) uses e4m3 for activations/weights in forward and e5m2 (wider range) for the
incoming gradients in backward — implemented with a custom VJP.

The module-conversion entry point is `fp8_autocast(...)`: a flax method interceptor
that rewrites every bound `nn.Dense.__call__` to the fp8 path without touching the
module tree or params (the functional analogue of `convert_model` swapping Linear
layers).
"""

from __future__ import annotations

import contextlib
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

E4M3 = jnp.float8_e4m3fn
E5M2 = jnp.float8_e5m2
E4M3_MAX = 448.0
E5M2_MAX = 57344.0


def quantize_fp8(x, dtype=E4M3):
    """Per-tensor dynamic scaling: returns (x_fp8, scale) with x ≈ x_fp8 * scale."""
    fmax = E4M3_MAX if dtype == E4M3 else E5M2_MAX
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    scale = jnp.maximum(amax, 1e-12) / fmax
    q = (x.astype(jnp.float32) / scale).astype(dtype)
    return q, scale


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def fp8_matmul(x, w, hybrid: bool = True):
    """`x @ w` with fp8 operands and fp32 accumulation.

    x: [..., K], w: [K, N]. Forward casts both to e4m3; backward casts the cotangent
    to e5m2 when `hybrid` (TE HYBRID recipe) else e4m3.
    """
    out, _ = _fp8_matmul_fwd(x, w, hybrid)
    return out


def _fp8_dot(a, a_scale, b, b_scale, dims):
    out = jax.lax.dot_general(a, b, dims, preferred_element_type=jnp.float32)
    return out * (a_scale * b_scale)


def _fp8_matmul_fwd(x, w, hybrid):
    xq, sx = quantize_fp8(x, E4M3)
    wq, sw = quantize_fp8(w, E4M3)
    contract = (((x.ndim - 1,), (0,)), ((), ()))
    out = _fp8_dot(xq, sx, wq, sw, contract).astype(x.dtype)
    return out, (xq, sx, wq, sw)


def _fp8_matmul_bwd(hybrid, res, g):
    xq, sx, wq, sw = res
    gdtype = E5M2 if hybrid else E4M3
    gq, sg = quantize_fp8(g, gdtype)
    # dx = g @ w.T : contract g's last dim with w's last dim
    dims_dx = (((g.ndim - 1,), (1,)), ((), ()))
    dx = _fp8_dot(gq, sg, wq, sw, dims_dx).astype(g.dtype)
    # dw = x.T @ g : contract all batch dims of x with those of g
    batch_dims = tuple(range(g.ndim - 1))
    dims_dw = ((batch_dims, batch_dims), ((), ()))
    dw = _fp8_dot(xq, sx, gq, sg, dims_dw).astype(g.dtype)
    return dx, dw


fp8_matmul.defvjp(_fp8_matmul_fwd, _fp8_matmul_bwd)


def fp8_dense_apply(module, x):
    """Compute a bound `nn.Dense` with the fp8 path, reusing its own params."""
    kernel = module.get_variable("params", "kernel")
    hybrid = _RECIPE_STATE["hybrid"]
    y = fp8_matmul(x, kernel.astype(x.dtype), hybrid)
    if module.use_bias:
        bias = module.get_variable("params", "bias")
        y = y + bias.astype(y.dtype)
    return y


_RECIPE_STATE = {"hybrid": True}


@contextlib.contextmanager
def fp8_autocast(fp8_recipe=None):
    """Run flax applies under fp8: every `nn.Dense.__call__` inside this context uses
    `fp8_matmul` (reference fp8_autocast + convert_model, utils/transformer_engine.py).
    """
    import flax.linen as nn

    hybrid = True
    if fp8_recipe is not None and getattr(fp8_recipe, "fp8_format", "HYBRID") == "E4M3":
        hybrid = False

    def interceptor(next_fun, args, kwargs, context):
        if isinstance(context.module, nn.Dense) and context.method_name == "__call__":
            return fp8_dense_apply(context.module, args[0])
        return next_fun(*args, **kwargs)

    prev = _RECIPE_STATE["hybrid"]
    _RECIPE_STATE["hybrid"] = hybrid
    try:
        with nn.intercept_methods(interceptor):
            yield
    finally:
        _RECIPE_STATE["hybrid"] = prev


class Fp8Dense:
    """Factory for a Dense layer that always runs fp8 (for model authors who want fp8
    outside the autocast context)."""

    def __new__(cls, features: int, use_bias: bool = True, name: Optional[str] = None):
        import flax.linen as nn

        class _Fp8Dense(nn.Module):
            features: int
            use_bias: bool = True

            @nn.compact
            def __call__(self, x):
                kernel = self.param(
                    "kernel", nn.initializers.lecun_normal(), (x.shape[-1], self.features)
                )
                y = fp8_matmul(x, kernel.astype(x.dtype), _RECIPE_STATE["hybrid"])
                if self.use_bias:
                    y = y + self.param("bias", nn.initializers.zeros, (self.features,)).astype(y.dtype)
                return y

        return _Fp8Dense(features=features, use_bias=use_bias, name=name)

"""fp8 matmuls: XLA-native replacement for TransformerEngine / MS-AMP
(reference utils/transformer_engine.py:24-80, accelerator.py:1922-1956).

The reference converts `nn.Linear` → TE modules whose CUDA kernels run fp8 GEMMs with a
*delayed* scaling recipe (amax history). On TPU, XLA exposes fp8 dtypes
(`float8_e4m3fn`, `float8_e5m2`) directly to `dot_general`, so fp8 needs no kernel
library — just scaled casts around the dot. The DEFAULT scaling is *dynamic*
(per-tensor amax computed in-graph): the amax reduction fuses into the preceding
producer, which costs almost nothing on TPU and is measurably tighter than a history
window (docs/limitations.md). TE's delayed recipe is also implemented —
`FP8RecipeKwargs(scaling="delayed", amax_history_len=H, amax_compute_algo=...)`
selects it: see `fp8_matmul_delayed` (explicit meta threading, grad history via the
meta cotangent) and the `fp8_meta` module collection under `fp8_autocast`.

Format policy follows the recipe: "E4M3" uses e4m3 everywhere; "HYBRID" (default, TE
parity) uses e4m3 for activations/weights in forward and e5m2 (wider range) for the
incoming gradients in backward — implemented with a custom VJP.

The module-conversion entry point is `fp8_autocast(...)`: a flax method interceptor
that rewrites every bound `nn.Dense.__call__` to the fp8 path without touching the
module tree or params (the functional analogue of `convert_model` swapping Linear
layers).
"""

from __future__ import annotations

import contextlib
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

E4M3 = jnp.float8_e4m3fn
E5M2 = jnp.float8_e5m2
E4M3_MAX = 448.0
E5M2_MAX = 57344.0


def quantize_fp8(x, dtype=E4M3):
    """Per-tensor dynamic scaling: returns (x_fp8, scale) with x ≈ x_fp8 * scale."""
    fmax = E4M3_MAX if dtype == E4M3 else E5M2_MAX
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    scale = jnp.maximum(amax, 1e-12) / fmax
    q = (x.astype(jnp.float32) / scale).astype(dtype)
    return q, scale


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def fp8_matmul(x, w, hybrid: bool = True):
    """`x @ w` with fp8 operands and fp32 accumulation.

    x: [..., K], w: [K, N]. Forward casts both to e4m3; backward casts the cotangent
    to e5m2 when `hybrid` (TE HYBRID recipe) else e4m3.
    """
    out, _ = _fp8_matmul_fwd(x, w, hybrid)
    return out


def _fp8_dot(a, a_scale, b, b_scale, dims):
    out = jax.lax.dot_general(a, b, dims, preferred_element_type=jnp.float32)
    return out * (a_scale * b_scale)


def _fp8_matmul_fwd(x, w, hybrid):
    xq, sx = quantize_fp8(x, E4M3)
    wq, sw = quantize_fp8(w, E4M3)
    contract = (((x.ndim - 1,), (0,)), ((), ()))
    out = _fp8_dot(xq, sx, wq, sw, contract).astype(x.dtype)
    return out, (xq, sx, wq, sw)


def _fp8_matmul_bwd(hybrid, res, g):
    xq, sx, wq, sw = res
    gdtype = E5M2 if hybrid else E4M3
    gq, sg = quantize_fp8(g, gdtype)
    # dx = g @ w.T : contract g's last dim with w's last dim
    dims_dx = (((g.ndim - 1,), (1,)), ((), ()))
    dx = _fp8_dot(gq, sg, wq, sw, dims_dx).astype(g.dtype)
    # dw = x.T @ g : contract all batch dims of x with those of g
    batch_dims = tuple(range(g.ndim - 1))
    dims_dw = ((batch_dims, batch_dims), ((), ()))
    dw = _fp8_dot(xq, sx, gq, sg, dims_dw).astype(g.dtype)
    return dx, dw


fp8_matmul.defvjp(_fp8_matmul_fwd, _fp8_matmul_bwd)


# ------------------------------------------------------------- delayed scaling
#
# TE DelayedScaling parity (reference utils/transformer_engine.py:24-80,
# FP8RecipeKwargs dataclasses.py:186): scales come from a rolling amax-history
# WINDOW of previous steps instead of the current tensor. On GPU that exists to
# break the cast→reduce kernel dependency; on TPU the in-graph amax fuses into
# the producer, so dynamic scaling stays the default (docs/limitations.md) —
# delayed is provided for recipe parity and for users porting TE configs.
#
# The functional shape: one meta pytree of three histories per matmul, threaded
# explicitly through the step. Forward scales read the window; the OBSERVED
# amaxes (including the gradient's, known only in backward) leave the VJP as
# the meta argument's "cotangent" — so `jax.grad(..., argnums=meta)` returns
# the UPDATED meta, which the caller installs for the next step (the
# overwrite-with-gradient pattern public flax fp8 ops use). One matmul per meta
# per step: reuse under an accumulation scan would SUM the history cotangents.


def init_fp8_meta(history_len: int = 16):
    """Fresh (cold) delayed-scaling state for ONE matmul: zeros mean "no amax
    observed", which `_history_scale` maps to scale 1.0 — TE's init — until
    real amaxes roll in."""
    z = jnp.zeros((int(history_len),), jnp.float32)
    return {"x_amax_history": z, "w_amax_history": z, "g_amax_history": z}


def _history_scale(history, fmax, algo: str = "max"):
    """TE amax_compute_algo semantics: 'max' covers the whole window (robust to
    spikes, coarser after them), 'most_recent' tracks the last step only."""
    amax = history[-1] if algo == "most_recent" else jnp.max(history)
    return jnp.where(amax > 0.0, jnp.maximum(amax, 1e-12) / fmax, 1.0)


def _roll_amax(history, amax):
    return jnp.concatenate([history[1:], jnp.reshape(amax, (1,)).astype(jnp.float32)])


def _quantize_with_scale(x, scale, dtype):
    """Cast with an EXTERNAL (history) scale. Unlike the dynamic path the scale
    may under-estimate the current tensor, so clip to the representable range —
    TE's saturating-cast behavior — instead of overflowing to NaN/max garbage."""
    fmax = E4M3_MAX if dtype == E4M3 else E5M2_MAX
    q = jnp.clip(x.astype(jnp.float32) / scale, -fmax, fmax).astype(dtype)
    return q


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fp8_matmul_delayed(x, w, meta, hybrid: bool = True, amax_algo: str = "max"):
    """`x @ w` in fp8 under the DELAYED recipe: forward scales from
    `meta['x_amax_history']` / `['w_amax_history']`, backward grad scale from
    `meta['g_amax_history']`. The gradient with respect to `meta` IS the
    updated meta (histories rolled with this step's observed amaxes)::

        grads, new_meta = jax.grad(loss, argnums=(0, 2))(x, w, meta)
        # next step uses new_meta

    x: [..., K], w: [K, N]; `hybrid` selects e5m2 for the backward cotangent
    (TE HYBRID) else e4m3 everywhere.
    """
    out, _ = _fp8_delayed_fwd(x, w, meta, hybrid, amax_algo)
    return out


def _fp8_delayed_fwd(x, w, meta, hybrid, amax_algo="max"):
    sx = _history_scale(meta["x_amax_history"], E4M3_MAX, amax_algo)
    sw = _history_scale(meta["w_amax_history"], E4M3_MAX, amax_algo)
    xq = _quantize_with_scale(x, sx, E4M3)
    wq = _quantize_with_scale(w, sw, E4M3)
    contract = (((x.ndim - 1,), (0,)), ((), ()))
    out = _fp8_dot(xq, sx, wq, sw, contract).astype(x.dtype)
    amax_x = jnp.max(jnp.abs(x.astype(jnp.float32)))
    amax_w = jnp.max(jnp.abs(w.astype(jnp.float32)))
    return out, (xq, sx, wq, sw, meta, amax_x, amax_w)


def _fp8_delayed_bwd(hybrid, amax_algo, res, g):
    xq, sx, wq, sw, meta, amax_x, amax_w = res
    gdtype = E5M2 if hybrid else E4M3
    gmax = E5M2_MAX if hybrid else E4M3_MAX
    sg = _history_scale(meta["g_amax_history"], gmax, amax_algo)
    gq = _quantize_with_scale(g, sg, gdtype)
    dims_dx = (((g.ndim - 1,), (1,)), ((), ()))
    dx = _fp8_dot(gq, sg, wq, sw, dims_dx).astype(g.dtype)
    batch_dims = tuple(range(g.ndim - 1))
    dims_dw = ((batch_dims, batch_dims), ((), ()))
    dw = _fp8_dot(xq, sx, gq, sg, dims_dw).astype(g.dtype)
    amax_g = jnp.max(jnp.abs(g.astype(jnp.float32)))
    new_meta = {
        "x_amax_history": _roll_amax(meta["x_amax_history"], amax_x),
        "w_amax_history": _roll_amax(meta["w_amax_history"], amax_w),
        "g_amax_history": _roll_amax(meta["g_amax_history"], amax_g),
    }
    return dx, dw, new_meta


fp8_matmul_delayed.defvjp(_fp8_delayed_fwd, _fp8_delayed_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fp8_matmul_fwd_scaled(x, w, sx, sw, hybrid: bool = True):
    """Forward with EXTERNAL scales, backward with dynamic grad scaling — the
    autocast delayed mode (module-owned forward histories; the grad history has
    no flax-mutable channel in backward, and dynamic grads are strictly more
    accurate anyway)."""
    out, _ = _fwd_scaled(x, w, sx, sw, hybrid)
    return out


def _fwd_scaled(x, w, sx, sw, hybrid):
    xq = _quantize_with_scale(x, sx, E4M3)
    wq = _quantize_with_scale(w, sw, E4M3)
    contract = (((x.ndim - 1,), (0,)), ((), ()))
    out = _fp8_dot(xq, sx, wq, sw, contract).astype(x.dtype)
    return out, (xq, sx, wq, sw)


def _bwd_scaled(hybrid, res, g):
    xq, sx, wq, sw = res
    gdtype = E5M2 if hybrid else E4M3
    gq, sg = quantize_fp8(g, gdtype)
    dims_dx = (((g.ndim - 1,), (1,)), ((), ()))
    dx = _fp8_dot(gq, sg, wq, sw, dims_dx).astype(g.dtype)
    batch_dims = tuple(range(g.ndim - 1))
    dims_dw = ((batch_dims, batch_dims), ((), ()))
    dw = _fp8_dot(xq, sx, gq, sg, dims_dw).astype(g.dtype)
    return dx, dw, jnp.zeros_like(sx), jnp.zeros_like(sw)


_fp8_matmul_fwd_scaled.defvjp(_fwd_scaled, _bwd_scaled)


def fp8_dense_apply(module, x):
    """Compute a bound `nn.Dense` with the fp8 path, reusing its own params."""
    kernel = module.get_variable("params", "kernel")
    hybrid = _RECIPE_STATE["hybrid"]
    if _RECIPE_STATE["scaling"] == "delayed":
        return _fp8_dense_apply_delayed(module, x, kernel, hybrid)
    y = fp8_matmul(x, kernel.astype(x.dtype), hybrid)
    if module.use_bias:
        bias = module.get_variable("params", "bias")
        y = y + bias.astype(y.dtype)
    return y


def _fp8_dense_apply_delayed(module, x, kernel, hybrid):
    """Autocast delayed mode: the Dense's forward amax histories live in its
    own `fp8_meta` variable collection (TE keeps fp8 meta tensors on the
    module the same way). Histories update when the caller's `apply` marks
    `fp8_meta` mutable — `model.apply(vars, x, mutable=["fp8_meta"])` — and
    freeze (scales read, no writes) otherwise, e.g. at eval."""
    hlen = _RECIPE_STATE["history_len"]
    algo = _RECIPE_STATE["amax_algo"]
    cold = jnp.zeros((hlen,), jnp.float32)
    if module.has_variable("fp8_meta", "x_amax_history"):
        hx = module.get_variable("fp8_meta", "x_amax_history")
        hw = module.get_variable("fp8_meta", "w_amax_history")
    else:
        hx = hw = cold
    w = kernel.astype(x.dtype)
    sx = _history_scale(hx, E4M3_MAX, algo)
    sw = _history_scale(hw, E4M3_MAX, algo)
    y = _fp8_matmul_fwd_scaled(x, w, sx, sw, hybrid)
    if module.is_mutable_collection("fp8_meta"):
        amax_x = jnp.max(jnp.abs(x.astype(jnp.float32)))
        amax_w = jnp.max(jnp.abs(w.astype(jnp.float32)))
        module.put_variable("fp8_meta", "x_amax_history", _roll_amax(hx, amax_x))
        module.put_variable("fp8_meta", "w_amax_history", _roll_amax(hw, amax_w))
    if module.use_bias:
        bias = module.get_variable("params", "bias")
        y = y + bias.astype(y.dtype)
    return y


_RECIPE_STATE = {"hybrid": True, "scaling": "dynamic", "history_len": 16, "amax_algo": "max"}


@contextlib.contextmanager
def fp8_autocast(fp8_recipe=None):
    """Run flax applies under fp8: every `nn.Dense.__call__` inside this context uses
    `fp8_matmul` (reference fp8_autocast + convert_model, utils/transformer_engine.py).

    `fp8_recipe.scaling="delayed"` selects history-based forward scales (see
    `_fp8_dense_apply_delayed`); the default "dynamic" computes per-tensor amax
    in-graph — on TPU the reduction fuses into the producer, so dynamic is both
    cheaper than a history side-channel and strictly tighter (measured on the
    regression task in tests/test_fp8.py: see docs/limitations.md).
    """
    import flax.linen as nn

    hybrid = True
    scaling = "dynamic"
    history_len = 16
    amax_algo = "max"
    if fp8_recipe is not None:
        if getattr(fp8_recipe, "fp8_format", "HYBRID") == "E4M3":
            hybrid = False
        scaling = getattr(fp8_recipe, "scaling", "dynamic")
        history_len = int(getattr(fp8_recipe, "amax_history_len", 16) or 16)
        amax_algo = getattr(fp8_recipe, "amax_compute_algo", "max")

    def interceptor(next_fun, args, kwargs, context):
        if isinstance(context.module, nn.Dense) and context.method_name == "__call__":
            # init pass: params don't exist yet — run the normal path so the
            # kernel/bias get created, fp8 takes over from the first apply.
            if context.module.has_variable("params", "kernel"):
                return fp8_dense_apply(context.module, args[0])
        return next_fun(*args, **kwargs)

    prev = dict(_RECIPE_STATE)
    _RECIPE_STATE.update(
        hybrid=hybrid, scaling=scaling, history_len=history_len, amax_algo=amax_algo
    )
    try:
        with nn.intercept_methods(interceptor):
            yield
    finally:
        _RECIPE_STATE.update(prev)


class Fp8Dense:
    """Factory for a Dense layer that always runs fp8 (for model authors who want fp8
    outside the autocast context)."""

    def __new__(cls, features: int, use_bias: bool = True, name: Optional[str] = None):
        import flax.linen as nn

        class _Fp8Dense(nn.Module):
            features: int
            use_bias: bool = True

            @nn.compact
            def __call__(self, x):
                kernel = self.param(
                    "kernel", nn.initializers.lecun_normal(), (x.shape[-1], self.features)
                )
                y = fp8_matmul(x, kernel.astype(x.dtype), _RECIPE_STATE["hybrid"])
                if self.use_bias:
                    y = y + self.param("bias", nn.initializers.zeros, (self.features,)).astype(y.dtype)
                return y

        return _Fp8Dense(features=features, use_bias=use_bias, name=name)

"""Shared online-softmax accumulator helpers for the Pallas attention kernels.

Both attention kernel families — the training flash kernel
(`ops/flash_attention.py`) and the serving paged-decode/block-verify kernels
(`ops/paged_attention.py`) — stream K/V blocks through the same numerically
stable accumulator: running max `m`, running normalizer `l`, and an
unnormalized output accumulator `acc`, all fp32 regardless of input dtype.
The update lives here ONCE so the two kernel families can never drift apart
on the one piece of math their parity contract depends on.

Layout convention (Mosaic): the per-row `m`/`l` stats ride a broadcast
128-lane trailing axis (`LANE`) because the minimum TPU tile is (8, 128) on
the last two dims — a `[rows]`-shaped stat cannot be blocked per grid step.
Same workaround as jax's in-tree TPU flash kernel's l/m buffers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: Additive mask value: large enough to zero a softmax lane, small enough that
#: exp(NEG_INF - m) never produces inf/nan under fp32.
NEG_INF = -1e30

#: Broadcast trailing-lane width for per-row softmax stats (Mosaic min tile).
LANE = 128


def init_softmax_state(acc, m_scr, l_scr):
    """Reset the accumulator scratch at the start of a row's K/V walk
    (`acc` [rows, D] fp32, `m_scr`/`l_scr` [rows, LANE] fp32)."""
    acc[:] = jnp.zeros_like(acc)
    m_scr[:] = jnp.full_like(m_scr, NEG_INF)
    l_scr[:] = jnp.zeros_like(l_scr)


def online_softmax_update(s, v, acc, m_scr, l_scr):
    """Fold one K/V block into the running softmax state.

    Args:
        s: [rows, block_k] fp32 scores for this block, already scaled and
            masked (masked lanes at `NEG_INF`).
        v: [block_k, D] fp32 value block.
        acc / m_scr / l_scr: scratch refs as in `init_softmax_state`.
    """
    m_prev = m_scr[:, 0:1]  # [rows, 1] (lane dim is broadcast)
    l_prev = l_scr[:, 0:1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)  # [rows, block_k]
    correction = jnp.exp(m_prev - m_new)  # [rows, 1]
    l_new = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
    acc[:] = acc[:] * correction + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)


def finalize_softmax(acc, m_scr, l_scr):
    """(normalized output [rows, D], logsumexp [rows, 1]) after the last block.

    Rows whose every lane was masked (l == 0) normalize against a tiny floor
    instead of dividing by zero — they come out ~0, never NaN, which is what
    lets inactive serving slots ride the same dispatch as live ones.
    """
    l = l_scr[:, 0:1]
    safe_l = jnp.maximum(l, 1e-30)
    lse = (m_scr[:, 0:1] + jnp.log(safe_l)).astype(jnp.float32)
    return acc[:] / safe_l, lse

"""Inference quantization: int8 weight-only matmuls and the quantized KV page
pool — the serving-side bandwidth multipliers (ROADMAP item 5).

Decode is HBM-bandwidth-bound: every step streams the weights and the live KV
pages, so bytes-per-value is a direct throughput multiplier. Two independent
seams, both selected by engine/model config and both keeping the
compiled-once discipline (dtypes are static config; every scale is a traced
ARRAY operand, never a Python scalar — TPU117 lints the violation):

  - **Weight-only int8** (`weight_dtype="int8"`): per-output-channel symmetric
    scales computed ONCE at weight-load/`swap_weights` time
    (`quantize_params_int8` — the engine's `params` setter calls it), applied
    in the matmul epilogue by a flax method interceptor (`weight_autocast`,
    the same mechanism as `fp8_autocast` in `ops/fp8.py`): every bound
    `nn.Dense.__call__` whose kernel is a quantized entry computes
    ``(x @ q) * scale`` — the int8 kernel streams from HBM at 1 byte/value,
    the cast fuses into the matmul read, and the scale is one fused
    elementwise epilogue. Per-output-channel scaling makes the epilogue EXACT
    with respect to dequantize-then-matmul.
  - **Quantized KV page pool** (`kv_cache_dtype="int8" | "fp8_e4m3"`): the
    paged slot cache (`ops/attention._write_slot_pool`) stores pages in the
    quantized dtype with per-page-per-head scales riding in a parallel
    ``[num_pages, heads]`` pool array inside the same flax "cache" collection.
    The XLA gather path dequantizes on read (the parity oracle); the Pallas
    paged kernels (`ops/paged_attention.py`) fuse the dequant into the
    page-streaming online-softmax loop, so quantized decode moves int8/fp8
    bytes per page, not bf16.

Page-scale maintenance (the part unique to an incrementally-written cache):
a page's scale can only be finalized when its content stops changing, but
decode appends one token at a time. The write path therefore keeps the
invariant ``stored_q * scale == value`` by construction: a write at page
offset 0 RESETS the page's scale (fresh page, stale content from a previous
occupant must not pin an old range); every write raises the scale to cover
the incoming token's amax (`scale = max(scale, amax/qmax)`); and when the
scale grows, the page's EXISTING rows are requantized in the same dispatch
(`ratio = old/new`, one page-sized read-modify-write — bytes proportional to
the pages touched this step, not the pool). fp8 (e4m3) follows the
`ops/fp8.py` scaled-cast machinery (`E4M3_MAX` saturating casts); int8 is
symmetric round-to-nearest at qmax 127.

int4 weight/KV packing is explicitly out of scope here (docs/limitations.md);
`utils/quantization.py` keeps the bnb-parity int4/nf4 *storage* path for
loading, which `_params_resolver` dequantizes in-program.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .fp8 import E4M3, E4M3_MAX

#: Supported KV page-pool storage dtypes. "bf16" means UNQUANTIZED — pages
#: keep the model's compute dtype (bf16 on accelerators, f32 in CPU tests).
KV_CACHE_DTYPES = ("bf16", "int8", "fp8_e4m3")

#: Supported weight storage dtypes for the serving engines.
WEIGHT_DTYPES = ("bf16", "int8")

#: Scale floor: avoids div-by-zero for all-zero pages/channels without
#: perturbing any real scale (activations/weights sit orders of magnitude up).
_TINY = 1e-12

INT8_MAX = 127.0


def kv_quant_spec(kv_cache_dtype: str) -> Optional[Tuple[Any, float]]:
    """``(storage dtype, qmax)`` for a quantized KV cache dtype, or None for
    the unquantized "bf16" default. Raises on anything off the supported set
    (the same set TPU117 lints literals against)."""
    if kv_cache_dtype == "bf16":
        return None
    if kv_cache_dtype == "int8":
        return jnp.int8, INT8_MAX
    if kv_cache_dtype == "fp8_e4m3":
        return E4M3, E4M3_MAX
    raise ValueError(
        f"unknown kv_cache_dtype {kv_cache_dtype!r}; expected one of {KV_CACHE_DTYPES}"
    )


def kv_spec_for_dtype(dtype) -> Optional[Tuple[Any, float]]:
    """``(dtype, qmax)`` for a pool leaf's STORAGE dtype (the inverse lookup
    of `kv_quant_spec` used by the cache-pytree gather/scatter helpers), or
    None for unquantized float pools."""
    if dtype == jnp.int8:
        return jnp.int8, INT8_MAX
    if dtype == E4M3:
        return E4M3, E4M3_MAX
    return None


def _cast_quantized(x, dtype, qmax):
    """fp32 values already divided by their scale -> storage dtype. int8
    rounds to nearest then clips; fp8 saturates (the `ops/fp8.py`
    `_quantize_with_scale` behavior) — the cast itself rounds."""
    if dtype == jnp.int8:
        return jnp.clip(jnp.round(x), -qmax, qmax).astype(jnp.int8)
    return jnp.clip(x, -qmax, qmax).astype(dtype)


def quantize_kv(x, scale, dtype, qmax):
    """Quantize K/V values against a broadcastable traced `scale` array."""
    return _cast_quantized(x.astype(jnp.float32) / jnp.maximum(scale, _TINY), dtype, qmax)


def dequantize_kv(q, scale, dtype=jnp.float32):
    """``q * scale`` in fp32, cast to the requested compute dtype. `scale`
    must be a traced array broadcastable against `q` (TPU117)."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def requantize_kv(q, ratio, dtype, qmax):
    """Re-express stored quantized values under a grown scale:
    ``q_new = q_old * (old_scale / new_scale)``. `ratio` <= 1 for real
    growth; a freshly-reset page carries ratio 0, which zeroes its stale
    content in the same op."""
    return _cast_quantized(q.astype(jnp.float32) * ratio, dtype, qmax)


def quantized_pool_write(pool, scale, x, pid, off, spec):
    """The quantized half of the paged cache's token write: scatter this
    dispatch's ``[B, s, h, d]`` K or V rows into the quantized page pool
    through ``(pid, off)`` (``[B, s]`` pool-page ids / in-page offsets),
    maintaining the per-page-per-head `scale` array ``[num_pages, h]``.

    Invariant on exit: every live row of every touched page satisfies
    ``dequantize(stored, scale[page, head]) ~= written value`` —
      1. a write at offset 0 resets the page's scale (new occupant),
      2. the scale rises to cover each incoming token (scatter-max),
      3. pages whose scale changed are requantized in place (ratio =
         old/new; bytes proportional to pages touched, not the pool).
    Duplicate page ids across rows only occur for the scratch page, whose
    content is never attended. All arrays are traced operands."""
    dtype, qmax = spec
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)  # [B, s, h]
    # (1) reset: route non-offset-0 writes' reset at the scratch page, whose
    # scale is meaningless (its rows sit above every live position).
    reset_pid = jnp.where(off == 0, pid, 0)
    scale_after_reset = scale.at[reset_pid].set(0.0)
    # (2) raise: every token this dispatch writes is representable.
    new_scale = scale_after_reset.at[pid].max(amax / qmax)
    safe_scale = jnp.maximum(new_scale, _TINY)
    # (3) requantize the touched pages under their (possibly) grown scale.
    ratio = scale_after_reset / safe_scale  # [num_pages, h]
    touched = pool[pid]  # [B, s, page_size, h, d]
    requant = requantize_kv(touched, ratio[pid][:, :, None, :, None], dtype, qmax)
    pool = pool.at[pid].set(requant)
    q = quantize_kv(x, safe_scale[pid][..., None], dtype, qmax)
    pool = pool.at[pid, off].set(q)
    return pool, new_scale


def quantize_kv_pages(blocks, spec):
    """Whole-page quantization for the insert path (`tree_scatter_pages`):
    `blocks` ``[P, ..., page_size, h, d]`` float pages -> (quantized blocks,
    per-page-per-head scales ``[P, ..., h]``). Scale covers the page's amax,
    so a freshly-prefilled page round-trips within half a quantization step."""
    dtype, qmax = spec
    amax = jnp.max(jnp.abs(blocks.astype(jnp.float32)), axis=(-3, -1))  # [P, ..., h]
    scale = amax / qmax
    q = quantize_kv(blocks, scale[..., None, :, None], dtype, qmax)
    return q, scale


def dequantize_kv_pages(blocks, scale, dtype):
    """Inverse of `quantize_kv_pages` for gathered pages: `blocks`
    ``[..., P, page_size, h, d]`` quantized, `scale` ``[..., P, h]``."""
    return dequantize_kv(blocks, scale[..., :, None, :, None], dtype)


# ------------------------------------------------------------------- weights

#: Key names of a quantized kernel entry (a plain dict so the params tree
#: stays a vanilla pytree for jit/device_put/save_pytree).
_QKEYS = frozenset(("q", "scale"))


def is_quantized_kernel(value) -> bool:
    """True for a `quantize_weight_int8` entry ({"q": int8, "scale": f32})."""
    return isinstance(value, dict) and set(value.keys()) == set(_QKEYS)


def quantize_weight_int8(w) -> Dict[str, Any]:
    """Per-output-channel symmetric int8: scales over every axis but the last
    (the output-feature axis of a flax Dense kernel ``[K, N]``), computed once
    at load time. ``w ~= q * scale`` with `scale` shaped ``[N]``."""
    w32 = jnp.asarray(w).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=tuple(range(w32.ndim - 1)))
    scale = absmax / INT8_MAX
    q = jnp.clip(jnp.round(w32 / jnp.maximum(scale, _TINY)), -INT8_MAX, INT8_MAX)
    return {"q": q.astype(jnp.int8), "scale": scale}


def dequantize_weight_int8(entry, dtype=jnp.float32):
    return (entry["q"].astype(jnp.float32) * entry["scale"]).astype(dtype)


def quantize_params_int8(params):
    """Params-tree transform for the serving engines: every floating Dense
    kernel (path leaf named ``kernel``, ndim >= 2) becomes a quantized entry;
    embeddings, norms, biases and already-quantized entries pass through
    untouched (idempotent — re-applying on swap never double-quantizes).
    The module tree is untouched: `weight_autocast` intercepts the consuming
    ``nn.Dense.__call__`` at trace time."""
    def _q(path, leaf):
        name = str(getattr(path[-1], "key", getattr(path[-1], "name", path[-1])))
        if (
            name == "kernel"
            and getattr(leaf, "ndim", 0) >= 2
            and jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)
        ):
            return quantize_weight_int8(leaf)
        return leaf

    return jax.tree_util.tree_map_with_path(_q, params)


def params_nbytes(params) -> int:
    """Actual stored bytes of a (possibly quantized) params tree — what the
    bench reports as weight footprint."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += int(leaf.size) * np.dtype(leaf.dtype).itemsize
    return total


def _int8_dense_apply(module, x):
    """Compute a bound `nn.Dense` whose kernel is a quantized entry: the int8
    matrix feeds the MXU in the compute dtype (the cast fuses into the HBM
    read) and the per-output-channel scale lands in the epilogue — exact
    w.r.t. dequantize-then-matmul because the scale is constant per output
    column of the dot."""
    entry = module.get_variable("params", "kernel")
    q, scale = entry["q"], entry["scale"]
    contract = (((x.ndim - 1,), (0,)), ((), ()))
    y = jax.lax.dot_general(
        x, q.astype(x.dtype), contract, preferred_element_type=jnp.float32
    )
    y = (y * scale.astype(jnp.float32)).astype(x.dtype)
    if module.use_bias:
        y = y + module.get_variable("params", "bias").astype(y.dtype)
    return y


@contextlib.contextmanager
def weight_autocast(weight_dtype: str = "int8"):
    """Run flax applies with quantized-weight matmuls: every bound
    `nn.Dense.__call__` whose kernel is a `quantize_params_int8` entry uses
    the int8 epilogue path (the `fp8_autocast` interceptor pattern,
    ops/fp8.py). "bf16" is a no-op context so call sites can wrap
    unconditionally; dense (unquantized) kernels fall through untouched, so
    partially-quantized trees and init passes keep working."""
    if weight_dtype not in WEIGHT_DTYPES:
        raise ValueError(
            f"unknown weight_dtype {weight_dtype!r}; expected one of {WEIGHT_DTYPES}"
        )
    if weight_dtype == "bf16":
        yield
        return
    import flax.linen as nn

    def interceptor(next_fun, args, kwargs, context):
        if isinstance(context.module, nn.Dense) and context.method_name == "__call__":
            if context.module.has_variable("params", "kernel") and is_quantized_kernel(
                context.module.get_variable("params", "kernel")
            ):
                return _int8_dense_apply(context.module, args[0])
        return next_fun(*args, **kwargs)

    with nn.intercept_methods(interceptor):
        yield

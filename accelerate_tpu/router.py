"""Replicated serving fleet: a health-routed front-end over N engines.

A single `ContinuousBatcher` is one process-wide failure domain: a SIGKILL, a
hung dispatch, or a poisoned executable takes down ALL traffic. This module
splits the fleet from the engine:

  - `ReplicaSet` owns N engine workers (in-process `ContinuousBatcher`s by
    default; the `engine_factory` seam is where subprocess/mesh-spanning
    engines plug in) plus the per-replica **health state machine**::

        live -> degraded -> ejected -> rejoining -> live
                   ^------------------------------------'

    driven by heartbeats (a replica with work that stops finishing steps),
    queue-depth / step-latency signals (degraded), and consecutive dispatch
    failures (ejected). An ejected replica re-enters through a cooldown and a
    `rejoining` probation window before it is `live` again; a replica whose
    engine died outright is rebuilt from the factory on rejoin.

  - `Router` is the front-end with the SAME surface as `ContinuousBatcher`
    (`submit` / `cancel` / `step` / `run` / `drain` / `close` / `release`,
    `results`, `pending`, `stats`): least-loaded routing over the routable
    replicas with bounded per-replica backpressure (`max_queue` rides down to
    every engine; a fleet-wide full queue surfaces as `QueueFull`), a default
    per-request deadline (`default_deadline_s`) so no request can wait
    forever, and safe failure handling:

      * a request that NEVER streamed a token is re-dispatched to another
        replica (`router_retries_total`, bounded by `max_retries`);
      * a request that already emitted tokens is finished with
        ``finish_reason="replica_lost"`` — partial tokens kept, never a
        silently duplicated stream;
      * optional **TTFT hedging**: a request still queued (zero tokens) past
        the hedge threshold is duplicated onto a second replica; the first
        copy to stream wins, the loser is cancelled, and only the winner's
        tokens are ever forwarded. The threshold is a static `hedge_after_s`
        OR a live `hedge_quantile` of the router's own `serving_ttft_seconds`
        histogram (disabled below `hedge_min_samples` observations — no
        hedging off a cold histogram, no stale hand-tuned constant).

  - `swap_weights(params)` is the zero-downtime rolling deploy: one replica at
    a time is drained (unroutable, finishes its own work while the rest keep
    serving), its params are replaced in place (same pytree structure — no
    recompile; params are per-dispatch operands), and it rejoins before the
    next replica drains. The fleet never drops below N-1 serving capacity.

  - **Out-of-process workers** (`out_of_process=True`, or any
    `engine_factory` returning `worker.SubprocessEngine`s): each replica is a
    real OS process hosting one engine behind the length-prefixed JSON IPC in
    `accelerate_tpu.worker`. The health machine's existing eject/rebuild path
    becomes true process supervision — a SIGKILLed or hung worker surfaces as
    `WorkerGone` from `step()`, is ejected, and the factory respawns a fresh
    process that pre-warms its executables before taking traffic (rejoins
    WARM). The in-process default stays the fast path and the parity oracle.

  - **Autoscaling** (`min_replicas`/`max_replicas`): the fleet floats on the
    signals the health machine already computes — scale up on fleet queue
    depth per routable replica (or the TTFT histogram's p99 against
    `autoscale_ttft_target_s`), retire the newest idle replica after
    `idle_retire_s` of a fully idle fleet, one action per
    `autoscale_cooldown_s`, every transition journaled.

  - **Admission control** (`tenant_queue_limit`): with the fleet saturated,
    requests queue at the ROUTER in per-tenant bounded queues drained in
    priority-then-fair-share order (strict `Request.priority` first,
    round-robin across tenants at equal priority) — one tenant's burst
    degrades into bounded queueing + `QueueFull` for THAT tenant, not a
    fleet-wide rejection of everyone.

Everything here is host-side bookkeeping on host scalars — the device-facing
work stays inside each engine, and the router adds zero device syncs (the same
discipline `analysis` rule TPU114 lints the construction side of).

Telemetry: `router_retries_total`, `router_ejected_total`,
`router_hedges_total` / `router_hedge_wins_total`, per-replica state/load
gauges, and one `serve.route` span per request (the engine's `serve.request`
span stitches under it), all documented in docs/observability.md and
docs/serving.md.
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .logging import get_logger
from .serving import (
    FINISH_REASONS,
    ContinuousBatcher,
    EngineClosed,
    QueueFull,
    Request,
    RequestResult,
)
from .telemetry import MetricsRegistry
from .telemetry.tracing import default_tracer

logger = get_logger(__name__)

#: Env var `accelerate-tpu launch --replicas` exports: the fleet size a serving
#: script should build when it does not hard-code one (`Router(replicas=None)`).
SERVE_REPLICAS_ENV = "ACCELERATE_TPU_SERVE_REPLICAS"

#: Terminal finish reasons a Router result can carry: the engine set plus
#: `replica_lost` (the request's replica failed after it had already streamed
#: tokens — re-dispatching would duplicate output, so the router surfaces the
#: loss explicitly with the partial tokens kept).
ROUTER_FINISH_REASONS = FINISH_REASONS + ("replica_lost",)

#: Health states, in escalation order. `draining` is the rolling-swap state —
#: unroutable like `ejected`, but healthy and finishing its own work.
#: `retired` is terminal: an autoscaler-removed replica — engine closed (a
#: subprocess worker's process exits), never rejoins, never routed.
#: `reconnecting` is the transport-fault state (socket fleets): the worker
#: process is presumed alive but the link tore — unroutable while the engine
#: proxy re-handshakes under its backoff budget; heals back to `live` on
#: reconnect, escalates through the ordinary death path (WorkerGone ->
#: eject/rebuild) only when the budget exhausts.
REPLICA_STATES = ("live", "degraded", "ejected", "rejoining", "draining", "retired",
                  "reconnecting")
_STATE_CODE = {s: i for i, s in enumerate(REPLICA_STATES)}


class ReplicaLost(RuntimeError):
    """Internal marker for a replica-level failure (engine death)."""


def default_replicas() -> int:
    """Fleet size when the caller does not pass one: the launch env protocol
    (`launch --replicas N` -> ``ACCELERATE_TPU_SERVE_REPLICAS``), else 2."""
    raw = os.environ.get(SERVE_REPLICAS_ENV, "").strip()
    if raw.isdigit() and int(raw) >= 1:
        return int(raw)
    return 2


def _normalize_params(params_or_model) -> Dict[str, Any]:
    """Accept a params pytree or a Model bundle; return the engine-shaped
    ``{"params": ...}`` dict (`ContinuousBatcher.params` convention)."""
    params = getattr(params_or_model, "params", params_or_model)
    return params if "params" in params else {"params": params}


@dataclass
class Replica:
    """One engine worker plus its health bookkeeping (all host scalars)."""

    index: int
    engine: ContinuousBatcher
    state: str = "live"
    consecutive_failures: int = 0
    #: Engine is gone (process death / fatal dispatch): rejoin must rebuild.
    dead: bool = False
    #: Last time this replica finished a step (or went idle) successfully.
    last_ok: float = 0.0
    #: When the replica entered `ejected` (cooldown anchor).
    ejected_at: Optional[float] = None
    #: Router cycles survived in `rejoining` (probation counter).
    probation_ok: int = 0
    #: When degraded pressure was last observed (recovery anchor).
    unhealthy_at: Optional[float] = None

    @property
    def routable(self) -> bool:
        return self.state in ("live", "degraded", "rejoining")


class ReplicaSet:
    """Owns the N engine workers and the per-replica health state machine.

    The set never routes — that is the `Router`'s job — it answers "which
    replicas may take work, in what preference order" and performs the state
    transitions (eject / cooldown / probation / rejoin / drain-for-swap),
    journaling every transition to `state_log` with the clock the Router
    shares, so chaos invariants can audit routing decisions against health
    history.
    """

    def __init__(
        self,
        model,
        replicas: int,
        engine_kwargs: Optional[Dict[str, Any]] = None,
        engine_factory: Optional[Callable[[int], ContinuousBatcher]] = None,
        registry: Optional[MetricsRegistry] = None,
        tracer=None,
        clock: Callable[[], float] = time.perf_counter,
        eject_after_failures: int = 3,
        rejoin_cooldown_s: float = 1.0,
        probation_steps: int = 2,
        stall_degrade_s: Optional[float] = 5.0,
        degrade_recover_s: float = 1.0,
        heartbeat_timeout_s: Optional[float] = 30.0,
    ):
        if replicas < 1:
            raise ValueError("a ReplicaSet needs at least one replica")
        self.model = model
        self.engine_kwargs = dict(engine_kwargs or {})
        self.registry = registry if registry is not None else MetricsRegistry()
        self.tracer = tracer if tracer is not None else default_tracer()
        self._clock = clock
        self.eject_after_failures = int(eject_after_failures)
        self.rejoin_cooldown_s = float(rejoin_cooldown_s)
        self.probation_steps = int(probation_steps)
        self.stall_degrade_s = stall_degrade_s
        self.degrade_recover_s = float(degrade_recover_s)
        self.heartbeat_timeout_s = heartbeat_timeout_s
        #: Hooks called with (index, engine) after every engine build/rebuild —
        #: the chaos `RouterInjector` re-arms its dispatch wraps through this.
        self.on_engine_built: List[Callable[[int, ContinuousBatcher], None]] = []
        #: Weights applied to rebuilt engines (updated by rolling swaps).
        self.current_params: Optional[Dict[str, Any]] = None
        #: Every state transition: {"t", "replica", "from", "to", "why"}.
        #: Bounded like the Router's routing journal (transitions are rare,
        #: but a flapping replica over months must not grow host memory).
        self.state_log: deque = deque(maxlen=10_000)
        self._engine_factory = engine_factory
        self._m_ejected = self.registry.counter(
            "router_ejected_total", help="replica ejections (health machine -> ejected)"
        )
        self._g_live = self.registry.gauge(
            "router_replicas_live", help="replicas currently in the live state"
        )
        self._g_state: Dict[int, Any] = {}
        self._g_load: Dict[int, Any] = {}
        self.replicas: List[Replica] = []
        for _ in range(replicas):
            self.add_replica(why="initial fleet")
        self._refresh_gauges()

    def _ensure_gauges(self, index: int):
        if index in self._g_state:
            return
        self._g_state[index] = self.registry.gauge(
            "router_replica_state",
            help="health state code (0=live 1=degraded 2=ejected 3=rejoining "
            "4=draining 5=retired 6=reconnecting)",
            labels={"replica": str(index)},
        )
        self._g_load[index] = self.registry.gauge(
            "router_replica_load",
            help="queued + in-flight requests on this replica",
            labels={"replica": str(index)},
        )

    # ------------------------------------------------------------------ fleet size
    def add_replica(self, why: str = "scale up") -> Replica:
        """Grow the fleet by one replica (a new index, never a reused one —
        journals and chaos targeting stay unambiguous). The engine is built —
        and, for subprocess factories, spawned + warmed — before the replica
        becomes routable, so scale-up traffic never pays a compile."""
        index = len(self.replicas)
        self._ensure_gauges(index)
        replica = Replica(index=index, engine=self._build_engine(index), last_ok=self._clock())
        self.replicas.append(replica)
        self.state_log.append(
            {"t": self._clock(), "replica": index, "from": "new", "to": "live", "why": why}
        )
        self.tracer.event("router.replica_added", category="router", replica=index, why=why)
        logger.info("router: replica %d added (%s)", index, why)
        self._refresh_gauges()
        return replica

    def retire_replica(self, index: int, why: str = "scale down") -> Replica:
        """Remove one replica permanently: its engine closes (a subprocess
        worker exits), the state machine records terminal `retired`, and the
        index is never routed or rejoined again."""
        replica = self.replicas[index]
        if replica.state == "retired":
            return replica
        if not replica.dead:
            try:
                replica.engine.close()
            except Exception:  # noqa: BLE001 — a dying engine must not block retirement
                logger.warning("router: replica %d engine close failed on retire", index)
        replica.dead = True
        self.set_state(replica, "retired", why)
        return replica

    # ------------------------------------------------------------------ build
    def _engine_kwargs_for(self, index: int) -> Dict[str, Any]:
        """Per-replica engine kwargs: a tensor-parallel fleet (`engine_kwargs`
        ``tp=N``) gives each replica its OWN N-device submesh — replica r
        spans devices ``[r*N, (r+1)*N)`` when the topology has that many,
        wrapping around otherwise (`parallel.sharding.serving_tp_mesh`
        resolves the group; CPU smoke meshes oversubscribe harmlessly). A
        mesh-spanning engine is just one replica, so replication over TP
        groups composes with health routing, retries, hedging and rolling
        swaps for free."""
        kwargs = dict(self.engine_kwargs)
        tp = int(kwargs.get("tp", 1) or 1)
        if tp > 1 and kwargs.get("tp_devices") is None:
            kwargs.setdefault("tp_group", index)
        return kwargs

    def _build_engine(self, index: int) -> ContinuousBatcher:
        if self._engine_factory is not None:
            engine = self._engine_factory(index)
        else:
            engine = ContinuousBatcher(
                self.model, tracer=self.tracer, **self._engine_kwargs_for(index)
            )
        if self.current_params is not None:
            engine.params = self.current_params
        # Share ONE params tree across the fleet: a weight_dtype="int8"
        # engine's setter quantizes, and without this rebind every replica
        # would quantize the same raw tree into its OWN int8+scale copy
        # (N x the weight HBM). Adopting the first engine's (possibly
        # quantized) tree makes later setter calls pass-throughs — the
        # setter is idempotent. Subprocess engines keep params worker-side
        # (their getter returns None), so the controller copy stays as-is.
        # Mesh-spanning engines are excluded: their setters re-shard onto
        # their OWN submesh, so adopting one replica's placed tree would
        # just churn device_put round trips through every other group.
        if getattr(engine, "params", None) is not None and getattr(engine, "mesh", None) is None:
            self.current_params = engine.params
        attach = getattr(engine, "attach_telemetry", None)
        if attach is not None:
            # Subprocess proxies report reconnects/frame errors/RTTs into the
            # fleet's shared registry, labeled by replica index, and stitch
            # their serve.reconnect spans into the fleet trace.
            attach(self.registry, tracer=self.tracer, replica=index)
        for hook in self.on_engine_built:
            hook(index, engine)
        return engine

    # ------------------------------------------------------------------ state
    def set_state(self, replica: Replica, state: str, why: str):
        if state not in REPLICA_STATES:
            raise ValueError(f"unknown replica state {state!r}")
        if replica.state == state:
            return
        old = replica.state
        replica.state = state
        now = self._clock()
        self.state_log.append(
            {"t": now, "replica": replica.index, "from": old, "to": state, "why": why}
        )
        self.tracer.event(
            "router.replica_state", category="router",
            replica=replica.index, **{"from": old, "to": state}, why=why,
        )
        logger.info(
            "router: replica %d %s -> %s (%s)", replica.index, old, state, why
        )
        if state == "ejected":
            replica.ejected_at = now
            self._m_ejected.inc()
        if state == "rejoining":
            replica.probation_ok = 0
        if state == "live":
            replica.consecutive_failures = 0
            replica.ejected_at = None
            replica.unhealthy_at = None
        self._refresh_gauges()

    def _refresh_gauges(self):
        self._g_live.set(sum(r.state == "live" for r in self.replicas))
        for r in self.replicas:
            self._g_state[r.index].set(_STATE_CODE[r.state])
            self._g_load[r.index].set(0 if r.dead else r.engine.load)

    # ------------------------------------------------------------------ health
    def record_step(self, replica: Replica, duration_s: float, errored: bool):
        """Fold one driven engine step into the health machine: failures feed
        the consecutive counter (ejecting at the threshold), slow steps degrade,
        clean fast steps heal and advance probation."""
        now = self._clock()
        if errored:
            replica.consecutive_failures += 1
            replica.unhealthy_at = now
            if replica.state == "rejoining":
                self.set_state(replica, "ejected", "failure during rejoin probation")
            elif replica.consecutive_failures >= self.eject_after_failures:
                self.set_state(
                    replica, "ejected",
                    f"{replica.consecutive_failures} consecutive dispatch failures",
                )
            elif replica.state == "live":
                self.set_state(replica, "degraded", "dispatch failure")
            return
        replica.consecutive_failures = 0
        replica.last_ok = now
        slow = self.stall_degrade_s is not None and duration_s > self.stall_degrade_s
        pressured = (
            replica.engine.max_queue is not None
            and replica.engine.queue_depth >= replica.engine.max_queue
        )
        if slow or pressured:
            replica.unhealthy_at = now
            if replica.state == "live":
                self.set_state(
                    replica, "degraded",
                    f"slow step ({duration_s:.3f}s)" if slow else "queue at capacity",
                )
            return
        if replica.state == "degraded" and (
            replica.unhealthy_at is None
            or now - replica.unhealthy_at >= self.degrade_recover_s
        ):
            self.set_state(replica, "live", "healthy again")
        elif replica.state == "rejoining":
            replica.probation_ok += 1
            if replica.probation_ok >= self.probation_steps:
                self.set_state(replica, "live", "probation passed")

    def heartbeat_expired(self, replica: Replica) -> bool:
        """A replica that HAS work but has not finished a step inside the
        heartbeat window is hung (the subprocess-worker seam; in-process
        engines step synchronously and rarely trip this)."""
        if self.heartbeat_timeout_s is None or replica.dead:
            return False
        if not replica.engine.pending:
            replica.last_ok = self._clock()
            return False
        return self._clock() - replica.last_ok > self.heartbeat_timeout_s

    def poll(self):
        """Cooldown sweep: ejected replicas whose cooldown elapsed re-enter as
        `rejoining` (rebuilding the engine first when it died with the fault).
        A FAILED rebuild (a subprocess respawn that never reaches its ready
        handshake, an OOM during engine construction) must not escape into the
        router's step loop — that would crash the whole fleet over one
        replica, the exact blast radius this layer exists to remove. The
        replica stays ejected and retries after another full cooldown."""
        now = self._clock()
        for replica in self.replicas:
            if replica.state != "ejected" or replica.ejected_at is None:
                continue
            if now - replica.ejected_at < self.rejoin_cooldown_s:
                continue
            if replica.dead:
                try:
                    replica.engine = self._build_engine(replica.index)
                except (KeyboardInterrupt, SystemExit):
                    raise
                except BaseException as exc:  # noqa: BLE001 — rebuild failure stays per-replica
                    logger.warning(
                        "router: replica %d rebuild failed (%r); retrying after cooldown",
                        replica.index, exc,
                    )
                    replica.ejected_at = now
                    continue
                replica.dead = False
            self.set_state(replica, "rejoining", "cooldown elapsed")
        self._refresh_gauges()

    # ------------------------------------------------------------------ routing view
    def candidates(self) -> List[Replica]:
        """Routable replicas in preference order: live first, then degraded,
        then rejoining (probation traffic) — least-loaded within each class.
        Ejected and draining replicas are NEVER returned."""
        order = {"live": 0, "degraded": 1, "rejoining": 2}
        routable = [r for r in self.replicas if r.routable and not r.dead]
        return sorted(routable, key=lambda r: (order[r.state], r.engine.load, r.index))


class Router:
    """The replicated serving front-end: same surface as `ContinuousBatcher`,
    N engines behind it. See the module docstring for the full contract.

    Typical driving loop (identical to the single-engine one)::

        router = Router(model, replicas=3, num_slots=8, max_queue=64,
                        default_deadline_s=60.0)
        for r in requests:
            router.submit(r)
        while router.pending:
            for request_id, new_tokens in router.step():
                stream(request_id, new_tokens)
        router.swap_weights(new_model)   # rolling deploy, fleet stays >= N-1
    """

    def __init__(
        self,
        model,
        replicas: Optional[int] = None,
        max_queue: Optional[int] = 64,
        default_deadline_s: Optional[float] = None,
        hedge_after_s: Optional[float] = None,
        hedge_quantile: Optional[float] = None,
        hedge_min_samples: int = 20,
        max_retries: int = 1,
        retry_window_s: float = 5.0,
        tenant_queue_limit: Optional[int] = None,
        min_replicas: Optional[int] = None,
        max_replicas: Optional[int] = None,
        autoscale_queue_high: float = 2.0,
        autoscale_ttft_target_s: Optional[float] = None,
        autoscale_cooldown_s: float = 5.0,
        idle_retire_s: float = 30.0,
        out_of_process: bool = False,
        worker_kwargs: Optional[Dict[str, Any]] = None,
        registry: Optional[MetricsRegistry] = None,
        tracer=None,
        clock: Callable[[], float] = time.perf_counter,
        engine_factory: Optional[Callable[[int], ContinuousBatcher]] = None,
        eject_after_failures: int = 3,
        rejoin_cooldown_s: float = 1.0,
        probation_steps: int = 2,
        stall_degrade_s: Optional[float] = 5.0,
        degrade_recover_s: float = 1.0,
        heartbeat_timeout_s: Optional[float] = 30.0,
        **engine_kwargs,
    ):
        if replicas is not None:
            n = int(replicas)
        elif min_replicas is not None:
            n = int(min_replicas)
        else:
            n = default_replicas()
        self._clock = clock
        self.metrics = registry if registry is not None else MetricsRegistry()
        self.tracer = tracer if tracer is not None else default_tracer()
        self.max_queue = None if max_queue is None else int(max_queue)
        self.default_deadline_s = default_deadline_s
        if hedge_after_s is not None and hedge_quantile is not None:
            raise ValueError(
                "pass hedge_after_s (static threshold) OR hedge_quantile "
                "(derived from the live TTFT histogram), not both"
            )
        if hedge_quantile is not None and not 0.0 < hedge_quantile < 1.0:
            raise ValueError("hedge_quantile must be in (0, 1)")
        self.hedge_after_s = hedge_after_s
        self.hedge_quantile = hedge_quantile
        self.hedge_min_samples = int(hedge_min_samples)
        self.max_retries = int(max_retries)
        self.retry_window_s = float(retry_window_s)
        # Admission control (fair-share, per-tenant): None keeps the legacy
        # fleet-wide QueueFull contract; an int bounds EACH tenant's
        # router-level wait queue so one tenant's burst degrades into bounded
        # queueing for that tenant while the rest keep admitting.
        self.tenant_queue_limit = (
            None if tenant_queue_limit is None else int(tenant_queue_limit)
        )
        if self.tenant_queue_limit is not None and self.tenant_queue_limit < 1:
            raise ValueError("tenant_queue_limit must be >= 1 (or None to disable)")
        self._admission: Dict[str, deque] = {}
        self._admission_rr: List[str] = []  # round-robin order across tenants
        # Autoscaling: enabled when max_replicas is set; the fleet floats in
        # [min_replicas, max_replicas] on queue-depth / TTFT pressure.
        self.min_replicas = n if min_replicas is None else int(min_replicas)
        self.max_replicas = None if max_replicas is None else int(max_replicas)
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas is not None and self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        self.autoscale_queue_high = float(autoscale_queue_high)
        self.autoscale_ttft_target_s = autoscale_ttft_target_s
        self.autoscale_cooldown_s = float(autoscale_cooldown_s)
        self.idle_retire_s = float(idle_retire_s)
        self._last_scale_t: Optional[float] = None
        self._idle_since: Optional[float] = None
        engine_kwargs = dict(engine_kwargs)
        engine_kwargs.setdefault("max_queue", self.max_queue)
        if out_of_process and int(engine_kwargs.get("tp", 1) or 1) > 1:
            # The subprocess factory bypasses ReplicaSet._engine_kwargs_for,
            # so every worker would build its submesh at the default
            # tp_group=0 — all replicas silently sharing one device block.
            # Refuse rather than degrade (multi-host TP workers are ROADMAP
            # item 2); the serve CLI carries the same guard.
            raise ValueError(
                "tp > 1 composes with in-process replicas only for now: "
                "subprocess workers pin their own device view, so an "
                "out-of-process TP fleet would stack every replica on the "
                "same device block — pass out_of_process=False"
            )
        if out_of_process and engine_factory is None:
            from .worker import make_subprocess_factory

            engine_factory = make_subprocess_factory(
                model, engine_kwargs=engine_kwargs, **(worker_kwargs or {})
            )
        self.replica_set = ReplicaSet(
            model,
            n,
            engine_kwargs=engine_kwargs,
            engine_factory=engine_factory,
            registry=self.metrics,
            tracer=self.tracer,
            clock=clock,
            eject_after_failures=eject_after_failures,
            rejoin_cooldown_s=rejoin_cooldown_s,
            probation_steps=probation_steps,
            stall_degrade_s=stall_degrade_s,
            degrade_recover_s=degrade_recover_s,
            heartbeat_timeout_s=heartbeat_timeout_s,
        )
        self.results: Dict[int, RequestResult] = {}
        #: request_id -> tracking record (attempts, stream state, span).
        self._tracked: Dict[int, Dict[str, Any]] = {}
        #: engine-level id -> (request_id, attempt dict); engine ids are
        #: globally unique across replicas so retries/hedges never collide.
        self._engine_map: Dict[int, Tuple[int, Dict[str, Any]]] = {}
        self._next_engine_id = 0
        self._retry_queue: deque = deque()
        self._no_capacity_since: Optional[float] = None
        self._closed = False
        self._draining = False
        #: Pending rolling swap: {"params", "queue": [indices], "active": idx}.
        self._swap: Optional[Dict[str, Any]] = None
        #: Every routing decision: {"t", "request_id", "replica", "kind",
        #: "state"} — the chaos no-route-to-ejected invariant audits this.
        #: Bounded (newest-kept ring, like the flight recorder) so a
        #: long-running fleet's journal cannot grow host memory without limit.
        self.routing_log: deque = deque(maxlen=10_000)

        self._m_requests = self.metrics.counter(
            "router_requests_total", help="requests accepted by the router"
        )
        self._m_retries = self.metrics.counter(
            "router_retries_total",
            help="never-streamed requests re-dispatched after a replica failure",
        )
        self._m_hedges = self.metrics.counter(
            "router_hedges_total", help="TTFT hedge copies dispatched"
        )
        self._m_hedge_wins = self.metrics.counter(
            "router_hedge_wins_total", help="requests whose hedge copy streamed first"
        )
        self._m_finish = {
            reason: self.metrics.counter(
                "router_requests_finished_total",
                help="router-level terminal finish reasons",
                labels={"reason": reason},
            )
            for reason in ROUTER_FINISH_REASONS
        }
        # Router-level TTFT: submit() -> first forwarded token, fleet-wide.
        # This is the histogram hedge_quantile and the autoscaler's TTFT signal
        # read — it works identically for in-process and subprocess fleets
        # (engine-side serving_ttft histograms live in each engine's registry).
        self._m_ttft = self.metrics.histogram(
            "serving_ttft_seconds",
            help="router submit() -> first streamed token (host wall clock)",
        )
        self._m_scale_up = self.metrics.counter(
            "router_scale_up_total", help="autoscaler replica additions"
        )
        self._m_scale_down = self.metrics.counter(
            "router_scale_down_total", help="autoscaler replica retirements"
        )
        self._g_replicas = self.metrics.gauge(
            "router_replicas_total", help="replicas not retired (fleet size)"
        )
        self._g_admission = self.metrics.gauge(
            "router_admission_queue_depth",
            help="requests waiting in router-level tenant admission queues",
        )
        self._m_admission_rejected: Dict[str, Any] = {}
        self._g_replicas.set(self.num_replicas)

    # ------------------------------------------------------------------ views
    @property
    def num_replicas(self) -> int:
        return len(self.replica_set.replicas)

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def pending(self) -> bool:
        return any(not t["result"].finished for t in self._tracked.values())

    @property
    def swap_in_progress(self) -> bool:
        return self._swap is not None

    @property
    def active_replicas(self) -> int:
        """Replicas that are part of the fleet (not autoscaler-retired)."""
        return sum(r.state != "retired" for r in self.replica_set.replicas)

    @property
    def replica_states(self) -> Dict[int, str]:
        return {r.index: r.state for r in self.replica_set.replicas}

    @property
    def stats(self) -> Dict[str, Any]:
        view = {
            "replicas": self.num_replicas,
            "active_replicas": self.active_replicas,
            "replica_states": self.replica_states,
            "retries": int(self._m_retries.value),
            "ejected": int(self.replica_set._m_ejected.value),
            "hedges": int(self._m_hedges.value),
            "hedge_wins": int(self._m_hedge_wins.value),
            "hedge_threshold_s": self.hedge_threshold(),
            "finish_reasons": {
                reason: int(counter.value) for reason, counter in self._m_finish.items()
            },
            "per_replica": [
                None if r.dead else r.engine.stats for r in self.replica_set.replicas
            ],
        }
        if self.max_replicas is not None:
            view["autoscale"] = {
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "scale_ups": int(self._m_scale_up.value),
                "scale_downs": int(self._m_scale_down.value),
            }
        if self.tenant_queue_limit is not None:
            view["admission"] = {
                "tenant_queue_limit": self.tenant_queue_limit,
                "queued": {t: len(q) for t, q in self._admission.items() if q},
                "rejected": {
                    t: int(c.value) for t, c in self._m_admission_rejected.items()
                },
            }
        return view

    def warm_inserts(self) -> Dict[int, List[int]]:
        """Precompile every replica's insert-bucket ladder (the bench's
        mechanical 0-recompile guarantee, fleet edition)."""
        return {
            r.index: r.engine.warm_inserts()
            for r in self.replica_set.replicas
            if not r.dead
        }

    # ------------------------------------------------------------------ submit
    def submit(self, request: Request) -> int:
        """Route + enqueue on the least-loaded routable replica. Same caller
        contract as the engine: `ValueError` for malformed requests,
        `QueueFull` when EVERY routable replica's bounded queue is at capacity,
        `EngineClosed` after `close()`/mid-`drain()`."""
        if self._closed:
            raise EngineClosed("router is closed")
        if self._draining:
            raise EngineClosed("router is draining; resubmit after drain() returns")
        if request.request_id in self.results:
            raise ValueError(f"duplicate request_id {request.request_id}")
        ids = np.asarray(request.input_ids, np.int32).reshape(-1)
        deadline_s = request.deadline_s
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        now = self._clock()
        tracked: Dict[str, Any] = {
            "request": dataclasses.replace(request, input_ids=ids, deadline_s=deadline_s),
            "result": RequestResult(request.request_id, arrival_time=request.arrival_time),
            "attempts": [],
            "winner": None,  # engine_id of the attempt whose tokens we forward
            "retries": 0,
            "hedged": False,
            "submit_t": now,
            "deadline_at": None if deadline_s is None else now + float(deadline_s),
            "span": None,
        }
        span = self.tracer.start_span(
            "serve.route", category="router",
            request_id=int(request.request_id), replicas=self.num_replicas,
        )
        tracked["span"] = span
        # With admission control armed, a new request may not jump ahead of
        # tenants already queued at the router: it enqueues behind them and the
        # sweep dispatches in priority/fair-share order.
        queued_behind = self.tenant_queue_limit is not None and any(
            self._admission.values()
        )
        try:
            attempt = None if queued_behind else self._dispatch(tracked, kind="submit")
        except ValueError:
            span.annotate(error="invalid_request").end()
            raise
        if attempt is None:
            if self.tenant_queue_limit is None:
                span.annotate(error="queue_full").end()
                raise QueueFull(
                    "every routable replica's queue is at capacity; shed load or retry later"
                )
            # Admission control: the fleet is saturated — queue at the ROUTER
            # in this tenant's bounded fair-share queue instead of failing the
            # whole fleet closed. Only this tenant's own bound rejects.
            tenant = request.tenant or "default"
            queue = self._admission.get(tenant)
            if queue is None:
                queue = self._admission[tenant] = deque()
                self._admission_rr.append(tenant)
            if len(queue) >= self.tenant_queue_limit:
                self._admission_rejected(tenant).inc()
                span.annotate(error="queue_full", tenant=tenant).end()
                raise QueueFull(
                    f"tenant {tenant!r} admission queue is at "
                    f"tenant_queue_limit={self.tenant_queue_limit}; shed load or retry later"
                )
            queue.append(request.request_id)
            span.event("admission_queued", tenant=tenant, depth=len(queue))
            self._g_admission.set(sum(len(q) for q in self._admission.values()))
        self.results[request.request_id] = tracked["result"]
        self._tracked[request.request_id] = tracked
        self._m_requests.inc()
        return request.request_id

    def _admission_rejected(self, tenant: str):
        counter = self._m_admission_rejected.get(tenant)
        if counter is None:
            counter = self._m_admission_rejected[tenant] = self.metrics.counter(
                "router_admission_rejected_total",
                help="requests rejected at a tenant's bounded admission queue",
                labels={"tenant": tenant},
            )
        return counter

    def _admission_sweep(self):
        """Drain the per-tenant admission queues into replica capacity:
        strict priority first (a tenant whose head request carries a higher
        `priority` dispatches before lower ones), round-robin across tenants
        at equal priority (fair share — no tenant starves another at its own
        priority level). Expired queued requests finish `timeout`."""
        if not self._admission:
            return
        progressed = True
        while progressed:
            progressed = False
            heads: List[Tuple[int, int, str]] = []
            for rr_pos, tenant in enumerate(self._admission_rr):
                queue = self._admission.get(tenant)
                while queue:
                    tracked = self._tracked.get(queue[0])
                    if tracked is None or tracked["result"].finished:
                        queue.popleft()  # cancelled / finished while queued
                        continue
                    now = self._clock()
                    deadline_at = tracked["deadline_at"]
                    if deadline_at is not None and now >= deadline_at:
                        self._finish(tracked, "timeout")
                        queue.popleft()
                        continue
                    heads.append((-int(tracked["request"].priority), rr_pos, tenant))
                    break
            for _neg_priority, _rr_pos, tenant in sorted(heads):
                queue = self._admission[tenant]
                if not queue:
                    continue
                tracked = self._tracked.get(queue[0])
                if tracked is None:
                    queue.popleft()
                    continue
                attempt = self._dispatch(tracked, kind="admit")
                if attempt is None:
                    continue  # no capacity for this one; try other tenants
                queue.popleft()
                # Fair share: a tenant that just dispatched goes to the back
                # of the round-robin order.
                self._admission_rr.remove(tenant)
                self._admission_rr.append(tenant)
                progressed = True
        self._g_admission.set(sum(len(q) for q in self._admission.values()))

    def _dispatch(self, tracked: Dict[str, Any], kind: str) -> Optional[Dict[str, Any]]:
        """Place one attempt of `tracked` on the best routable replica (skipping
        replicas that already host an attempt). Returns the attempt record, or
        None when no replica could take it. `ValueError` from engine validation
        propagates (the caller's bug, reported synchronously, like the engine)."""
        exclude = {a["replica"] for a in tracked["attempts"] if not a["done"]}
        if kind == "retry":
            # Do not retry onto the replica that just failed the request.
            exclude |= {a["replica"] for a in tracked["attempts"]}
        request = tracked["request"]
        now = self._clock()
        deadline_at = tracked["deadline_at"]
        remaining = None if deadline_at is None else max(deadline_at - now, 0.0)
        for replica in self.replica_set.candidates():
            if replica.index in exclude:
                continue
            engine_id = self._next_engine_id
            engine_request = dataclasses.replace(
                request, request_id=engine_id, deadline_s=remaining
            )
            try:
                replica.engine.submit(engine_request)
            except QueueFull:
                continue
            except EngineClosed:
                continue
            self._next_engine_id += 1
            attempt = {"replica": replica.index, "engine_id": engine_id,
                       "kind": kind, "done": False}
            tracked["attempts"].append(attempt)
            self._engine_map[engine_id] = (request.request_id, attempt)
            self.routing_log.append({
                "t": now, "request_id": request.request_id,
                "replica": replica.index, "kind": kind, "state": replica.state,
            })
            span = tracked["span"]
            if span is not None:
                span.event(kind, replica=replica.index, engine_id=engine_id)
            self.replica_set._refresh_gauges()
            return attempt
        return None

    # ------------------------------------------------------------------ cancel / release
    def cancel(self, request_id: int) -> bool:
        """Cancel a queued or in-flight request on whichever replica(s) own it:
        the result finishes `cancelled` with partial tokens kept — the same
        terminal contract as the single-engine path. Returns False when already
        finished; raises KeyError for an unknown id."""
        tracked = self._tracked[request_id]
        if tracked["result"].finished:
            return False
        self._finish(tracked, "cancelled")
        return True

    def release(self, request_id: int) -> RequestResult:
        """Drop a FINISHED request's result (host-memory hygiene, engine
        contract)."""
        result = self.results[request_id]
        if not result.finished:
            raise ValueError(f"request {request_id} is still in flight")
        del self.results[request_id]
        self._tracked.pop(request_id, None)
        return result

    def _abandon_attempt(self, attempt: Dict[str, Any]):
        """Cancel one engine-level attempt and drop its mapping (router-initiated:
        the engine's `cancelled` result must never resurface as ours)."""
        if attempt["done"]:
            return
        attempt["done"] = True
        self._engine_map.pop(attempt["engine_id"], None)
        replica = self.replica_set.replicas[attempt["replica"]]
        if replica.dead:
            return
        try:
            replica.engine.cancel(attempt["engine_id"])
            replica.engine.release(attempt["engine_id"])
        except (KeyError, ValueError):
            pass

    def _finish(self, tracked: Dict[str, Any], reason: str, error: Optional[str] = None):
        for attempt in tracked["attempts"]:
            self._abandon_attempt(attempt)
        result = tracked["result"]
        if result.finished:
            return
        result.finished = True
        result.finish_time = self._clock()
        result.finish_reason = reason
        if error is not None:
            result.error = error
        self._m_finish[reason].inc()
        span = tracked["span"]
        if span is not None:
            span.annotate(finish_reason=reason, tokens=len(result.tokens),
                          retries=tracked["retries"])
            if error is not None:
                span.annotate(error=error)
            span.end()

    # ------------------------------------------------------------------ failure handling
    def _handle_attempt_failure(self, tracked: Dict[str, Any], attempt: Dict[str, Any],
                                error: str):
        """The safe re-dispatch rule: a request that already streamed tokens
        surfaces `replica_lost` (tokens kept, never duplicated); a never-
        streamed one retries on another replica inside its retry budget."""
        attempt["done"] = True
        self._engine_map.pop(attempt["engine_id"], None)
        result = tracked["result"]
        if result.finished:
            return
        if any(not a["done"] for a in tracked["attempts"]):
            return  # a hedge copy is still running; it carries the request
        if result.tokens:
            self._finish(tracked, "replica_lost", error=error)
            return
        if tracked["retries"] >= self.max_retries:
            self._finish(tracked, "error", error=error)
            return
        tracked["retries"] += 1
        self._retry_queue.append(tracked["request"].request_id)

    def fail_replica(self, index: int, reason: str = "killed", dead: bool = True):
        """Handle an observed replica failure (the chaos / ops seam; also what
        `step()` calls when an engine dies under it). Every request with an
        attempt on the replica goes through the re-dispatch rule; the replica
        is ejected and — when `dead` — its engine is rebuilt on rejoin."""
        replica = self.replica_set.replicas[index]
        victims = [
            (rid, attempt) for eid, (rid, attempt) in list(self._engine_map.items())
            if attempt["replica"] == index and not attempt["done"]
        ]
        for rid, attempt in victims:
            if not dead and not replica.dead:
                # Engine is still healthy (soft kill): free its slot/queue entry.
                try:
                    replica.engine.cancel(attempt["engine_id"])
                    replica.engine.release(attempt["engine_id"])
                except (KeyError, ValueError):
                    pass
            tracked = self._tracked.get(rid)
            if tracked is not None:
                self._handle_attempt_failure(tracked, attempt, error=f"replica {index} {reason}")
        if dead and not replica.dead:
            # The engine is being written off for a rebuild: tear the old one
            # down NOW. An out-of-process worker that failed via error replies
            # still has a live process — left to the garbage collector it
            # would linger holding device memory next to its replacement.
            terminate = getattr(replica.engine, "terminate", None)
            try:
                if terminate is not None:
                    terminate()
                else:
                    replica.engine.close()
            except Exception:  # noqa: BLE001 — teardown of a failed engine is best-effort
                logger.warning("router: replica %d engine teardown failed on eject", index)
        replica.dead = replica.dead or bool(dead)
        self.replica_set.set_state(replica, "ejected", reason)

    # ------------------------------------------------------------------ hedging
    def hedge_threshold(self) -> Optional[float]:
        """The live hedge trigger in seconds, or None when hedging is off.
        Static `hedge_after_s` wins when set; otherwise `hedge_quantile` reads
        the router's own `serving_ttft_seconds` histogram — hedging stays
        DISABLED until `hedge_min_samples` observations exist, so a cold fleet
        never hedges off noise (and a stale hand-tuned constant never fires
        at yesterday's latency)."""
        if self.hedge_after_s is not None:
            return self.hedge_after_s
        if self.hedge_quantile is None:
            return None
        if self._m_ttft.count < self.hedge_min_samples:
            return None
        return self._m_ttft.quantile(self.hedge_quantile)

    def _hedge_sweep(self):
        threshold = self.hedge_threshold()
        if threshold is None:
            return
        now = self._clock()
        for tracked in self._tracked.values():
            result = tracked["result"]
            if result.finished or result.tokens or tracked["hedged"]:
                continue
            if now - tracked["submit_t"] < threshold:
                continue
            if sum(not a["done"] for a in tracked["attempts"]) != 1:
                continue
            attempt = self._dispatch(tracked, kind="hedge")
            if attempt is not None:
                tracked["hedged"] = True
                self._m_hedges.inc()

    # ------------------------------------------------------------------ retries
    def _retry_sweep(self):
        if not self._retry_queue:
            self._no_capacity_since = None
            return
        pending = len(self._retry_queue)
        for _ in range(pending):
            rid = self._retry_queue.popleft()
            tracked = self._tracked.get(rid)
            if tracked is None or tracked["result"].finished:
                continue
            deadline_at = tracked["deadline_at"]
            now = self._clock()
            if deadline_at is not None and now >= deadline_at:
                self._finish(tracked, "timeout")
                continue
            attempt = self._dispatch(tracked, kind="retry")
            if attempt is None:
                self._retry_queue.append(rid)
            else:
                # Counted at DISPATCH (not at queue time) so the counter and
                # the routing journal's `retry` entries reconcile exactly.
                self._m_retries.inc()
        if self._retry_queue:
            now = self._clock()
            if self._no_capacity_since is None:
                self._no_capacity_since = now
            elif now - self._no_capacity_since > self.retry_window_s:
                # The whole fleet has been unroutable for the retry window:
                # surface the loss instead of queueing invisibly forever.
                while self._retry_queue:
                    tracked = self._tracked.get(self._retry_queue.popleft())
                    if tracked is not None and not tracked["result"].finished:
                        self._finish(tracked, "error", error="no routable replica")
        else:
            self._no_capacity_since = None

    # ------------------------------------------------------------------ autoscaling
    def _fleet_queue_depth(self) -> int:
        depth = len(self._retry_queue) + sum(len(q) for q in self._admission.values())
        for replica in self.replica_set.replicas:
            if not replica.dead and replica.state != "retired":
                depth += replica.engine.queue_depth
        return depth

    def _autoscale_sweep(self):
        """Traffic-adaptive fleet sizing inside [min_replicas, max_replicas]:
        scale UP on queue-depth pressure (fleet queue depth per routable
        replica >= `autoscale_queue_high`) or — when `autoscale_ttft_target_s`
        is set — on the live TTFT histogram's p99 exceeding the target; scale
        DOWN by retiring one replica after the fleet has been fully idle for
        `idle_retire_s`. One action per `autoscale_cooldown_s`, journaled on
        the state log like every other transition."""
        if self.max_replicas is None:
            return
        now = self._clock()
        active = [r for r in self.replica_set.replicas if r.state != "retired"]
        routable = [r for r in active if r.routable and not r.dead]
        queue_depth = self._fleet_queue_depth()
        pressure = queue_depth >= self.autoscale_queue_high * max(len(routable), 1)
        if not pressure and self.autoscale_ttft_target_s is not None:
            if self._m_ttft.count >= self.hedge_min_samples:
                p99 = self._m_ttft.quantile(0.99)
                pressure = p99 is not None and p99 > self.autoscale_ttft_target_s
        cooled = (
            self._last_scale_t is None
            or now - self._last_scale_t >= self.autoscale_cooldown_s
        )
        if pressure:
            self._idle_since = None
            if len(active) < self.max_replicas and cooled:
                # NOTE: the build is synchronous — an out-of-process spawn
                # blocks this step for the worker's cold start (it comes up
                # WARM in exchange). The cooldown bounds how often that cost
                # can recur; a failed spawn backs off the same way instead of
                # crashing the serving loop.
                try:
                    self.replica_set.add_replica(
                        why=f"autoscale up: fleet queue depth {queue_depth}"
                    )
                except (KeyboardInterrupt, SystemExit):
                    raise
                except BaseException as exc:  # noqa: BLE001 — spawn failure must not kill serving
                    logger.warning("router: autoscale spawn failed (%r); backing off", exc)
                    self._last_scale_t = now
                    return
                self._last_scale_t = now
                self._m_scale_up.inc()
                self._g_replicas.set(self.active_replicas)
            return
        load = sum(
            r.engine.load for r in active if not r.dead
        )
        if queue_depth == 0 and load == 0:
            if self._idle_since is None:
                self._idle_since = now
            elif (
                now - self._idle_since >= self.idle_retire_s
                and len(active) > self.min_replicas
                and cooled
            ):
                # Retire the NEWEST idle live replica: scale-down unwinds
                # scale-up, and the original fleet keeps its indices.
                victim = next(
                    (r for r in reversed(active)
                     if r.state == "live" and not r.engine.pending),
                    None,
                )
                if victim is not None:
                    self.replica_set.retire_replica(
                        victim.index, why="autoscale down: fleet idle"
                    )
                    self._last_scale_t = now
                    self._idle_since = now  # next retirement waits a full window
                    self._m_scale_down.inc()
                    self._g_replicas.set(self.active_replicas)
        else:
            self._idle_since = None

    # ------------------------------------------------------------------ swap
    def swap_weights(self, params_or_model, wait: bool = True) -> List[Tuple[int, List[int]]]:
        """Rolling weight swap: one replica at a time drains (unroutable,
        finishing its own work while the rest serve), gets the new params
        applied in place (per-dispatch operands — no recompile), and rejoins
        before the next drains; the fleet never drops below N-1 routable.

        `wait=True` (default) drives `step()` until the swap completes and
        returns the stream events those steps produced (nothing is dropped);
        `wait=False` just arms the swap — the caller's own `step()` loop
        advances it.

        Pass RAW (unquantized) params for any fleet: engines built with
        `weight_dtype="int8"` (riding `engine_kwargs`) re-quantize in their
        `params` setter — per-output-channel scales are recomputed at swap
        time, exactly as at load time (subprocess workers do the same in
        their `set_params` op after the file handoff)."""
        if self._closed:
            raise EngineClosed("router is closed")
        if self._swap is not None:
            raise RuntimeError("a weight swap is already in progress")
        params = _normalize_params(params_or_model)
        self._swap = {
            "params": params,
            "queue": deque(r.index for r in self.replica_set.replicas),
            "active": None,
        }
        events: List[Tuple[int, List[int]]] = []
        if wait:
            while self._swap is not None:
                events.extend(self.step())
        return events

    def _advance_swap(self):
        swap = self._swap
        if swap is None:
            return
        if swap["active"] is None:
            if not swap["queue"]:
                self.replica_set.current_params = swap["params"]
                self.tracer.event("router.swap_complete", category="router")
                self._swap = None
                return
            index = swap["queue"].popleft()
            replica = self.replica_set.replicas[index]
            if replica.dead or replica.state == "ejected":
                # A dead/ejected replica gets the new params via the rebuild
                # path on rejoin — nothing to drain.
                self.replica_set.current_params = swap["params"]
                return self._advance_swap()
            swap["active"] = index
            self.replica_set.set_state(replica, "draining", "rolling weight swap")
            return
        replica = self.replica_set.replicas[swap["active"]]
        if replica.dead or replica.state == "ejected":
            # The draining replica failed mid-swap: it will pick the new
            # params up through the rebuild/rejoin path instead.
            self.replica_set.current_params = swap["params"]
            swap["active"] = None
            return self._advance_swap()
        if not replica.engine.pending:
            replica.engine.params = swap["params"]
            # One quantize per swap, not per replica: adopt the first
            # swapped engine's (possibly quantized) tree so the remaining
            # replicas' setters share it by reference (idempotent setter;
            # subprocess engines expose no params and keep the raw tree;
            # mesh-spanning engines keep the raw tree too — each TP group
            # re-shards onto its own submesh at its setter).
            if (
                getattr(replica.engine, "params", None) is not None
                and getattr(replica.engine, "mesh", None) is None
            ):
                swap["params"] = replica.engine.params
            self.replica_set.set_state(replica, "live", "weights swapped")
            self.tracer.event(
                "router.replica_swapped", category="router", replica=replica.index
            )
            swap["active"] = None
            self._advance_swap()

    # ------------------------------------------------------------------ step
    def step(self) -> List[Tuple[int, List[int]]]:
        """One fleet cycle: advance swaps/cooldowns, re-dispatch retries, hedge
        stale queued requests, drive every replica's engine one step, forward
        the winning attempts' tokens, and fold failures through the health
        machine. Returns `(request_id, new_tokens)` in stream order, exactly
        like the engine."""
        if self._closed:
            return []
        self.replica_set.poll()
        self._advance_swap()
        self._autoscale_sweep()
        self._admission_sweep()
        self._retry_sweep()
        self._hedge_sweep()
        events: List[Tuple[int, List[int]]] = []
        for replica in self.replica_set.replicas:
            if replica.dead or replica.state in ("ejected", "retired"):
                continue
            if (
                not replica.engine.pending
                and not getattr(replica.engine, "reconnecting", False)
                and replica.state not in ("rejoining", "degraded", "reconnecting")
            ):
                # (reconnecting must still be stepped even when idle: step()
                # is what drives the engine proxy's reconnect attempts. The
                # engine attribute is checked too — an idle engine can tear
                # during a failed submit, before the router state catches up.)
                replica.last_ok = self._clock()
                continue
            t0 = self._clock()
            try:
                engine_events = replica.engine.step()
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as exc:  # noqa: BLE001 — a dead engine must not kill the fleet
                # An exception ESCAPING the engine (its own fault isolation
                # swallows ordinary dispatch errors) is replica death — the
                # in-process analogue of a serving worker SIGKILL.
                logger.warning("router: replica %d died in step(): %r", replica.index, exc)
                self.fail_replica(replica.index, reason=f"engine died: {exc!r}", dead=True)
                continue
            events.extend(self._forward_events(replica, engine_events))
            if getattr(replica.engine, "reconnecting", False):
                # Transport fault, not death: park the replica unroutable and
                # keep stepping it (each step drives one reconnect attempt).
                # The health machine is bypassed — a reconnect in progress is
                # neither a dispatch failure nor a hang — and budget
                # exhaustion surfaces as WorkerGone from step() above,
                # escalating through the ordinary fail_replica path.
                self.replica_set.set_state(
                    replica, "reconnecting", "transport tore — reconnect in progress"
                )
                replica.last_ok = self._clock()
                continue
            if replica.state == "reconnecting":
                self.replica_set.set_state(replica, "live", "transport reconnected")
            errored = self._collect_finished(replica)
            self.replica_set.record_step(replica, self._clock() - t0, errored)
            if self.replica_set.heartbeat_expired(replica):
                self.fail_replica(
                    replica.index, reason="heartbeat expired (hung engine)", dead=True
                )
        self.replica_set._refresh_gauges()
        return events

    def _forward_events(self, replica: Replica,
                        engine_events: List[Tuple[int, List[int]]]) -> List[Tuple[int, List[int]]]:
        out: List[Tuple[int, List[int]]] = []
        for engine_id, toks in engine_events:
            mapped = self._engine_map.get(engine_id)
            if mapped is None or not toks:
                continue
            rid, attempt = mapped
            tracked = self._tracked.get(rid)
            if tracked is None or tracked["result"].finished:
                continue
            if tracked["winner"] is None:
                tracked["winner"] = engine_id
                if attempt["kind"] == "hedge":
                    self._m_hedge_wins.inc()
                # First token decided the race: cancel every other copy so the
                # loser can never stream a duplicate.
                for other in tracked["attempts"]:
                    if other is not attempt:
                        self._abandon_attempt(other)
                span = tracked["span"]
                if span is not None:
                    span.event("first_token", replica=replica.index,
                               hedge=attempt["kind"] == "hedge")
            if tracked["winner"] != engine_id:
                continue  # a losing copy raced a token out before its cancel
            tracked["result"].tokens.extend(toks)
            if tracked["result"].first_token_time is None:
                now = self._clock()
                tracked["result"].first_token_time = now
                # The live TTFT signal hedge_quantile and the autoscaler read.
                self._m_ttft.observe(max(now - tracked["submit_t"], 0.0))
            out.append((rid, list(toks)))
        return out

    def _collect_finished(self, replica: Replica) -> bool:
        """Scan the replica's finished engine results, map them to router
        outcomes, and release them from the engine. Returns True when any
        attempt failed at the replica level this step (feeds the health
        machine's consecutive-failure counter)."""
        errored = False
        finished = [
            (eid, res) for eid, res in replica.engine.results.items() if res.finished
        ]
        for engine_id, res in finished:
            mapped = self._engine_map.get(engine_id)
            if mapped is None:
                # A copy we already abandoned (hedge loser / router cancel).
                try:
                    replica.engine.release(engine_id)
                except (KeyError, ValueError):
                    pass
                continue
            rid, attempt = mapped
            tracked = self._tracked.get(rid)
            replica.engine.release(engine_id)
            if tracked is None or tracked["result"].finished:
                attempt["done"] = True
                self._engine_map.pop(engine_id, None)
                continue
            reason = res.finish_reason
            if reason == "error":
                errored = True
                self._handle_attempt_failure(tracked, attempt, error=res.error or "error")
                continue
            attempt["done"] = True
            self._engine_map.pop(engine_id, None)
            if tracked["winner"] not in (None, engine_id):
                continue  # the losing copy of a hedge finished; winner carries on
            # Forward any tokens the engine finished with that we have not
            # streamed yet (first-token-at-insert of a winning copy whose
            # terminal landed in the same engine step).
            if len(res.tokens) > len(tracked["result"].tokens) and reason in ("eos", "length"):
                missing = res.tokens[len(tracked["result"].tokens):]
                tracked["result"].tokens.extend(missing)
            self._finish(tracked, reason, error=res.error)
        return errored

    # ------------------------------------------------------------------ drive / lifecycle
    def run(self, requests: Optional[List[Request]] = None) -> Dict[int, np.ndarray]:
        for req in requests or ():
            self.submit(req)
        while self.pending:
            self.step()
        return {rid: np.asarray(r.tokens, np.int32) for rid, r in self.results.items()}

    def drain(self) -> Dict[int, RequestResult]:
        """Flush: refuse new submissions while finishing everything in flight
        across the fleet, then reopen."""
        self._draining = True
        try:
            while self.pending:
                self.step()
        finally:
            self._draining = False
        return self.results

    def close(self) -> Dict[int, RequestResult]:
        """Terminal shutdown: unfinished requests finish `cancelled` (partial
        tokens kept), every engine closes, the router refuses new work."""
        if self._closed:
            return self.results
        for tracked in self._tracked.values():
            if not tracked["result"].finished:
                self._finish(tracked, "cancelled")
        for replica in self.replica_set.replicas:
            if not replica.dead:
                replica.engine.close()
        self._closed = True
        return self.results

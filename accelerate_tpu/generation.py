"""KV-cached autoregressive generation — the serving path behind the reference's
big-model-inference benchmark (benchmarks/big_model_inference.py: model load time +
per-token generation latency are the published numbers, benchmarks/README.md:27-37).

TPU design: one compiled prefill (writes the whole prompt into the KV cache and
returns first-token logits — the TTFT program) plus one compiled decode step
([B, 1] token → logits, cache written in place via donation, so the cache never
round-trips HBM↔host). The cache lives in the flax "cache" collection
(models/llama.py LlamaAttention decode path) with static capacity
`prompt_len + max_new_tokens` — static shapes keep both programs cached in the
compilation cache across calls.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp


@dataclass
class GenerationConfig:
    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0  # 0 = full vocab
    eos_token_id: Optional[int] = None
    pad_token_id: Optional[int] = None  # fill for finished rows; defaults to eos


def _sample(logits, config: GenerationConfig, rng):
    """[B, V] logits -> [B] token ids."""
    if not config.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), rng
    logits = logits.astype(jnp.float32) / jnp.maximum(config.temperature, 1e-6)
    if config.top_k:
        kth = jax.lax.top_k(logits, config.top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    rng, sub = jax.random.split(rng)
    return jax.random.categorical(sub, logits, axis=-1).astype(jnp.int32), rng


class Generator:
    """Compiled prefill + decode-step pair for a causal-LM Model bundle.

    Reusable across prompts of the same (batch, prompt_len) shape; per-token decode is
    shape-stable for any prompt length up to the cache capacity.
    """

    def __init__(self, model, max_new_tokens: int = 32, max_length: Optional[int] = None):
        if getattr(model, "module", None) is None or not hasattr(model.module, "config"):
            raise ValueError("generate() needs a Model bundle built from an in-tree flax module")
        self.base_config = model.module.config
        self.params = model.params
        self.max_new_tokens = max_new_tokens
        self.max_length = max_length or self.base_config.max_position_embeddings
        decode_cfg = dataclasses.replace(self.base_config, decode_cache_length=self.max_length)
        self.decode_module = type(model.module)(decode_cfg)

        module = self.decode_module

        def prefill(params, input_ids, positions):
            logits, mutated = module.apply(
                params, input_ids, None, positions, mutable=["cache"]
            )
            return logits[:, -1, :], mutated["cache"]

        def step(params, cache, token, position):
            logits, mutated = module.apply(
                {**params, "cache": cache}, token[:, None], None, position[:, None], mutable=["cache"]
            )
            return logits[:, -1, :], mutated["cache"]

        self._prefill = jax.jit(prefill)
        self._step = jax.jit(step, donate_argnums=(1,))

    def __call__(self, input_ids, generation_config: Optional[GenerationConfig] = None, rng=None, **kwargs):
        config = generation_config or GenerationConfig(**kwargs)
        if rng is None:
            rng = jax.random.key(0)
        input_ids = jnp.asarray(input_ids, jnp.int32)
        b, prompt_len = input_ids.shape
        max_new = min(config.max_new_tokens, self.max_length - prompt_len)
        if max_new <= 0:
            raise ValueError(
                f"Prompt length {prompt_len} leaves no room in the {self.max_length}-token cache"
            )
        positions = jnp.broadcast_to(jnp.arange(prompt_len)[None, :], (b, prompt_len))
        params = self.params if "params" in self.params else {"params": self.params}
        logits, cache = self._prefill(params, input_ids, positions)

        tokens = []
        token, rng = _sample(logits, config, rng)
        tokens.append(token)
        finished = np.zeros(b, dtype=bool)
        pad_id = config.pad_token_id if config.pad_token_id is not None else config.eos_token_id
        for i in range(1, max_new):
            if config.eos_token_id is not None:
                finished |= np.asarray(tokens[-1]) == config.eos_token_id
                if finished.all():
                    break
            position = jnp.full((b,), prompt_len + i - 1, jnp.int32)
            logits, cache = self._step(params, cache, tokens[-1], position)
            token, rng = _sample(logits, config, rng)
            if config.eos_token_id is not None and finished.any():
                # Rows past their EOS emit pad/eos, matching HF generate's padding.
                token = jnp.where(jnp.asarray(finished), jnp.int32(pad_id), token)
            tokens.append(token)
        generated = jnp.stack(tokens, axis=1)
        return jnp.concatenate([input_ids, generated], axis=1)


def generate(model, input_ids, max_new_tokens: int = 32, **kwargs):
    """One-shot convenience: build a Generator and run it (HF `model.generate` shape)."""
    gen_kwargs = {
        k: kwargs.pop(k)
        for k in ("do_sample", "temperature", "top_k", "eos_token_id", "pad_token_id")
        if k in kwargs
    }
    generator = Generator(model, max_new_tokens=max_new_tokens, **kwargs)
    return generator(input_ids, GenerationConfig(max_new_tokens=max_new_tokens, **gen_kwargs))

"""KV-cached autoregressive generation — the serving path behind the reference's
big-model-inference benchmark (benchmarks/big_model_inference.py: model load time +
per-token generation latency are the published numbers, benchmarks/README.md:27-37).

TPU design: one compiled prefill (writes the whole prompt into the KV cache and
returns first-token logits — the TTFT program) plus ONE compiled decode LOOP
(`lax.while_loop` carrying the cache, token, rng, and finished mask) that runs
sampling, EOS masking, and early exit entirely on device. A per-token Python loop
would pay a host round-trip per token — measured 71 ms/token over a tunneled v5e
vs 3.1 ms/token fused. The loop's token-count bound is a traced scalar inside a
power-of-two-bucketed buffer, so prompt-length changes don't recompile it. The
cache lives in the flax "cache" collection (models/llama.py LlamaAttention decode
path) with static capacity `max_length`.
"""

from __future__ import annotations

import dataclasses
import threading
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp


@dataclass
class GenerationConfig:
    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0  # 0 = full vocab
    top_p: float = 1.0  # nucleus sampling; 1.0 = disabled (applied after top_k, HF order)
    repetition_penalty: float = 1.0  # HF CTRL-style: seen tokens' logits /p (if >0) else *p
    eos_token_id: Optional[int] = None
    pad_token_id: Optional[int] = None  # fill for finished rows; defaults to eos
    # Self-speculative decode (speculative.py): > 0 turns each fused-loop
    # iteration into an n-gram draft + one (draft_tokens+1)-position verify
    # dispatch that emits every greedily-confirmed draft plus one bonus token.
    # Greedy-only (do_sample / repetition_penalty raise) and token-identical
    # to draft_tokens=0 by construction; both knobs shape the compiled loop.
    draft_tokens: int = 0
    draft_ngram: int = 2


def _sample(logits, config: GenerationConfig, rng, temperature=None):
    """[B, V] logits -> [B] token ids. `temperature` may be a traced scalar (the
    fused decode loop passes it as an operand so changing it never recompiles)."""
    if not config.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), rng
    if temperature is None:
        temperature = config.temperature
    logits = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)
    if config.top_k:
        kth = jax.lax.top_k(logits, config.top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    if config.top_p < 1.0:
        # Nucleus: keep the smallest prefix of the descending-prob ordering
        # whose mass reaches top_p (the top token always survives: its
        # EXCLUSIVE cumulative mass is 0 < top_p). Sort/cumsum/threshold is
        # jit-static — no shapes depend on the data.
        sorted_logits = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        exclusive_cum = jnp.cumsum(probs, axis=-1) - probs
        keep = exclusive_cum < config.top_p
        # min_tokens_to_keep=1 (HF semantics): top_p <= 0 would otherwise mask
        # EVERYTHING and categorical over all -1e30 samples uniform gibberish.
        keep = keep.at[..., 0].set(True)
        kth = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True)
        logits = jnp.where(logits < kth, -1e30, logits)
    rng, sub = jax.random.split(rng)
    return jax.random.categorical(sub, logits, axis=-1).astype(jnp.int32), rng


def _apply_repetition_penalty(logits, presence, penalty: float):
    """HF RepetitionPenaltyLogitsProcessor (CTRL) semantics: every token marked
    in `presence` [B, V] gets its logit divided by the penalty when positive,
    multiplied when negative — both push re-use down for penalty > 1."""
    scores = logits.astype(jnp.float32)
    penalized = jnp.where(scores > 0, scores / penalty, scores * penalty)
    return jnp.where(presence, penalized, scores)


def _trim_at_eos(generated, eos_token_id, max_new: int):
    """HF generate's output contract: the fused loop emits a fixed [B, max_new]
    buffer (pad after EOS); return only up to the step where every row had
    finished. One host read of the small token matrix."""
    if eos_token_id is None:
        return generated
    toks = np.asarray(generated)
    all_finished = ((toks == eos_token_id).cumsum(axis=1) > 0).all(axis=0)
    idx = np.argmax(all_finished) if all_finished.any() else max_new - 1
    return generated[:, : idx + 1]


def _bucket_for(max_new: int) -> int:
    return 1 << (max_new - 1).bit_length()  # next power of two >= max_new


def _rewind_cache_index(cache, delta):
    """Roll back every attention module's `cache_index` by `delta` — the
    speculative accept/reject step: a verify block wrote draft_tokens+1 K/V
    rows and advanced the shared index past them, but only the accepted prefix
    may count. The rejected tail stays physically in the cache; it is
    unreachable (`update_decode_cache` masks `cols < cache_index + s`, and the
    next block's writes start AT the rewound index, covering the stale region
    before any query can see it). `delta` may be a traced scalar."""
    def fix(path, leaf):
        key = getattr(path[-1], "key", None) if path else None
        return leaf - delta if key == "cache_index" else leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


def _operand(value, dtype):
    """Explicit host-to-device push of a scalar/array operand. The numpy hop
    matters: `jnp.asarray(python_scalar)` (and eager jnp ops on Python
    constants) are IMPLICIT transfers that an armed `jax.transfer_guard`
    ("disallow") rejects, while `jnp.asarray(np.ndarray)` is explicit — the
    sanctioned step-boundary pattern, with a strong dtype so jit signatures
    never drift."""
    return jnp.asarray(np.asarray(value, dtype))


_DEFAULT_RNG = None


def _default_rng():
    """The rng=None default key, built once and reused: `jax.random.key(0)`
    per call is an implicit host-to-device push that (a) costs a transfer per
    generate() and (b) trips an armed TraceGuard. Key values are immutable
    (consumers split, never mutate), so sharing is semantically identical to a
    fresh key(0) each call. Built lazily — never at import time (TPU109)."""
    global _DEFAULT_RNG
    if _DEFAULT_RNG is None:
        _DEFAULT_RNG = jax.random.key(0)
    return _DEFAULT_RNG


def _params_resolver(model):
    """params -> params preprocessing for the compiled programs. Quantized bundles
    (load_and_quantize_model) carry QuantTensor leaves that the raw flax module
    can't consume; dequantize INSIDE the program so XLA keeps the int8/packed
    buffers in HBM and fuses `scale * q` into each consumer — serving stays at the
    quantized footprint (the reference's bnb int8 inference path)."""
    from .utils.quantization import dequantize_params, is_quant_entry

    leaves = jax.tree_util.tree_leaves(model.params, is_leaf=is_quant_entry)
    if not any(is_quant_entry(l) for l in leaves):
        return lambda p: p
    qc = getattr(model, "quantization_config", None)
    compute_dtype = getattr(qc, "compute_dtype", None) or jnp.bfloat16
    return lambda p: dequantize_params(p, compute_dtype)


def make_causal_programs(
    module,
    resolve,
    full_prefill_logits: bool = False,
    step_mask_operand: bool = False,
    verify_block: bool = False,
):
    """(prefill, step[, verify]) raw callables for a decode-cache causal-LM
    module — the factored seam that `Generator` jits directly and
    `serving.ContinuousBatcher` composes into its slot-insert / chunked-decode
    programs.

    `prefill(params, input_ids, positions, attention_mask=None)` writes the whole
    prompt into a fresh cache and returns `(last_logits, cache)` — or the full
    `[B, S, V]` logits with `full_prefill_logits=True` (serving's bucketed insert
    reads the logits at each prompt's REAL length, not the padded end);
    `step(params, cache, token, position)` advances one token. Both are un-jitted
    so callers can trace them inside larger fused programs.

    `step_mask_operand=True` gives `step` a fifth argument threaded through as
    the module's `attention_mask`: the PAGED slot cache reads it as the
    [B, pages_per_slot] int32 page table (a traced operand — the one decode
    executable survives every admission), since slot decode never carries a
    boolean mask of its own. The module config's `decode_attention_impl`
    decides what the step/verify programs DO with that table: "xla" gathers
    the pages into a logical buffer (parity oracle), "pallas_paged" hands the
    table to the fused `ops/paged_attention` kernels — either way the program
    signatures here are identical, so serving's compiled-once discipline and
    the traced-operand page tables are implementation-agnostic.

    Weight-only quantization rides the module config's `weight_dtype`
    ("bf16" default): "int8" wraps every apply below in
    `ops.quantization.weight_autocast`, so Dense kernels stored as
    per-output-channel int8 entries (`quantize_params_int8` — the serving
    engine's params setter) compute through the fused int8-epilogue matmul.
    The wrap is trace-time only (the interceptor rewrites the bound method
    during tracing); "bf16" is a no-op context.

    `verify_block=True` appends the speculative-decode seam to the tuple:
    `verify(params, cache, tokens, positions[, mask])` scores a [B, s] token
    BLOCK (the pending token plus s-1 draft proposals) in ONE dispatch,
    writing every block position's K/V and returning the full [B, s, V]
    logits plus the mutated cache — the multi-token twin of `step`, with the
    same mask-operand convention. Position j's logits are computed after
    exactly the block prefix <= j (the cache paths mask per-query), so
    `argmax(logits[:, j])` is precisely the token greedy decode would emit
    after accepting the first j block tokens — the property the accept loop
    relies on for token-identical output."""

    from .ops.quantization import weight_autocast

    weight_dtype = getattr(getattr(module, "config", None), "weight_dtype", "bf16")

    def prefill(params, input_ids, positions, attention_mask=None):
        # attention_mask (left-padded batch prompts): rides into the cached
        # attention as the persistent pad mask (update_decode_cache).
        with weight_autocast(weight_dtype):
            logits, mutated = module.apply(
                resolve(params), input_ids, attention_mask, positions, mutable=["cache"]
            )
        if full_prefill_logits:
            return logits, mutated["cache"]
        return logits[:, -1, :], mutated["cache"]

    def step(params, cache, token, position):
        with weight_autocast(weight_dtype):
            logits, mutated = module.apply(
                {**resolve(params), "cache": cache},
                token[:, None],
                None,
                position[:, None],
                mutable=["cache"],
            )
        return logits[:, -1, :], mutated["cache"]

    def step_with_mask(params, cache, token, position, mask):
        with weight_autocast(weight_dtype):
            logits, mutated = module.apply(
                {**resolve(params), "cache": cache},
                token[:, None],
                mask,
                position[:, None],
                mutable=["cache"],
            )
        return logits[:, -1, :], mutated["cache"]

    def verify(params, cache, tokens, positions):
        with weight_autocast(weight_dtype):
            logits, mutated = module.apply(
                {**resolve(params), "cache": cache}, tokens, None, positions, mutable=["cache"]
            )
        return logits, mutated["cache"]

    def verify_with_mask(params, cache, tokens, positions, mask):
        with weight_autocast(weight_dtype):
            logits, mutated = module.apply(
                {**resolve(params), "cache": cache}, tokens, mask, positions, mutable=["cache"]
            )
        return logits, mutated["cache"]

    step_fn = step_with_mask if step_mask_operand else step
    if verify_block:
        return prefill, step_fn, (verify_with_mask if step_mask_operand else verify)
    return prefill, step_fn


def make_cached_prefill_program(module, resolve):
    """`prefill_with_cache(params, cache, input_ids, positions)` — prefill a
    token block INTO AN EXISTING dense decode cache, continuing at the cache's
    own `cache_index` instead of position 0, and return the full `[B, S, V]`
    logits plus the mutated cache. The paged serving engine's shared-prefix
    insert drives this: the prefix pages are gathered into a batch-1 dense cache
    (`cache_index` = matched length), only the unmatched SUFFIX runs through the
    model here — the prefill FLOPs a shared system prompt would have cost are
    simply never issued — and the result is scattered back into pool pages."""

    from .ops.quantization import weight_autocast

    weight_dtype = getattr(getattr(module, "config", None), "weight_dtype", "bf16")

    def prefill_with_cache(params, cache, input_ids, positions):
        with weight_autocast(weight_dtype):
            logits, mutated = module.apply(
                {**resolve(params), "cache": cache},
                input_ids,
                None,
                positions,
                mutable=["cache"],
            )
        return logits, mutated["cache"]

    return prefill_with_cache


class Generator:
    """Compiled prefill + decode-step pair for a causal-LM Model bundle.

    Reusable across prompts of the same (batch, prompt_len) shape; per-token decode is
    shape-stable for any prompt length up to the cache capacity.
    """

    def __init__(self, model, max_new_tokens: int = 32, max_length: Optional[int] = None):
        if getattr(model, "module", None) is None or not hasattr(model.module, "config"):
            raise ValueError("generate() needs a Model bundle built from an in-tree flax module")
        self.base_config = model.module.config
        self.params = model.params
        self.max_new_tokens = max_new_tokens
        self.max_length = max_length or self.base_config.max_position_embeddings
        decode_cfg = dataclasses.replace(self.base_config, decode_cache_length=self.max_length)
        self.decode_module = type(model.module)(decode_cfg)

        prefill, step, verify = make_causal_programs(
            self.decode_module, _params_resolver(model), verify_block=True
        )
        self._prefill = jax.jit(prefill)
        self._step_inner = step  # un-jitted: traced inside the fused decode loop
        self._verify_inner = verify  # un-jitted: traced inside the speculative loop
        self._decode_cache = {}

    def _decode_fn(self, bucket: int, config: GenerationConfig):
        """ONE compiled program for the whole decode loop (lax.while_loop): sampling,
        EOS masking, and early exit all happen on device. A Python token loop would
        pay one host round-trip per token — on a tunneled TPU that serializes decode
        at network latency (~70 ms/token measured) instead of step latency.

        `bucket` (power of two) sizes the output buffer; the actual token bound is a
        TRACED scalar, so varying prompt lengths / max_new_tokens reuse one
        executable per bucket instead of recompiling the whole model."""
        # Only WHETHER a penalty applies shapes the program (the presence carry);
        # the penalty VALUE rides as a traced operand like temperature, so
        # sweeping it never recompiles the fused loop.
        # draft_ngram is inert without draft_tokens: normalize it out of the
        # key so a draft_tokens=0 control run never recompiles an identical
        # plain loop per ngram value.
        key = (bucket, config.do_sample, config.eos_token_id, config.pad_token_id,
               config.repetition_penalty != 1.0, config.draft_tokens,
               config.draft_ngram if config.draft_tokens else 0)
        if config.draft_tokens:
            if key not in self._decode_cache:
                self._decode_cache[key] = self._speculative_decode_fn(bucket, config)
            return self._decode_cache[key]
        if config.do_sample:
            # top_k and top_p shape the program (lax.top_k / the nucleus
            # threshold are trace-time); temperature rides in as a traced
            # operand so it never forces a recompile. Omitting a program-shaping
            # field here silently serves a STALE sampler compiled for another
            # config — exactly what happened when top_p first landed.
            key += (config.top_k, config.top_p)
        if key in self._decode_cache:
            return self._decode_cache[key]

        eos = config.eos_token_id
        pad_id = config.pad_token_id if config.pad_token_id is not None else (eos if eos is not None else 0)
        step_inner = self._step_inner
        use_penalty = config.repetition_penalty != 1.0

        def decode(params, cache, first_logits, next_positions, limit, temperature, penalty, rng, presence, *extra):
            # `next_positions`: the LOGICAL position of the first generated token —
            # a scalar (uniform prompts; Seq2Seq passes 1) or a per-row [B] vector
            # (left-padded ragged prompts: row with r real tokens continues at r).
            # `presence`: [B, V] bool of already-seen tokens when the config sets a
            # repetition penalty (the caller seeds it from the prompt; each
            # generated token joins it on device), else None.
            # `extra` operands (e.g. the encoder output for seq2seq models) thread
            # through unchanged to every step_inner call.
            b = first_logits.shape[0]

            def pick(logits, presence, rng):
                if use_penalty:
                    logits = _apply_repetition_penalty(logits, presence, penalty)
                token, rng = _sample(logits, config, rng, temperature)
                if use_penalty:
                    presence = presence.at[jnp.arange(b), token].set(True)
                return token, presence, rng

            token, presence, rng = pick(first_logits, presence, rng)
            tokens = jnp.full((b, bucket), jnp.int32(pad_id))
            tokens = tokens.at[:, 0].set(token)
            finished = jnp.zeros((b,), bool)

            def cond(carry):
                i, tokens, cache, token, rng, finished, presence = carry
                more = i < limit
                if eos is not None:
                    more &= ~jnp.all(finished | (token == eos))
                return more

            def body(carry):
                i, tokens, cache, token, rng, finished, presence = carry
                if eos is not None:
                    finished = finished | (token == eos)
                position = jnp.broadcast_to(next_positions + i - 1, (b,)).astype(jnp.int32)
                logits, cache = step_inner(params, cache, token, position, *extra)
                token, presence, rng = pick(logits, presence, rng)
                if eos is not None:
                    # Rows past their EOS emit pad/eos, matching HF generate's padding.
                    token = jnp.where(finished, jnp.int32(pad_id), token)
                tokens = tokens.at[:, i].set(token)
                return (i + 1, tokens, cache, token, rng, finished, presence)

            carry = (jnp.int32(1), tokens, cache, token, rng, finished, presence)
            _, tokens, cache, _, _, _, _ = jax.lax.while_loop(cond, body, carry)
            return tokens, cache

        fn = jax.jit(decode, donate_argnums=(1,))
        self._decode_cache[key] = fn
        return fn

    def _speculative_decode_fn(self, bucket: int, config: GenerationConfig):
        """The fused decode loop's draft-then-verify variant: each
        `lax.while_loop` iteration proposes `config.draft_tokens` continuations
        with the on-device n-gram drafter (`speculative.propose_ngram_drafts`
        over a history buffer riding the carry), scores the pending token plus
        all drafts in ONE (draft_tokens+1)-position verify dispatch, and emits
        the longest greedily-confirmed draft prefix plus one bonus token — so
        an iteration emits 1..draft_tokens+1 tokens for the latency of one
        dispatch, and greedy output stays token-identical to the plain loop
        (every emitted token is the model's own argmax given exactly the
        accepted prefix).

        Batch rows advance in LOCKSTEP (the dense cache's `cache_index` is
        shared): the accepted length is the minimum across unfinished rows, so
        a batch-1 call gets the full speedup and larger batches degrade toward
        plain decode, never past it. Rows that finish early emit pads, exactly
        like the plain loop. The rejected K/V tail is rolled back by rewinding
        `cache_index` (`_rewind_cache_index`); the token/history buffers carry
        `bucket + draft_tokens` columns of slack so the last block's masked
        window writes stay in bounds."""
        from .speculative import greedy_accept_length, propose_ngram_drafts

        if config.do_sample:
            raise ValueError(
                "speculative decoding is greedy-only: draft verification accepts "
                "argmax matches, which is not distribution-preserving under "
                "sampling — set do_sample=False or draft_tokens=0"
            )
        if config.repetition_penalty != 1.0:
            raise ValueError(
                "speculative decoding does not compose with repetition_penalty "
                "(the presence update is order-dependent across a verified "
                "block); set repetition_penalty=1.0 or draft_tokens=0"
            )
        eos = config.eos_token_id
        pad_id = config.pad_token_id if config.pad_token_id is not None else (eos if eos is not None else 0)
        verify_inner = self._verify_inner
        k_draft, m_gram = config.draft_tokens, config.draft_ngram

        def decode(params, cache, first_logits, next_positions, limit, history, hist_base, *extra):
            # `history` [B, max_length + k] int32: the observed context in
            # PHYSICAL order (prompt buffer, then generated tokens), seeded
            # with the prompt by the caller; `hist_base` = prompt buffer width.
            b = first_logits.shape[0]
            token = jnp.argmax(first_logits, axis=-1).astype(jnp.int32)
            width = bucket + k_draft
            tokens = jnp.full((b, width), jnp.int32(pad_id))
            tokens = tokens.at[:, 0].set(token)
            history = history.at[jnp.arange(b), hist_base].set(token)
            finished = (token == eos) if eos is not None else jnp.zeros((b,), bool)
            js = jnp.arange(k_draft + 1, dtype=jnp.int32)

            def cond(carry):
                i, tokens, cache, token, finished, history = carry
                more = i < limit
                if eos is not None:
                    more &= ~jnp.all(finished)
                return more

            def body(carry):
                i, tokens, cache, token, finished, history = carry
                hist_len = hist_base + i
                drafts, valid_len = propose_ngram_drafts(history, hist_len, k_draft, m_gram)
                block = jnp.concatenate([token[:, None], drafts], axis=1)
                base = jnp.broadcast_to(next_positions + i - 1, (b,)).astype(jnp.int32)
                positions = base[:, None] + js[None, :]
                logits, cache = verify_inner(params, cache, block, positions, *extra)
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, k+1]
                accept = greedy_accept_length(drafts, greedy[:, :k_draft], valid_len)
                if eos is not None:
                    # Finished rows emit pads regardless; don't let them drag
                    # the lockstep minimum below the live rows' acceptance.
                    accept = jnp.where(finished, k_draft, accept)
                a_min = jnp.minimum(jnp.min(accept), limit - i - 1)  # scalar
                emit = js <= a_min
                if eos is not None:
                    cols, fin = [], finished
                    for j in range(k_draft + 1):
                        e = jnp.where(fin, jnp.int32(pad_id), greedy[:, j])
                        cols.append(e)
                        fin = fin | ((e == eos) & emit[j])
                    emitted = jnp.stack(cols, axis=1)
                    finished = fin
                else:
                    emitted = greedy
                # Masked window writes: positions past a_min keep their old
                # buffer contents (the next iteration starts there).
                window = jax.lax.dynamic_slice(tokens, (jnp.int32(0), i), (b, k_draft + 1))
                tokens = jax.lax.dynamic_update_slice(
                    tokens, jnp.where(emit[None, :], emitted, window), (jnp.int32(0), i)
                )
                hwin = jax.lax.dynamic_slice(history, (jnp.int32(0), hist_len), (b, k_draft + 1))
                history = jax.lax.dynamic_update_slice(
                    history, jnp.where(emit[None, :], emitted, hwin), (jnp.int32(0), hist_len)
                )
                token = jax.lax.dynamic_slice_in_dim(emitted, a_min, 1, axis=1)[:, 0]
                # Count only the accepted prefix: rewind the shared cache index
                # past the k - a_min rejected draft rows this dispatch wrote.
                cache = _rewind_cache_index(cache, k_draft - a_min)
                return (i + a_min + 1, tokens, cache, token, finished, history)

            carry = (jnp.int32(1), tokens, cache, token, finished, history)
            _, tokens, cache, _, _, _ = jax.lax.while_loop(cond, body, carry)
            return tokens, cache

        # Donate only the cache: the history buffer has no same-shaped output
        # to alias (tokens is [B, bucket + k]), so donating it just warns.
        return jax.jit(decode, donate_argnums=(1,))

    def __call__(
        self,
        input_ids,
        generation_config: Optional[GenerationConfig] = None,
        rng=None,
        attention_mask=None,
        **kwargs,
    ):
        """`attention_mask` ([B, prompt_len] 1/0) enables ragged batch prompts via
        the HF LEFT-padding convention: pads go at the START of each row. Rotary/
        learned positions come from the mask's cumsum (first real token = position
        0) and the pad slots stay masked for the whole decode via the cache's
        persistent pad mask."""
        config = generation_config or GenerationConfig(**kwargs)
        if rng is None:
            rng = _default_rng()
        # Host copy first (free for numpy/list inputs, one explicit drain for
        # device inputs): the host tail concatenates against it, so the prompt
        # matrix is never drained a second time after decode.
        ids_host = np.asarray(input_ids, np.int32)
        input_ids = jnp.asarray(ids_host)
        b, prompt_len = input_ids.shape
        max_new = min(config.max_new_tokens, self.max_length - prompt_len)
        if max_new <= 0:
            raise ValueError(
                f"Prompt length {prompt_len} leaves no room in the {self.max_length}-token cache"
            )
        if attention_mask is not None:
            # Validate on the HOST copy: an implicit bool() on a device value
            # would trip jax.transfer_guard("disallow") when a TraceGuard is
            # armed around this call (np.asarray is an explicit, sanctioned
            # step-boundary read).
            am_host = np.asarray(attention_mask)
            if am_host.ndim != 2 or am_host.shape != input_ids.shape:
                raise ValueError(
                    f"attention_mask must be [batch, prompt_len] matching input_ids "
                    f"{input_ids.shape}, got {am_host.shape}"
                )
            # LEFT padding only (prefill samples from the LAST slot's logits and
            # decode continues at each row's real length): a right-padded batch
            # would silently continue from a pad token's logits.
            if not (am_host[:, -1] == 1).all():
                raise ValueError(
                    "attention_mask looks right-padded (a row's last slot is 0); "
                    "Generator uses the HF LEFT-padding convention — put pads at "
                    "the START of each row"
                )
            am = jnp.asarray(am_host.astype(np.int32))
            # Host-side (numpy) position prep + ONE explicit push each: eager
            # jnp ops here would implicitly transfer their Python constants on
            # every call (and trip an armed TraceGuard).
            positions = _operand(np.clip(np.cumsum(am_host, axis=-1) - 1, 0, None), np.int32)
            # Per-row LOGICAL position base for decode: row with r real tokens
            # continues at position r (physical cache slots stay uniform).
            next_positions = _operand(am_host.sum(-1), np.int32)
            prefill_args = (input_ids, positions, am)
        else:
            positions = _operand(
                np.broadcast_to(np.arange(prompt_len)[None, :], (b, prompt_len)), np.int32
            )
            next_positions = _operand(np.full((b,), prompt_len), np.int32)
            prefill_args = (input_ids, positions)
        presence = None
        if config.repetition_penalty != 1.0:
            # Seed the seen-token set from the REAL prompt tokens (pad slots of a
            # left-padded batch must not mark token id 0 as seen).
            real = (
                am.astype(bool)
                if attention_mask is not None
                else jnp.ones((b, prompt_len), bool)
            )
            presence = (
                jnp.zeros((b, self.base_config.vocab_size), bool)
                .at[jnp.arange(b)[:, None], input_ids]
                .max(real)
            )
        params = self.params if "params" in self.params else {"params": self.params}
        logits, cache = self._prefill(params, *prefill_args)
        if config.draft_tokens:
            # Speculative loop operands: the history buffer (physical order —
            # prompt buffer incl. any left pads, then generated tokens) and its
            # base width. Fixed [B, max_length + k] shape, so varying prompt
            # lengths reuse the one compiled loop per bucket, like the cache.
            hist = np.zeros((b, self.max_length + config.draft_tokens), np.int32)
            hist[:, :prompt_len] = ids_host
            generated, _cache = self._decode_fn(_bucket_for(max_new), config)(
                params,
                cache,
                logits,
                next_positions,
                _operand(max_new, np.int32),
                jnp.asarray(hist),
                _operand(prompt_len, np.int32),
            )
        else:
            generated, _cache = self._decode_fn(_bucket_for(max_new), config)(
                params,
                cache,
                logits,
                next_positions,
                _operand(max_new, np.int32),
                _operand(config.temperature, np.float32),
                _operand(config.repetition_penalty, np.float32),
                rng,
                presence,
            )
        # Host tail entirely in numpy: even a static eager slice on a device
        # array dispatches dynamic_slice with implicitly-pushed start indices,
        # which an armed transfer guard rejects. One explicit drain (the host
        # read _trim_at_eos needs anyway), trim, one explicit push back.
        gen_host = np.asarray(generated)[:, :max_new]
        gen_host = _trim_at_eos(gen_host, config.eos_token_id, max_new)
        return jnp.asarray(np.concatenate([ids_host, gen_host], axis=1))


class Seq2SeqGenerator:
    """Compiled encode + fused decode loop for encoder-decoder Model bundles (T5):
    the encoder runs ONCE per prompt, then the same on-device `lax.while_loop`
    decode as `Generator`, with the encoder output riding along as a loop operand.

    The decoder module must expose `encode(input_ids, attention_mask)` and
    `decode(decoder_input_ids, encoder_hidden, positions, enc_mask)` methods plus a
    `decode_cache_length` config field (models/t5.py is the in-tree shape)."""

    def __init__(self, model, max_new_tokens: int = 32, decoder_start_token_id: int = 0):
        module = getattr(model, "module", None)
        if module is None or not hasattr(module, "encode"):
            raise ValueError("Seq2SeqGenerator needs a Model bundle with an encoder-decoder flax module")
        self.base_config = module.config
        self.params = model.params if "params" in model.params else {"params": model.params}
        self.max_new_tokens = max_new_tokens
        self.start_id = decoder_start_token_id
        decode_cfg = dataclasses.replace(module.config, decode_cache_length=max_new_tokens + 1)
        self.module = type(module)(decode_cfg, use_cache=True)
        mod = self.module
        resolve = _params_resolver(model)

        def encode(params, input_ids, attention_mask):
            return mod.apply(resolve(params), input_ids, attention_mask, method="encode")

        def prime(params, encoder_hidden, enc_mask, start_tokens):
            # Write the start token at decoder position 0 and return its logits.
            logits, mutated = mod.apply(
                resolve(params),
                start_tokens[:, None],
                encoder_hidden,
                jnp.zeros((1,), jnp.int32),
                enc_mask,
                mutable=["cache"],
                method="decode",
            )
            return logits[:, -1, :], mutated["cache"]

        def step(params, cache, token, position, encoder_hidden, enc_mask):
            logits, mutated = mod.apply(
                {**resolve(params), "cache": cache},
                token[:, None],
                encoder_hidden,
                position[:1],  # decoder positions are shared across the batch
                enc_mask,
                mutable=["cache"],
                method="decode",
            )
            return logits[:, -1, :], mutated["cache"]

        self._encode = jax.jit(encode)
        self._prime = jax.jit(prime)
        self._step_inner = step  # traced inside the fused decode loop
        self._decode_cache = {}

    _decode_fn = Generator._decode_fn  # same bucketed fused-loop builder

    def __call__(self, input_ids, generation_config: Optional[GenerationConfig] = None, rng=None, **kwargs):
        attention_mask = kwargs.pop("attention_mask", None)  # before GenerationConfig(**kwargs)
        explicit_request = generation_config is not None or "max_new_tokens" in kwargs
        config = generation_config or GenerationConfig(**kwargs)
        if config.draft_tokens:
            raise ValueError(
                "speculative decoding (draft_tokens > 0) is causal-LM only; the "
                "encoder-decoder decode path has no verify-block seam"
            )
        if rng is None:
            rng = _default_rng()
        input_ids = jnp.asarray(input_ids, jnp.int32)
        b = input_ids.shape[0]
        enc_mask = (
            jnp.asarray(np.asarray(attention_mask, bool))[:, None, None, :]
            if attention_mask is not None
            else _operand(np.ones((b, 1, 1, input_ids.shape[1])), bool)
        )
        max_new = config.max_new_tokens
        if not explicit_request:
            # Bare call: the dataclass default (32) is not a user request — fill
            # whatever budget this generator was built with.
            max_new = min(max_new, self.max_new_tokens)
        elif max_new > self.max_new_tokens:
            raise ValueError(
                f"Requested {max_new} new tokens but this generator's decoder "
                f"cache was sized for {self.max_new_tokens}; rebuild with a larger max_new_tokens"
            )
        am = jnp.asarray(attention_mask, jnp.int32) if attention_mask is not None else None
        encoder_hidden = self._encode(self.params, input_ids, am)
        start = _operand(np.full((b,), self.start_id), np.int32)
        first_logits, cache = self._prime(self.params, encoder_hidden, enc_mask, start)
        presence = None
        if config.repetition_penalty != 1.0:
            # Encoder-decoder penalty covers the DECODER context (HF semantics):
            # seed with the start token only.
            presence = (
                jnp.zeros((b, self.base_config.vocab_size), bool)
                .at[jnp.arange(b), start]
                .set(True)
            )
        generated, _cache = self._decode_fn(_bucket_for(max_new), config)(
            self.params,
            cache,
            first_logits,
            _operand(1, np.int32),  # the start token occupies cache position 0
            _operand(max_new, np.int32),
            _operand(config.temperature, np.float32),
            _operand(config.repetition_penalty, np.float32),
            rng,
            presence,
            encoder_hidden,
            enc_mask,
        )
        # numpy host tail (see Generator.__call__): drain once, trim, push back.
        gen_host = np.asarray(generated)[:, :max_new]
        gen_host = _trim_at_eos(gen_host, config.eos_token_id, max_new)
        return jnp.asarray(gen_host)  # decoder tokens only (HF seq2seq generate shape)


# Warm-executable cache for the module-level generate() convenience: keyed on the
# MODEL'S identity (weakly — a dead model must not pin its Generator, and a reused
# id() must not serve another model's programs) plus any Generator kwargs.
# max_new_tokens is NOT part of the key: the Generator's cache capacity comes from
# max_length/max_position_embeddings and the fused loop buckets per call, so one
# cached Generator serves every budget. A hit also requires `model.params` to be
# the SAME object the Generator holds — `model.params = new_params` (the
# train-then-sample pattern) must rebuild, never decode with stale weights.
# Without the cache every convenience call paid a fresh prefill+decode compile
# (~seconds) for byte-identical programs.
_GENERATOR_CACHE: dict = {}
_GENERATOR_CACHE_MAX = 8
# generate() was stateless (and so trivially thread-safe) before the cache; the
# lock covers only dict bookkeeping — Generator construction/compilation runs
# outside it (two racing misses both build; last insert wins).
_GENERATOR_CACHE_LOCK = threading.Lock()


def _evict_dead_generator_entries(dead_ref):
    """weakref finalizer: a collected model must not pin its Generator (params
    device buffers + compiled executables) until an id()-colliding lookup or LRU
    overflow happens to evict it."""
    with _GENERATOR_CACHE_LOCK:
        for key in [k for k, (r, _) in _GENERATOR_CACHE.items() if r is dead_ref]:
            _GENERATOR_CACHE.pop(key, None)


def _cached_generator(model, max_new_tokens: int, **kwargs) -> Generator:
    import weakref

    key = (id(model), tuple(sorted(kwargs.items())))
    with _GENERATOR_CACHE_LOCK:
        hit = _GENERATOR_CACHE.get(key)
        if hit is not None:
            ref, generator = hit
            if ref() is model and generator.params is model.params:
                _GENERATOR_CACHE[key] = _GENERATOR_CACHE.pop(key)  # LRU bump
                return generator
            _GENERATOR_CACHE.pop(key, None)  # dead/reused id() or rebound params
    generator = Generator(model, max_new_tokens=max_new_tokens, **kwargs)
    try:
        ref = weakref.ref(model, _evict_dead_generator_entries)
    except TypeError:  # non-weakref-able bundle: don't cache rather than leak
        return generator
    with _GENERATOR_CACHE_LOCK:
        _GENERATOR_CACHE[key] = (ref, generator)
        while len(_GENERATOR_CACHE) > _GENERATOR_CACHE_MAX:
            del _GENERATOR_CACHE[next(iter(_GENERATOR_CACHE))]
    return generator


def generate(model, input_ids, max_new_tokens: int = 32, **kwargs):
    """One-shot convenience: build (or reuse — see `_cached_generator`) a
    Generator and run it (HF `model.generate` shape)."""
    gen_kwargs = {
        k: kwargs.pop(k)
        for k in ("do_sample", "temperature", "top_k", "top_p", "repetition_penalty",
                  "eos_token_id", "pad_token_id", "draft_tokens", "draft_ngram")
        if k in kwargs
    }
    attention_mask = kwargs.pop("attention_mask", None)
    generator = _cached_generator(model, max_new_tokens, **kwargs)
    return generator(
        input_ids,
        GenerationConfig(max_new_tokens=max_new_tokens, **gen_kwargs),
        attention_mask=attention_mask,
    )

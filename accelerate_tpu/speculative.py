"""On-device self-speculation primitives: n-gram / prompt-lookup drafting and
greedy draft verification.

Classic draft-then-verify speculation amortizes one model dispatch over k
candidate tokens: a cheap DRAFTER proposes k continuations, one multi-token
VERIFY dispatch scores all k+1 positions at once, and the longest draft prefix
that matches the model's own greedy choices is accepted — plus one "bonus"
token from the verify logits, so every verify step emits at least as much as a
plain decode step. Greedy output is token-identical to non-speculative decode
by construction: every emitted token IS the model's argmax given exactly the
accepted prefix.

This module implements the SELF-speculation variant (Saxena's prompt-lookup
decoding): the drafter is an n-gram matcher over the request's own observed
context (prompt + generated tokens), so there is no second model to load,
shard, or keep in sync — which is what lets the fused decode loop stay ONE
executable. Both helpers here are pure jax functions with static shapes,
designed to be traced INSIDE the decode program (`generation.Generator`'s
fused loop, `serving.ContinuousBatcher`'s chunk scan): no host round-trip ever
happens between draft, verify, and accept. They are deliberately tiny —
O(B * H * ngram) integer compares — next to the verify matmuls they ride with.

Degenerate inputs degrade to plain decode, never to wrong output: no n-gram
match, a context shorter than the n-gram, or an exhausted continuation all
yield `valid_len == 0`, and `greedy_accept_length` masks every draft position
at or past `valid_len`, so a useless draft costs one verify dispatch (exactly
one plain step's work) and emits the same one token a plain step would.
"""

from __future__ import annotations

import jax.numpy as jnp

#: Default number of draft tokens proposed per verify step.
DEFAULT_DRAFT_TOKENS = 4
#: Default n-gram length the drafter matches on (bigram, the prompt-lookup
#: sweet spot: long enough to be selective, short enough to fire often).
DEFAULT_DRAFT_NGRAM = 2


def propose_ngram_drafts(history, hist_len, draft_tokens: int, ngram: int = DEFAULT_DRAFT_NGRAM):
    """Prompt-lookup draft proposal, fully on device.

    For each row, take the trailing `ngram` tokens of the observed context,
    find the MOST RECENT earlier occurrence of that n-gram in the context, and
    propose the `draft_tokens` tokens that followed it. Proposals are therefore
    always verbatim continuations of observed context — never out-of-vocab,
    never fabricated.

    Args:
        history: [B, H] int32 — observed tokens (prompt + generated) packed at
            the start of each row; entries at index >= hist_len are ignored.
        hist_len: [B] (or scalar) int32 — observed length per row, INCLUDING
            the pending token the next verify step will score.
        draft_tokens: static k, number of proposals per row.
        ngram: static match length m (>= 1).

    Returns:
        (drafts, valid_len): drafts [B, k] int32 and valid_len [B] int32 in
        [0, k]. Only `drafts[:, :valid_len]` are meaningful proposals (always
        observed-context continuations); positions at or past valid_len are
        clipped gather artifacts the verifier must mask (and
        `greedy_accept_length` does).
    """
    if draft_tokens < 1:
        raise ValueError("draft_tokens must be >= 1")
    if ngram < 1:
        raise ValueError("ngram must be >= 1")
    b, h = history.shape
    k, m = int(draft_tokens), int(ngram)
    hist_len = jnp.broadcast_to(jnp.asarray(hist_len, jnp.int32), (b,))
    starts = jnp.arange(h, dtype=jnp.int32)
    # Trailing n-gram per row (the query): history[hist_len - m : hist_len].
    tail_idx = jnp.clip(hist_len[:, None] - m + jnp.arange(m, dtype=jnp.int32)[None, :], 0, h - 1)
    tail = jnp.take_along_axis(history, tail_idx, axis=1)  # [B, m]
    # match[b, i] == True iff history[b, i : i + m] equals the tail n-gram.
    # jnp.roll(-t) aligns history[i + t] at column i; columns where i + t wraps
    # past H are masked off.
    match = jnp.ones((b, h), bool)
    for t in range(m):
        shifted = jnp.roll(history, -t, axis=1)
        match &= (shifted == tail[:, t : t + 1]) & ((starts + t) < h)[None, :]
    # Exclude the trailing occurrence itself (its continuation is the unknown
    # future) and any start whose n-gram isn't fully inside the observed
    # context. hist_len < m + 1 leaves no admissible start at all.
    match &= starts[None, :] < (hist_len[:, None] - m)
    j = jnp.max(jnp.where(match, starts[None, :], -1), axis=1)  # most recent hit
    found = j >= 0
    cont = jnp.clip(j[:, None] + m + jnp.arange(k, dtype=jnp.int32)[None, :], 0, h - 1)
    drafts = jnp.take_along_axis(history, cont, axis=1).astype(jnp.int32)
    # Never propose past the observed context: a hit right before the tail has
    # fewer than k observed continuation tokens.
    valid_len = jnp.where(found, jnp.minimum(k, hist_len - (j + m)), 0).astype(jnp.int32)
    return drafts, valid_len


def greedy_accept_length(drafts, greedy_targets, valid_len):
    """Longest accepted draft prefix under greedy verification.

    `greedy_targets[:, i]` is the model's argmax at verify position i — the
    token the model itself would have emitted after the (accepted) prefix
    ending at draft i-1. Draft i is accepted iff every earlier draft was
    accepted, it is a real proposal (`i < valid_len`), and it matches the
    model's choice. Returns [B] int32 counts in [0, k].
    """
    b, k = drafts.shape
    ok = (drafts == greedy_targets) & (jnp.arange(k, dtype=jnp.int32)[None, :] < valid_len[:, None])
    return jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1).astype(jnp.int32)

__version__ = "0.1.0"

# Honor JAX_PLATFORMS via jax.config as well as the env var: some TPU PJRT plugins
# hook get_backend and ignore the env var, reaching (slowly, serialized) for real
# hardware even in CPU-only child processes. The config path bypasses the hook.
import os as _os

if _os.environ.get("JAX_PLATFORMS"):
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception:
        pass
del _os

from .accelerator import Accelerator
from .state import AcceleratorState, GradientState, PartialState
from .logging import get_logger
from .modeling import Model, PreparedModel
from .optimizer import AcceleratedOptimizer, GradScaler
from .scheduler import AcceleratedScheduler
from .data_loader import SimpleDataLoader, prepare_data_loader, skip_first_batches
from .local_sgd import LocalSGD
from .launchers import debug_launcher, notebook_launcher
from .fault_tolerance import PREEMPTED_EXIT_CODE, PreemptionHandler, Supervisor
from .generation import GenerationConfig, Generator, generate
from .hooks import (
    CpuOffload,
    ModelHook,
    SequentialHook,
    UserCpuOffloadHook,
    add_hook_to_module,
    cpu_offload_with_hook,
    remove_hook_from_module,
)
from .tracking import GeneralTracker
from .telemetry import MetricsRegistry, ProfilerManager, StepTimeline, TrackerBridge
from .utils import (
    DataLoaderConfiguration,
    DeepSpeedPlugin,
    DistributedType,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    MegatronLMPlugin,
    ParallelismConfig,
    ProjectConfiguration,
    SequenceParallelPlugin,
    find_executable_batch_size,
)

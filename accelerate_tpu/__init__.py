__version__ = "0.1.0"

from .state import AcceleratorState, GradientState, PartialState
from .logging import get_logger
from .utils import (
    DataLoaderConfiguration,
    DeepSpeedPlugin,
    DistributedType,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    MegatronLMPlugin,
    ParallelismConfig,
    ProjectConfiguration,
    SequenceParallelPlugin,
    find_executable_batch_size,
)

"""Fused training step: one compiled program per optimizer step.

The eager-feel path (`Accelerator.backward` -> `optimizer.step` -> `zero_grad`)
dispatches >=3 compiled programs per step (grad, accumulate-add, update) with host
round-trips between them — the reference's backward/step choreography
(accelerator.py:2093-2121, optimizer.py:125-168) translated call-for-call. On TPU
the dispatch gaps are dead MXU time, so the hot path belongs in ONE jitted call:
value_and_grad + optional global-norm clip + optax update, with donated
params/opt-state so XLA updates weights in place in HBM.

Gradient accumulation becomes a `lax.scan` over microbatches inside the same
program (SURVEY §7 "hard parts": the `sync_gradients` boundary is the scan
boundary), instead of N eager microbatch dispatches plus an accumulate-add each.

The eager API remains the compatibility surface; `Accelerator.train_step` is the
performance path used by `bench.py` and `examples/`.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from .logging import get_logger

logger = get_logger(__name__)


class FusedTrainStep:
    """Callable `step_fn(batch) -> loss` running grad+clip+update as one program.

    - `loss_fn(params, *args, **kwargs)` returns a scalar loss (or `(loss, aux)`);
      defaults to the model bundle's `loss`.
    - `accumulation_steps=k > 1`: the call takes ONE positional batch pytree whose
      arrays stack k microbatches along dim 0 (shape `[k*b, ...]`); gradients are
      accumulated across a `lax.scan` and the mean microbatch loss is returned
      (aux outputs are not available in this mode).
    - fp16 dynamic loss scaling and skipped-step detection follow the eager path's
      contract (`optimizer.step_was_skipped`, scaler backoff).
    - The learning-rate override installed by `AcceleratedScheduler.step()` via
      `optimizer.set_learning_rate` is honored (requires `optax.inject_hyperparams`,
      same as the eager path).
    - `steps_per_call=K > 1` runs K FULL optimizer steps as one compiled program
      (an outer `lax.scan` whose carry is (params, opt_state)): the call takes one
      batch pytree stacking K step-batches along dim 0 (`[K*b, ...]`) and returns
      the last step's loss (loss functions returning `(loss, aux)` are rejected —
      the scan would drop every step's aux). This is the device-training-loop mode: per-call host
      work (argument processing, dispatch, a tunneled-TPU round trip) is paid once
      per K steps instead of per step, which is where small-step configs lose
      their MFU. LR override and loss scale are read once per call, so a
      scheduler advances in K-step strides; dynamic fp16 scaling needs per-step
      host decisions and is rejected (use bf16 — TPU-native — or K=1).
    """

    def __init__(
        self,
        model,
        optimizer,
        loss_fn: Optional[Callable] = None,
        max_grad_norm: Optional[float] = None,
        accumulation_steps: int = 1,
        gradient_state=None,
        steps_per_call: int = 1,
        tracer=None,
    ):
        self.model = model
        self.optimizer = optimizer
        # Optional telemetry tracer (Accelerator.train_step passes its own):
        # program (re)builds and skipped fp16 steps become trace events, so a
        # timeline shows WHY a step was slow (fresh trace) or absent (skip).
        self.tracer = tracer
        self.loss_fn = loss_fn if loss_fn is not None else model.loss
        self.max_grad_norm = max_grad_norm
        self.accumulation_steps = int(accumulation_steps or 1)
        self.steps_per_call = int(steps_per_call or 1)
        if self.steps_per_call > 1:
            scaler = optimizer.scaler
            if scaler is not None and scaler.enabled:
                raise ValueError(
                    "steps_per_call > 1 cannot honor dynamic fp16 loss scaling "
                    "(scale updates are per-step host decisions); use bf16 mixed "
                    "precision or steps_per_call=1"
                )
            if optimizer.offload_opt_state:
                raise ValueError(
                    "steps_per_call > 1 is incompatible with offloaded optimizer "
                    "state (each step streams state through HBM group by group)"
                )
        self.gradient_state = gradient_state
        self._jitted: dict = {}

    # ---- program construction ---------------------------------------------------------
    def _build(self, with_lr: bool):
        import jax
        import jax.numpy as jnp

        tx = self.optimizer.tx
        k = self.accumulation_steps
        max_norm = self.max_grad_norm
        scaler = self.optimizer.scaler
        use_scaler = scaler is not None and scaler.enabled
        loss_fn = self.loss_fn
        mesh = getattr(self.model, "mesh", None)

        def grads_of(params, scale, *args, **kwargs):
            def scaled(p):
                out = loss_fn(p, *args, **kwargs)
                loss, aux = out if isinstance(out, tuple) else (out, None)
                return loss * scale, (loss, aux)

            return jax.grad(scaled, has_aux=True)(params)

        def split_leading(batch, n, what):
            def _split(x):
                if x.shape[0] % n:
                    raise ValueError(f"{what}={n} must divide the batch dim ({x.shape[0]})")
                return x.reshape((n, x.shape[0] // n) + x.shape[1:])

            mb = jax.tree_util.tree_map(_split, batch)
            if mesh is not None and ("data" in mesh.shape or "fsdp" in mesh.shape):
                from jax.sharding import NamedSharding, PartitionSpec

                axes = tuple(a for a in ("data", "fsdp") if a in mesh.shape)
                spec = NamedSharding(mesh, PartitionSpec(None, axes))

                def _constrain(x):
                    if x.ndim >= 2:
                        return jax.lax.with_sharding_constraint(x, spec)
                    return x

                mb = jax.tree_util.tree_map(_constrain, mb)
            return mb

        def split_microbatches(batch):
            return split_leading(batch, k, "accumulation_steps")

        to_compute = getattr(self.model, "to_compute_memory", lambda p: p)
        opt_to_compute = self.optimizer.opt_to_compute_memory

        def compute_grads(params, scale, *args, **kwargs):
            if k > 1:
                if len(args) != 1 or kwargs:
                    raise ValueError(
                        "accumulation_steps > 1 takes exactly one positional batch pytree"
                    )
                microbatches = split_microbatches(args[0])
                # reduce_dtype (FSDP MixedPrecision parity): the accumulation
                # buffer dtype. With bf16 params, k bf16 adds roll off mantissa
                # bits; an fp32 buffer keeps the accumulated gradient exact, cast
                # back to the param dtype only at the update.
                reduce_dtype = getattr(self.model, "reduce_dtype", None)

                def body(acc, mbatch):
                    g, (loss, _aux) = grads_of(params, scale, mbatch)
                    if reduce_dtype is not None:
                        g = jax.tree_util.tree_map(lambda x: x.astype(reduce_dtype), g)
                    return jax.tree_util.tree_map(jnp.add, acc, g), loss

                zeros = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, reduce_dtype or p.dtype), params
                )
                grads, losses = jax.lax.scan(body, zeros, microbatches)
                if reduce_dtype is not None:
                    grads = jax.tree_util.tree_map(lambda g, p: g.astype(p.dtype), grads, params)
                return grads, jnp.mean(losses), None
            grads, (loss, aux) = grads_of(params, scale, *args, **kwargs)
            return grads, loss, aux

        if self.optimizer.offload_opt_state:
            # Chunked-offload mode: the update CANNOT live in this program (streaming
            # the whole host-resident state would OOM HBM — optimizer.py
            # apply_chunked_update). This program does grads + unscale/finite/clip
            # (the shared unscale_and_clip, same ordering as apply_update_core); the
            # per-group update programs follow in __call__.
            from .optimizer import unscale_and_clip

            def grads_program(params, scale, inv_scale, *args, **kwargs):
                params = to_compute(params)
                grads, loss, aux = compute_grads(params, scale, *args, **kwargs)
                grads, finite = unscale_and_clip(grads, inv_scale, max_norm, use_scaler)
                return grads, loss, aux, finite

            return jax.jit(grads_program)

        # Pin updated params/opt-state to their DERIVED shardings: the jit has no
        # out_shardings, so without constraints XLA may re-layout outputs (e.g.
        # shard a replicated embedding over fsdp after step 1), silently drifting
        # from the wrap policy the user configured and changing the collective
        # pattern between the first and later steps.
        param_out_sharding = getattr(self.model, "param_compute_sharding", None)
        opt_out_sharding = getattr(self.optimizer, "_opt_compute_sharding", None) or getattr(
            self.optimizer, "opt_state_sharding", None
        )

        from .optimizer import apply_update_core

        def one_step(params, opt_state, scale, inv_scale, lr, *args, **kwargs):
            grads, loss, aux = compute_grads(params, scale, *args, **kwargs)
            new_params, new_opt_state, finite = apply_update_core(
                tx,
                params,
                opt_state,
                grads,
                inv_scale,
                lr if with_lr else None,
                use_scaler=use_scaler,
                max_norm=max_norm,
            )
            if param_out_sharding is not None:
                new_params = jax.lax.with_sharding_constraint(new_params, param_out_sharding)
            if opt_out_sharding is not None:
                new_opt_state = jax.lax.with_sharding_constraint(new_opt_state, opt_out_sharding)
            return new_params, new_opt_state, loss, aux, finite

        n_steps = self.steps_per_call

        def fused(params, opt_state, scale, inv_scale, lr, *args, **kwargs):
            # Host-offloaded tiers stream to device memory at the top of the
            # program; the caller writes results back to pinned host.
            params = to_compute(params)
            opt_state = opt_to_compute(opt_state)
            if n_steps == 1:
                return one_step(params, opt_state, scale, inv_scale, lr, *args, **kwargs)

            # Device training loop: scan K full optimizer steps over K stacked
            # step-batches. One dispatch, one donation round trip, K updates.
            if len(args) != 1 or kwargs:
                raise ValueError("steps_per_call > 1 takes exactly one positional batch pytree")
            step_batches = split_leading(args[0], n_steps, "steps_per_call")

            def body(carry, sbatch):
                p, s = carry
                new_p, new_s, loss, aux, finite = one_step(p, s, scale, inv_scale, lr, sbatch)
                if aux is not None:
                    # Trace-time check: the scan returns only the last step's
                    # loss, so an aux value would be silently dropped and the
                    # caller's `loss, aux = step_fn(batch)` unpack would break.
                    raise ValueError(
                        "steps_per_call > 1 does not support loss functions that "
                        "return (loss, aux); use steps_per_call=1 for aux outputs"
                    )
                return (new_p, new_s), (loss, finite)

            (new_params, new_opt_state), (losses, finites) = jax.lax.scan(
                body, (params, opt_state), step_batches
            )
            return new_params, new_opt_state, losses[-1], None, jnp.all(finites)

        return jax.jit(fused, donate_argnums=(0, 1))

    # ---- the hot call -----------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        import jax.numpy as jnp

        opt = self.optimizer
        scaler = opt.scaler
        use_scaler = scaler is not None and scaler.enabled
        loss_scale = scaler.scale if use_scaler else 1.0
        scale = loss_scale / self.accumulation_steps
        inv_scale = 1.0 / loss_scale
        lr = opt._lr_override
        with_lr = lr is not None
        # In offload mode the jitted program is grads-only — lr enters via
        # apply_chunked_update — so one cache entry serves both lr states
        # (a with_lr-keyed cache would recompile the identical program the
        # first time a scheduler installs an override). The sentinel keeps it
        # distinct from the fused program in case offload_opt_state is toggled
        # mid-run (e.g. LocalSGD collapse).
        if opt.offload_opt_state and self.steps_per_call > 1:
            # Guarded at construction, but offload can be toggled after (e.g.
            # LocalSGD collapse): the offload program has no step scan and would
            # silently consume the [K*b] stacked batch as ONE giant step.
            raise ValueError(
                "steps_per_call > 1 is incompatible with offloaded optimizer state "
                "(toggled on after train_step was built); rebuild with steps_per_call=1"
            )
        cache_key = "offload" if opt.offload_opt_state else with_lr
        if cache_key not in self._jitted:
            if self.tracer is not None:
                self.tracer.event(
                    "train.build_program", category="train", key=str(cache_key)
                )
            self._jitted[cache_key] = self._build(cache_key)
        # Scalars change rarely (scale only on scaler growth/backoff, lr per
        # scheduler step); cache their device buffers so the hot loop doesn't pay
        # three host->device transfers per step.
        key = (scale, inv_scale, lr if with_lr else 0.0)
        if key != getattr(self, "_scalar_key", None):
            self._scalar_key = key
            self._scalar_bufs = tuple(jnp.asarray(v, jnp.float32) for v in key)
        if opt.offload_opt_state:
            # grads program (unscale+clip inside), then the chunked per-group update.
            grads, loss, aux, finite = self._jitted[cache_key](
                self.model.params, self._scalar_bufs[0], self._scalar_bufs[1], *args, **kwargs
            )
            new_params, finite = opt.apply_chunked_update(
                self.model.params, grads, 1.0, lr, finite=finite
            )
            self.model.params = new_params
        else:
            new_params, new_opt_state, loss, aux, finite = self._jitted[cache_key](
                self.model.params,
                opt.opt_state,
                *self._scalar_bufs,
                *args,
                **kwargs,
            )
            if hasattr(self.model, "to_storage_memory"):
                new_params = self.model.to_storage_memory(new_params)
            self.model.params = new_params
            opt.opt_state = opt.opt_to_storage_memory(new_opt_state)
        opt._grads = None
        opt._accum_count = 0
        if use_scaler:
            found_inf = not bool(finite)
            scaler.update(found_inf)
            opt.step_was_skipped = found_inf
            if found_inf:
                logger.warning(
                    "Skipping fused step: non-finite gradients (loss scale -> %s)", scaler.scale
                )
                if self.tracer is not None:
                    self.tracer.event(
                        "train.step_skipped", category="train",
                        loss_scale=float(scaler.scale),
                    )
        else:
            opt.step_was_skipped = False
        # Every fused call IS a full optimizer step: mark the sync boundary so
        # schedulers/clipping/gather_for_metrics see the same contract as the
        # eager accumulate() flow.
        if self.gradient_state is not None:
            self.gradient_state._set_sync_gradients(True)
        from .utils.environment import fence_if_cpu

        fence_if_cpu(loss)
        if aux is not None:
            return loss, aux
        return loss

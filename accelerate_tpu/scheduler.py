"""Learning-rate scheduler wrapper (L3; reference scheduler.py:25-98).

In optax, schedules are usually baked into the transformation (step-indexed functions) —
that remains the recommended fast path and needs no wrapper. `AcceleratedScheduler`
exists for the reference's eager contract: a `.step()`-driven schedule that

  - only advances when the optimizer actually stepped (so skipped fp16 steps and
    accumulation no-op steps don't advance the schedule — reference scheduler.py:54-71);
  - advances `num_processes`× per call when `split_batches=False` so wall-clock schedule
    progress matches the global batch (reference scheduler.py:73-82);
  - pushes the current LR into the optimizer via `optax.inject_hyperparams` state.

Accepts either an optax schedule function (`step -> lr`) or any object with
`step()`/`get_last_lr()`.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Union

from .state import AcceleratorState, GradientState


class AcceleratedScheduler:
    def __init__(
        self,
        scheduler: Union[Callable, object],
        optimizers,
        step_with_optimizer: bool = True,
        split_batches: bool = False,
    ):
        self.scheduler = scheduler
        self.optimizers = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
        self.split_batches = split_batches
        self.step_with_optimizer = step_with_optimizer
        self.gradient_state = GradientState()
        self._step_count = 0
        self._last_lr: Optional[List[float]] = None
        # Seed the optimizers with the schedule's initial LR.
        self._apply_lr()

    def _compute_lr(self) -> Optional[float]:
        if callable(self.scheduler):
            return float(self.scheduler(self._step_count))
        if hasattr(self.scheduler, "get_last_lr"):
            lr = self.scheduler.get_last_lr()
            return float(lr[0]) if isinstance(lr, (list, tuple)) else float(lr)
        return None

    def _apply_lr(self):
        lr = self._compute_lr()
        if lr is not None:
            for opt in self.optimizers:
                if hasattr(opt, "set_learning_rate"):
                    opt.set_learning_rate(lr)
            self._last_lr = [lr]

    def step(self, *args, **kwargs):
        if self.step_with_optimizer:
            # Only advance at accumulation sync points...
            if not self.gradient_state.sync_gradients:
                return
            # ...and only if no optimizer skipped its step (fp16 overflow).
            if any(getattr(opt, "step_was_skipped", False) for opt in self.optimizers):
                return
            num_processes = 1 if self.split_batches else AcceleratorState().num_processes
            self._step_count += num_processes
        else:
            self._step_count += 1
        if not callable(self.scheduler) and hasattr(self.scheduler, "step"):
            self.scheduler.step(*args, **kwargs)
        self._apply_lr()

    def get_last_lr(self) -> Optional[List[float]]:
        return self._last_lr

    @property
    def step_count(self) -> int:
        return self._step_count

    def state_dict(self):
        inner = None
        if not callable(self.scheduler) and hasattr(self.scheduler, "state_dict"):
            inner = self.scheduler.state_dict()
        return {"step_count": self._step_count, "last_lr": self._last_lr, "inner": inner}

    def load_state_dict(self, state):
        self._step_count = state["step_count"]
        self._last_lr = state.get("last_lr")
        if state.get("inner") is not None and hasattr(self.scheduler, "load_state_dict"):
            self.scheduler.load_state_dict(state["inner"])
        self._apply_lr()

"""Checkpoint save/load (L3; reference checkpointing.py 273 LoC).

Full training-state round trip: model params, optimizer state (+loss scaler), scheduler,
seedable-sampler epochs, host RNG streams, and user-registered custom objects
(reference save_accelerator_state :51 / load_accelerator_state :152).

Storage format — TPU-native two-tier:
  - *Pytree files* (`save_pytree`/`load_pytree`): arrays flattened to a `path -> array`
    dict in one compressed .npz plus a JSON manifest of the tree structure and dtypes
    (bfloat16 round-trips via a uint16 view). Single-file, torch-free, safetensors-like.
  - *Sharded checkpoints*: when arrays aren't fully addressable (multi-host) the orbax/
    tensorstore path (`save_sharded`/`load_sharded`) writes per-shard — the
    torch.distributed.checkpoint replacement (reference utils/fsdp_utils.py:85-147).

Crash safety — every artifact commits via temp-file + fsync + `os.replace`, so a
SIGKILL at any byte offset leaves either the previous complete file or nothing,
never a torn one. Pytree manifests carry a SHA-256 digest of their `.npz` payload
(verified on load); `CheckpointManager` extends the same discipline to whole
checkpoint *directories*: artifacts land in a hidden staging dir, a checkpoint-level
`MANIFEST.json` with per-file digests is the commit record, the staging dir is
renamed into place atomically, a `latest` pointer is swapped, and keep-last-N
rotation plus retry-with-backoff on transient I/O errors keep long runs bounded.
Resolution (`resolve("latest")`) walks newest→oldest and skips any checkpoint whose
digests don't verify — resume survives a kill mid-save by falling back to the last
verified checkpoint.

Checkpoint rotation (`ProjectConfiguration.total_limit`) is handled by the Accelerator
through `CheckpointManager` (reference accelerator.py:2868-2894).
"""

from __future__ import annotations

import copy
import hashlib
import json
import os
import pickle
import random
import shutil
import tempfile
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .logging import get_logger
from .utils.constants import (
    MODEL_NAME,
    OPTIMIZER_NAME,
    RNG_STATE_NAME,
    SAMPLER_NAME,
    SCALER_NAME,
    SCHEDULER_NAME,
)
from .utils.imports import is_orbax_available

logger = get_logger(__name__)

_BF16_MARKER = "bfloat16"

# Checkpoint-directory commit record written by `CheckpointManager` / `write_checkpoint_manifest`.
CHECKPOINT_MANIFEST_NAME = "MANIFEST.json"
LATEST_POINTER_NAME = "latest"
_STAGING_PREFIX = ".tmp-"

# Per-host sharded layout: each process writes only its addressable shards into
# `host_{process_index:04d}/` inside the checkpoint directory; `SHARD_DONE` is
# the host's last artifact (the cross-host commit sentinel rank 0 waits on
# before the digest scan).
SHARD_HOST_PREFIX = "host_"
SHARD_DONE_NAME = "SHARD_DONE"

# Chaos seam (`accelerate_tpu.chaos.injectors.FilesystemInjector`): when armed,
# consulted at the fault-relevant points of the commit sequence — artifact
# write entry, the payload fsync, the rename window, the directory publish.
# None in production; every call site is a single attribute test.
_chaos_hooks = None


class CheckpointCorruptError(RuntimeError):
    """An artifact failed digest verification (torn write, bit rot, truncation)."""


class CheckpointCommitError(RuntimeError):
    """A checkpoint commit failed (or was aborted) after the save was accepted.

    For asynchronous saves this is how the failure-surfacing contract is kept:
    the background committer stores its failure and the NEXT barrier — the
    following `save_state`, an explicit `drain()`, or the shutdown flush —
    raises it. A failed async commit is never silently dropped."""


def _fsync_directory(path: str):
    """fsync a directory so a just-committed rename survives power loss. Best
    effort: some filesystems/platforms refuse O_RDONLY dir fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, writer: Callable, mode: str = "wb"):
    """Commit a file via temp-in-same-dir + flush + fsync + `os.replace`.

    `writer(fileobj)` produces the content. A kill at any byte offset leaves the
    destination either absent or its previous complete version — readers never
    observe a torn file. The temp name is randomized (mkstemp) so concurrent
    writers in one directory can't collide."""
    path = str(path)
    hooks = _chaos_hooks
    if hooks is not None:
        hooks.on_write(path)
    directory = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=os.path.basename(path) + ".tmp-")
    try:
        with os.fdopen(fd, mode) as f:
            writer(f)
            f.flush()
            if hooks is not None:
                hooks.on_fsync(path)
            os.fsync(f.fileno())
        if hooks is not None:
            hooks.on_rename(path)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_directory(directory)


def atomic_write_bytes(path: str, data: bytes):
    atomic_write(path, lambda f: f.write(data))


def atomic_write_json(path: str, obj):
    atomic_write(path, lambda f: json.dump(obj, f), mode="w")


def file_sha256(path: str, chunk_size: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(chunk_size), b""):
            h.update(chunk)
    return h.hexdigest()


def _flatten_with_paths(tree):
    from .parallel.sharding import tree_paths_and_leaves

    return tree_paths_and_leaves(tree)


def save_pytree(tree, path: str):
    """Save an array pytree: `<path>` (.npz) + `<path>.manifest.json` (structure)."""
    import jax

    flat, treedef = _flatten_with_paths(tree)
    arrays = {}
    manifest = {"paths": [], "dtypes": [], "treedef": None}
    for i, (p, leaf) in enumerate(flat):
        arr = np.asarray(jax.device_get(leaf)) if isinstance(leaf, jax.Array) else np.asarray(leaf)
        key = f"arr_{i}"
        if _has_bf16(arr):
            arrays[key] = arr.view(np.uint16)
            manifest["dtypes"].append(_BF16_MARKER)
        else:
            arrays[key] = arr
            manifest["dtypes"].append(str(arr.dtype))
        manifest["paths"].append(p)
    manifest["treedef"] = pickle.dumps(treedef).hex()
    path = str(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    npz_path = path if path.endswith(".npz") else path + ".npz"
    # Commit order matters: payload first, then the manifest carrying its digest
    # — the manifest is the record a loader trusts, so it must never describe a
    # payload that isn't fully on disk.
    atomic_write(npz_path, lambda f: np.savez_compressed(f, **arrays))
    manifest["npz_sha256"] = file_sha256(npz_path)
    atomic_write_json(_manifest_path(path), manifest)


def _has_bf16(arr) -> bool:
    return arr.dtype.name == "bfloat16"


def _manifest_path(path: str) -> str:
    base = path[: -len(".npz")] if path.endswith(".npz") else path
    return base + ".manifest.json"


def load_pytree(path: str, verify: bool = True):
    """Inverse of `save_pytree`; returns numpy leaves (placed by the caller).

    With `verify` (default) the payload's SHA-256 is checked against the digest
    the manifest recorded at save time; a mismatch (truncated npz, bit rot)
    raises `CheckpointCorruptError` instead of half-reading a torn file.
    Manifests from before the digest field load unverified."""
    import jax
    import jax.numpy as jnp

    path = str(path)
    npz_path = path if path.endswith(".npz") else path + ".npz"
    with open(_manifest_path(path)) as f:
        manifest = json.load(f)
    expected = manifest.get("npz_sha256")
    if verify and expected is not None:
        actual = file_sha256(npz_path)
        if actual != expected:
            raise CheckpointCorruptError(
                f"{npz_path}: SHA-256 mismatch (manifest {expected[:12]}…, file {actual[:12]}…) "
                "— torn or corrupted checkpoint artifact"
            )
    treedef = pickle.loads(bytes.fromhex(manifest["treedef"]))
    data = np.load(npz_path)
    leaves = []
    for i, dtype in enumerate(manifest["dtypes"]):
        arr = data[f"arr_{i}"]
        if dtype == _BF16_MARKER:
            arr = arr.view(jnp.bfloat16)
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ------------------------------------------------------------------ snapshots
def snapshot_pytree(tree):
    """Copy an array pytree to host NOW, so the caller may keep mutating (or
    donating) the originals while a background committer serializes the copy.

    Device-to-host copies are started non-blocking for every leaf first
    (`copy_to_host_async`, where the backend exposes it) and only then
    gathered, so the transfers overlap instead of serializing per leaf. This
    is the blocking half of an async save — cheap host RAM traffic, no disk.

    Non-fully-addressable leaves (multi-host sharded arrays) cannot be
    snapshotted whole on one process; use the per-host sharded layout
    (`snapshot_shards`) for those."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            if not leaf.is_fully_addressable:
                raise ValueError(
                    "snapshot_pytree cannot snapshot a non-fully-addressable array; "
                    "save with sharded=True so each host snapshots only its shards"
                )
            try:
                leaf.copy_to_host_async()
            except Exception:  # noqa: BLE001 — optional fast path only
                pass
    host = []
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            host.append(np.asarray(jax.device_get(leaf)))
        elif isinstance(leaf, np.ndarray):
            # A numpy leaf is HOST state the train loop may mutate in place
            # while the background committer serializes — alias it and the
            # commit tears; copy it like everything else.
            host.append(np.array(leaf, copy=True))
        else:
            host.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, host)


def _index_bounds(index, shape) -> List[List[int]]:
    """Normalize a shard index (tuple of slices) to [[start, stop], ...]."""
    bounds = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        bounds.append([start, stop])
    return bounds


def snapshot_shards(tree):
    """This process's addressable shards of an array pytree, copied to host.

    Returns ``(entries, treedef)`` where each entry is ``{"path",
    "global_shape", "dtype", "shards": [(bounds, np.ndarray), ...]}`` and
    ``bounds`` is ``[[start, stop], ...]`` per dimension in the GLOBAL array.
    Replicated shards (several local devices holding the same slice) are
    deduplicated by bounds — each process persists each distinct slice once.
    Works for fully-addressable arrays too (one shard covering everything), so
    single-host sharded checkpoints use the same format."""
    import jax

    flat, treedef = _flatten_with_paths(tree)
    # Start every device->host copy before gathering any (overlapped DMA).
    for _path, leaf in flat:
        if isinstance(leaf, jax.Array):
            for shard in leaf.addressable_shards:
                try:
                    shard.data.copy_to_host_async()
                except Exception:  # noqa: BLE001 — optional fast path only
                    pass
    entries = []
    for path, leaf in flat:
        if isinstance(leaf, jax.Array):
            shape = tuple(int(d) for d in leaf.shape)
            dtype = leaf.dtype
            seen: Dict[tuple, Any] = {}
            for shard in leaf.addressable_shards:
                bounds = _index_bounds(shard.index, shape)
                key = tuple(tuple(b) for b in bounds)
                if key not in seen:
                    seen[key] = (bounds, np.asarray(jax.device_get(shard.data)))
            shards = list(seen.values())
        else:
            # Copy, never alias (same contract as snapshot_pytree): a numpy
            # leaf the train loop mutates in place would tear mid-serialize
            # under the background committer.
            arr = np.array(leaf, copy=True)
            shape = tuple(int(d) for d in arr.shape)
            dtype = arr.dtype
            shards = [([[0, d] for d in shape], arr)]
        entries.append(
            {"path": path, "global_shape": list(shape), "dtype": dtype, "shards": shards}
        )
    return entries, treedef


def shard_host_dir(process_index: int) -> str:
    return f"{SHARD_HOST_PREFIX}{int(process_index):04d}"


def shard_host_dirs(directory: str) -> List[str]:
    """Sorted per-host subdirectories of a sharded checkpoint."""
    directory = str(directory)
    if not os.path.isdir(directory):
        return []
    return sorted(
        os.path.join(directory, name)
        for name in os.listdir(directory)
        if name.startswith(SHARD_HOST_PREFIX)
        and name[len(SHARD_HOST_PREFIX):].isdigit()
        and os.path.isdir(os.path.join(directory, name))
    )


def is_sharded_checkpoint_dir(directory: str) -> bool:
    return bool(shard_host_dirs(directory))


def save_pytree_shards(entries, treedef, path: str, process_index: int = 0):
    """Write one process's shard set (from `snapshot_shards`) as `<path>.npz`
    plus a manifest: the per-host sibling of `save_pytree`. Same commit order
    (payload first, then the digest-carrying manifest) and the same bf16
    uint16-view convention, so `write_checkpoint_manifest`'s digest reuse and
    `verify_checkpoint_dir` treat shard files like any other pytree artifact."""
    arrays = {}
    manifest: Dict[str, Any] = {
        "format": 1,
        "kind": "shards",
        "process_index": int(process_index),
        "paths": [],
        "dtypes": [],
        "global_shapes": [],
        "shards": [],
    }
    for i, entry in enumerate(entries):
        dtype = np.dtype(entry["dtype"]) if not hasattr(entry["dtype"], "name") else entry["dtype"]
        is_bf16 = getattr(dtype, "name", str(dtype)) == _BF16_MARKER
        manifest["paths"].append(entry["path"])
        manifest["dtypes"].append(_BF16_MARKER if is_bf16 else str(dtype))
        manifest["global_shapes"].append(list(entry["global_shape"]))
        shard_meta = []
        for j, (bounds, arr) in enumerate(entry["shards"]):
            key = f"arr_{i}_s{j}"
            arrays[key] = arr.view(np.uint16) if _has_bf16(arr) else arr
            shard_meta.append({"key": key, "bounds": [list(b) for b in bounds]})
        manifest["shards"].append(shard_meta)
    manifest["treedef"] = pickle.dumps(treedef).hex()
    path = str(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    npz_path = path if path.endswith(".npz") else path + ".npz"
    atomic_write(npz_path, lambda f: np.savez_compressed(f, **arrays))
    manifest["npz_sha256"] = file_sha256(npz_path)
    atomic_write_json(_manifest_path(path), manifest)


def save_pytree_host_shards(tree, path: str, process_index: int = 0):
    """`snapshot_shards` + `save_pytree_shards` in one call (the synchronous
    sharded-save convenience)."""
    entries, treedef = snapshot_shards(tree)
    save_pytree_shards(entries, treedef, path, process_index=process_index)


def _load_shard_file(path: str, verify: bool = True):
    """One host's shard file -> (manifest, npz data). Digest-verified like
    `load_pytree`."""
    path = str(path)
    npz_path = path if path.endswith(".npz") else path + ".npz"
    with open(_manifest_path(path)) as f:
        manifest = json.load(f)
    expected = manifest.get("npz_sha256")
    if verify and expected is not None:
        actual = file_sha256(npz_path)
        if actual != expected:
            raise CheckpointCorruptError(
                f"{npz_path}: SHA-256 mismatch (manifest {expected[:12]}…, file {actual[:12]}…) "
                "— torn or corrupted shard artifact"
            )
    return manifest, np.load(npz_path)


def load_pytree_gathered(checkpoint_dir: str, name: str, verify: bool = True):
    """Gather-on-load: assemble the FULL pytree `name` from every
    `host_*/<name>` shard file of a per-host sharded checkpoint.

    Works on any topology that can see all the host files (shared filesystem,
    or a single-host restore of a pod checkpoint — the test/recovery path the
    sharded layout must always support). Every leaf's shards must cover its
    global shape; a missing host file or an uncovered region raises instead of
    returning silently-zero parameters."""
    import jax
    import jax.numpy as jnp

    host_dirs = shard_host_dirs(checkpoint_dir)
    if not host_dirs:
        raise FileNotFoundError(f"{checkpoint_dir} has no {SHARD_HOST_PREFIX}* shard dirs")
    reference = None
    leaves_by_path: Dict[str, np.ndarray] = {}
    covered: Dict[str, int] = {}
    seen_bounds: Dict[str, set] = {}
    for host_dir in host_dirs:
        path = os.path.join(host_dir, name)
        if not os.path.isfile(path if path.endswith(".npz") else path + ".npz"):
            raise FileNotFoundError(
                f"sharded checkpoint {checkpoint_dir} is missing {os.path.basename(host_dir)}/{name}"
            )
        manifest, data = _load_shard_file(path, verify=verify)
        if reference is None:
            reference = manifest
        for i, leaf_path in enumerate(manifest["paths"]):
            dtype = manifest["dtypes"][i]
            shape = tuple(manifest["global_shapes"][i])
            if leaf_path not in leaves_by_path:
                np_dtype = np.uint16 if dtype == _BF16_MARKER else np.dtype(dtype)
                leaves_by_path[leaf_path] = np.zeros(shape, np_dtype)
                covered[leaf_path] = 0
                seen_bounds[leaf_path] = set()
            target = leaves_by_path[leaf_path]
            for shard in manifest["shards"][i]:
                bounds = shard["bounds"]
                key = tuple(tuple(b) for b in bounds)
                arr = data[shard["key"]]
                slices = tuple(slice(b[0], b[1]) for b in bounds)
                target[slices] = arr
                if key not in seen_bounds[leaf_path]:
                    seen_bounds[leaf_path].add(key)
                    covered[leaf_path] += int(np.prod([b[1] - b[0] for b in bounds]) if bounds else 1)
    assert reference is not None
    for i, leaf_path in enumerate(reference["paths"]):
        total = int(np.prod(reference["global_shapes"][i]) if reference["global_shapes"][i] else 1)
        if covered.get(leaf_path, 0) < total:
            raise CheckpointCorruptError(
                f"sharded checkpoint {checkpoint_dir}: shards of {leaf_path!r} cover "
                f"{covered.get(leaf_path, 0)}/{total} elements — a host's shard file is missing"
            )
    leaves = []
    for i, leaf_path in enumerate(reference["paths"]):
        arr = leaves_by_path[leaf_path]
        if reference["dtypes"][i] == _BF16_MARKER:
            arr = arr.view(jnp.bfloat16)
        leaves.append(arr)
    treedef = pickle.loads(bytes.fromhex(reference["treedef"]))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def wait_for_path(
    path: str,
    timeout_s: float = 600.0,
    poll_s: float = 0.05,
    abort: Optional[threading.Event] = None,
):
    """Poll until `path` exists — the non-main side of a file handshake (a
    collective barrier is illegal on a background committer thread)."""
    deadline = time.monotonic() + timeout_s
    while not os.path.exists(str(path)):
        if abort is not None and abort.is_set():
            raise CheckpointCommitError(f"aborted while waiting for {path}")
        if time.monotonic() >= deadline:
            raise CheckpointCommitError(f"timed out after {timeout_s:.0f}s waiting for {path}")
        time.sleep(poll_s)


def wait_for_shard_hosts(
    directory: str,
    num_hosts: int,
    timeout_s: float = 600.0,
    poll_s: float = 0.05,
    abort: Optional[threading.Event] = None,
):
    """Block until every host's `SHARD_DONE` sentinel exists under
    `directory/host_*/` — the cross-host commit barrier rank 0 runs before the
    digest scan. File-based on purpose: it is safe on a background committer
    thread, where a collective barrier is not."""
    deadline = time.monotonic() + timeout_s
    expected = [os.path.join(str(directory), shard_host_dir(i), SHARD_DONE_NAME) for i in range(num_hosts)]
    while True:
        missing = [p for p in expected if not os.path.isfile(p)]
        if not missing:
            return
        if abort is not None and abort.is_set():
            raise CheckpointCommitError("sharded commit aborted while waiting for host shards")
        if time.monotonic() >= deadline:
            raise CheckpointCommitError(
                f"timed out after {timeout_s:.0f}s waiting for host shard sentinels: "
                f"{[os.path.relpath(p, str(directory)) for p in missing]}"
            )
        time.sleep(poll_s)


def save_sharded(tree, directory: str):
    """Sharded (multi-host / non-addressable) checkpoint via orbax/tensorstore."""
    if not is_orbax_available():
        raise ImportError("Sharded checkpointing requires orbax-checkpoint")
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.abspath(directory), tree, force=True)


def load_sharded(directory: str, target=None, shardings=None):
    if not is_orbax_available():
        raise ImportError("Sharded checkpointing requires orbax-checkpoint")
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    restore_args = None
    if shardings is not None:
        import jax

        restore_args = jax.tree_util.tree_map(lambda s: ocp.ArrayRestoreArgs(sharding=s), shardings)
    return ckptr.restore(os.path.abspath(directory), item=target, restore_args=restore_args)


def _all_addressable(tree) -> bool:
    import jax

    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return False
    return True


# ------------------------------------------------------------------ safetensors export
def _parse_size(size) -> int:
    """'5GB' / '500MB' / int -> bytes."""
    if isinstance(size, int):
        return size
    s = str(size).strip().upper()
    for suffix, mult in (("GIB", 2**30), ("MIB", 2**20), ("KIB", 2**10), ("GB", 10**9), ("MB", 10**6), ("KB", 10**3)):
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)]) * mult)  # float first: '0.5GB' != 0
    return int(s)


def _leaf_to_host(leaf):
    """One leaf -> numpy on host. Non-addressable (multi-host sharded) arrays are
    allgathered process-wide — the per-PARAM gather keeps host memory bounded by
    one tensor, not the model (the reference's sharded save_model concern,
    accelerator.py:2691)."""
    import jax

    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def save_model_safetensors(params, save_directory: str, max_shard_size="5GB") -> list:
    """Write a params pytree as (sharded) safetensors with an HF-style index
    (reference save_model accelerator.py:2691 / shard_checkpoint utils/modeling.py:206).

    Tensor names are the '/'-joined pytree paths, so `load_model_safetensors`
    rebuilds the exact tree. One file under `max_shard_size` is written as
    `model.safetensors`; larger exports split into `model-00001-of-000NN.safetensors`
    plus `model.safetensors.index.json` (`utils/constants.py` SAFE_WEIGHTS_*).
    Parameters stream to host ONE AT A TIME — a fully-sharded model never
    materializes whole on any single host.

    Call on EVERY process (the non-addressable gather is a collective); only the
    main process writes. Returns the list of files written (empty on non-main).
    """
    import jax
    from safetensors.numpy import save_file

    from .utils.constants import SAFE_WEIGHTS_INDEX_NAME, SAFE_WEIGHTS_NAME

    def _atomic_save_file(tensors, target):
        # safetensors wants a filename, not a fileobj: write a sibling temp file,
        # fsync it, and commit with os.replace (same torn-write guarantee as
        # `atomic_write`).
        tmp = f"{target}.tmp-{os.getpid()}"
        try:
            save_file(tensors, tmp)
            with open(tmp, "rb+") as f:
                os.fsync(f.fileno())
            os.replace(tmp, target)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        _fsync_directory(os.path.dirname(target) or ".")

    is_main = jax.process_index() == 0
    os.makedirs(save_directory, exist_ok=True)
    flat, _ = _flatten_with_paths(params)
    budget = _parse_size(max_shard_size)

    # Plan shards greedily by byte size (no data movement yet).
    shards, current, current_bytes = [], [], 0
    sizes = {}
    for path, leaf in flat:
        nbytes = int(np.prod(getattr(leaf, "shape", ()) or ())) * np.dtype(leaf.dtype).itemsize
        sizes[path] = nbytes
        if current and current_bytes + nbytes > budget:
            shards.append(current)
            current, current_bytes = [], 0
        current.append((path, leaf))
        current_bytes += nbytes
    if current:
        shards.append(current)

    written = []
    if len(shards) == 1:
        tensors = {p: _leaf_to_host(leaf) for p, leaf in shards[0]}
        target = os.path.join(save_directory, SAFE_WEIGHTS_NAME)
        if is_main:
            _atomic_save_file(tensors, target)
            written.append(target)
        return written

    weight_map = {}
    for i, shard in enumerate(shards):
        fname = f"model-{i + 1:05d}-of-{len(shards):05d}.safetensors"
        tensors = {p: _leaf_to_host(leaf) for p, leaf in shard}
        if is_main:
            _atomic_save_file(tensors, os.path.join(save_directory, fname))
            written.append(os.path.join(save_directory, fname))
        for p, _ in shard:
            weight_map[p] = fname
        del tensors  # free the host copies before gathering the next shard
    if is_main:
        index = {
            "metadata": {"total_size": int(sum(sizes.values()))},
            "weight_map": weight_map,
        }
        index_path = os.path.join(save_directory, SAFE_WEIGHTS_INDEX_NAME)
        # Index last: it references the shards, so it must never exist before
        # every shard it names is fully committed.
        atomic_write(index_path, lambda f: json.dump(index, f, indent=2), mode="w")
        written.append(index_path)
    return written


def load_model_safetensors(directory: str):
    """Inverse of `save_model_safetensors`: rebuild the params pytree (nested dicts)
    from a safetensors file/shard directory. Leaves come back as numpy (bf16 via
    ml_dtypes); place with `PreparedModel.load_state_dict` or `place_params`."""
    from .utils.hf_loading import load_hf_state_dict

    flat = load_hf_state_dict(directory)
    tree: dict = {}
    for path, arr in flat.items():
        parts = path.split("/")
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = arr
    return tree


def save_accelerator_state(
    output_dir: str,
    models: list,
    optimizers: list,
    schedulers: list,
    dataloaders: list,
    rng_key=None,
    scaler=None,
    save_on_each_node: bool = False,
    state_dict_type: str = "SHARDED_STATE_DICT",
) -> str:
    """Save the complete training state (reference checkpointing.py:51-149).

    `state_dict_type` (FSDP plugin knob) governs multi-host layout: with
    SHARDED_STATE_DICT (default) non-addressable trees write per-shard via
    orbax/tensorstore; FULL_STATE_DICT consolidates them — each tensor is
    allgathered ONE AT A TIME and the main process writes a single npz
    (reference fsdp_utils.py:54-209 FULL vs SHARDED state dict extraction)."""
    from .state import PartialState

    state = PartialState()
    output_dir = Path(output_dir)
    os.makedirs(output_dir, exist_ok=True)

    def _save_tree(tree, name):
        if _all_addressable(tree):
            if state.is_main_process or save_on_each_node:
                save_pytree(tree, str(output_dir / name))
        elif state_dict_type == "FULL_STATE_DICT":
            import jax

            flat, treedef = _flatten_with_paths(tree)
            leaves = [_leaf_to_host(leaf) for _, leaf in flat]  # collective: all procs
            if state.is_main_process or save_on_each_node:
                save_pytree(jax.tree_util.tree_unflatten(treedef, leaves), str(output_dir / name))
        else:
            save_sharded(tree, str(output_dir / f"{name}.sharded"))

    for i, model in enumerate(models):
        name = f"{MODEL_NAME}.npz" if i == 0 else f"{MODEL_NAME}_{i}.npz"
        _save_tree(model.state_dict(), name)
        logger.info("Model weights saved in %s", output_dir / name)

    for i, opt in enumerate(optimizers):
        name = f"{OPTIMIZER_NAME}.npz" if i == 0 else f"{OPTIMIZER_NAME}_{i}.npz"
        _save_tree(opt.state_dict()["opt_state"], name)
        if opt.scaler is not None and (state.is_main_process or save_on_each_node):
            atomic_write_json(output_dir / f"{SCALER_NAME}_{i}.json", opt.scaler.state_dict())

    if state.is_main_process or save_on_each_node:
        for i, sched in enumerate(schedulers):
            name = f"{SCHEDULER_NAME}.bin" if i == 0 else f"{SCHEDULER_NAME}_{i}.bin"
            sched_state = sched.state_dict()
            atomic_write(output_dir / name, lambda f, s=sched_state: pickle.dump(s, f))

        for i, dl in enumerate(dataloaders):
            sampler = _find_seedable_sampler(dl)
            if sampler is not None:
                name = f"{SAMPLER_NAME}.bin" if i == 0 else f"{SAMPLER_NAME}_{i}.bin"
                # The loader's pass counter rides along: it is what
                # `DataLoaderShard.__iter__` feeds `set_epoch()` on the next
                # pass, and it disambiguates a mid-pass save (iteration ==
                # sampler.epoch: replay this epoch's permutation + skip) from
                # an epoch-boundary save (iteration == epoch + 1: the next
                # pass must draw a FRESH permutation, not repeat the last).
                # Explicit format marker: load-side sniffing by key presence
                # ("sampler" in payload) breaks the day a sampler's own
                # state_dict grows a 'sampler' key — version the envelope.
                payload = {"format": 2, "sampler": sampler.state_dict()}
                if hasattr(dl, "iteration"):
                    payload["loader_iteration"] = dl.iteration
                atomic_write(output_dir / name, lambda f, p=payload: pickle.dump(p, f))

    # RNG states are per-process (reference saves `random_states_{i}.pkl`,
    # checkpointing.py:122-151).
    rng_states = {"python": random.getstate(), "numpy": np.random.get_state()}
    if rng_key is not None:
        import jax

        rng_states["jax"] = np.asarray(jax.random.key_data(rng_key))
    atomic_write(
        output_dir / f"{RNG_STATE_NAME}_{state.process_index}.pkl",
        lambda f: pickle.dump(rng_states, f),
    )
    return str(output_dir)


def _find_seedable_sampler(dataloader):
    from .data_loader import SeedableRandomSampler

    candidates = [
        getattr(dataloader, "synchronized_generator", None),
        getattr(getattr(dataloader, "batch_sampler", None), "sampler", None),
    ]
    base = getattr(dataloader, "base_loader", None)
    if base is not None:
        bs = getattr(base, "batch_sampler", None)
        candidates.append(getattr(bs, "sampler", None))
        inner = getattr(bs, "batch_sampler", None)
        if inner is not None:
            candidates.append(getattr(inner, "sampler", None))
    for c in candidates:
        if isinstance(c, SeedableRandomSampler):
            return c
    return None


# ------------------------------------------------------------ snapshot-then-commit state
def _sampler_payload(dl) -> Optional[dict]:
    """The versioned sampler envelope `save_accelerator_state` writes (format 2:
    sampler state + the loader's pass counter), or None when the loader has no
    seedable sampler."""
    sampler = _find_seedable_sampler(dl)
    if sampler is None:
        return None
    payload = {"format": 2, "sampler": copy.deepcopy(sampler.state_dict())}
    if hasattr(dl, "iteration"):
        payload["loader_iteration"] = dl.iteration
    return payload


def snapshot_accelerator_state(
    models: list,
    optimizers: list,
    schedulers: list,
    dataloaders: list,
    rng_key=None,
    sharded: bool = False,
    custom_objects: tuple = (),
) -> dict:
    """The BLOCKING half of an async save: copy every piece of training state
    to host memory and return it as plain data, so a background committer can
    serialize and fsync it while the train loop keeps stepping (and donating
    the very buffers this snapshot copied).

    With ``sharded=True``, array trees snapshot as this process's addressable
    shards (`snapshot_shards`) — the per-host layout each process later writes
    under its own ``host_*/`` subdirectory. Host-side objects (schedulers,
    sampler envelopes, custom state) are deep-copied: the live objects keep
    mutating the moment this returns."""
    snap_tree = snapshot_shards if sharded else snapshot_pytree
    snapshot: Dict[str, Any] = {"sharded": bool(sharded)}
    snapshot["models"] = [snap_tree(m.state_dict()) for m in models]
    snapshot["optimizers"] = [snap_tree(opt.state_dict()["opt_state"]) for opt in optimizers]
    snapshot["scalers"] = [
        copy.deepcopy(opt.scaler.state_dict()) if opt.scaler is not None else None
        for opt in optimizers
    ]
    snapshot["schedulers"] = [copy.deepcopy(s.state_dict()) for s in schedulers]
    snapshot["samplers"] = [_sampler_payload(dl) for dl in dataloaders]
    rng_states: Dict[str, Any] = {"python": random.getstate(), "numpy": np.random.get_state()}
    if rng_key is not None:
        import jax

        rng_states["jax"] = np.asarray(jax.random.key_data(rng_key))
    snapshot["rng"] = rng_states
    snapshot["custom"] = [copy.deepcopy(obj.state_dict()) for obj in custom_objects]
    return snapshot


def write_accelerator_snapshot(
    snapshot: dict,
    output_dir: str,
    process_index: int = 0,
    num_processes: int = 1,
    is_main: bool = True,
    save_on_each_node: bool = False,
    abort: Optional[threading.Event] = None,
    shard_barrier_timeout_s: float = 600.0,
) -> str:
    """Serialize a `snapshot_accelerator_state` snapshot into `output_dir` —
    the COMMIT half, safe on a background thread (no live objects, no device
    arrays, no collectives).

    Unsharded snapshots reproduce `save_accelerator_state`'s exact file layout,
    so `load_accelerator_state` reads them unchanged. Sharded snapshots write
    this process's array shards under ``host_{process_index:04d}/`` (model,
    optimizer, and this process's RNG stream), finish the host dir with the
    ``SHARD_DONE`` sentinel, and — on the main process — wait for every other
    host's sentinel (file barrier: a collective would be illegal here) before
    returning, so the caller's digest scan sees the complete shard set.
    Host-side objects (schedulers, samplers, scalers, custom state) stay
    top-level and main-process-owned in both layouts."""
    output_dir = Path(output_dir)
    if is_main or num_processes == 1:
        os.makedirs(output_dir, exist_ok=True)
    else:
        # Non-main hosts must NOT create the (staging) directory themselves:
        # the main host clears and recreates it at the start of the commit, so
        # a non-main host that raced ahead would have its freshly-written
        # shards rmtree'd from under it. Wait for main's mkdir instead — the
        # file-handshake half of the barrier the committer thread cannot run
        # as a collective. (Worst case — staging litter from a KILLED previous
        # save of the same step satisfies this wait early and main's recreate
        # reaps this host's writes: the SHARD_DONE wait then times the commit
        # out. A failed save, never a published checkpoint missing a host.)
        wait_for_path(str(output_dir), timeout_s=shard_barrier_timeout_s, abort=abort)
    sharded = bool(snapshot.get("sharded"))
    if sharded:
        host_root = output_dir / shard_host_dir(process_index)
        os.makedirs(host_root, exist_ok=True)
        array_dir = host_root
    else:
        array_dir = output_dir

    def check_abort(where: str):
        if abort is not None and abort.is_set():
            raise CheckpointCommitError(f"checkpoint commit aborted before {where}")

    host_files: List[str] = []
    for i, tree in enumerate(snapshot["models"]):
        name = f"{MODEL_NAME}.npz" if i == 0 else f"{MODEL_NAME}_{i}.npz"
        check_abort(name)
        if sharded:
            entries, treedef = tree
            save_pytree_shards(entries, treedef, str(array_dir / name), process_index)
        elif is_main or save_on_each_node:
            save_pytree(tree, str(array_dir / name))
        host_files.append(name)
    for i, tree in enumerate(snapshot["optimizers"]):
        name = f"{OPTIMIZER_NAME}.npz" if i == 0 else f"{OPTIMIZER_NAME}_{i}.npz"
        check_abort(name)
        if sharded:
            entries, treedef = tree
            save_pytree_shards(entries, treedef, str(array_dir / name), process_index)
        elif is_main or save_on_each_node:
            save_pytree(tree, str(array_dir / name))
        host_files.append(name)
        scaler_state = snapshot["scalers"][i]
        if scaler_state is not None and (is_main or save_on_each_node):
            atomic_write_json(output_dir / f"{SCALER_NAME}_{i}.json", scaler_state)

    rng_name = f"{RNG_STATE_NAME}_{process_index}.pkl"
    rng_target = (array_dir if sharded else output_dir) / rng_name
    atomic_write(rng_target, lambda f: pickle.dump(snapshot["rng"], f))

    if is_main:
        for i, sched_state in enumerate(snapshot["schedulers"]):
            name = f"{SCHEDULER_NAME}.bin" if i == 0 else f"{SCHEDULER_NAME}_{i}.bin"
            atomic_write(output_dir / name, lambda f, s=sched_state: pickle.dump(s, f))
        for i, payload in enumerate(snapshot["samplers"]):
            if payload is None:
                continue
            name = f"{SAMPLER_NAME}.bin" if i == 0 else f"{SAMPLER_NAME}_{i}.bin"
            atomic_write(output_dir / name, lambda f, p=payload: pickle.dump(p, f))
        for i, obj_state in enumerate(snapshot.get("custom", [])):
            location = output_dir / f"custom_checkpoint_{i}.pkl"
            atomic_write(location, lambda f, s=obj_state: pickle.dump(s, f))

    if sharded:
        # The host's last artifact: its commit sentinel. Written atomically so
        # its presence means every file it names is fully on disk.
        atomic_write_json(
            host_root / SHARD_DONE_NAME,
            {"process_index": int(process_index), "files": sorted(host_files)},
        )
        if is_main and num_processes > 1:
            check_abort("host shard barrier")
            wait_for_shard_hosts(
                str(output_dir), num_processes, timeout_s=shard_barrier_timeout_s, abort=abort
            )
    return str(output_dir)


def sharded_manifest_extra(num_processes: int) -> dict:
    """The topology block a sharded checkpoint's MANIFEST.json carries, so
    resolve/restore tooling knows the shard set without globbing."""
    return {
        "sharded": {
            "num_hosts": int(num_processes),
            "hosts": [shard_host_dir(i) for i in range(num_processes)],
        }
    }


def load_accelerator_state(
    input_dir: str,
    models: list,
    optimizers: list,
    schedulers: list,
    dataloaders: list,
    load_rng: bool = True,
):
    """Restore the complete training state (reference checkpointing.py:152-254).

    Returns the restored jax RNG key if one was saved (or None)."""
    input_dir = Path(input_dir)

    for i, model in enumerate(models):
        name = f"{MODEL_NAME}.npz" if i == 0 else f"{MODEL_NAME}_{i}.npz"
        if (input_dir / f"{name}.sharded").exists():
            params = load_sharded(str(input_dir / f"{name}.sharded"), shardings=model.param_sharding)
        else:
            params = load_pytree(str(input_dir / name))
        model.load_state_dict(params)
        logger.info("Model weights loaded from %s", input_dir / name)

    for i, opt in enumerate(optimizers):
        name = f"{OPTIMIZER_NAME}.npz" if i == 0 else f"{OPTIMIZER_NAME}_{i}.npz"
        if (input_dir / f"{name}.sharded").exists():
            opt_state = load_sharded(str(input_dir / f"{name}.sharded"), shardings=opt.opt_state_sharding)
        else:
            opt_state = load_pytree(str(input_dir / name))
        scaler_state = None
        scaler_path = input_dir / f"{SCALER_NAME}_{i}.json"
        if scaler_path.exists():
            with open(scaler_path) as f:
                scaler_state = json.load(f)
        opt.load_state_dict({"opt_state": opt_state, "scaler": scaler_state})

    return _load_host_side_state(input_dir, schedulers, dataloaders, load_rng)


def _load_host_side_state(
    input_dir: Path,
    schedulers: list,
    dataloaders: list,
    load_rng: bool,
    rng_dir: Optional[Path] = None,
):
    """Schedulers, sampler envelopes, and RNG streams — the host-side half of a
    restore, shared by the flat and per-host-sharded layouts (`rng_dir` points
    at the host subdirectory holding this process's RNG pickle when sharded).
    Returns the restored jax RNG key, or None."""
    import jax

    from .state import PartialState

    state = PartialState()
    input_dir = Path(input_dir)
    rng_dir = Path(rng_dir) if rng_dir is not None else input_dir

    for i, sched in enumerate(schedulers):
        name = f"{SCHEDULER_NAME}.bin" if i == 0 else f"{SCHEDULER_NAME}_{i}.bin"
        if (input_dir / name).exists():
            with open(input_dir / name, "rb") as f:
                sched.load_state_dict(pickle.load(f))

    for i, dl in enumerate(dataloaders):
        sampler = _find_seedable_sampler(dl)
        name = f"{SAMPLER_NAME}.bin" if i == 0 else f"{SAMPLER_NAME}_{i}.bin"
        if sampler is not None and (input_dir / name).exists():
            with open(input_dir / name, "rb") as f:
                payload = pickle.load(f)
            if payload.get("format") == 2:
                sampler.load_state_dict(payload["sampler"])
                loader_iteration = payload.get("loader_iteration")
            elif "format" in payload:
                # A versioned envelope from a NEWER writer: refuse loudly
                # instead of feeding the whole envelope into load_state_dict
                # and crashing on a missing key three frames deeper.
                raise ValueError(
                    f"unsupported sampler checkpoint format {payload['format']!r} in "
                    f"{input_dir / name} (this version reads format 2 and earlier)"
                )
            elif "sampler" in payload:
                # round-4 wrapped format (pre-marker): {"sampler": ..., "loader_iteration": ...}
                sampler.load_state_dict(payload["sampler"])
                loader_iteration = payload.get("loader_iteration")
            else:  # pre-round-4 checkpoint: bare sampler state_dict
                sampler.load_state_dict(payload)
                loader_iteration = payload.get("epoch")
            # Realign the loader's pass counter: `DataLoaderShard.__iter__`
            # calls `set_epoch(self.iteration)` at the top of every pass, and
            # a fresh process's 0 would clobber the restored epoch — the
            # resumed pass would replay epoch 0's permutation, so
            # `skip_first_batches` would skip the WRONG samples.
            if loader_iteration is not None and hasattr(dl, "iteration"):
                dl.iteration = loader_iteration

    rng_key = None
    if load_rng:
        rng_path = rng_dir / f"{RNG_STATE_NAME}_{state.process_index}.pkl"
        if not rng_path.exists() and rng_dir != input_dir:
            # Gather-on-load of a pod checkpoint on fewer hosts: fall back to
            # host 0's RNG stream (process indices shifted under it).
            rng_path = input_dir / shard_host_dir(0) / f"{RNG_STATE_NAME}_0.pkl"
        if rng_path.exists():
            with open(rng_path, "rb") as f:
                rng_states = pickle.load(f)
            random.setstate(rng_states["python"])
            np.random.set_state(rng_states["numpy"])
            if "jax" in rng_states:
                rng_key = jax.random.wrap_key_data(np.asarray(rng_states["jax"]))
    return rng_key


def load_sharded_accelerator_state(
    input_dir: str,
    models: list,
    optimizers: list,
    schedulers: list,
    dataloaders: list,
    load_rng: bool = True,
):
    """Restore from a per-host sharded checkpoint (``host_*/`` layout).

    Array trees gather-on-load (`load_pytree_gathered`) — every host's shard
    files are read and assembled into full host arrays, which placement
    (`load_state_dict` -> the model's shardings) then re-shards onto the
    CURRENT mesh. This restores on the same topology AND on a single host (the
    preemption-recovery and test path); the cost is one full-tree
    materialization per process, the price of topology independence. Returns
    the restored jax RNG key, or None."""
    from .state import PartialState

    state = PartialState()
    input_dir = Path(input_dir)

    for i, model in enumerate(models):
        name = f"{MODEL_NAME}.npz" if i == 0 else f"{MODEL_NAME}_{i}.npz"
        params = load_pytree_gathered(str(input_dir), name)
        model.load_state_dict(params)
        logger.info("Model weights gathered from shards of %s", input_dir / name)

    for i, opt in enumerate(optimizers):
        name = f"{OPTIMIZER_NAME}.npz" if i == 0 else f"{OPTIMIZER_NAME}_{i}.npz"
        opt_state = load_pytree_gathered(str(input_dir), name)
        scaler_state = None
        scaler_path = input_dir / f"{SCALER_NAME}_{i}.json"
        if scaler_path.exists():
            with open(scaler_path) as f:
                scaler_state = json.load(f)
        opt.load_state_dict({"opt_state": opt_state, "scaler": scaler_state})

    rng_dir = input_dir / shard_host_dir(state.process_index)
    return _load_host_side_state(input_dir, schedulers, dataloaders, load_rng, rng_dir=rng_dir)


def save_custom_state(obj, path: str, index: int = 0):
    """Pickle an object exposing state_dict() (reference checkpointing.py:257)."""
    location = Path(path) / f"custom_checkpoint_{index}.pkl"
    logger.info("Saving the state of %s to %s", type(obj).__name__, location)
    obj_state = obj.state_dict()
    atomic_write(location, lambda f: pickle.dump(obj_state, f))


# ------------------------------------------------------------------ async committer
class AsyncCommitter:
    """One background checkpoint commit at a time, with the barrier-surfacing
    failure contract.

    ``submit(fn, label)`` first barriers on the previous commit (raising its
    stored failure, if any) and then runs ``fn(abort_event)`` on a daemon
    thread. ``wait()`` joins the in-flight commit and raises its failure;
    ``drain()`` is the shutdown alias. ``abort_and_join()`` sets the abort
    event — consulted by `CheckpointManager.save` at every phase boundary — and
    joins WITHOUT raising: the hard-shutdown path, where the process is dying
    and an unpublished commit must stay unpublished (a half-dead process must
    never publish a checkpoint).

    Failure wrapping: ordinary exceptions surface as `CheckpointCommitError`
    (with ``__cause__`` preserved); BaseExceptions that are not Exceptions
    (KeyboardInterrupt, an injected kill) re-raise as themselves — they mean
    "this process is dying", not "this commit failed"."""

    def __init__(self, name: str = "ckpt-committer"):
        self.name = name
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._abort = threading.Event()
        self._label: Optional[str] = None
        self._lock = threading.Lock()

    @property
    def in_flight(self) -> bool:
        thread = self._thread
        return thread is not None and thread.is_alive()

    @property
    def label(self) -> Optional[str]:
        return self._label

    def _raise_pending(self):
        error, self._error = self._error, None
        if error is None:
            return
        if isinstance(error, Exception):
            raise CheckpointCommitError(
                f"background checkpoint commit failed ({self._label}): {error}"
            ) from error
        raise error  # process-death class (KeyboardInterrupt / injected kill)

    def poll(self):
        """Non-blocking surface of a DEAD committer's process-death failure
        (BaseException-not-Exception only — an ordinary commit failure keeps
        to the barrier contract and waits for the next `wait()`)."""
        if self.in_flight:
            return
        if self._error is not None and not isinstance(self._error, Exception):
            error, self._error = self._error, None
            raise error

    def wait(self, timeout: Optional[float] = None):
        """Barrier on the in-flight commit; raises its failure (and any stored
        failure from an earlier commit)."""
        thread = self._thread
        if thread is not None:
            thread.join(timeout)
            if thread.is_alive():
                raise CheckpointCommitError(
                    f"background checkpoint commit still running after {timeout}s ({self._label})"
                )
            self._thread = None
        self._raise_pending()

    def drain(self, timeout: Optional[float] = None):
        self.wait(timeout)

    def abort_and_join(self, timeout: float = 30.0) -> Optional[BaseException]:
        """Hard shutdown: request abort, join, and RETURN (not raise) whatever
        the commit died of. The abort event is left set — this committer is
        done; build a fresh one to save again."""
        self._abort.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout)
            self._thread = None
        error, self._error = self._error, None
        return error

    def submit(self, fn: Callable[[threading.Event], Any], label: str = "checkpoint"):
        """Barrier on the previous commit, then start `fn(abort_event)` in the
        background. Raises the previous commit's failure HERE — the contract's
        "surfaces on the next save" barrier."""
        with self._lock:
            self.wait()
            if self._abort.is_set():
                raise CheckpointCommitError("committer was aborted; create a fresh one")
            self._label = label

            def run():
                try:
                    fn(self._abort)
                except BaseException as exc:  # noqa: BLE001 — stored, surfaced at the barrier
                    self._error = exc
                    logger.warning("background checkpoint commit (%s) failed: %r", label, exc)

            self._thread = threading.Thread(target=run, name=self.name, daemon=True)
            self._thread.start()


# ------------------------------------------------------------------ crash-safe manager
def _rmtree_missing_ok(path: str):
    """`shutil.rmtree` that treats an already-gone tree as success — required
    under `_retry` (chaos-surfaced bug): a first attempt that raised a
    transient error AFTER deleting most of the tree must not make the retry
    fail on the now-missing path and abort a save whose rotation had in fact
    completed."""
    try:
        shutil.rmtree(path)
    except FileNotFoundError:
        pass


def write_checkpoint_manifest(
    directory: str, step: Optional[int] = None, extra: Optional[dict] = None
) -> str:
    """Commit record for a checkpoint DIRECTORY: scan every artifact, digest it,
    and atomically write `MANIFEST.json`. Written LAST — its presence asserts
    every file it names was fully on disk first. `extra` merges additional
    top-level fields into the record (e.g. the sharded-layout topology block)."""
    directory = str(directory)
    entries = []
    for root, dirs, names in os.walk(directory):
        dirs[:] = [d for d in dirs if not d.startswith(_STAGING_PREFIX)]
        for name in names:
            # Skip the commit record itself, the latest pointer, and atomic-write
            # temp litter a killed previous writer may have left behind.
            if name in (CHECKPOINT_MANIFEST_NAME, LATEST_POINTER_NAME) or ".tmp-" in name:
                continue
            entries.append((os.path.relpath(os.path.join(root, name), directory), name))
    # Reuse the digests `save_pytree` already computed: each `X.manifest.json`
    # records the SHA-256 of its just-written sibling `X.npz`. The npz payloads
    # are the bulk of a checkpoint, so this turns the digest scan's second full
    # disk read of the model/optimizer state into a JSON lookup — save latency
    # matters most on the preemption path, where it races the hard kill.
    known = {}
    for rel, name in entries:
        if not name.endswith(".manifest.json"):
            continue
        try:
            with open(os.path.join(directory, rel)) as f:
                digest = json.load(f).get("npz_sha256")
        except (OSError, ValueError):  # ValueError: JSON errors AND flipped-byte utf-8 tears
            continue
        if digest:
            known[rel[: -len(".manifest.json")] + ".npz"] = digest
    files = {
        rel: known.get(rel) or file_sha256(os.path.join(directory, rel)) for rel, _ in entries
    }
    manifest_path = os.path.join(directory, CHECKPOINT_MANIFEST_NAME)
    record = {"format": 1, "step": step, "files": files}
    if extra:
        record.update(extra)
    atomic_write_json(manifest_path, record)
    return manifest_path


def verify_checkpoint_dir(directory: str) -> bool:
    """True iff the directory carries a `MANIFEST.json` and every file it names
    exists with a matching SHA-256. A directory without a manifest (killed before
    commit, or a pre-digest legacy checkpoint) does NOT verify."""
    manifest_path = os.path.join(str(directory), CHECKPOINT_MANIFEST_NAME)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        # ValueError, not just JSONDecodeError (chaos-surfaced bug): a single
        # flipped byte can make the manifest invalid UTF-8, and the resulting
        # UnicodeDecodeError used to CRASH resolution instead of reading as
        # "this checkpoint does not verify — fall back".
        return False
    for rel, digest in manifest.get("files", {}).items():
        full = os.path.join(str(directory), rel)
        try:
            if file_sha256(full) != digest:
                logger.warning("checkpoint %s: digest mismatch on %s", directory, rel)
                return False
        except OSError:
            logger.warning("checkpoint %s: missing artifact %s", directory, rel)
            return False
    return True


class CheckpointManager:
    """Rotated, digest-verified, atomically-published checkpoints under one base dir.

    Layout::

        base_dir/
          checkpoint_0/          # complete, committed (has MANIFEST.json)
          checkpoint_1/
          latest                 # text file naming the newest committed checkpoint
          .tmp-checkpoint_2/     # in-flight staging (ignored by readers, reaped)

    `save(step, write_fn)` stages everything in a hidden temp directory, writes the
    per-file digest manifest, `os.replace`s the directory into place (the single
    commit point — a kill before it leaves only ignorable staging litter), swaps
    the `latest` pointer, and rotates to `keep_last_n`. Transient I/O errors in the
    commit sequence retry with exponential backoff. `resolve("latest")` returns the
    newest checkpoint that VERIFIES, falling back past a corrupt or torn newest one.

    The `latest` pointer file is a breadcrumb for humans and external tooling
    (and the `is_manager_dir` sniff), NOT the source of truth for resume:
    `resolve()` always re-verifies from the directory listing, so a pointer left
    stale by a kill between publish and pointer swap — or pointing at a
    checkpoint that later rotted — can never misdirect a resume.

    Multi-process: pass `is_main`/`barrier` so every process writes its per-process
    artifacts into the shared staging dir while exactly one commits.
    """

    def __init__(
        self,
        base_dir: str,
        keep_last_n: Optional[int] = None,
        retries: int = 3,
        backoff_seconds: float = 0.1,
    ):
        if keep_last_n is not None and keep_last_n < 1:
            raise ValueError(f"keep_last_n must be >= 1 (got {keep_last_n})")
        self.base_dir = str(base_dir)
        self.keep_last_n = keep_last_n
        self.retries = retries
        self.backoff_seconds = backoff_seconds
        # Steps staged by in-flight save() calls (a background committer's
        # checkpoint is invisible on disk until its publish rename): consulted
        # by next_step() under the lock so two overlapping saves can never be
        # handed the same step number.
        self._step_lock = threading.Lock()
        self._inflight_steps: set = set()

    # ---------------------------------------------------------------- inventory
    def checkpoints(self) -> List[Tuple[int, str]]:
        """(step, path) pairs sorted numerically ascending (lexicographic listdir
        would order checkpoint_10 before checkpoint_9)."""
        if not os.path.isdir(self.base_dir):
            return []
        out = []
        for name in os.listdir(self.base_dir):
            if name.startswith(_STAGING_PREFIX) or not name.startswith("checkpoint_"):
                continue
            suffix = name[len("checkpoint_"):]
            if suffix.isdigit() and os.path.isdir(os.path.join(self.base_dir, name)):
                out.append((int(suffix), os.path.join(self.base_dir, name)))
        return sorted(out)

    def next_step(self) -> int:
        """Next unused step number — race-safe against a background committer:
        a step whose `save()` is still in flight (staged, not yet published, so
        invisible to the directory listing) is already taken. Callers that
        interleave `next_step()` with async `save()`s therefore never collide;
        the regression this pins is two overlapping saves both minting step N."""
        with self._step_lock:
            ckpts = self.checkpoints()
            disk_next = ckpts[-1][0] + 1 if ckpts else 0
            inflight_next = max(self._inflight_steps) + 1 if self._inflight_steps else 0
            return max(disk_next, inflight_next)

    def latest_verified(self) -> Optional[str]:
        """Newest checkpoint whose digests verify; corrupt/torn ones are skipped
        with a warning (the resume-past-a-bad-newest fallback).

        Legacy checkpoints (written before the manifest discipline, so they have
        no `MANIFEST.json` to verify against) are not abandoned: when NOTHING
        digest-verifies, the newest manifest-less one is returned as a last
        resort — an in-place upgrade must still resume from its old saves. A
        directory whose manifest EXISTS but fails is definitely torn and is
        never used."""
        legacy = None
        for step, path in reversed(self.checkpoints()):
            if verify_checkpoint_dir(path):
                return path
            if not os.path.isfile(os.path.join(path, CHECKPOINT_MANIFEST_NAME)):
                if legacy is None:
                    legacy = path
            else:
                logger.warning(
                    "checkpoint %s failed verification (torn or corrupt); falling back", path
                )
        if legacy is not None:
            logger.warning(
                "no digest-verified checkpoint under %s; falling back to legacy "
                "pre-manifest checkpoint %s (loaded without directory-level verification)",
                self.base_dir, legacy,
            )
        return legacy

    def resolve(self, spec: Optional[str] = None) -> str:
        """'latest'/None -> newest VERIFIED checkpoint; an explicit path is
        verified and returned. Raises FileNotFoundError when nothing usable
        exists and CheckpointCorruptError for an explicitly-named bad one."""
        if spec in (None, "latest"):
            path = self.latest_verified()
            if path is None:
                raise FileNotFoundError(
                    f"no verified checkpoint under {self.base_dir} "
                    f"({len(self.checkpoints())} candidate(s) present)"
                )
            return path
        spec = str(spec)
        if not os.path.isdir(spec):
            raise FileNotFoundError(f"checkpoint directory {spec} does not exist")
        if os.path.isfile(os.path.join(spec, CHECKPOINT_MANIFEST_NAME)) and not verify_checkpoint_dir(spec):
            raise CheckpointCorruptError(f"checkpoint {spec} failed digest verification")
        return spec

    @staticmethod
    def is_manager_dir(path: str) -> bool:
        """A base dir the manager owns (vs a concrete checkpoint dir): has a
        `latest` pointer or `checkpoint_N` children but no own MANIFEST."""
        path = str(path)
        if not os.path.isdir(path) or os.path.isfile(os.path.join(path, CHECKPOINT_MANIFEST_NAME)):
            return False
        if os.path.isfile(os.path.join(path, LATEST_POINTER_NAME)):
            return True
        return bool(CheckpointManager(path).checkpoints())

    # ---------------------------------------------------------------- commit path
    def _retry(self, fn, what: str):
        for attempt in range(self.retries + 1):
            try:
                return fn()
            except OSError as exc:
                if attempt == self.retries:
                    raise
                delay = self.backoff_seconds * (2**attempt)
                logger.warning(
                    "transient I/O error during %s (%s); retry %d/%d in %.2fs",
                    what, exc, attempt + 1, self.retries, delay,
                )
                time.sleep(delay)

    def clean_staging(self):
        """Reap staging litter left by a killed save (never a committed checkpoint)."""
        if not os.path.isdir(self.base_dir):
            return
        for name in os.listdir(self.base_dir):
            if name.startswith(_STAGING_PREFIX):
                shutil.rmtree(os.path.join(self.base_dir, name), ignore_errors=True)

    @staticmethod
    def _check_abort(abort: Optional[threading.Event], where: str):
        """Abort is the committer-shutdown analogue of a kill: consulted at
        every phase boundary of the commit sequence so an aborted background
        commit stops BEFORE the publish rename — a dying process must leave
        staging litter, never a newly-visible checkpoint."""
        if abort is not None and abort.is_set():
            raise CheckpointCommitError(f"checkpoint commit aborted before {where}")

    def save(
        self,
        step: int,
        write_fn: Callable[[str], Any],
        is_main: bool = True,
        barrier: Optional[Callable[[], Any]] = None,
        abort: Optional[threading.Event] = None,
        manifest_extra: Optional[dict] = None,
    ) -> str:
        """Stage -> digest-manifest -> atomic publish -> latest pointer -> rotate.

        `write_fn(staging_dir)` writes every artifact. The checkpoint only becomes
        visible (and `latest` only advances) after everything it contains — and
        the manifest describing it — is fully on disk. `abort` (an Event, set by
        `AsyncCommitter.abort_and_join`) stops the commit at the next phase
        boundary without publishing; `manifest_extra` merges extra fields into
        the commit record (the sharded-layout topology block)."""
        barrier = barrier or (lambda: None)
        final = os.path.join(self.base_dir, f"checkpoint_{step}")
        with self._step_lock:
            if step in self._inflight_steps:
                raise ValueError(
                    f"checkpoint step {step} already has a save in flight; overlapping "
                    "saves must use distinct steps (next_step() hands them out race-safely)"
                )
            self._inflight_steps.add(step)
        try:
            replace_torn = False
            if os.path.exists(final):
                # A resumed run that fell back past a torn newest checkpoint will
                # re-save its step number: replacing a directory whose manifest
                # FAILS is safe (it can never serve a resume). A verified one — or
                # a manifest-less LEGACY one, which resume may still fall back to —
                # is never clobbered.
                has_manifest = os.path.isfile(os.path.join(final, CHECKPOINT_MANIFEST_NAME))
                if not has_manifest or verify_checkpoint_dir(final):
                    raise ValueError(
                        f"Checkpoint directory {final} already exists; use a different step "
                        "or a fresh base directory."
                    )
                logger.warning("replacing unverifiable existing checkpoint %s", final)
                replace_torn = True
            staging = os.path.join(self.base_dir, f"{_STAGING_PREFIX}checkpoint_{step}")
            if is_main:
                os.makedirs(self.base_dir, exist_ok=True)
                shutil.rmtree(staging, ignore_errors=True)
                os.makedirs(staging)
            barrier()  # staging dir exists before any process writes into it
            self._check_abort(abort, "artifact write")
            write_fn(staging)
            barrier()  # every process's artifacts are in before the digest scan
            self._check_abort(abort, "manifest write")
            if is_main:
                self._retry(
                    lambda: write_checkpoint_manifest(staging, step, extra=manifest_extra),
                    "manifest write",
                )
                if replace_torn:
                    # Retire the torn dir just before publishing: the new checkpoint
                    # (manifest included) is already fully on disk in staging, so a
                    # kill in this window loses nothing that could have been loaded.
                    self._retry(lambda: _rmtree_missing_ok(final), f"reap of torn {final}")
                self._check_abort(abort, "publish")
                self._retry(lambda: self._publish(staging, final), "checkpoint publish")
                self._rotate(keep=final)
            barrier()
            return final
        finally:
            with self._step_lock:
                self._inflight_steps.discard(step)

    def _publish(self, staging: str, final: str):
        # Idempotent under `_retry` (chaos-surfaced bug): a transient failure
        # AFTER the rename — the directory fsync or the pointer write — used to
        # make the retry re-run `os.replace` on a staging dir that no longer
        # exists, so a fully-committed checkpoint still raised out of save()
        # and the caller burned a restart on a save that had in fact succeeded.
        # The rename is THE commit point; once `final` exists, a retry only
        # needs to finish the pointer swap.
        hooks = _chaos_hooks
        if os.path.isdir(staging):
            if hooks is not None:
                hooks.on_publish_rename(staging, final)
            os.replace(staging, final)  # THE commit point (atomic dir rename)
        elif not os.path.isdir(final):
            raise FileNotFoundError(
                f"checkpoint publish lost both staging ({staging}) and committed ({final}) dirs"
            )
        _fsync_directory(self.base_dir)
        atomic_write(
            os.path.join(self.base_dir, LATEST_POINTER_NAME),
            lambda f: f.write(os.path.basename(final)),
            mode="w",
        )
        if hooks is not None:
            hooks.on_published(final)

    def _rotate(self, keep: str):
        if self.keep_last_n is None:
            return
        ckpts = self.checkpoints()
        excess = len(ckpts) - self.keep_last_n
        if excess <= 0:
            return
        # Strictly oldest-first by step. Manifest-less directories are LEGACY
        # checkpoints (in the post-manifest world a torn save never becomes a
        # `checkpoint_N` at all — the staging rename is atomic), so they age
        # out in step order like any other checkpoint rather than being
        # preferentially destroyed while they may still be the only resumable
        # state.
        for _step, path in ckpts:
            if excess <= 0:
                break
            if os.path.abspath(path) == os.path.abspath(keep):
                continue  # never reap the checkpoint just committed
            logger.info("rotating out checkpoint %s (keep_last_n=%d)", path, self.keep_last_n)
            self._retry(lambda p=path: _rmtree_missing_ok(p), f"rotation of {path}")
            excess -= 1


def load_custom_state(obj, path: str, index: int = 0):
    """(reference checkpointing.py:267)"""
    location = Path(path) / f"custom_checkpoint_{index}.pkl"
    if not location.exists():
        # Hard failure on purpose: silently keeping the object's constructed
        # state would resume at a wrong position (e.g. a step counter at 0 on
        # fully-trained weights). The usual cause is actionable.
        raise FileNotFoundError(
            f"Checkpoint has no saved state for registered object {index} "
            f"({type(obj).__name__}) at {location}. If this object was "
            "registered for checkpointing AFTER the checkpoint was written, "
            "resume once without registering it (or write a fresh checkpoint)."
        )
    with open(location, "rb") as f:
        obj.load_state_dict(pickle.load(f))


# ------------------------------------------------------------------ adaptive cadence
class AdaptiveSaveInterval:
    """Goodput-driven checkpoint cadence: derive *how often to save* from the
    MEASURED cost of saving versus a lost-work budget, instead of a fixed step
    count (ROADMAP 4b).

    Two observations feed the controller (both host-side seconds, typically
    straight out of the goodput ledger's "checkpoint" cause):

      - ``observe_step(seconds)``  — one training step's wall clock;
      - ``observe_save(seconds)``  — one save's BLOCKING cost (for async saves
        this is only the snapshot+barrier time, exactly what the ledger
        charges — the background commit is free cadence-wise).

    Both are folded into exponential moving averages, and the interval is::

        budget_cap     = lost_checkpoint_s / avg_step_s      # save at least
                                                             # this often: a
                                                             # crash loses at
                                                             # most the budget
        overhead_floor = avg_save_s / (overhead_fraction * avg_step_s)
                                                             # save at most
                                                             # this often: save
                                                             # cost stays under
                                                             # the goodput
                                                             # fraction
        interval = clamp(budget_cap, min_interval, max_interval)
        interval = max(interval, overhead_floor)             # goodput wins a
                                                             # conflict (warned
                                                             # once): a budget
                                                             # you cannot
                                                             # afford degrades
                                                             # rather than
                                                             # drowning the run
                                                             # in saves

    A ``fixed_interval`` turns the controller into the classic every-N-steps
    cadence (observations still recorded, so flipping to adaptive later has
    warm EMAs). The controller is pure observation -> arithmetic: no clocks,
    no I/O — unit-testable against a `chaos.FakeClock`-driven ledger.
    """

    def __init__(
        self,
        lost_checkpoint_s: float = 300.0,
        overhead_fraction: float = 0.05,
        min_interval: int = 1,
        max_interval: int = 100_000,
        ema: float = 0.3,
        fixed_interval: Optional[int] = None,
    ):
        if lost_checkpoint_s <= 0:
            raise ValueError("lost_checkpoint_s must be > 0")
        if not 0 < overhead_fraction < 1:
            raise ValueError("overhead_fraction must be in (0, 1)")
        if min_interval < 1 or max_interval < min_interval:
            raise ValueError("need 1 <= min_interval <= max_interval")
        if not 0 < ema <= 1:
            raise ValueError("ema must be in (0, 1]")
        if fixed_interval is not None and fixed_interval < 1:
            raise ValueError("fixed_interval must be >= 1")
        self.lost_checkpoint_s = float(lost_checkpoint_s)
        self.overhead_fraction = float(overhead_fraction)
        self.min_interval = int(min_interval)
        self.max_interval = int(max_interval)
        self.ema = float(ema)
        self.fixed_interval = fixed_interval
        self.avg_step_s: Optional[float] = None
        self.avg_save_s: Optional[float] = None
        self.steps_observed = 0
        self.saves_observed = 0
        self._warned_unaffordable = False

    def _fold(self, current: Optional[float], sample: float) -> float:
        sample = max(float(sample), 0.0)
        if current is None:
            return sample
        return (1.0 - self.ema) * current + self.ema * sample

    def observe_step(self, seconds: float):
        self.avg_step_s = self._fold(self.avg_step_s, seconds)
        self.steps_observed += 1

    def observe_save(self, seconds: float):
        self.avg_save_s = self._fold(self.avg_save_s, seconds)
        self.saves_observed += 1

    @property
    def interval(self) -> Optional[int]:
        """Steps between saves under the current measurements; None until at
        least one step has been observed (no cadence without a step clock)."""
        if self.fixed_interval is not None:
            return self.fixed_interval
        if self.avg_step_s is None:
            return None
        step_s = max(self.avg_step_s, 1e-9)
        budget_cap = int(self.lost_checkpoint_s / step_s)
        interval = max(self.min_interval, min(budget_cap, self.max_interval))
        if self.avg_save_s is not None and self.avg_save_s > 0:
            overhead_floor = int(
                -(-self.avg_save_s // (self.overhead_fraction * step_s))
            )
            if overhead_floor > interval:
                if not self._warned_unaffordable and overhead_floor > budget_cap:
                    self._warned_unaffordable = True
                    logger.warning(
                        "adaptive save interval: a save costs %.3fs against %.4fs steps — "
                        "holding the lost-work budget of %.1fs would spend more than "
                        "%.0f%% of wall clock on checkpoints; stretching the interval to "
                        "%d steps (effective exposure %.1fs). Cut save cost (async_save/"
                        "sharded_save) or raise lost_checkpoint_s.",
                        self.avg_save_s, step_s, self.lost_checkpoint_s,
                        self.overhead_fraction * 100, overhead_floor,
                        overhead_floor * step_s,
                    )
                interval = min(overhead_floor, self.max_interval)
        return interval

    def should_save(self, steps_since_save: int) -> bool:
        interval = self.interval
        return interval is not None and steps_since_save >= interval

    def describe(self) -> dict:
        """Controller state for logs/telemetry (host scalars only)."""
        return {
            "interval": self.interval,
            "fixed": self.fixed_interval,
            "avg_step_s": self.avg_step_s,
            "avg_save_s": self.avg_save_s,
            "steps_observed": self.steps_observed,
            "saves_observed": self.saves_observed,
            "lost_checkpoint_s": self.lost_checkpoint_s,
            "overhead_fraction": self.overhead_fraction,
        }

"""Checkpoint save/load (L3; reference checkpointing.py 273 LoC).

Full training-state round trip: model params, optimizer state (+loss scaler), scheduler,
seedable-sampler epochs, host RNG streams, and user-registered custom objects
(reference save_accelerator_state :51 / load_accelerator_state :152).

Storage format — TPU-native two-tier:
  - *Pytree files* (`save_pytree`/`load_pytree`): arrays flattened to a `path -> array`
    dict in one compressed .npz plus a JSON manifest of the tree structure and dtypes
    (bfloat16 round-trips via a uint16 view). Single-file, torch-free, safetensors-like.
  - *Sharded checkpoints*: when arrays aren't fully addressable (multi-host) the orbax/
    tensorstore path (`save_sharded`/`load_sharded`) writes per-shard — the
    torch.distributed.checkpoint replacement (reference utils/fsdp_utils.py:85-147).

Checkpoint rotation (`ProjectConfiguration.total_limit`) is handled by the Accelerator
(reference accelerator.py:2868-2894).
"""

from __future__ import annotations

import json
import os
import pickle
import random
from pathlib import Path
from typing import Any, Optional

import numpy as np

from .logging import get_logger
from .utils.constants import (
    MODEL_NAME,
    OPTIMIZER_NAME,
    RNG_STATE_NAME,
    SAMPLER_NAME,
    SCALER_NAME,
    SCHEDULER_NAME,
)
from .utils.imports import is_orbax_available

logger = get_logger(__name__)

_BF16_MARKER = "bfloat16"


def _flatten_with_paths(tree):
    from .parallel.sharding import tree_paths_and_leaves

    return tree_paths_and_leaves(tree)


def save_pytree(tree, path: str):
    """Save an array pytree: `<path>` (.npz) + `<path>.manifest.json` (structure)."""
    import jax

    flat, treedef = _flatten_with_paths(tree)
    arrays = {}
    manifest = {"paths": [], "dtypes": [], "treedef": None}
    for i, (p, leaf) in enumerate(flat):
        arr = np.asarray(jax.device_get(leaf)) if isinstance(leaf, jax.Array) else np.asarray(leaf)
        key = f"arr_{i}"
        if _has_bf16(arr):
            arrays[key] = arr.view(np.uint16)
            manifest["dtypes"].append(_BF16_MARKER)
        else:
            arrays[key] = arr
            manifest["dtypes"].append(str(arr.dtype))
        manifest["paths"].append(p)
    manifest["treedef"] = pickle.dumps(treedef).hex()
    path = str(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez_compressed(path if path.endswith(".npz") else path + ".npz", **arrays)
    with open(_manifest_path(path), "w") as f:
        json.dump(manifest, f)


def _has_bf16(arr) -> bool:
    return arr.dtype.name == "bfloat16"


def _manifest_path(path: str) -> str:
    base = path[: -len(".npz")] if path.endswith(".npz") else path
    return base + ".manifest.json"


def load_pytree(path: str):
    """Inverse of `save_pytree`; returns numpy leaves (placed by the caller)."""
    import jax
    import jax.numpy as jnp

    path = str(path)
    npz_path = path if path.endswith(".npz") else path + ".npz"
    with open(_manifest_path(path)) as f:
        manifest = json.load(f)
    treedef = pickle.loads(bytes.fromhex(manifest["treedef"]))
    data = np.load(npz_path)
    leaves = []
    for i, dtype in enumerate(manifest["dtypes"]):
        arr = data[f"arr_{i}"]
        if dtype == _BF16_MARKER:
            arr = arr.view(jnp.bfloat16)
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_sharded(tree, directory: str):
    """Sharded (multi-host / non-addressable) checkpoint via orbax/tensorstore."""
    if not is_orbax_available():
        raise ImportError("Sharded checkpointing requires orbax-checkpoint")
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.abspath(directory), tree, force=True)


def load_sharded(directory: str, target=None, shardings=None):
    if not is_orbax_available():
        raise ImportError("Sharded checkpointing requires orbax-checkpoint")
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    restore_args = None
    if shardings is not None:
        import jax

        restore_args = jax.tree_util.tree_map(lambda s: ocp.ArrayRestoreArgs(sharding=s), shardings)
    return ckptr.restore(os.path.abspath(directory), item=target, restore_args=restore_args)


def _all_addressable(tree) -> bool:
    import jax

    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return False
    return True


def save_accelerator_state(
    output_dir: str,
    models: list,
    optimizers: list,
    schedulers: list,
    dataloaders: list,
    rng_key=None,
    scaler=None,
    save_on_each_node: bool = False,
) -> str:
    """Save the complete training state (reference checkpointing.py:51-149)."""
    from .state import PartialState

    state = PartialState()
    output_dir = Path(output_dir)
    os.makedirs(output_dir, exist_ok=True)

    for i, model in enumerate(models):
        name = f"{MODEL_NAME}.npz" if i == 0 else f"{MODEL_NAME}_{i}.npz"
        params = model.state_dict()
        if _all_addressable(params):
            if state.is_main_process or save_on_each_node:
                save_pytree(params, str(output_dir / name))
        else:
            save_sharded(params, str(output_dir / f"{name}.sharded"))
        logger.info("Model weights saved in %s", output_dir / name)

    for i, opt in enumerate(optimizers):
        name = f"{OPTIMIZER_NAME}.npz" if i == 0 else f"{OPTIMIZER_NAME}_{i}.npz"
        opt_state = opt.state_dict()["opt_state"]
        if _all_addressable(opt_state):
            if state.is_main_process or save_on_each_node:
                save_pytree(opt_state, str(output_dir / name))
        else:
            save_sharded(opt_state, str(output_dir / f"{name}.sharded"))
        if opt.scaler is not None and (state.is_main_process or save_on_each_node):
            with open(output_dir / f"{SCALER_NAME}_{i}.json", "w") as f:
                json.dump(opt.scaler.state_dict(), f)

    if state.is_main_process or save_on_each_node:
        for i, sched in enumerate(schedulers):
            name = f"{SCHEDULER_NAME}.bin" if i == 0 else f"{SCHEDULER_NAME}_{i}.bin"
            with open(output_dir / name, "wb") as f:
                pickle.dump(sched.state_dict(), f)

        for i, dl in enumerate(dataloaders):
            sampler = _find_seedable_sampler(dl)
            if sampler is not None:
                name = f"{SAMPLER_NAME}.bin" if i == 0 else f"{SAMPLER_NAME}_{i}.bin"
                with open(output_dir / name, "wb") as f:
                    pickle.dump(sampler.state_dict(), f)

    # RNG states are per-process (reference saves `random_states_{i}.pkl`,
    # checkpointing.py:122-151).
    rng_states = {"python": random.getstate(), "numpy": np.random.get_state()}
    if rng_key is not None:
        import jax

        rng_states["jax"] = np.asarray(jax.random.key_data(rng_key))
    with open(output_dir / f"{RNG_STATE_NAME}_{state.process_index}.pkl", "wb") as f:
        pickle.dump(rng_states, f)
    return str(output_dir)


def _find_seedable_sampler(dataloader):
    from .data_loader import SeedableRandomSampler

    candidates = [
        getattr(dataloader, "synchronized_generator", None),
        getattr(getattr(dataloader, "batch_sampler", None), "sampler", None),
    ]
    base = getattr(dataloader, "base_loader", None)
    if base is not None:
        bs = getattr(base, "batch_sampler", None)
        candidates.append(getattr(bs, "sampler", None))
        inner = getattr(bs, "batch_sampler", None)
        if inner is not None:
            candidates.append(getattr(inner, "sampler", None))
    for c in candidates:
        if isinstance(c, SeedableRandomSampler):
            return c
    return None


def load_accelerator_state(
    input_dir: str,
    models: list,
    optimizers: list,
    schedulers: list,
    dataloaders: list,
    load_rng: bool = True,
):
    """Restore the complete training state (reference checkpointing.py:152-254).

    Returns the restored jax RNG key if one was saved (or None)."""
    import jax

    from .state import PartialState

    state = PartialState()
    input_dir = Path(input_dir)

    for i, model in enumerate(models):
        name = f"{MODEL_NAME}.npz" if i == 0 else f"{MODEL_NAME}_{i}.npz"
        if (input_dir / f"{name}.sharded").exists():
            params = load_sharded(str(input_dir / f"{name}.sharded"), shardings=model.param_sharding)
        else:
            params = load_pytree(str(input_dir / name))
        model.load_state_dict(params)
        logger.info("Model weights loaded from %s", input_dir / name)

    for i, opt in enumerate(optimizers):
        name = f"{OPTIMIZER_NAME}.npz" if i == 0 else f"{OPTIMIZER_NAME}_{i}.npz"
        if (input_dir / f"{name}.sharded").exists():
            opt_state = load_sharded(str(input_dir / f"{name}.sharded"), shardings=opt.opt_state_sharding)
        else:
            opt_state = load_pytree(str(input_dir / name))
        scaler_state = None
        scaler_path = input_dir / f"{SCALER_NAME}_{i}.json"
        if scaler_path.exists():
            with open(scaler_path) as f:
                scaler_state = json.load(f)
        opt.load_state_dict({"opt_state": opt_state, "scaler": scaler_state})

    for i, sched in enumerate(schedulers):
        name = f"{SCHEDULER_NAME}.bin" if i == 0 else f"{SCHEDULER_NAME}_{i}.bin"
        if (input_dir / name).exists():
            with open(input_dir / name, "rb") as f:
                sched.load_state_dict(pickle.load(f))

    for i, dl in enumerate(dataloaders):
        sampler = _find_seedable_sampler(dl)
        name = f"{SAMPLER_NAME}.bin" if i == 0 else f"{SAMPLER_NAME}_{i}.bin"
        if sampler is not None and (input_dir / name).exists():
            with open(input_dir / name, "rb") as f:
                sampler.load_state_dict(pickle.load(f))

    rng_key = None
    if load_rng:
        rng_path = input_dir / f"{RNG_STATE_NAME}_{state.process_index}.pkl"
        if rng_path.exists():
            with open(rng_path, "rb") as f:
                rng_states = pickle.load(f)
            random.setstate(rng_states["python"])
            np.random.set_state(rng_states["numpy"])
            if "jax" in rng_states:
                rng_key = jax.random.wrap_key_data(np.asarray(rng_states["jax"]))
    return rng_key


def save_custom_state(obj, path: str, index: int = 0):
    """Pickle an object exposing state_dict() (reference checkpointing.py:257)."""
    location = Path(path) / f"custom_checkpoint_{index}.pkl"
    logger.info("Saving the state of %s to %s", type(obj).__name__, location)
    with open(location, "wb") as f:
        pickle.dump(obj.state_dict(), f)


def load_custom_state(obj, path: str, index: int = 0):
    """(reference checkpointing.py:267)"""
    location = Path(path) / f"custom_checkpoint_{index}.pkl"
    with open(location, "rb") as f:
        obj.load_state_dict(pickle.load(f))

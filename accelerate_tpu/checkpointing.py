"""Checkpoint save/load (L3; reference checkpointing.py 273 LoC).

Full training-state round trip: model params, optimizer state (+loss scaler), scheduler,
seedable-sampler epochs, host RNG streams, and user-registered custom objects
(reference save_accelerator_state :51 / load_accelerator_state :152).

Storage format — TPU-native two-tier:
  - *Pytree files* (`save_pytree`/`load_pytree`): arrays flattened to a `path -> array`
    dict in one compressed .npz plus a JSON manifest of the tree structure and dtypes
    (bfloat16 round-trips via a uint16 view). Single-file, torch-free, safetensors-like.
  - *Sharded checkpoints*: when arrays aren't fully addressable (multi-host) the orbax/
    tensorstore path (`save_sharded`/`load_sharded`) writes per-shard — the
    torch.distributed.checkpoint replacement (reference utils/fsdp_utils.py:85-147).

Checkpoint rotation (`ProjectConfiguration.total_limit`) is handled by the Accelerator
(reference accelerator.py:2868-2894).
"""

from __future__ import annotations

import json
import os
import pickle
import random
from pathlib import Path
from typing import Any, Optional

import numpy as np

from .logging import get_logger
from .utils.constants import (
    MODEL_NAME,
    OPTIMIZER_NAME,
    RNG_STATE_NAME,
    SAMPLER_NAME,
    SCALER_NAME,
    SCHEDULER_NAME,
)
from .utils.imports import is_orbax_available

logger = get_logger(__name__)

_BF16_MARKER = "bfloat16"


def _flatten_with_paths(tree):
    from .parallel.sharding import tree_paths_and_leaves

    return tree_paths_and_leaves(tree)


def save_pytree(tree, path: str):
    """Save an array pytree: `<path>` (.npz) + `<path>.manifest.json` (structure)."""
    import jax

    flat, treedef = _flatten_with_paths(tree)
    arrays = {}
    manifest = {"paths": [], "dtypes": [], "treedef": None}
    for i, (p, leaf) in enumerate(flat):
        arr = np.asarray(jax.device_get(leaf)) if isinstance(leaf, jax.Array) else np.asarray(leaf)
        key = f"arr_{i}"
        if _has_bf16(arr):
            arrays[key] = arr.view(np.uint16)
            manifest["dtypes"].append(_BF16_MARKER)
        else:
            arrays[key] = arr
            manifest["dtypes"].append(str(arr.dtype))
        manifest["paths"].append(p)
    manifest["treedef"] = pickle.dumps(treedef).hex()
    path = str(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez_compressed(path if path.endswith(".npz") else path + ".npz", **arrays)
    with open(_manifest_path(path), "w") as f:
        json.dump(manifest, f)


def _has_bf16(arr) -> bool:
    return arr.dtype.name == "bfloat16"


def _manifest_path(path: str) -> str:
    base = path[: -len(".npz")] if path.endswith(".npz") else path
    return base + ".manifest.json"


def load_pytree(path: str):
    """Inverse of `save_pytree`; returns numpy leaves (placed by the caller)."""
    import jax
    import jax.numpy as jnp

    path = str(path)
    npz_path = path if path.endswith(".npz") else path + ".npz"
    with open(_manifest_path(path)) as f:
        manifest = json.load(f)
    treedef = pickle.loads(bytes.fromhex(manifest["treedef"]))
    data = np.load(npz_path)
    leaves = []
    for i, dtype in enumerate(manifest["dtypes"]):
        arr = data[f"arr_{i}"]
        if dtype == _BF16_MARKER:
            arr = arr.view(jnp.bfloat16)
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_sharded(tree, directory: str):
    """Sharded (multi-host / non-addressable) checkpoint via orbax/tensorstore."""
    if not is_orbax_available():
        raise ImportError("Sharded checkpointing requires orbax-checkpoint")
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.abspath(directory), tree, force=True)


def load_sharded(directory: str, target=None, shardings=None):
    if not is_orbax_available():
        raise ImportError("Sharded checkpointing requires orbax-checkpoint")
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    restore_args = None
    if shardings is not None:
        import jax

        restore_args = jax.tree_util.tree_map(lambda s: ocp.ArrayRestoreArgs(sharding=s), shardings)
    return ckptr.restore(os.path.abspath(directory), item=target, restore_args=restore_args)


def _all_addressable(tree) -> bool:
    import jax

    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return False
    return True


# ------------------------------------------------------------------ safetensors export
def _parse_size(size) -> int:
    """'5GB' / '500MB' / int -> bytes."""
    if isinstance(size, int):
        return size
    s = str(size).strip().upper()
    for suffix, mult in (("GIB", 2**30), ("MIB", 2**20), ("KIB", 2**10), ("GB", 10**9), ("MB", 10**6), ("KB", 10**3)):
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)]) * mult)  # float first: '0.5GB' != 0
    return int(s)


def _leaf_to_host(leaf):
    """One leaf -> numpy on host. Non-addressable (multi-host sharded) arrays are
    allgathered process-wide — the per-PARAM gather keeps host memory bounded by
    one tensor, not the model (the reference's sharded save_model concern,
    accelerator.py:2691)."""
    import jax

    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def save_model_safetensors(params, save_directory: str, max_shard_size="5GB") -> list:
    """Write a params pytree as (sharded) safetensors with an HF-style index
    (reference save_model accelerator.py:2691 / shard_checkpoint utils/modeling.py:206).

    Tensor names are the '/'-joined pytree paths, so `load_model_safetensors`
    rebuilds the exact tree. One file under `max_shard_size` is written as
    `model.safetensors`; larger exports split into `model-00001-of-000NN.safetensors`
    plus `model.safetensors.index.json` (`utils/constants.py` SAFE_WEIGHTS_*).
    Parameters stream to host ONE AT A TIME — a fully-sharded model never
    materializes whole on any single host.

    Call on EVERY process (the non-addressable gather is a collective); only the
    main process writes. Returns the list of files written (empty on non-main).
    """
    import jax
    from safetensors.numpy import save_file

    from .utils.constants import SAFE_WEIGHTS_INDEX_NAME, SAFE_WEIGHTS_NAME

    is_main = jax.process_index() == 0
    os.makedirs(save_directory, exist_ok=True)
    flat, _ = _flatten_with_paths(params)
    budget = _parse_size(max_shard_size)

    # Plan shards greedily by byte size (no data movement yet).
    shards, current, current_bytes = [], [], 0
    sizes = {}
    for path, leaf in flat:
        nbytes = int(np.prod(getattr(leaf, "shape", ()) or ())) * np.dtype(leaf.dtype).itemsize
        sizes[path] = nbytes
        if current and current_bytes + nbytes > budget:
            shards.append(current)
            current, current_bytes = [], 0
        current.append((path, leaf))
        current_bytes += nbytes
    if current:
        shards.append(current)

    written = []
    if len(shards) == 1:
        tensors = {p: _leaf_to_host(leaf) for p, leaf in shards[0]}
        target = os.path.join(save_directory, SAFE_WEIGHTS_NAME)
        if is_main:
            save_file(tensors, target)
            written.append(target)
        return written

    weight_map = {}
    for i, shard in enumerate(shards):
        fname = f"model-{i + 1:05d}-of-{len(shards):05d}.safetensors"
        tensors = {p: _leaf_to_host(leaf) for p, leaf in shard}
        if is_main:
            save_file(tensors, os.path.join(save_directory, fname))
            written.append(os.path.join(save_directory, fname))
        for p, _ in shard:
            weight_map[p] = fname
        del tensors  # free the host copies before gathering the next shard
    if is_main:
        index = {
            "metadata": {"total_size": int(sum(sizes.values()))},
            "weight_map": weight_map,
        }
        index_path = os.path.join(save_directory, SAFE_WEIGHTS_INDEX_NAME)
        with open(index_path, "w") as f:
            json.dump(index, f, indent=2)
        written.append(index_path)
    return written


def load_model_safetensors(directory: str):
    """Inverse of `save_model_safetensors`: rebuild the params pytree (nested dicts)
    from a safetensors file/shard directory. Leaves come back as numpy (bf16 via
    ml_dtypes); place with `PreparedModel.load_state_dict` or `place_params`."""
    from .utils.hf_loading import load_hf_state_dict

    flat = load_hf_state_dict(directory)
    tree: dict = {}
    for path, arr in flat.items():
        parts = path.split("/")
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = arr
    return tree


def save_accelerator_state(
    output_dir: str,
    models: list,
    optimizers: list,
    schedulers: list,
    dataloaders: list,
    rng_key=None,
    scaler=None,
    save_on_each_node: bool = False,
    state_dict_type: str = "SHARDED_STATE_DICT",
) -> str:
    """Save the complete training state (reference checkpointing.py:51-149).

    `state_dict_type` (FSDP plugin knob) governs multi-host layout: with
    SHARDED_STATE_DICT (default) non-addressable trees write per-shard via
    orbax/tensorstore; FULL_STATE_DICT consolidates them — each tensor is
    allgathered ONE AT A TIME and the main process writes a single npz
    (reference fsdp_utils.py:54-209 FULL vs SHARDED state dict extraction)."""
    from .state import PartialState

    state = PartialState()
    output_dir = Path(output_dir)
    os.makedirs(output_dir, exist_ok=True)

    def _save_tree(tree, name):
        if _all_addressable(tree):
            if state.is_main_process or save_on_each_node:
                save_pytree(tree, str(output_dir / name))
        elif state_dict_type == "FULL_STATE_DICT":
            import jax

            flat, treedef = _flatten_with_paths(tree)
            leaves = [_leaf_to_host(leaf) for _, leaf in flat]  # collective: all procs
            if state.is_main_process or save_on_each_node:
                save_pytree(jax.tree_util.tree_unflatten(treedef, leaves), str(output_dir / name))
        else:
            save_sharded(tree, str(output_dir / f"{name}.sharded"))

    for i, model in enumerate(models):
        name = f"{MODEL_NAME}.npz" if i == 0 else f"{MODEL_NAME}_{i}.npz"
        _save_tree(model.state_dict(), name)
        logger.info("Model weights saved in %s", output_dir / name)

    for i, opt in enumerate(optimizers):
        name = f"{OPTIMIZER_NAME}.npz" if i == 0 else f"{OPTIMIZER_NAME}_{i}.npz"
        _save_tree(opt.state_dict()["opt_state"], name)
        if opt.scaler is not None and (state.is_main_process or save_on_each_node):
            with open(output_dir / f"{SCALER_NAME}_{i}.json", "w") as f:
                json.dump(opt.scaler.state_dict(), f)

    if state.is_main_process or save_on_each_node:
        for i, sched in enumerate(schedulers):
            name = f"{SCHEDULER_NAME}.bin" if i == 0 else f"{SCHEDULER_NAME}_{i}.bin"
            with open(output_dir / name, "wb") as f:
                pickle.dump(sched.state_dict(), f)

        for i, dl in enumerate(dataloaders):
            sampler = _find_seedable_sampler(dl)
            if sampler is not None:
                name = f"{SAMPLER_NAME}.bin" if i == 0 else f"{SAMPLER_NAME}_{i}.bin"
                # The loader's pass counter rides along: it is what
                # `DataLoaderShard.__iter__` feeds `set_epoch()` on the next
                # pass, and it disambiguates a mid-pass save (iteration ==
                # sampler.epoch: replay this epoch's permutation + skip) from
                # an epoch-boundary save (iteration == epoch + 1: the next
                # pass must draw a FRESH permutation, not repeat the last).
                # Explicit format marker: load-side sniffing by key presence
                # ("sampler" in payload) breaks the day a sampler's own
                # state_dict grows a 'sampler' key — version the envelope.
                payload = {"format": 2, "sampler": sampler.state_dict()}
                if hasattr(dl, "iteration"):
                    payload["loader_iteration"] = dl.iteration
                with open(output_dir / name, "wb") as f:
                    pickle.dump(payload, f)

    # RNG states are per-process (reference saves `random_states_{i}.pkl`,
    # checkpointing.py:122-151).
    rng_states = {"python": random.getstate(), "numpy": np.random.get_state()}
    if rng_key is not None:
        import jax

        rng_states["jax"] = np.asarray(jax.random.key_data(rng_key))
    with open(output_dir / f"{RNG_STATE_NAME}_{state.process_index}.pkl", "wb") as f:
        pickle.dump(rng_states, f)
    return str(output_dir)


def _find_seedable_sampler(dataloader):
    from .data_loader import SeedableRandomSampler

    candidates = [
        getattr(dataloader, "synchronized_generator", None),
        getattr(getattr(dataloader, "batch_sampler", None), "sampler", None),
    ]
    base = getattr(dataloader, "base_loader", None)
    if base is not None:
        bs = getattr(base, "batch_sampler", None)
        candidates.append(getattr(bs, "sampler", None))
        inner = getattr(bs, "batch_sampler", None)
        if inner is not None:
            candidates.append(getattr(inner, "sampler", None))
    for c in candidates:
        if isinstance(c, SeedableRandomSampler):
            return c
    return None


def load_accelerator_state(
    input_dir: str,
    models: list,
    optimizers: list,
    schedulers: list,
    dataloaders: list,
    load_rng: bool = True,
):
    """Restore the complete training state (reference checkpointing.py:152-254).

    Returns the restored jax RNG key if one was saved (or None)."""
    import jax

    from .state import PartialState

    state = PartialState()
    input_dir = Path(input_dir)

    for i, model in enumerate(models):
        name = f"{MODEL_NAME}.npz" if i == 0 else f"{MODEL_NAME}_{i}.npz"
        if (input_dir / f"{name}.sharded").exists():
            params = load_sharded(str(input_dir / f"{name}.sharded"), shardings=model.param_sharding)
        else:
            params = load_pytree(str(input_dir / name))
        model.load_state_dict(params)
        logger.info("Model weights loaded from %s", input_dir / name)

    for i, opt in enumerate(optimizers):
        name = f"{OPTIMIZER_NAME}.npz" if i == 0 else f"{OPTIMIZER_NAME}_{i}.npz"
        if (input_dir / f"{name}.sharded").exists():
            opt_state = load_sharded(str(input_dir / f"{name}.sharded"), shardings=opt.opt_state_sharding)
        else:
            opt_state = load_pytree(str(input_dir / name))
        scaler_state = None
        scaler_path = input_dir / f"{SCALER_NAME}_{i}.json"
        if scaler_path.exists():
            with open(scaler_path) as f:
                scaler_state = json.load(f)
        opt.load_state_dict({"opt_state": opt_state, "scaler": scaler_state})

    for i, sched in enumerate(schedulers):
        name = f"{SCHEDULER_NAME}.bin" if i == 0 else f"{SCHEDULER_NAME}_{i}.bin"
        if (input_dir / name).exists():
            with open(input_dir / name, "rb") as f:
                sched.load_state_dict(pickle.load(f))

    for i, dl in enumerate(dataloaders):
        sampler = _find_seedable_sampler(dl)
        name = f"{SAMPLER_NAME}.bin" if i == 0 else f"{SAMPLER_NAME}_{i}.bin"
        if sampler is not None and (input_dir / name).exists():
            with open(input_dir / name, "rb") as f:
                payload = pickle.load(f)
            if payload.get("format") == 2:
                sampler.load_state_dict(payload["sampler"])
                loader_iteration = payload.get("loader_iteration")
            elif "format" in payload:
                # A versioned envelope from a NEWER writer: refuse loudly
                # instead of feeding the whole envelope into load_state_dict
                # and crashing on a missing key three frames deeper.
                raise ValueError(
                    f"unsupported sampler checkpoint format {payload['format']!r} in "
                    f"{input_dir / name} (this version reads format 2 and earlier)"
                )
            elif "sampler" in payload:
                # round-4 wrapped format (pre-marker): {"sampler": ..., "loader_iteration": ...}
                sampler.load_state_dict(payload["sampler"])
                loader_iteration = payload.get("loader_iteration")
            else:  # pre-round-4 checkpoint: bare sampler state_dict
                sampler.load_state_dict(payload)
                loader_iteration = payload.get("epoch")
            # Realign the loader's pass counter: `DataLoaderShard.__iter__`
            # calls `set_epoch(self.iteration)` at the top of every pass, and
            # a fresh process's 0 would clobber the restored epoch — the
            # resumed pass would replay epoch 0's permutation, so
            # `skip_first_batches` would skip the WRONG samples.
            if loader_iteration is not None and hasattr(dl, "iteration"):
                dl.iteration = loader_iteration

    rng_key = None
    if load_rng:
        rng_path = input_dir / f"{RNG_STATE_NAME}_{state.process_index}.pkl"
        if rng_path.exists():
            with open(rng_path, "rb") as f:
                rng_states = pickle.load(f)
            random.setstate(rng_states["python"])
            np.random.set_state(rng_states["numpy"])
            if "jax" in rng_states:
                rng_key = jax.random.wrap_key_data(np.asarray(rng_states["jax"]))
    return rng_key


def save_custom_state(obj, path: str, index: int = 0):
    """Pickle an object exposing state_dict() (reference checkpointing.py:257)."""
    location = Path(path) / f"custom_checkpoint_{index}.pkl"
    logger.info("Saving the state of %s to %s", type(obj).__name__, location)
    with open(location, "wb") as f:
        pickle.dump(obj.state_dict(), f)


def load_custom_state(obj, path: str, index: int = 0):
    """(reference checkpointing.py:267)"""
    location = Path(path) / f"custom_checkpoint_{index}.pkl"
    if not location.exists():
        # Hard failure on purpose: silently keeping the object's constructed
        # state would resume at a wrong position (e.g. a step counter at 0 on
        # fully-trained weights). The usual cause is actionable.
        raise FileNotFoundError(
            f"Checkpoint has no saved state for registered object {index} "
            f"({type(obj).__name__}) at {location}. If this object was "
            "registered for checkpointing AFTER the checkpoint was written, "
            "resume once without registering it (or write a fresh checkpoint)."
        )
    with open(location, "rb") as f:
        obj.load_state_dict(pickle.load(f))

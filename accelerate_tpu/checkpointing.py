"""Checkpoint save/load (L3; reference checkpointing.py 273 LoC).

Full training-state round trip: model params, optimizer state (+loss scaler), scheduler,
seedable-sampler epochs, host RNG streams, and user-registered custom objects
(reference save_accelerator_state :51 / load_accelerator_state :152).

Storage format — TPU-native two-tier:
  - *Pytree files* (`save_pytree`/`load_pytree`): arrays flattened to a `path -> array`
    dict in one compressed .npz plus a JSON manifest of the tree structure and dtypes
    (bfloat16 round-trips via a uint16 view). Single-file, torch-free, safetensors-like.
  - *Sharded checkpoints*: when arrays aren't fully addressable (multi-host) the orbax/
    tensorstore path (`save_sharded`/`load_sharded`) writes per-shard — the
    torch.distributed.checkpoint replacement (reference utils/fsdp_utils.py:85-147).

Crash safety — every artifact commits via temp-file + fsync + `os.replace`, so a
SIGKILL at any byte offset leaves either the previous complete file or nothing,
never a torn one. Pytree manifests carry a SHA-256 digest of their `.npz` payload
(verified on load); `CheckpointManager` extends the same discipline to whole
checkpoint *directories*: artifacts land in a hidden staging dir, a checkpoint-level
`MANIFEST.json` with per-file digests is the commit record, the staging dir is
renamed into place atomically, a `latest` pointer is swapped, and keep-last-N
rotation plus retry-with-backoff on transient I/O errors keep long runs bounded.
Resolution (`resolve("latest")`) walks newest→oldest and skips any checkpoint whose
digests don't verify — resume survives a kill mid-save by falling back to the last
verified checkpoint.

Checkpoint rotation (`ProjectConfiguration.total_limit`) is handled by the Accelerator
through `CheckpointManager` (reference accelerator.py:2868-2894).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import random
import shutil
import tempfile
import time
from pathlib import Path
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from .logging import get_logger
from .utils.constants import (
    MODEL_NAME,
    OPTIMIZER_NAME,
    RNG_STATE_NAME,
    SAMPLER_NAME,
    SCALER_NAME,
    SCHEDULER_NAME,
)
from .utils.imports import is_orbax_available

logger = get_logger(__name__)

_BF16_MARKER = "bfloat16"

# Checkpoint-directory commit record written by `CheckpointManager` / `write_checkpoint_manifest`.
CHECKPOINT_MANIFEST_NAME = "MANIFEST.json"
LATEST_POINTER_NAME = "latest"
_STAGING_PREFIX = ".tmp-"

# Chaos seam (`accelerate_tpu.chaos.injectors.FilesystemInjector`): when armed,
# consulted at the fault-relevant points of the commit sequence — artifact
# write entry, the payload fsync, the rename window, the directory publish.
# None in production; every call site is a single attribute test.
_chaos_hooks = None


class CheckpointCorruptError(RuntimeError):
    """An artifact failed digest verification (torn write, bit rot, truncation)."""


def _fsync_directory(path: str):
    """fsync a directory so a just-committed rename survives power loss. Best
    effort: some filesystems/platforms refuse O_RDONLY dir fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, writer: Callable, mode: str = "wb"):
    """Commit a file via temp-in-same-dir + flush + fsync + `os.replace`.

    `writer(fileobj)` produces the content. A kill at any byte offset leaves the
    destination either absent or its previous complete version — readers never
    observe a torn file. The temp name is randomized (mkstemp) so concurrent
    writers in one directory can't collide."""
    path = str(path)
    hooks = _chaos_hooks
    if hooks is not None:
        hooks.on_write(path)
    directory = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=os.path.basename(path) + ".tmp-")
    try:
        with os.fdopen(fd, mode) as f:
            writer(f)
            f.flush()
            if hooks is not None:
                hooks.on_fsync(path)
            os.fsync(f.fileno())
        if hooks is not None:
            hooks.on_rename(path)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_directory(directory)


def atomic_write_bytes(path: str, data: bytes):
    atomic_write(path, lambda f: f.write(data))


def atomic_write_json(path: str, obj):
    atomic_write(path, lambda f: json.dump(obj, f), mode="w")


def file_sha256(path: str, chunk_size: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(chunk_size), b""):
            h.update(chunk)
    return h.hexdigest()


def _flatten_with_paths(tree):
    from .parallel.sharding import tree_paths_and_leaves

    return tree_paths_and_leaves(tree)


def save_pytree(tree, path: str):
    """Save an array pytree: `<path>` (.npz) + `<path>.manifest.json` (structure)."""
    import jax

    flat, treedef = _flatten_with_paths(tree)
    arrays = {}
    manifest = {"paths": [], "dtypes": [], "treedef": None}
    for i, (p, leaf) in enumerate(flat):
        arr = np.asarray(jax.device_get(leaf)) if isinstance(leaf, jax.Array) else np.asarray(leaf)
        key = f"arr_{i}"
        if _has_bf16(arr):
            arrays[key] = arr.view(np.uint16)
            manifest["dtypes"].append(_BF16_MARKER)
        else:
            arrays[key] = arr
            manifest["dtypes"].append(str(arr.dtype))
        manifest["paths"].append(p)
    manifest["treedef"] = pickle.dumps(treedef).hex()
    path = str(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    npz_path = path if path.endswith(".npz") else path + ".npz"
    # Commit order matters: payload first, then the manifest carrying its digest
    # — the manifest is the record a loader trusts, so it must never describe a
    # payload that isn't fully on disk.
    atomic_write(npz_path, lambda f: np.savez_compressed(f, **arrays))
    manifest["npz_sha256"] = file_sha256(npz_path)
    atomic_write_json(_manifest_path(path), manifest)


def _has_bf16(arr) -> bool:
    return arr.dtype.name == "bfloat16"


def _manifest_path(path: str) -> str:
    base = path[: -len(".npz")] if path.endswith(".npz") else path
    return base + ".manifest.json"


def load_pytree(path: str, verify: bool = True):
    """Inverse of `save_pytree`; returns numpy leaves (placed by the caller).

    With `verify` (default) the payload's SHA-256 is checked against the digest
    the manifest recorded at save time; a mismatch (truncated npz, bit rot)
    raises `CheckpointCorruptError` instead of half-reading a torn file.
    Manifests from before the digest field load unverified."""
    import jax
    import jax.numpy as jnp

    path = str(path)
    npz_path = path if path.endswith(".npz") else path + ".npz"
    with open(_manifest_path(path)) as f:
        manifest = json.load(f)
    expected = manifest.get("npz_sha256")
    if verify and expected is not None:
        actual = file_sha256(npz_path)
        if actual != expected:
            raise CheckpointCorruptError(
                f"{npz_path}: SHA-256 mismatch (manifest {expected[:12]}…, file {actual[:12]}…) "
                "— torn or corrupted checkpoint artifact"
            )
    treedef = pickle.loads(bytes.fromhex(manifest["treedef"]))
    data = np.load(npz_path)
    leaves = []
    for i, dtype in enumerate(manifest["dtypes"]):
        arr = data[f"arr_{i}"]
        if dtype == _BF16_MARKER:
            arr = arr.view(jnp.bfloat16)
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_sharded(tree, directory: str):
    """Sharded (multi-host / non-addressable) checkpoint via orbax/tensorstore."""
    if not is_orbax_available():
        raise ImportError("Sharded checkpointing requires orbax-checkpoint")
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.abspath(directory), tree, force=True)


def load_sharded(directory: str, target=None, shardings=None):
    if not is_orbax_available():
        raise ImportError("Sharded checkpointing requires orbax-checkpoint")
    import orbax.checkpoint as ocp

    ckptr = ocp.PyTreeCheckpointer()
    restore_args = None
    if shardings is not None:
        import jax

        restore_args = jax.tree_util.tree_map(lambda s: ocp.ArrayRestoreArgs(sharding=s), shardings)
    return ckptr.restore(os.path.abspath(directory), item=target, restore_args=restore_args)


def _all_addressable(tree) -> bool:
    import jax

    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return False
    return True


# ------------------------------------------------------------------ safetensors export
def _parse_size(size) -> int:
    """'5GB' / '500MB' / int -> bytes."""
    if isinstance(size, int):
        return size
    s = str(size).strip().upper()
    for suffix, mult in (("GIB", 2**30), ("MIB", 2**20), ("KIB", 2**10), ("GB", 10**9), ("MB", 10**6), ("KB", 10**3)):
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)]) * mult)  # float first: '0.5GB' != 0
    return int(s)


def _leaf_to_host(leaf):
    """One leaf -> numpy on host. Non-addressable (multi-host sharded) arrays are
    allgathered process-wide — the per-PARAM gather keeps host memory bounded by
    one tensor, not the model (the reference's sharded save_model concern,
    accelerator.py:2691)."""
    import jax

    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def save_model_safetensors(params, save_directory: str, max_shard_size="5GB") -> list:
    """Write a params pytree as (sharded) safetensors with an HF-style index
    (reference save_model accelerator.py:2691 / shard_checkpoint utils/modeling.py:206).

    Tensor names are the '/'-joined pytree paths, so `load_model_safetensors`
    rebuilds the exact tree. One file under `max_shard_size` is written as
    `model.safetensors`; larger exports split into `model-00001-of-000NN.safetensors`
    plus `model.safetensors.index.json` (`utils/constants.py` SAFE_WEIGHTS_*).
    Parameters stream to host ONE AT A TIME — a fully-sharded model never
    materializes whole on any single host.

    Call on EVERY process (the non-addressable gather is a collective); only the
    main process writes. Returns the list of files written (empty on non-main).
    """
    import jax
    from safetensors.numpy import save_file

    from .utils.constants import SAFE_WEIGHTS_INDEX_NAME, SAFE_WEIGHTS_NAME

    def _atomic_save_file(tensors, target):
        # safetensors wants a filename, not a fileobj: write a sibling temp file,
        # fsync it, and commit with os.replace (same torn-write guarantee as
        # `atomic_write`).
        tmp = f"{target}.tmp-{os.getpid()}"
        try:
            save_file(tensors, tmp)
            with open(tmp, "rb+") as f:
                os.fsync(f.fileno())
            os.replace(tmp, target)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        _fsync_directory(os.path.dirname(target) or ".")

    is_main = jax.process_index() == 0
    os.makedirs(save_directory, exist_ok=True)
    flat, _ = _flatten_with_paths(params)
    budget = _parse_size(max_shard_size)

    # Plan shards greedily by byte size (no data movement yet).
    shards, current, current_bytes = [], [], 0
    sizes = {}
    for path, leaf in flat:
        nbytes = int(np.prod(getattr(leaf, "shape", ()) or ())) * np.dtype(leaf.dtype).itemsize
        sizes[path] = nbytes
        if current and current_bytes + nbytes > budget:
            shards.append(current)
            current, current_bytes = [], 0
        current.append((path, leaf))
        current_bytes += nbytes
    if current:
        shards.append(current)

    written = []
    if len(shards) == 1:
        tensors = {p: _leaf_to_host(leaf) for p, leaf in shards[0]}
        target = os.path.join(save_directory, SAFE_WEIGHTS_NAME)
        if is_main:
            _atomic_save_file(tensors, target)
            written.append(target)
        return written

    weight_map = {}
    for i, shard in enumerate(shards):
        fname = f"model-{i + 1:05d}-of-{len(shards):05d}.safetensors"
        tensors = {p: _leaf_to_host(leaf) for p, leaf in shard}
        if is_main:
            _atomic_save_file(tensors, os.path.join(save_directory, fname))
            written.append(os.path.join(save_directory, fname))
        for p, _ in shard:
            weight_map[p] = fname
        del tensors  # free the host copies before gathering the next shard
    if is_main:
        index = {
            "metadata": {"total_size": int(sum(sizes.values()))},
            "weight_map": weight_map,
        }
        index_path = os.path.join(save_directory, SAFE_WEIGHTS_INDEX_NAME)
        # Index last: it references the shards, so it must never exist before
        # every shard it names is fully committed.
        atomic_write(index_path, lambda f: json.dump(index, f, indent=2), mode="w")
        written.append(index_path)
    return written


def load_model_safetensors(directory: str):
    """Inverse of `save_model_safetensors`: rebuild the params pytree (nested dicts)
    from a safetensors file/shard directory. Leaves come back as numpy (bf16 via
    ml_dtypes); place with `PreparedModel.load_state_dict` or `place_params`."""
    from .utils.hf_loading import load_hf_state_dict

    flat = load_hf_state_dict(directory)
    tree: dict = {}
    for path, arr in flat.items():
        parts = path.split("/")
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = arr
    return tree


def save_accelerator_state(
    output_dir: str,
    models: list,
    optimizers: list,
    schedulers: list,
    dataloaders: list,
    rng_key=None,
    scaler=None,
    save_on_each_node: bool = False,
    state_dict_type: str = "SHARDED_STATE_DICT",
) -> str:
    """Save the complete training state (reference checkpointing.py:51-149).

    `state_dict_type` (FSDP plugin knob) governs multi-host layout: with
    SHARDED_STATE_DICT (default) non-addressable trees write per-shard via
    orbax/tensorstore; FULL_STATE_DICT consolidates them — each tensor is
    allgathered ONE AT A TIME and the main process writes a single npz
    (reference fsdp_utils.py:54-209 FULL vs SHARDED state dict extraction)."""
    from .state import PartialState

    state = PartialState()
    output_dir = Path(output_dir)
    os.makedirs(output_dir, exist_ok=True)

    def _save_tree(tree, name):
        if _all_addressable(tree):
            if state.is_main_process or save_on_each_node:
                save_pytree(tree, str(output_dir / name))
        elif state_dict_type == "FULL_STATE_DICT":
            import jax

            flat, treedef = _flatten_with_paths(tree)
            leaves = [_leaf_to_host(leaf) for _, leaf in flat]  # collective: all procs
            if state.is_main_process or save_on_each_node:
                save_pytree(jax.tree_util.tree_unflatten(treedef, leaves), str(output_dir / name))
        else:
            save_sharded(tree, str(output_dir / f"{name}.sharded"))

    for i, model in enumerate(models):
        name = f"{MODEL_NAME}.npz" if i == 0 else f"{MODEL_NAME}_{i}.npz"
        _save_tree(model.state_dict(), name)
        logger.info("Model weights saved in %s", output_dir / name)

    for i, opt in enumerate(optimizers):
        name = f"{OPTIMIZER_NAME}.npz" if i == 0 else f"{OPTIMIZER_NAME}_{i}.npz"
        _save_tree(opt.state_dict()["opt_state"], name)
        if opt.scaler is not None and (state.is_main_process or save_on_each_node):
            atomic_write_json(output_dir / f"{SCALER_NAME}_{i}.json", opt.scaler.state_dict())

    if state.is_main_process or save_on_each_node:
        for i, sched in enumerate(schedulers):
            name = f"{SCHEDULER_NAME}.bin" if i == 0 else f"{SCHEDULER_NAME}_{i}.bin"
            sched_state = sched.state_dict()
            atomic_write(output_dir / name, lambda f, s=sched_state: pickle.dump(s, f))

        for i, dl in enumerate(dataloaders):
            sampler = _find_seedable_sampler(dl)
            if sampler is not None:
                name = f"{SAMPLER_NAME}.bin" if i == 0 else f"{SAMPLER_NAME}_{i}.bin"
                # The loader's pass counter rides along: it is what
                # `DataLoaderShard.__iter__` feeds `set_epoch()` on the next
                # pass, and it disambiguates a mid-pass save (iteration ==
                # sampler.epoch: replay this epoch's permutation + skip) from
                # an epoch-boundary save (iteration == epoch + 1: the next
                # pass must draw a FRESH permutation, not repeat the last).
                # Explicit format marker: load-side sniffing by key presence
                # ("sampler" in payload) breaks the day a sampler's own
                # state_dict grows a 'sampler' key — version the envelope.
                payload = {"format": 2, "sampler": sampler.state_dict()}
                if hasattr(dl, "iteration"):
                    payload["loader_iteration"] = dl.iteration
                atomic_write(output_dir / name, lambda f, p=payload: pickle.dump(p, f))

    # RNG states are per-process (reference saves `random_states_{i}.pkl`,
    # checkpointing.py:122-151).
    rng_states = {"python": random.getstate(), "numpy": np.random.get_state()}
    if rng_key is not None:
        import jax

        rng_states["jax"] = np.asarray(jax.random.key_data(rng_key))
    atomic_write(
        output_dir / f"{RNG_STATE_NAME}_{state.process_index}.pkl",
        lambda f: pickle.dump(rng_states, f),
    )
    return str(output_dir)


def _find_seedable_sampler(dataloader):
    from .data_loader import SeedableRandomSampler

    candidates = [
        getattr(dataloader, "synchronized_generator", None),
        getattr(getattr(dataloader, "batch_sampler", None), "sampler", None),
    ]
    base = getattr(dataloader, "base_loader", None)
    if base is not None:
        bs = getattr(base, "batch_sampler", None)
        candidates.append(getattr(bs, "sampler", None))
        inner = getattr(bs, "batch_sampler", None)
        if inner is not None:
            candidates.append(getattr(inner, "sampler", None))
    for c in candidates:
        if isinstance(c, SeedableRandomSampler):
            return c
    return None


def load_accelerator_state(
    input_dir: str,
    models: list,
    optimizers: list,
    schedulers: list,
    dataloaders: list,
    load_rng: bool = True,
):
    """Restore the complete training state (reference checkpointing.py:152-254).

    Returns the restored jax RNG key if one was saved (or None)."""
    import jax

    from .state import PartialState

    state = PartialState()
    input_dir = Path(input_dir)

    for i, model in enumerate(models):
        name = f"{MODEL_NAME}.npz" if i == 0 else f"{MODEL_NAME}_{i}.npz"
        if (input_dir / f"{name}.sharded").exists():
            params = load_sharded(str(input_dir / f"{name}.sharded"), shardings=model.param_sharding)
        else:
            params = load_pytree(str(input_dir / name))
        model.load_state_dict(params)
        logger.info("Model weights loaded from %s", input_dir / name)

    for i, opt in enumerate(optimizers):
        name = f"{OPTIMIZER_NAME}.npz" if i == 0 else f"{OPTIMIZER_NAME}_{i}.npz"
        if (input_dir / f"{name}.sharded").exists():
            opt_state = load_sharded(str(input_dir / f"{name}.sharded"), shardings=opt.opt_state_sharding)
        else:
            opt_state = load_pytree(str(input_dir / name))
        scaler_state = None
        scaler_path = input_dir / f"{SCALER_NAME}_{i}.json"
        if scaler_path.exists():
            with open(scaler_path) as f:
                scaler_state = json.load(f)
        opt.load_state_dict({"opt_state": opt_state, "scaler": scaler_state})

    for i, sched in enumerate(schedulers):
        name = f"{SCHEDULER_NAME}.bin" if i == 0 else f"{SCHEDULER_NAME}_{i}.bin"
        if (input_dir / name).exists():
            with open(input_dir / name, "rb") as f:
                sched.load_state_dict(pickle.load(f))

    for i, dl in enumerate(dataloaders):
        sampler = _find_seedable_sampler(dl)
        name = f"{SAMPLER_NAME}.bin" if i == 0 else f"{SAMPLER_NAME}_{i}.bin"
        if sampler is not None and (input_dir / name).exists():
            with open(input_dir / name, "rb") as f:
                payload = pickle.load(f)
            if payload.get("format") == 2:
                sampler.load_state_dict(payload["sampler"])
                loader_iteration = payload.get("loader_iteration")
            elif "format" in payload:
                # A versioned envelope from a NEWER writer: refuse loudly
                # instead of feeding the whole envelope into load_state_dict
                # and crashing on a missing key three frames deeper.
                raise ValueError(
                    f"unsupported sampler checkpoint format {payload['format']!r} in "
                    f"{input_dir / name} (this version reads format 2 and earlier)"
                )
            elif "sampler" in payload:
                # round-4 wrapped format (pre-marker): {"sampler": ..., "loader_iteration": ...}
                sampler.load_state_dict(payload["sampler"])
                loader_iteration = payload.get("loader_iteration")
            else:  # pre-round-4 checkpoint: bare sampler state_dict
                sampler.load_state_dict(payload)
                loader_iteration = payload.get("epoch")
            # Realign the loader's pass counter: `DataLoaderShard.__iter__`
            # calls `set_epoch(self.iteration)` at the top of every pass, and
            # a fresh process's 0 would clobber the restored epoch — the
            # resumed pass would replay epoch 0's permutation, so
            # `skip_first_batches` would skip the WRONG samples.
            if loader_iteration is not None and hasattr(dl, "iteration"):
                dl.iteration = loader_iteration

    rng_key = None
    if load_rng:
        rng_path = input_dir / f"{RNG_STATE_NAME}_{state.process_index}.pkl"
        if rng_path.exists():
            with open(rng_path, "rb") as f:
                rng_states = pickle.load(f)
            random.setstate(rng_states["python"])
            np.random.set_state(rng_states["numpy"])
            if "jax" in rng_states:
                rng_key = jax.random.wrap_key_data(np.asarray(rng_states["jax"]))
    return rng_key


def save_custom_state(obj, path: str, index: int = 0):
    """Pickle an object exposing state_dict() (reference checkpointing.py:257)."""
    location = Path(path) / f"custom_checkpoint_{index}.pkl"
    logger.info("Saving the state of %s to %s", type(obj).__name__, location)
    obj_state = obj.state_dict()
    atomic_write(location, lambda f: pickle.dump(obj_state, f))


# ------------------------------------------------------------------ crash-safe manager
def _rmtree_missing_ok(path: str):
    """`shutil.rmtree` that treats an already-gone tree as success — required
    under `_retry` (chaos-surfaced bug): a first attempt that raised a
    transient error AFTER deleting most of the tree must not make the retry
    fail on the now-missing path and abort a save whose rotation had in fact
    completed."""
    try:
        shutil.rmtree(path)
    except FileNotFoundError:
        pass


def write_checkpoint_manifest(directory: str, step: Optional[int] = None) -> str:
    """Commit record for a checkpoint DIRECTORY: scan every artifact, digest it,
    and atomically write `MANIFEST.json`. Written LAST — its presence asserts
    every file it names was fully on disk first."""
    directory = str(directory)
    entries = []
    for root, dirs, names in os.walk(directory):
        dirs[:] = [d for d in dirs if not d.startswith(_STAGING_PREFIX)]
        for name in names:
            # Skip the commit record itself, the latest pointer, and atomic-write
            # temp litter a killed previous writer may have left behind.
            if name in (CHECKPOINT_MANIFEST_NAME, LATEST_POINTER_NAME) or ".tmp-" in name:
                continue
            entries.append((os.path.relpath(os.path.join(root, name), directory), name))
    # Reuse the digests `save_pytree` already computed: each `X.manifest.json`
    # records the SHA-256 of its just-written sibling `X.npz`. The npz payloads
    # are the bulk of a checkpoint, so this turns the digest scan's second full
    # disk read of the model/optimizer state into a JSON lookup — save latency
    # matters most on the preemption path, where it races the hard kill.
    known = {}
    for rel, name in entries:
        if not name.endswith(".manifest.json"):
            continue
        try:
            with open(os.path.join(directory, rel)) as f:
                digest = json.load(f).get("npz_sha256")
        except (OSError, ValueError):  # ValueError: JSON errors AND flipped-byte utf-8 tears
            continue
        if digest:
            known[rel[: -len(".manifest.json")] + ".npz"] = digest
    files = {
        rel: known.get(rel) or file_sha256(os.path.join(directory, rel)) for rel, _ in entries
    }
    manifest_path = os.path.join(directory, CHECKPOINT_MANIFEST_NAME)
    atomic_write_json(manifest_path, {"format": 1, "step": step, "files": files})
    return manifest_path


def verify_checkpoint_dir(directory: str) -> bool:
    """True iff the directory carries a `MANIFEST.json` and every file it names
    exists with a matching SHA-256. A directory without a manifest (killed before
    commit, or a pre-digest legacy checkpoint) does NOT verify."""
    manifest_path = os.path.join(str(directory), CHECKPOINT_MANIFEST_NAME)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        # ValueError, not just JSONDecodeError (chaos-surfaced bug): a single
        # flipped byte can make the manifest invalid UTF-8, and the resulting
        # UnicodeDecodeError used to CRASH resolution instead of reading as
        # "this checkpoint does not verify — fall back".
        return False
    for rel, digest in manifest.get("files", {}).items():
        full = os.path.join(str(directory), rel)
        try:
            if file_sha256(full) != digest:
                logger.warning("checkpoint %s: digest mismatch on %s", directory, rel)
                return False
        except OSError:
            logger.warning("checkpoint %s: missing artifact %s", directory, rel)
            return False
    return True


class CheckpointManager:
    """Rotated, digest-verified, atomically-published checkpoints under one base dir.

    Layout::

        base_dir/
          checkpoint_0/          # complete, committed (has MANIFEST.json)
          checkpoint_1/
          latest                 # text file naming the newest committed checkpoint
          .tmp-checkpoint_2/     # in-flight staging (ignored by readers, reaped)

    `save(step, write_fn)` stages everything in a hidden temp directory, writes the
    per-file digest manifest, `os.replace`s the directory into place (the single
    commit point — a kill before it leaves only ignorable staging litter), swaps
    the `latest` pointer, and rotates to `keep_last_n`. Transient I/O errors in the
    commit sequence retry with exponential backoff. `resolve("latest")` returns the
    newest checkpoint that VERIFIES, falling back past a corrupt or torn newest one.

    The `latest` pointer file is a breadcrumb for humans and external tooling
    (and the `is_manager_dir` sniff), NOT the source of truth for resume:
    `resolve()` always re-verifies from the directory listing, so a pointer left
    stale by a kill between publish and pointer swap — or pointing at a
    checkpoint that later rotted — can never misdirect a resume.

    Multi-process: pass `is_main`/`barrier` so every process writes its per-process
    artifacts into the shared staging dir while exactly one commits.
    """

    def __init__(
        self,
        base_dir: str,
        keep_last_n: Optional[int] = None,
        retries: int = 3,
        backoff_seconds: float = 0.1,
    ):
        if keep_last_n is not None and keep_last_n < 1:
            raise ValueError(f"keep_last_n must be >= 1 (got {keep_last_n})")
        self.base_dir = str(base_dir)
        self.keep_last_n = keep_last_n
        self.retries = retries
        self.backoff_seconds = backoff_seconds

    # ---------------------------------------------------------------- inventory
    def checkpoints(self) -> List[Tuple[int, str]]:
        """(step, path) pairs sorted numerically ascending (lexicographic listdir
        would order checkpoint_10 before checkpoint_9)."""
        if not os.path.isdir(self.base_dir):
            return []
        out = []
        for name in os.listdir(self.base_dir):
            if name.startswith(_STAGING_PREFIX) or not name.startswith("checkpoint_"):
                continue
            suffix = name[len("checkpoint_"):]
            if suffix.isdigit() and os.path.isdir(os.path.join(self.base_dir, name)):
                out.append((int(suffix), os.path.join(self.base_dir, name)))
        return sorted(out)

    def next_step(self) -> int:
        ckpts = self.checkpoints()
        return ckpts[-1][0] + 1 if ckpts else 0

    def latest_verified(self) -> Optional[str]:
        """Newest checkpoint whose digests verify; corrupt/torn ones are skipped
        with a warning (the resume-past-a-bad-newest fallback).

        Legacy checkpoints (written before the manifest discipline, so they have
        no `MANIFEST.json` to verify against) are not abandoned: when NOTHING
        digest-verifies, the newest manifest-less one is returned as a last
        resort — an in-place upgrade must still resume from its old saves. A
        directory whose manifest EXISTS but fails is definitely torn and is
        never used."""
        legacy = None
        for step, path in reversed(self.checkpoints()):
            if verify_checkpoint_dir(path):
                return path
            if not os.path.isfile(os.path.join(path, CHECKPOINT_MANIFEST_NAME)):
                if legacy is None:
                    legacy = path
            else:
                logger.warning(
                    "checkpoint %s failed verification (torn or corrupt); falling back", path
                )
        if legacy is not None:
            logger.warning(
                "no digest-verified checkpoint under %s; falling back to legacy "
                "pre-manifest checkpoint %s (loaded without directory-level verification)",
                self.base_dir, legacy,
            )
        return legacy

    def resolve(self, spec: Optional[str] = None) -> str:
        """'latest'/None -> newest VERIFIED checkpoint; an explicit path is
        verified and returned. Raises FileNotFoundError when nothing usable
        exists and CheckpointCorruptError for an explicitly-named bad one."""
        if spec in (None, "latest"):
            path = self.latest_verified()
            if path is None:
                raise FileNotFoundError(
                    f"no verified checkpoint under {self.base_dir} "
                    f"({len(self.checkpoints())} candidate(s) present)"
                )
            return path
        spec = str(spec)
        if not os.path.isdir(spec):
            raise FileNotFoundError(f"checkpoint directory {spec} does not exist")
        if os.path.isfile(os.path.join(spec, CHECKPOINT_MANIFEST_NAME)) and not verify_checkpoint_dir(spec):
            raise CheckpointCorruptError(f"checkpoint {spec} failed digest verification")
        return spec

    @staticmethod
    def is_manager_dir(path: str) -> bool:
        """A base dir the manager owns (vs a concrete checkpoint dir): has a
        `latest` pointer or `checkpoint_N` children but no own MANIFEST."""
        path = str(path)
        if not os.path.isdir(path) or os.path.isfile(os.path.join(path, CHECKPOINT_MANIFEST_NAME)):
            return False
        if os.path.isfile(os.path.join(path, LATEST_POINTER_NAME)):
            return True
        return bool(CheckpointManager(path).checkpoints())

    # ---------------------------------------------------------------- commit path
    def _retry(self, fn, what: str):
        for attempt in range(self.retries + 1):
            try:
                return fn()
            except OSError as exc:
                if attempt == self.retries:
                    raise
                delay = self.backoff_seconds * (2**attempt)
                logger.warning(
                    "transient I/O error during %s (%s); retry %d/%d in %.2fs",
                    what, exc, attempt + 1, self.retries, delay,
                )
                time.sleep(delay)

    def clean_staging(self):
        """Reap staging litter left by a killed save (never a committed checkpoint)."""
        if not os.path.isdir(self.base_dir):
            return
        for name in os.listdir(self.base_dir):
            if name.startswith(_STAGING_PREFIX):
                shutil.rmtree(os.path.join(self.base_dir, name), ignore_errors=True)

    def save(
        self,
        step: int,
        write_fn: Callable[[str], Any],
        is_main: bool = True,
        barrier: Optional[Callable[[], Any]] = None,
    ) -> str:
        """Stage -> digest-manifest -> atomic publish -> latest pointer -> rotate.

        `write_fn(staging_dir)` writes every artifact. The checkpoint only becomes
        visible (and `latest` only advances) after everything it contains — and
        the manifest describing it — is fully on disk."""
        barrier = barrier or (lambda: None)
        final = os.path.join(self.base_dir, f"checkpoint_{step}")
        replace_torn = False
        if os.path.exists(final):
            # A resumed run that fell back past a torn newest checkpoint will
            # re-save its step number: replacing a directory whose manifest
            # FAILS is safe (it can never serve a resume). A verified one — or
            # a manifest-less LEGACY one, which resume may still fall back to —
            # is never clobbered.
            has_manifest = os.path.isfile(os.path.join(final, CHECKPOINT_MANIFEST_NAME))
            if not has_manifest or verify_checkpoint_dir(final):
                raise ValueError(
                    f"Checkpoint directory {final} already exists; use a different step "
                    "or a fresh base directory."
                )
            logger.warning("replacing unverifiable existing checkpoint %s", final)
            replace_torn = True
        staging = os.path.join(self.base_dir, f"{_STAGING_PREFIX}checkpoint_{step}")
        if is_main:
            os.makedirs(self.base_dir, exist_ok=True)
            shutil.rmtree(staging, ignore_errors=True)
            os.makedirs(staging)
        barrier()  # staging dir exists before any process writes into it
        write_fn(staging)
        barrier()  # every process's artifacts are in before the digest scan
        if is_main:
            self._retry(lambda: write_checkpoint_manifest(staging, step), "manifest write")
            if replace_torn:
                # Retire the torn dir just before publishing: the new checkpoint
                # (manifest included) is already fully on disk in staging, so a
                # kill in this window loses nothing that could have been loaded.
                self._retry(lambda: _rmtree_missing_ok(final), f"reap of torn {final}")
            self._retry(lambda: self._publish(staging, final), "checkpoint publish")
            self._rotate(keep=final)
        barrier()
        return final

    def _publish(self, staging: str, final: str):
        # Idempotent under `_retry` (chaos-surfaced bug): a transient failure
        # AFTER the rename — the directory fsync or the pointer write — used to
        # make the retry re-run `os.replace` on a staging dir that no longer
        # exists, so a fully-committed checkpoint still raised out of save()
        # and the caller burned a restart on a save that had in fact succeeded.
        # The rename is THE commit point; once `final` exists, a retry only
        # needs to finish the pointer swap.
        hooks = _chaos_hooks
        if os.path.isdir(staging):
            if hooks is not None:
                hooks.on_publish_rename(staging, final)
            os.replace(staging, final)  # THE commit point (atomic dir rename)
        elif not os.path.isdir(final):
            raise FileNotFoundError(
                f"checkpoint publish lost both staging ({staging}) and committed ({final}) dirs"
            )
        _fsync_directory(self.base_dir)
        atomic_write(
            os.path.join(self.base_dir, LATEST_POINTER_NAME),
            lambda f: f.write(os.path.basename(final)),
            mode="w",
        )
        if hooks is not None:
            hooks.on_published(final)

    def _rotate(self, keep: str):
        if self.keep_last_n is None:
            return
        ckpts = self.checkpoints()
        excess = len(ckpts) - self.keep_last_n
        if excess <= 0:
            return
        # Strictly oldest-first by step. Manifest-less directories are LEGACY
        # checkpoints (in the post-manifest world a torn save never becomes a
        # `checkpoint_N` at all — the staging rename is atomic), so they age
        # out in step order like any other checkpoint rather than being
        # preferentially destroyed while they may still be the only resumable
        # state.
        for _step, path in ckpts:
            if excess <= 0:
                break
            if os.path.abspath(path) == os.path.abspath(keep):
                continue  # never reap the checkpoint just committed
            logger.info("rotating out checkpoint %s (keep_last_n=%d)", path, self.keep_last_n)
            self._retry(lambda p=path: _rmtree_missing_ok(p), f"rotation of {path}")
            excess -= 1


def load_custom_state(obj, path: str, index: int = 0):
    """(reference checkpointing.py:267)"""
    location = Path(path) / f"custom_checkpoint_{index}.pkl"
    if not location.exists():
        # Hard failure on purpose: silently keeping the object's constructed
        # state would resume at a wrong position (e.g. a step counter at 0 on
        # fully-trained weights). The usual cause is actionable.
        raise FileNotFoundError(
            f"Checkpoint has no saved state for registered object {index} "
            f"({type(obj).__name__}) at {location}. If this object was "
            "registered for checkpointing AFTER the checkpoint was written, "
            "resume once without registering it (or write a fresh checkpoint)."
        )
    with open(location, "rb") as f:
        obj.load_state_dict(pickle.load(f))

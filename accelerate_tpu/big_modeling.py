"""Big-model machinery: run models larger than HBM (L5 sibling; parity: reference
big_modeling.py 627 + hooks.py 709).

TPU-native redesign of the reference's hook architecture. The reference monkey-patches
`module.forward` with AlignDevicesHooks that fault weights in from a weights_map
(hooks.py:212-389). Functional JAX can do better: the model is executed as an explicit
**layer stream** — prelude (embeddings), a loop of identically-shaped layer applications
(ONE compiled executable reused for every layer), then the tail — while a double-buffer
of `jax.device_put` transfers prefetches layer N+1's weights from host DRAM / disk-mmap
into HBM underneath layer N's compute. That is the AlignDevicesHook + `cpu_offload_with_
hook` pipeline (reference big_modeling.py:169-302) without any hooks.

Tiers: HBM (resident blocks) → host DRAM (numpy, pinned by the OS page cache) → disk
(`native/offload.py` single-blob store: striped pread on C++ threads + async readahead
tickets — the perf-bearing replacement for the reference's per-tensor mmap files,
utils/offload.py:25-192). Placement comes from `infer_auto_device_map`
(utils/modeling.py).
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Union

import numpy as np

from .logging import get_logger
from .modeling import Model
from .utils.modeling import (
    clean_device_map,
    get_balanced_memory,
    get_max_memory,
    group_into_blocks,
    infer_auto_device_map,
)
logger = get_logger(__name__)


class _DiskRef:
    """Placeholder leaf for a disk-resident tensor: (store, name) resolved at block
    fetch time so a streamed call only reads the layers it is about to run —
    `_fetch_block_pytree` issues one async readahead per tensor (striped pread on the
    store's C++ pool) before the blocking reads, so a block's tensors come off disk in
    parallel while the previous layer computes."""

    __slots__ = ("store", "name")

    def __init__(self, store, name):
        self.store = store
        self.name = name

    def read(self):
        return self.store.read(self.name)


def _resolve(leaf):
    return leaf.read() if isinstance(leaf, _DiskRef) else leaf


def init_empty_weights(module, *sample_args, **sample_kwargs):
    """Shape-only init: the meta-device replacement (reference big_modeling.py:56
    patches nn.Module registration; JAX just traces `module.init` without running it).

    Returns a pytree of jax.ShapeDtypeStruct — enough for planning, zero memory."""
    import jax

    return jax.eval_shape(lambda rng: module.init(rng, *sample_args, **sample_kwargs), jax.random.key(0))


@contextlib.contextmanager
def init_on_device(device):
    """Context parity shim (reference big_modeling.py:91): place initializers' outputs
    on `device` by making it the default."""
    import jax

    with jax.default_device(device):
        yield


class LayeredApply:
    """Protocol for layer-streamed execution: model families implement this to run
    over-HBM models (Llama/BERT ship implementations in accelerate_tpu.models).

    `prelude/layer/tail` receive the *sub*-pytrees produced by `split(params)`; layer
    params must be identically shaped across layers (one compiled executable)."""

    def split(self, params) -> tuple:
        """→ (prelude_params, [layer_params...], tail_params)"""
        raise NotImplementedError

    def join(self, prelude, layers, tail):
        """Inverse of split (used to reassemble a full pytree)."""
        raise NotImplementedError

    def apply_prelude(self, prelude_params, *args, **kwargs):
        raise NotImplementedError

    def apply_layer(self, layer_params, carry):
        raise NotImplementedError

    def apply_tail(self, tail_params, carry):
        raise NotImplementedError


class DispatchedModel:
    """A model whose parameter blocks live across HBM/host/disk per a device map
    (reference dispatch_model big_modeling.py:305-495 + hook machinery).

    Callable like a PreparedModel; when all blocks are device-resident this is a plain
    jitted apply, otherwise the layer stream runs with double-buffered weight prefetch.
    """

    def __init__(
        self,
        model: Model,
        device_map: Dict[str, Union[int, str]],
        offload_folder: Optional[str] = None,
        layered: Optional[LayeredApply] = None,
        compute_dtype=None,
    ):
        import jax

        self.module = model.module
        self.apply_fn = model.apply_fn
        self.layered = layered
        self.device_map = device_map
        self.offload_folder = offload_folder
        self.compute_dtype = compute_dtype
        self._jit_cache: dict = {}

        devices = jax.local_devices()
        blocks = group_into_blocks(model.params)
        from .parallel.sharding import tree_paths_and_leaves

        flat, self._treedef = tree_paths_and_leaves(model.params)
        self._paths = [p for p, _ in flat]

        # Place every leaf according to its block's tier.
        tier_of: Dict[str, Union[int, str]] = {}
        for block_name, paths in blocks.items():
            tier = _lookup_tier(device_map, block_name)
            for p in paths:
                tier_of[p] = tier
        self.tier_of = tier_of

        def _maybe_cast(x):
            # The planner sized blocks at compute_dtype; cast floats so budgets hold.
            if compute_dtype is None:
                return x
            import jax.numpy as jnp

            dt = getattr(x, "dtype", None)
            if dt is not None and jnp.issubdtype(dt, jnp.floating):
                return jnp.asarray(x, dtype=compute_dtype) if isinstance(x, jax.Array) else np.asarray(
                    jnp.asarray(np.asarray(x), dtype=compute_dtype)
                )
            return x

        self._leaves: Dict[str, Any] = {}
        self._resident_devices = set()
        self._disk_store = None
        for path, leaf in flat:
            tier = tier_of.get(path, 0)
            if tier == "disk":
                if offload_folder is None:
                    raise ValueError("device_map places blocks on disk; offload_folder is required")
                if self._disk_store is None:
                    from .native.offload import NativeOffloadStore

                    self._disk_store = NativeOffloadStore(offload_folder)
                    self._disk_store.reset()  # a previous run's blob would leak
                # One tensor at a time into the blob (host RAM never holds the
                # spilled blocks at once); index flushed once after the loop.
                self._disk_store.save(
                    {path: np.asarray(jax.device_get(_maybe_cast(leaf)))}, flush_index=False
                )
                self._leaves[path] = None  # resolved via the blob store
            elif tier == "cpu":
                self._leaves[path] = np.asarray(jax.device_get(_maybe_cast(leaf)))
            else:
                self._leaves[path] = jax.device_put(_maybe_cast(leaf), devices[int(tier)])
                self._resident_devices.add(int(tier))
        if self._disk_store is not None:
            self._disk_store.flush_index()
        self.hf_device_map = dict(device_map)  # reference exposes model.hf_device_map

    # -- leaf access -------------------------------------------------------------------
    def _get_leaf(self, path: str):
        """Leaf value, with disk leaves as lazy `_DiskRef`s (read at block fetch)."""
        leaf = self._leaves[path]
        if leaf is None:
            leaf = _DiskRef(self._disk_store, path)
        return leaf

    def materialize_params(self, device=None):
        """Full params pytree fetched to `device` (or default). For models that fit
        transiently; the streamed path avoids this."""
        import jax

        if self._disk_store is not None:  # one readahead ticket for the disk part
            self._disk_store.prefetch_many([p for p in self._paths if self._leaves[p] is None])
        leaves = [jax.device_put(np.asarray(_resolve(self._get_leaf(p)))) for p in self._paths]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    @property
    def resident_fraction(self) -> float:
        n_dev = sum(1 for p in self._paths if not isinstance(self.tier_of.get(p, 0), str))
        return n_dev / max(1, len(self._paths))

    # -- execution ---------------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        import jax

        all_resident = all(not isinstance(self.tier_of.get(p, 0), str) for p in self._paths)
        if all_resident and len(self._resident_devices) <= 1:
            if "apply" not in self._jit_cache:
                self._jit_cache["apply"] = jax.jit(self.apply_fn)
            params = jax.tree_util.tree_unflatten(self._treedef, [self._leaves[p] for p in self._paths])
            return self._jit_cache["apply"](params, *args, **kwargs)
        if self.layered is not None:
            # Blocks on several devices or host/disk tiers: stream layer-by-layer.
            # (Per-stage pipelined execution across devices is the PP-inference path;
            # here remote blocks are copied to the compute device per step.)
            return self._streamed_call(*args, **kwargs)
        logger.warning_once(
            "Model has offloaded blocks but no LayeredApply protocol; materializing all "
            "params per call (works only if the model fits HBM transiently)."
        )
        return self.apply_fn(self.materialize_params(), *args, **kwargs)

    def generate(self, input_ids, max_new_tokens: int = 32, eos_token_id=None, attention_mask=None):
        """Greedy generation through the tiered forward — the reference's
        big-model-inference benchmark shape (load + per-token generation with
        CPU/disk-offloaded weights, benchmarks/big_model_inference.py). Each token
        re-streams the offloaded layers over the full context; that IS the cost
        model the reference publishes (2.4-34 s/token for OPT-30B offload,
        benchmarks/README.md:36-37) — for fast decoding keep weights resident and
        use `accelerate_tpu.generation.Generator`.

        `attention_mask` (right-padded, HF convention) enables batches of
        unequal-length prompts: each row advances at its own frontier — the next
        token is read at column `len_r - 1` and written in place of the first pad
        — so every row stays a contiguous prefix and causal attention never sees
        another row's padding. Rows shorter than the longest finish their last
        `max_new_tokens` at the same step count; output is right-padded with 0.
        """
        import jax.numpy as jnp

        from .generation import _bucket_for

        ids = jnp.asarray(input_ids, jnp.int32)
        b, prompt_len = ids.shape
        if attention_mask is not None:
            am = jnp.asarray(attention_mask).astype(bool)
            lengths = am.sum(axis=1).astype(jnp.int32)
            # Per-row frontier writes assume right-padding (a contiguous prefix of
            # real tokens); a left-padded or holey mask would interleave garbage,
            # and an empty row would read its first logits at column -1 (wraparound).
            valid_prefixes = bool(jnp.all(am == (jnp.arange(prompt_len)[None, :] < lengths[:, None])))
            if not valid_prefixes or not bool(jnp.all(lengths >= 1)):
                raise ValueError(
                    "attention_mask must be right-padded (each row a non-empty prefix of "
                    "ones); re-tokenize with padding_side='right'"
                )
            ids = jnp.where(am, ids, 0)  # canonicalize pad slots; they get overwritten
            max_len = int(lengths.max())
        else:
            lengths = jnp.full((b,), prompt_len, jnp.int32)
            max_len = prompt_len
        cur = lengths  # per-row next write position
        finished = jnp.zeros((b,), bool)
        buf = ids
        steps_taken = 0
        for step in range(max_new_tokens):
            # The forward only needs to cover the read columns (cur-1 < max_len +
            # step); bucket that width to powers of two — padding after each row's
            # last real token is invisible under causal masking, and stable shapes
            # keep compiles O(log n), not O(n). `max_len + step` tracks cur.max()
            # on the host, avoiding a device sync per token.
            bucket = _bucket_for(max_len + step)
            if buf.shape[1] < bucket + 1:  # +1: room for this step's frontier write
                buf = jnp.pad(buf, ((0, 0), (0, bucket + 1 - buf.shape[1])))
            logits = self(buf[:, :bucket])
            nxt = jnp.argmax(logits[jnp.arange(b), cur - 1, :], axis=-1).astype(jnp.int32)
            if eos_token_id is not None:
                # Per-row EOS: finished rows emit pad/eos (HF generate padding),
                # and the loop stops as soon as EVERY row has finished — each
                # extra step re-streams the whole offloaded model.
                nxt = jnp.where(finished, jnp.int32(eos_token_id), nxt)
                finished = finished | (nxt == eos_token_id)
            buf = buf.at[jnp.arange(b), cur].set(nxt)
            cur = cur + 1
            steps_taken = step + 1
            if eos_token_id is not None and bool(finished.all()):
                break
        # Never return narrower than the input (callers slice continuations with
        # out[:, input_ids.shape[1]:], the HF right-padding idiom).
        return buf[:, : max(max_len + steps_taken, prompt_len)]

    def _fetch_block_pytree(self, subtree):
        """device_put a sub-pytree whose leaves may live on host/disk (async transfer).

        Disk leaves (`_DiskRef`) resolve here: readahead tickets for every tensor in
        the block first (parallel striped pread on the store's C++ pool), then the
        blocking reads consume them — and because JAX dispatch is async, even the
        blocking part overlaps the previous layer's device compute."""
        import jax

        from .parallel.sharding import tree_paths_and_leaves

        flat, treedef = tree_paths_and_leaves(subtree)
        disk_names = [leaf.name for _, leaf in flat if isinstance(leaf, _DiskRef)]
        if disk_names:
            self._disk_store.prefetch_many(disk_names)
        leaves = []
        for _, leaf in flat:
            leaf = _resolve(leaf)
            leaves.append(jax.device_put(np.asarray(leaf) if not isinstance(leaf, jax.Array) else leaf))
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _streamed_call(self, *args, **kwargs):
        """The AlignDevicesHook pipeline, functional: prelude → layer loop with
        double-buffered weight prefetch → tail (reference hooks.py:315-389 semantics)."""
        import jax

        params = jax.tree_util.tree_unflatten(
            self._treedef, [self._get_leaf(p) for p in self._paths]
        )
        prelude_p, layer_ps, tail_p = self.layered.split(params)

        if "prelude" not in self._jit_cache:
            self._jit_cache["prelude"] = jax.jit(self.layered.apply_prelude)
            self._jit_cache["layer"] = jax.jit(self.layered.apply_layer)
            self._jit_cache["tail"] = jax.jit(self.layered.apply_tail)

        carry = self._jit_cache["prelude"](self._fetch_block_pytree(prelude_p), *args, **kwargs)
        n = len(layer_ps)
        next_block = self._fetch_block_pytree(layer_ps[0]) if n else None
        for i in range(n):
            current = next_block
            if i + 1 < n:
                # Prefetch the next layer's weights while this layer computes:
                # device_put is async, so the H2D DMA overlaps the layer matmuls.
                next_block = self._fetch_block_pytree(layer_ps[i + 1])
            carry = self._jit_cache["layer"](current, carry)
        return self._jit_cache["tail"](self._fetch_block_pytree(tail_p), carry)


def _lookup_tier(device_map: dict, block_name: str):
    if block_name in device_map:
        return device_map[block_name]
    parts = block_name.split("/")
    for i in range(len(parts), -1, -1):
        prefix = "/".join(parts[:i])
        if prefix in device_map:
            return device_map[prefix]
    return 0


def dispatch_model(
    model: Model,
    device_map: Dict[str, Union[int, str]],
    offload_folder: Optional[str] = None,
    layered: Optional[LayeredApply] = None,
    dtype=None,
) -> DispatchedModel:
    """Place a materialized model across tiers (reference big_modeling.py:305)."""
    if isinstance(device_map, str):
        raise ValueError("Pass a concrete device_map dict; use load_checkpoint_and_dispatch for 'auto'")
    return DispatchedModel(
        model, clean_device_map(device_map), offload_folder=offload_folder, layered=layered, compute_dtype=dtype
    )


def cpu_offload(model: Model, layered: Optional[LayeredApply] = None) -> DispatchedModel:
    """All params on host DRAM, streamed per layer (reference big_modeling.py:169)."""
    return DispatchedModel(model, {"": "cpu"}, layered=layered)


def disk_offload(model: Model, offload_dir: str, layered: Optional[LayeredApply] = None) -> DispatchedModel:
    """All params in the disk store (reference big_modeling.py:231)."""
    return DispatchedModel(model, {"": "disk"}, offload_folder=offload_dir, layered=layered)


def load_checkpoint_and_dispatch(
    model: Model,
    checkpoint: Optional[str] = None,
    device_map: Union[str, dict, None] = "auto",
    max_memory: Optional[dict] = None,
    no_split_prefixes: Optional[List[str]] = None,
    offload_folder: Optional[str] = None,
    dtype=None,
    layered: Optional[LayeredApply] = None,
) -> DispatchedModel:
    """One call: balanced budgets → device map → (load) → dispatch
    (reference big_modeling.py:498-627)."""
    from .checkpointing import load_pytree

    if checkpoint is not None:
        params = load_pytree(checkpoint)
        model = Model(apply_fn=model.apply_fn, params=params, module=model.module, loss_fn=model.loss_fn,
                      sharding_rules=model.sharding_rules)
    if device_map == "auto" or device_map == "balanced":
        budgets = get_balanced_memory(model.params, max_memory, dtype=dtype)
        device_map = infer_auto_device_map(
            model.params, budgets, no_split_prefixes=no_split_prefixes, dtype=dtype
        )
    elif device_map == "sequential":
        device_map = infer_auto_device_map(
            model.params, get_max_memory(max_memory), no_split_prefixes=no_split_prefixes, dtype=dtype
        )
    logger.info("device_map tiers: %s", {k: v for k, v in list(device_map.items())[:8]})
    return dispatch_model(model, device_map, offload_folder=offload_folder, layered=layered, dtype=dtype)

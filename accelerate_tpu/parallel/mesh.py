"""Global device-mesh construction.

The single `Mesh` replaces every process-group in the reference (DDP/FSDP/Megatron
TP/PP/DP groups — reference utils/megatron_lm.py + torch.distributed group creation).
Axis order follows `constants.MESH_AXIS_NAMES`, laid out so that the innermost axes
(model/seq) map to the fastest ICI links while the outermost (data) may span DCN on
multi-slice/multi-host topologies.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..utils.constants import MESH_AXIS_NAMES
from ..utils.dataclasses import ParallelismConfig


def build_mesh(
    parallelism: Optional[ParallelismConfig] = None,
    devices: Optional[Sequence] = None,
    axis_names: Sequence[str] = MESH_AXIS_NAMES,
):
    """Build a `jax.sharding.Mesh` from a ParallelismConfig.

    Uses `mesh_utils.create_device_mesh` so the logical mesh is laid out along physical
    ICI topology (the TPU-native replacement for NCCL ring construction); falls back to a
    plain reshape on CPU/virtual platforms. Multi-host meshes with a data axis spanning
    hosts use `create_hybrid_device_mesh` so cross-DCN traffic stays on the data axis.
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if parallelism is None:
        parallelism = ParallelismConfig()
    if devices is None:
        devices = jax.devices()
    sizes = parallelism.resolve(len(devices))
    shape = tuple(sizes[name] for name in axis_names)

    if jax.process_count() > 1 and sizes.get("data", 1) % jax.process_count() == 0 and sizes.get("data", 1) > 1:
        try:
            per_host = list(shape)
            data_idx = list(axis_names).index("data")
            per_host[data_idx] = sizes["data"] // jax.process_count()
            dcn = [1] * len(shape)
            dcn[data_idx] = jax.process_count()
            device_array = mesh_utils.create_hybrid_device_mesh(
                tuple(per_host), tuple(dcn), devices=devices, allow_split_physical_axes=True
            )
            return Mesh(device_array, axis_names)
        except (ValueError, AssertionError, NotImplementedError):
            pass
    try:
        device_array = mesh_utils.create_device_mesh(shape, devices=devices, allow_split_physical_axes=True)
    except (ValueError, AssertionError, NotImplementedError):
        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, axis_names)


def slice_mesh(mesh, axis: str = "pipeline"):
    """Slice a global mesh into per-index submeshes along ``axis``.

    Returns a list of ``mesh.shape[axis]`` meshes, each holding the devices of
    one slice with ``axis`` REMOVED from the axis names — the MPMD pipeline
    runtime's stage meshes (each stage jit-compiles against its own submesh, so
    stages may hold unequal layer counts; activations hop between submeshes as
    explicit device-to-device transfers). The remaining axes keep their order
    and sizes, so a ("data", ..., "pipeline") global mesh yields ("data", ...)
    stage meshes whose data/model specs mean exactly what they mean globally.
    """
    from jax.sharding import Mesh

    names = list(mesh.axis_names)
    if axis not in names:
        raise ValueError(f"mesh has no {axis!r} axis (axes: {tuple(names)})")
    idx = names.index(axis)
    sub_names = tuple(n for n in names if n != axis)
    return [
        Mesh(np.take(mesh.devices, k, axis=idx), sub_names)
        for k in range(mesh.devices.shape[idx])
    ]


def get_default_mesh():
    """The mesh from AcceleratorState (building it on first use)."""
    from ..state import AcceleratorState

    return AcceleratorState().mesh


def mesh_axis_size(mesh, axis) -> int:
    if isinstance(axis, (tuple, list)):
        out = 1
        for a in axis:
            out *= mesh.shape[a]
        return out
    return mesh.shape[axis]

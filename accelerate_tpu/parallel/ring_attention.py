"""Ring attention: first-class sequence/context parallelism.

The reference cannot scale sequence length natively — its only SP surface is a Megatron
passthrough flag (SURVEY §5; reference dataclasses.py:1262-1265). Here SP is a mesh axis:
activations are sharded [batch, seq/axis, ...] over "seq", and attention runs as a ring
(see PAPERS.md: blockwise/ring attention literature):

  each device keeps its Q block resident and its K/V block rotating — at every step the
  local K/V block hops to the next device over ICI via `lax.ppermute` while the device
  computes blockwise attention against the block it just received, folding results with
  a streaming (flash-style) log-sum-exp accumulator. Communication is fully overlapped
  with the matmuls; HBM never holds more than one remote block.

`ring_attention` is the shard_map-level kernel; `sequence_parallel_attention` wraps it
in a `shard_map` over the active mesh so jit-level callers (the models' attention seam,
ops/attention.py) can dispatch to it transparently when mesh.shape["seq"] > 1.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np


def _axis_size(axis_name) -> int:
    """Static size of a named mesh axis from inside `shard_map`.

    `lax.psum(1, axis)` constant-folds to a Python int (no collective is
    emitted), which the ring loops need for `range()` unrolling. Newer jax
    exposes `lax.axis_size`; this works on every version in support."""
    from jax import lax

    size = getattr(lax, "axis_size", None)
    if size is not None:
        return size(axis_name)
    return lax.psum(1, axis_name)


def segment_mask(q_seg, kv_seg):
    """Packed-sequence attention mask: [B, Sq] x [B, Skv] ids -> [B, 1, Sq, Skv]
    boolean, True where the ids match. The ONE definition of segment semantics —
    shared by the dense path (ops/attention.py), the einsum ring, and allgather
    mode, so the three paths cannot diverge."""
    return q_seg[:, None, :, None] == kv_seg[:, None, None, :]


def _ring_step_block(q, k, v, m, l, o, q_offset, kv_offset, scale, causal, q_seg=None, kv_seg=None):
    """Fold one K/V block into the streaming-softmax accumulator.

    q: [B, Sq, H, D]; k/v: [B, Skv, H, D]; m/l: [B, H, Sq]; o: [B, Sq, H, D].
    Offsets are the blocks' global sequence starts (for causal masking).
    `q_seg`/`kv_seg` ([B, Sq]/[B, Skv]) restrict attention to equal segment ids
    (packed-sequence masking); rows whose segments never meet stay -inf and the
    accumulator guards below keep them NaN-free.
    """
    import jax.numpy as jnp

    if q.shape[2] != k.shape[2]:
        # GQA: expand kv heads per block at compute time — the ring rotates the small
        # hkv-sized blocks; XLA fuses this broadcast into the einsum.
        reps = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # [B,H,Sq,Skv]
    scores = scores.astype(jnp.float32)
    if causal:
        sq, skv = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(sq)[:, None]
        kv_pos = kv_offset + jnp.arange(skv)[None, :]
        scores = jnp.where((kv_pos <= q_pos)[None, None], scores, -jnp.inf)
    if q_seg is not None:
        scores = jnp.where(segment_mask(q_seg, kv_seg), scores, -jnp.inf)

    block_max = jnp.max(scores, axis=-1)  # [B,H,Sq]
    m_new = jnp.maximum(m, block_max)
    # Guard fully-masked blocks: exp(-inf - -inf) would be NaN.
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(jnp.where(jnp.isneginf(scores), -jnp.inf, scores - safe_m[..., None]))
    correction = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
    l_new = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def ring_attention(
    q,
    k,
    v,
    axis_name: str = "seq",
    causal: bool = False,
    scale: Optional[float] = None,
    segment_ids=None,
):
    """Shard_map-level ring attention over `axis_name`.

    All of q/k/v are the local sequence blocks [B, S_local, H, D] (same head counts —
    GQA expansion happens in the caller). `segment_ids` is the local [B, S_local]
    block of packed-sequence ids (attention allowed only within equal ids); the id
    block rotates around the ring with K/V. Returns [B, S_local, H, D] in q.dtype.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    axis_size = _axis_size(axis_name)
    axis_index = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    skv = k.shape[1]
    if scale is None:
        scale = 1.0 / np.sqrt(d)

    m = jnp.full((b, h, sq), -jnp.inf, dtype=jnp.float32)
    l = jnp.zeros((b, h, sq), dtype=jnp.float32)
    o = jnp.zeros((b, sq, h, d), dtype=jnp.float32)
    q_offset = axis_index * sq

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # axis_size is static inside shard_map, so a python loop fully unrolls the ring —
    # XLA then overlaps each ppermute (ICI DMA) with the next block's matmuls, since
    # the rotation is independent of the accumulator chain.
    k_cur, v_cur, seg_cur = k, v, segment_ids
    for step in range(axis_size):
        src = (axis_index - step) % axis_size  # whose block we hold at this step
        kv_offset = src * skv
        m, l, o = _ring_step_block(
            q, k_cur, v_cur, m, l, o, q_offset, kv_offset, scale, causal,
            q_seg=segment_ids, kv_seg=seg_cur,
        )
        if step < axis_size - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
            if seg_cur is not None:
                seg_cur = lax.ppermute(seg_cur, axis_name, perm)
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


# ------------------------------------------------------------- flash-through ring
# The per-device block compute runs the Pallas flash kernel (ops/flash_attention)
# instead of materialized einsum attention: forward combines per-block (out, lse)
# pairs with a log-sum-exp merge; backward re-runs the per-block flash backward
# against the GLOBAL lse (mathematically the global-softmax gradient) while the
# dk/dv accumulators rotate home with their blocks. This is what makes the
# long-context path flash end-to-end — no O(S_local x S_block) score tensor ever
# materializes (round-3 verdict weak #7).


def _ring_flash_fwd_impl(qt, kt, vt, axis_name, causal, scale, block_q, block_k, interpret):
    """qt/kt/vt: [BH, S_local, D]. Returns (out f32 [BH,S,D], lse f32 [BH,S])."""
    import jax.numpy as jnp
    from jax import lax

    from ..ops.flash_attention import LANE, NEG_INF, _fwd_call

    axis_size = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    BH, S, D = qt.shape
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def _block(kv, block_causal):
        o_b, lse_b = _fwd_call(qt, kv[0], kv[1], scale, block_causal, block_q, block_k, interpret)
        return o_b.astype(jnp.float32), lse_b[:, :, 0]

    def _skip(kv):
        return jnp.zeros((BH, S, D), jnp.float32), jnp.full((BH, S), NEG_INF, jnp.float32)

    o_acc = jnp.zeros((BH, S, D), jnp.float32)
    lse_acc = jnp.full((BH, S), NEG_INF, jnp.float32)
    k_cur, v_cur = kt, vt
    for step in range(axis_size):
        src = (idx - step) % axis_size
        if causal:
            # Block-level causal cases on the traced source index: the diagonal
            # block runs the causal kernel, blocks behind run full, blocks ahead
            # contribute nothing (their kernels never launch).
            o_b, lse_b = lax.cond(
                src == idx,
                lambda kv: _block(kv, True),
                lambda kv: lax.cond(src < idx, lambda kv2: _block(kv2, False), _skip, kv),
                (k_cur, v_cur),
            )
        else:
            o_b, lse_b = _block((k_cur, v_cur), False)
        m = jnp.maximum(lse_acc, lse_b)
        new_lse = m + jnp.log(jnp.exp(lse_acc - m) + jnp.exp(lse_b - m))
        o_acc = (
            o_acc * jnp.exp(lse_acc - new_lse)[..., None]
            + o_b * jnp.exp(lse_b - new_lse)[..., None]
        )
        lse_acc = new_lse
        if step < axis_size - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
    return o_acc, lse_acc


def _ring_flash_primal(qt, kt, vt, axis_name, causal, scale, block_q, block_k, interpret):
    out, _ = _ring_flash_fwd_impl(qt, kt, vt, axis_name, causal, scale, block_q, block_k, interpret)
    return out


def _ring_flash_vjp_fwd(qt, kt, vt, axis_name, causal, scale, block_q, block_k, interpret):
    out, lse = _ring_flash_fwd_impl(qt, kt, vt, axis_name, causal, scale, block_q, block_k, interpret)
    return out, (qt, kt, vt, out, lse)


def _ring_flash_vjp_bwd(axis_name, causal, scale, block_q, block_k, interpret, res, do):
    """Ring backward: each step runs the flash backward kernels for the held block
    against the global lse (p = exp(s - lse_global) IS the global softmax), adding
    dq locally and dk/dv into accumulators that rotate with the block; after a full
    cycle (+1 hop) every block's dk/dv lands back on its home device."""
    import jax.numpy as jnp
    from jax import lax

    from ..ops.flash_attention import LANE, _bwd_call

    qt, kt, vt, out, lse = res
    axis_size = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    BH, S, D = qt.shape
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    lse_lane = jnp.broadcast_to(lse[..., None], (BH, S, LANE))
    out_c = out.astype(qt.dtype)
    do_c = do.astype(qt.dtype)

    def _block(kv, block_causal):
        dq_b, dk_b, dv_b = _bwd_call(
            qt, kv[0], kv[1], out_c, lse_lane, do_c, scale, block_causal, block_q, block_k, interpret
        )
        return dq_b.astype(jnp.float32), dk_b.astype(jnp.float32), dv_b.astype(jnp.float32)

    def _skip(kv):
        return (
            jnp.zeros((BH, S, D), jnp.float32),
            jnp.zeros(kv[0].shape, jnp.float32),
            jnp.zeros(kv[1].shape, jnp.float32),
        )

    dq_acc = jnp.zeros((BH, S, D), jnp.float32)
    dk_cur = jnp.zeros(kt.shape, jnp.float32)
    dv_cur = jnp.zeros(vt.shape, jnp.float32)
    k_cur, v_cur = kt, vt
    for step in range(axis_size):
        src = (idx - step) % axis_size
        if causal:
            dq_b, dk_b, dv_b = lax.cond(
                src == idx,
                lambda kv: _block(kv, True),
                lambda kv: lax.cond(src < idx, lambda kv2: _block(kv2, False), _skip, kv),
                (k_cur, v_cur),
            )
        else:
            dq_b, dk_b, dv_b = _block((k_cur, v_cur), False)
        dq_acc = dq_acc + dq_b
        dk_cur = dk_cur + dk_b
        dv_cur = dv_cur + dv_b
        # The accumulators rotate AFTER every step (including the last): N hops
        # return each block's dk/dv to its home device. K/V themselves are dead
        # after the last kernel call — skip their final hop.
        if step < axis_size - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
        dk_cur = lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = lax.ppermute(dv_cur, axis_name, perm)
    return dq_acc.astype(qt.dtype), dk_cur.astype(kt.dtype), dv_cur.astype(vt.dtype)


_RING_FLASH = None


def _get_ring_flash():
    """Build the custom-VJP wrapper on first use (keeps module import jax-free,
    matching the file's lazy-import convention)."""
    global _RING_FLASH
    if _RING_FLASH is None:
        import jax

        fn = jax.custom_vjp(_ring_flash_primal, nondiff_argnums=(3, 4, 5, 6, 7, 8))
        fn.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)
        _RING_FLASH = fn
    return _RING_FLASH


def ring_flash_attention(
    q,
    k,
    v,
    axis_name: str = "seq",
    causal: bool = False,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
):
    """Flash-through ring attention on local [B, S_local, H, D] blocks.

    GQA expands KV heads up front (the ring then rotates expanded blocks —
    trading ICI bytes for a mask-free kernel). Requires 128-aligned (or
    whole-block) local sequence lengths; callers fall back to the einsum ring
    otherwise (`sequence_parallel_attention` handles the dispatch).
    """
    import jax
    import jax.numpy as jnp

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, hq, d = q.shape
    skv = k.shape[1]
    hkv = k.shape[2]
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    if hq != hkv:
        reps = hq // hkv
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
    block_q = min(128, s)
    block_k = min(128, skv)
    if s % block_q or skv % block_k:
        raise ValueError(f"local sequence lengths ({s}, {skv}) must divide blocks ({block_q}, {block_k})")
    qt = q.transpose(0, 2, 1, 3).reshape(b * hq, s, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hq, skv, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hq, skv, d)
    out = _get_ring_flash()(qt, kt, vt, axis_name, bool(causal), float(scale), block_q, block_k, interpret)
    return out.reshape(b, hq, s, d).transpose(0, 2, 1, 3).astype(q.dtype)


def allgather_attention(
    q, k, v, axis_name: str = "seq", causal: bool = False, scale=None, segment_ids=None
):
    """All-gather-KV sequence parallelism: cheaper at short context, more HBM
    (the SequenceParallelPlugin mode="allgather" path). `segment_ids` restricts
    attention to equal packed-sequence ids."""
    import jax.numpy as jnp
    from jax import lax

    axis_index = lax.axis_index(axis_name)
    sq = q.shape[1]
    k_full = lax.all_gather(k, axis_name, axis=1, tiled=True)
    v_full = lax.all_gather(v, axis_name, axis=1, tiled=True)
    from ..ops.attention import dot_product_attention

    skv = k_full.shape[1]
    mask = None
    if causal:
        q_pos = axis_index * sq + jnp.arange(sq)
        kv_pos = jnp.arange(skv)
        mask = (kv_pos[None, :] <= q_pos[:, None])[None, None]  # [1,1,Sq,Skv]
        mask = jnp.broadcast_to(mask, (q.shape[0], 1, sq, skv))
    if segment_ids is not None:
        seg_full = lax.all_gather(segment_ids, axis_name, axis=1, tiled=True)  # [B, Skv]
        same = segment_mask(segment_ids, seg_full)
        mask = same if mask is None else jnp.logical_and(mask, same)
    if mask is None:
        return dot_product_attention(q, k_full, v_full, scale=scale, implementation="xla")
    return dot_product_attention(q, k_full, v_full, mask=mask, scale=scale, implementation="xla")


def sequence_parallel_attention(
    q,
    k,
    v,
    mesh=None,
    causal: bool = False,
    scale: Optional[float] = None,
    mode: str = "ring",
    batch_axes=("data", "fsdp"),
    seq_axis: str = "seq",
    head_axis: Optional[str] = "model",
    segment_ids=None,
    use_flash: Optional[bool] = None,
):
    """Jit-level wrapper: shard_map the ring over the active mesh.

    Expects q/k/v global [B, S, H, D] with S divisible by the seq-axis size (and H by
    the model-axis size when TP is active — heads shard over "model", giving 2D
    (sequence × head) attention parallelism). `segment_ids` [B, S] enables packed-
    sequence masking (the id blocks rotate with K/V). Composable inside jit.

    Ring mode runs flash-through (`ring_flash_attention`) whenever possible —
    unsegmented attention with whole-block local lengths; `use_flash=False`
    forces the einsum block path, `True` asserts flash eligibility.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from .sharding import compat_shard_map as shard_map

    if mesh is None:
        from ..state import AcceleratorState

        mesh = AcceleratorState().mesh

    hq, hkv = q.shape[2], k.shape[2]
    head_size = mesh.shape.get(head_axis, 1) if head_axis is not None else 1
    use_heads = head_size > 1 and hq % head_size == 0 and hkv % head_size == 0
    hspec = head_axis if use_heads else None
    q_spec = P(batch_axes, seq_axis, hspec, None)
    kv_spec = P(batch_axes, seq_axis, hspec, None)
    seq_size = max(mesh.shape.get(seq_axis, 1), 1)
    s_local = q.shape[1] // seq_size
    skv_local = k.shape[1] // seq_size

    if mode == "ring":
        # The causal block classification (behind=full / diagonal=causal /
        # ahead=skip) assumes equal q/kv block lengths; unequal lengths must take
        # the einsum ring, whose global offsets handle them.
        lengths_ok = s_local > 0 and (not causal or s_local == skv_local)
        # Auto-flash only on TPU at 128-aligned local lengths (the MXU tile);
        # elsewhere interpret-mode Pallas would be orders of magnitude slower
        # than the einsum ring. Smaller blocks work (the kernel shrinks them)
        # but are explicit-opt-in — tests pass use_flash=True at tiny sizes.
        auto_ok = (
            segment_ids is None
            and lengths_ok
            and s_local % 128 == 0
            and skv_local % 128 == 0
            and jax.default_backend() == "tpu"
        )
        explicit_ok = segment_ids is None and lengths_ok and skv_local > 0
        if use_flash is None:
            use_flash = auto_ok
        elif use_flash and not explicit_ok:
            raise ValueError(
                "use_flash=True requires unsegmented attention with nonzero local "
                f"sequence lengths (and equal q/kv lengths when causal); got "
                f"s_local={s_local}, skv_local={skv_local}, segment_ids="
                f"{'set' if segment_ids is not None else 'None'}"
            )
    else:
        if use_flash:
            raise ValueError(f"use_flash=True requires mode='ring', got mode={mode!r}")
        use_flash = False

    if mode == "ring" and use_flash:
        # Varying-mesh-axes checking off: pallas_call inside shard_map can't
        # annotate its outputs; correctness is covered by the parity tests
        # (compat_shard_map handles the check_vma/check_rep rename).
        inner_flash = functools.partial(
            ring_flash_attention, axis_name=seq_axis, causal=causal, scale=scale
        )
        fn = shard_map(
            inner_flash, mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec),
            out_specs=q_spec, check_vma=False,
        )
        return fn(q, k, v)

    inner = ring_attention if mode == "ring" else allgather_attention
    if segment_ids is None:
        fn = shard_map(
            functools.partial(inner, axis_name=seq_axis, causal=causal, scale=scale),
            mesh=mesh,
            in_specs=(q_spec, kv_spec, kv_spec),
            out_specs=q_spec,
        )
        return fn(q, k, v)
    seg_spec = P(batch_axes, seq_axis)
    fn = shard_map(
        lambda q_, k_, v_, seg_: inner(
            q_, k_, v_, axis_name=seq_axis, causal=causal, scale=scale, segment_ids=seg_
        ),
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec, seg_spec),
        out_specs=q_spec,
    )
    return fn(q, k, v, segment_ids)

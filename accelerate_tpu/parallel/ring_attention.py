"""Ring attention: first-class sequence/context parallelism.

The reference cannot scale sequence length natively — its only SP surface is a Megatron
passthrough flag (SURVEY §5; reference dataclasses.py:1262-1265). Here SP is a mesh axis:
activations are sharded [batch, seq/axis, ...] over "seq", and attention runs as a ring
(see PAPERS.md: blockwise/ring attention literature):

  each device keeps its Q block resident and its K/V block rotating — at every step the
  local K/V block hops to the next device over ICI via `lax.ppermute` while the device
  computes blockwise attention against the block it just received, folding results with
  a streaming (flash-style) log-sum-exp accumulator. Communication is fully overlapped
  with the matmuls; HBM never holds more than one remote block.

`ring_attention` is the shard_map-level kernel; `sequence_parallel_attention` wraps it
in a `shard_map` over the active mesh so jit-level callers (the models' attention seam,
ops/attention.py) can dispatch to it transparently when mesh.shape["seq"] > 1.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np


def _ring_step_block(q, k, v, m, l, o, q_offset, kv_offset, scale, causal):
    """Fold one K/V block into the streaming-softmax accumulator.

    q: [B, Sq, H, D]; k/v: [B, Skv, H, D]; m/l: [B, H, Sq]; o: [B, Sq, H, D].
    Offsets are the blocks' global sequence starts (for causal masking).
    """
    import jax.numpy as jnp

    if q.shape[2] != k.shape[2]:
        # GQA: expand kv heads per block at compute time — the ring rotates the small
        # hkv-sized blocks; XLA fuses this broadcast into the einsum.
        reps = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # [B,H,Sq,Skv]
    scores = scores.astype(jnp.float32)
    if causal:
        sq, skv = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(sq)[:, None]
        kv_pos = kv_offset + jnp.arange(skv)[None, :]
        scores = jnp.where((kv_pos <= q_pos)[None, None], scores, -jnp.inf)

    block_max = jnp.max(scores, axis=-1)  # [B,H,Sq]
    m_new = jnp.maximum(m, block_max)
    # Guard fully-masked blocks: exp(-inf - -inf) would be NaN.
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(jnp.where(jnp.isneginf(scores), -jnp.inf, scores - safe_m[..., None]))
    correction = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
    l_new = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def ring_attention(
    q,
    k,
    v,
    axis_name: str = "seq",
    causal: bool = False,
    scale: Optional[float] = None,
):
    """Shard_map-level ring attention over `axis_name`.

    All of q/k/v are the local sequence blocks [B, S_local, H, D] (same head counts —
    GQA expansion happens in the caller). Returns [B, S_local, H, D] in q.dtype.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    axis_size = lax.axis_size(axis_name)
    axis_index = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    skv = k.shape[1]
    if scale is None:
        scale = 1.0 / np.sqrt(d)

    m = jnp.full((b, h, sq), -jnp.inf, dtype=jnp.float32)
    l = jnp.zeros((b, h, sq), dtype=jnp.float32)
    o = jnp.zeros((b, sq, h, d), dtype=jnp.float32)
    q_offset = axis_index * sq

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # axis_size is static inside shard_map, so a python loop fully unrolls the ring —
    # XLA then overlaps each ppermute (ICI DMA) with the next block's matmuls, since
    # the rotation is independent of the accumulator chain.
    k_cur, v_cur = k, v
    for step in range(axis_size):
        src = (axis_index - step) % axis_size  # whose block we hold at this step
        kv_offset = src * skv
        m, l, o = _ring_step_block(q, k_cur, v_cur, m, l, o, q_offset, kv_offset, scale, causal)
        if step < axis_size - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def allgather_attention(q, k, v, axis_name: str = "seq", causal: bool = False, scale=None):
    """All-gather-KV sequence parallelism: cheaper at short context, more HBM
    (the SequenceParallelPlugin mode="allgather" path)."""
    import jax.numpy as jnp
    from jax import lax

    axis_index = lax.axis_index(axis_name)
    sq = q.shape[1]
    k_full = lax.all_gather(k, axis_name, axis=1, tiled=True)
    v_full = lax.all_gather(v, axis_name, axis=1, tiled=True)
    from ..ops.attention import dot_product_attention

    if not causal:
        return dot_product_attention(q, k_full, v_full, scale=scale, implementation="xla")
    # Causal with a shifted query block: build the mask from global positions.
    skv = k_full.shape[1]
    q_pos = axis_index * sq + jnp.arange(sq)
    kv_pos = jnp.arange(skv)
    mask = (kv_pos[None, :] <= q_pos[:, None])[None, None]  # [1,1,Sq,Skv]
    mask = jnp.broadcast_to(mask, (q.shape[0], 1, sq, skv))
    return dot_product_attention(q, k_full, v_full, mask=mask, scale=scale, implementation="xla")


def sequence_parallel_attention(
    q,
    k,
    v,
    mesh=None,
    causal: bool = False,
    scale: Optional[float] = None,
    mode: str = "ring",
    batch_axes=("data", "fsdp"),
    seq_axis: str = "seq",
    head_axis: Optional[str] = "model",
):
    """Jit-level wrapper: shard_map the ring over the active mesh.

    Expects q/k/v global [B, S, H, D] with S divisible by the seq-axis size (and H by
    the model-axis size when TP is active — heads shard over "model", giving 2D
    (sequence × head) attention parallelism). Composable inside jit.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    if mesh is None:
        from ..state import AcceleratorState

        mesh = AcceleratorState().mesh

    hq, hkv = q.shape[2], k.shape[2]
    head_size = mesh.shape.get(head_axis, 1) if head_axis is not None else 1
    use_heads = head_size > 1 and hq % head_size == 0 and hkv % head_size == 0
    hspec = head_axis if use_heads else None
    q_spec = P(batch_axes, seq_axis, hspec, None)
    kv_spec = P(batch_axes, seq_axis, hspec, None)
    inner = ring_attention if mode == "ring" else allgather_attention

    fn = shard_map(
        functools.partial(inner, axis_name=seq_axis, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec),
        out_specs=q_spec,
    )
    return fn(q, k, v)

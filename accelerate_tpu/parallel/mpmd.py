"""MPMD pipeline runtime: the executor for NON-uniform stage plans.

The SPMD stage runner (parallel/pipeline.py) stacks layer params on a leading
[L] axis sharded over "stage" and scans them — which hard-requires every stage
to hold the SAME layer count, so the byte-balanced (usually unequal) stage
assignments `plan_pipeline_stages` emits had no executor. This module is that
executor, in the style of MPMD pipeline systems (arXiv:2412.14374): the global
("data", ..., "pipeline") mesh is sliced into one submesh per stage
(`mesh.slice_mesh`), each stage gets its OWN jit-compiled programs against its
own submesh — so stage 0 can hold 3 layers + the prelude while stage 1 holds
2 layers + the tail — and the host dispatches a 1F1B microbatch schedule
across the per-stage executables.

Contract highlights:

- **Stage params** follow `planner.build_stage_tree` paths verbatim
  (``layer_<i>`` / ``prelude`` / ``tail``), placed by the per-stage rules
  tables of an `MPMDTrainPlan` — the planner and the runtime shard the same
  leaf the same way because they address it by the same path.
- **Handoffs never touch the host**: activations (and the backward's
  cotangents) move between stage submeshes as explicit `jax.device_put`
  device-to-device transfers, legal under an armed TraceGuard (which guards
  h2d/d2h, not d2d). Microbatch slicing happens INSIDE a jitted split program
  with static bounds — an eager ``batch[lo:hi]`` would materialize its index
  scalars host-side and trip the h2d guard.
- **Backward is rematerialized** (GPipe-style): each stage saves only its
  per-microbatch INPUT carry; the backward program recomputes the stage
  forward under `jax.vjp`. Peak activation memory is O(in-flight microbatches)
  per stage, not O(microbatches x layers).
- **Grad math**: each backward carries the grads of the UNNORMALIZED
  ``(loss_sum, weight)`` pair (GSPMD inserts the data-axis psum per program),
  the per-microbatch grads accumulate into a donated buffer, and the final
  per-stage optimizer step scales by the global ``1/weight`` — bitwise the
  token-weighted mean loss the single-mesh 2D baseline optimizes.
- **Per-stage optimizer**: `init_optimizer_state` derives each stage's
  optimizer-state shardings from that stage's ZeRO opt-rules table
  (`MPMDTrainPlan.stage_opt_rules`), so weight-update sharding keeps working
  per submesh.

Tied embeddings are rejected: a tied lm head would put one buffer on both the
first and last stage submeshes with cross-mesh gradient coupling — use the
SPMD runner (`prepare_pipeline`) for tied-weight models.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from .pipeline import (
    _default_batch_to_args,
    default_causal_lm_logits_loss,
    find_tied_leaves,
)
from .planner import MPMDTrainPlan, build_stage_tree

__all__ = ["MPMDPipelinedModel", "prepare_mpmd_pipeline"]


def _donate(*argnums):
    """Donation argnums, backend-guarded: donating sharded operands into a
    fused update crashes XLA:CPU's host runtime over forced multi-device CPU
    meshes (SIGSEGV/SIGABRT inside the aliased executable — the same class
    optimizer.py's fused update guards against). Donation is a memory
    optimization, not a semantics change, so drop it on CPU; TPU/GPU keep
    the aliasing."""
    import jax

    return () if jax.default_backend() == "cpu" else argnums


def _partition_carry(carry):
    """Split a carry pytree into (diff, static, spec): floating leaves are
    differentiable and ship cotangents backward; integer leaves (positions,
    token masks) are along-for-the-ride. ``spec`` rebuilds the tree."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(carry)
    # issubdtype reads dtype METADATA — a plain Python bool even on tracers.
    is_diff = tuple(jnp.issubdtype(leaf.dtype, jnp.floating) for leaf in leaves)
    diff = tuple(leaf for leaf, d in zip(leaves, is_diff) if d)
    static = tuple(leaf for leaf, d in zip(leaves, is_diff) if not d)
    return diff, static, (treedef, is_diff)


def _combine_carry(diff, static, spec):
    import jax

    treedef, is_diff = spec
    diff_it, static_it = iter(diff), iter(static)
    leaves = [next(diff_it) if d else next(static_it) for d in is_diff]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _diff_leaves(carry):
    """The floating leaves of a carry, in flatten order — what backward
    programs emit/consume as the inter-stage cotangent tuple."""
    return _partition_carry(carry)[0]


class MPMDPipelinedModel:
    """A model executing an `MPMDTrainPlan`: per-stage jitted programs on
    per-stage submeshes, 1F1B host-dispatched schedule, d2d stage handoffs.

    Build via `Accelerator.prepare(sharding_rules="auto")` on a mesh with a
    "pipeline" axis, or directly with `prepare_mpmd_pipeline`.
    """

    is_pipelined = True
    is_mpmd = True
    offload_params = False

    def __init__(
        self,
        model,
        layered,
        mesh,
        plan: MPMDTrainPlan,
        logits_loss: Optional[Callable] = None,
        batch_to_args: Optional[Callable] = None,
        compute_dtype=None,
        autocast: bool = True,
    ):
        from .mesh import slice_mesh

        self.model = model
        self.layered = layered
        self.mesh = mesh
        self.plan = plan
        self.logits_loss = logits_loss or default_causal_lm_logits_loss
        self.batch_to_args = batch_to_args or _default_batch_to_args
        self.num_microbatches = plan.num_microbatches
        # Mixed precision, same contract as the SPMD runner: params and the
        # floating carry cast to compute_dtype at stage-program entry; master
        # params (and therefore the grads jax.vjp emits through the cast)
        # stay full precision.
        self.compute_dtype = compute_dtype
        self.autocast_enabled = autocast and compute_dtype is not None
        self.sharding_rules = None  # per-stage tables live on the plan
        self.opt_sharding_rules = None

        prelude, layers, tail = layered.split(model.params)
        if len(layers) != plan.stage_plan.num_layers:
            raise ValueError(
                f"plan covers {plan.stage_plan.num_layers} layers but the model "
                f"splits into {len(layers)}"
            )
        tied = find_tied_leaves(prelude, tail)
        if tied:
            raise NotImplementedError(
                f"tied prelude/tail weights {[p for p, _ in tied]} span the first "
                "and last stage submeshes — the MPMD runtime keeps stages on "
                "disjoint meshes. Use the SPMD stage runner (prepare_pipeline) "
                "for tied-weight models."
            )

        self.submeshes = slice_mesh(mesh, "pipeline")
        if len(self.submeshes) != plan.num_stages:
            raise ValueError(
                f"mesh pipeline axis has {len(self.submeshes)} slices but the "
                f"plan has {plan.num_stages} stages"
            )
        self.stage_params: List[Any] = []
        self._param_shardings: List[Any] = []
        for k in range(plan.num_stages):
            self._place_stage(k, build_stage_tree(prelude, layers, tail, plan.stage_plan, k))

        self._jitted = {}  # name -> jitted program (the compiled-once audit)
        self._bwd_specs = {}  # stage -> carry partition spec its bwd compiled for
        self._opt_states: Optional[List[Any]] = None
        self._opt_shardings: Optional[List[Any]] = None
        self._tx = None
        self._build_fixed_programs()

    # ------------------------------------------------------------- placement
    @property
    def num_stages(self) -> int:
        return self.plan.num_stages

    def _place_stage(self, k: int, tree) -> None:
        import jax

        from .sharding import derive_tp_param_shardings

        shardings = derive_tp_param_shardings(tree, self.submeshes[k], self.plan.stage_rules(k))
        self.stage_params.append(jax.device_put(tree, shardings))
        self._param_shardings.append(shardings)

    def _carry_shardings(self, tree, mesh):
        """Target shardings for a stage handoff: batch dim over "data", rest
        replicated — the residual stream's layout on every stage submesh, so
        the d2d transfer is a pure resharding with no host round-trip."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        data = mesh.shape.get("data", 1)

        def one(leaf):
            if leaf.ndim >= 1 and data > 1 and leaf.shape[0] % data == 0:
                return NamedSharding(mesh, PartitionSpec("data", *([None] * (leaf.ndim - 1))))
            return NamedSharding(mesh, PartitionSpec())

        return jax.tree_util.tree_map(one, tree)

    def _ship(self, tree, mesh):
        """Move a pytree onto ``mesh``: explicit device-to-device transfer
        (ICI/DCN), never through host — TraceGuard stays armed across it."""
        import jax

        return jax.device_put(tree, self._carry_shardings(tree, mesh))

    # -------------------------------------------------------------- programs
    def _stage_forward_fn(self, k: int):
        """Pure stage-k forward over its `build_stage_tree` params: prelude on
        stage 0, that stage's layers, tail (-> logits) on the last stage.
        Under autocast, params and the floating carry cast to compute_dtype
        at entry (the cast lives INSIDE the vjp in the backward programs, so
        grads come back in the master param dtype)."""
        from ..modeling import _cast_floating

        layered = self.layered
        idxs = tuple(self.plan.stage_plan.stage_layers(k))
        has_prelude = k == 0
        has_tail = k == self.num_stages - 1
        compute_dtype = self.compute_dtype if self.autocast_enabled else None

        def fwd(stage_params, x):
            if compute_dtype is not None:
                stage_params = _cast_floating(stage_params, compute_dtype)
                x = _cast_floating(x, compute_dtype)
            carry = layered.apply_prelude(stage_params["prelude"], *x) if has_prelude else x
            for i in idxs:
                carry = layered.apply_layer(stage_params[f"layer_{i}"], carry)
            if has_tail:
                return layered.apply_tail(stage_params["tail"], carry)
            return carry

        return fwd

    def _build_fixed_programs(self) -> None:
        """Programs whose shapes don't depend on the carry structure: forward
        per stage, microbatch split per boundary mesh, the loss finalizer.
        Backward programs compile lazily on the first step (they close over
        the carry's diff/static partition, known once a real batch flows)."""
        import jax

        for k in range(self.num_stages - 1):
            self._jitted[f"fwd{k}"] = self._make_fwd(k)

        # Two DISTINCT closures on purpose: `jax.jit` memoizes per function
        # object, so one shared `split` would pool both call structures (args
        # tuple vs batch dict) into one cache and read as a phantom recompile.
        self._jitted["split_first"] = self._make_split()
        self._jitted["split_last"] = self._make_split()

        def finalize(losses, weights):
            import jax.numpy as jnp

            total, weight = losses[0], weights[0]
            for x in losses[1:]:
                total = total + x
            for w in weights[1:]:
                weight = weight + w
            inv_w = 1.0 / jnp.maximum(weight, 1.0)
            return total * inv_w, inv_w

        self._jitted["finalize"] = jax.jit(finalize)

    def _make_fwd(self, k: int):
        """One stage's jitted forward — a method so every jit call site sits
        outside the per-stage construction loop (each stage is a DISTINCT
        function object with its own single-entry executable cache)."""
        import jax

        return jax.jit(self._stage_forward_fn(k))

    def _make_split(self):
        """Jitted microbatch split with STATIC slice bounds. Eager slicing of a
        device array (``batch[lo:hi]``) creates its index scalars host-side —
        an h2d transfer the armed TraceGuard rightly rejects; inside jit the
        bounds fold into the program."""
        import jax

        M = self.num_microbatches

        def split(tree):
            rows = jax.tree_util.tree_leaves(tree)[0].shape[0]
            # Shapes are static under trace, so this raises at (re)trace time —
            # BEFORE any wrong program runs. A silent `rows // M` here would
            # drop the remainder rows from every step (rows % M != 0) or feed
            # zero-row microbatches (rows < M: loss_sum=0, weight=0 — a no-op
            # step), i.e. wrong gradients with no error.
            if rows < M or rows % M != 0:
                raise ValueError(
                    f"global batch of {rows} rows is not divisible into the "
                    f"plan's num_microbatches={M} (plan was sized for a global "
                    f"batch of {M * self.plan.workload.batch}). Feed a batch whose "
                    f"leading dim is a multiple of {M}, or rebuild the plan "
                    "for the real batch size — Accelerator.prepare derives it "
                    "from a dataloader prepared in the same call, and "
                    "prepare_mpmd_pipeline takes batch=/num_microbatches= "
                    "directly."
                )
            step = rows // M
            out = []
            for m in range(M):
                lo = m * step
                out.append(
                    jax.tree_util.tree_map(lambda x, lo=lo, step=step: x[lo : lo + step], tree)
                )
            return tuple(out)

        return jax.jit(split)

    def _make_zero(self, k: int):
        import jax
        import jax.numpy as jnp

        return jax.jit(
            lambda p: jax.tree_util.tree_map(jnp.zeros_like, p),
            out_shardings=self._param_shardings[k],
        )

    def _ensure_zero(self, k: int):
        name = f"zero{k}"
        if name not in self._jitted:
            self._jitted[name] = self._make_zero(k)
        return self._jitted[name]

    def _make_bwd_mid(self, k: int, spec):
        """Backward for an interior stage: recompute the stage forward from the
        saved input carry under `jax.vjp`, accumulate param grads into the
        donated buffer, and emit the input-carry cotangents for stage k-1."""
        import jax

        stage_fwd = self._stage_forward_fn(k)
        acc_shardings = self._param_shardings[k]

        def bwd(params, static, diff, g_out, acc):
            def f(p, d):
                carry_out = stage_fwd(p, _combine_carry(d, static, spec))
                return _diff_leaves(carry_out)

            _, vjp_fn = jax.vjp(f, params, diff)
            grads, g_in = vjp_fn(tuple(g_out))
            new_acc = jax.tree_util.tree_map(jax.numpy.add, acc, grads)
            # Pin the accumulator to the param layout: the donated buffer
            # round-trips through this program once per microbatch, and an
            # XLA-chosen output sharding would silently recompile call #2.
            return jax.lax.with_sharding_constraint(new_acc, acc_shardings), g_in

        return jax.jit(bwd, donate_argnums=_donate(4))

    def _make_last(self, spec):
        """The last stage's fused forward+loss+backward: layers -> tail ->
        ``(loss_sum, weight)``, then the pullback seeded with ``(1, 0)`` — the
        weight is a count, not a differentiable output."""
        import jax
        import jax.numpy as jnp

        stage_fwd = self._stage_forward_fn(self.num_stages - 1)
        logits_loss = self.logits_loss
        acc_shardings = self._param_shardings[self.num_stages - 1]

        def last(params, static, diff, mb_batch, acc):
            def f(p, d):
                logits = stage_fwd(p, _combine_carry(d, static, spec))
                return logits_loss(logits, mb_batch)

            (loss_sum, weight), vjp_fn = jax.vjp(f, params, diff)
            grads, g_in = vjp_fn((jnp.ones_like(loss_sum), jnp.zeros_like(weight)))
            new_acc = jax.tree_util.tree_map(jnp.add, acc, grads)
            new_acc = jax.lax.with_sharding_constraint(new_acc, acc_shardings)
            return loss_sum, weight, new_acc, g_in

        return jax.jit(last, donate_argnums=_donate(4))

    def _make_bwd_first(self):
        """Stage 0's backward: recompute prelude+layers from the saved batch
        args; only param grads come back (token ids carry no cotangent)."""
        import jax

        stage_fwd = self._stage_forward_fn(0)
        acc_shardings = self._param_shardings[0]

        def bwd(params, args, g_out, acc):
            def f(p):
                return _diff_leaves(stage_fwd(p, args))

            _, vjp_fn = jax.vjp(f, params)
            (grads,) = vjp_fn(tuple(g_out))
            new_acc = jax.tree_util.tree_map(jax.numpy.add, acc, grads)
            return jax.lax.with_sharding_constraint(new_acc, acc_shardings)

        return jax.jit(bwd, donate_argnums=_donate(3))

    def _ensure_bwd(self, k: int, spec):
        """Backward program for stage k, compiled against ``spec`` (the carry's
        diff/static partition). A changed spec (e.g. a batch that grew an
        attention mask) rebuilds — TraceGuard will count the recompile, which
        is exactly the signal a shape-unstable input pipeline should trip."""
        name = f"bwd{k}"
        if self._bwd_specs.get(k) != spec:
            if k == self.num_stages - 1:
                self._jitted[name] = self._make_last(spec)
            else:
                self._jitted[name] = self._make_bwd_mid(k, spec)
            self._bwd_specs[k] = spec
        return self._jitted[name]

    def _ensure_bwd_first(self):
        if "bwd0" not in self._jitted:
            self._jitted["bwd0"] = self._make_bwd_first()
        return self._jitted["bwd0"]

    # -------------------------------------------------------------- optimizer
    def init_optimizer_state(self, tx) -> None:
        """Per-stage optimizer state, each placed by its stage's ZeRO
        opt-rules table on its own submesh (`derive_opt_state_shardings` —
        moments may shard over "data" where params replicate)."""
        import jax

        from .sharding import derive_opt_state_shardings

        self._tx = tx
        self._opt_states = []
        self._opt_shardings = []
        for k in range(self.num_stages):
            state_shapes = jax.eval_shape(tx.init, self.stage_params[k])
            shardings = derive_opt_state_shardings(
                state_shapes,
                self.submeshes[k],
                None,
                list(self.plan.stage_rules(k)),
                opt_rules=list(self.plan.stage_opt_rules(k) or []) or None,
            )
            self._opt_states.append(self._init_one_opt_state(k, tx, shardings))
            self._opt_shardings.append(shardings)

    def _init_one_opt_state(self, k: int, tx, shardings):
        import jax

        return jax.jit(tx.init, out_shardings=shardings)(self.stage_params[k])

    def _make_update(self, k: int):
        import jax
        import optax

        tx = self._tx

        def upd(params, opt_state, acc, inv_w):
            grads = jax.tree_util.tree_map(lambda g: (g * inv_w).astype(g.dtype), acc)
            updates, new_opt = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_opt

        return jax.jit(
            upd,
            donate_argnums=_donate(0, 1, 2),
            out_shardings=(self._param_shardings[k], self._opt_shardings[k]),
        )

    def _ensure_update(self, k: int):
        name = f"update{k}"
        if name not in self._jitted:
            self._jitted[name] = self._make_update(k)
        return self._jitted[name]

    # ------------------------------------------------------------------ step
    def _forward_chain(self, m: int, args0, saved) -> None:
        saved[0][m] = args0
        carry = self._jitted["fwd0"](self.stage_params[0], args0)
        for k in range(1, self.num_stages - 1):
            carry = self._ship(carry, self.submeshes[k])
            saved[k][m] = carry
            carry = self._jitted[f"fwd{k}"](self.stage_params[k], carry)
        last = self.num_stages - 1
        saved[last][m] = self._ship(carry, self.submeshes[last])

    def _backward_chain(self, m: int, mb_batch, saved, acc, losses, weights) -> None:
        last = self.num_stages - 1
        diff, static, spec = _partition_carry(saved[last].pop(m))
        loss_sum, weight, acc[last], g = self._ensure_bwd(last, spec)(
            self.stage_params[last], static, diff, mb_batch, acc[last]
        )
        losses.append(loss_sum)
        weights.append(weight)
        for k in range(self.num_stages - 2, 0, -1):
            g = self._ship(g, self.submeshes[k])
            diff, static, spec = _partition_carry(saved[k].pop(m))
            acc[k], g = self._ensure_bwd(k, spec)(
                self.stage_params[k], static, diff, g, acc[k]
            )
        g = self._ship(g, self.submeshes[0])
        args0 = saved[0].pop(m)
        acc[0] = self._ensure_bwd_first()(self.stage_params[0], args0, g, acc[0])

    def train_step(self, batch):
        """One full 1F1B optimizer step over the global batch. Returns the
        token-weighted mean loss (a device scalar on the last stage's mesh).

        Dispatch order is the classic schedule — forward chain for microbatch
        m, then (once the pipeline is full, m >= P-1) the backward chain for
        microbatch m-(P-1), then drain — and because jax dispatch is async the
        per-stage executables genuinely overlap across submeshes; the host
        never blocks between dispatches."""
        from ..utils.environment import fence_if_cpu

        if self._opt_states is None:
            raise RuntimeError(
                "optimizer state not initialized — prepare an optimizer "
                "(Accelerator.prepare) or call init_optimizer_state(tx) first"
            )
        P, M = self.num_stages, self.num_microbatches
        args = self.batch_to_args(batch)
        first_mbs = self._jitted["split_first"](self._ship(args, self.submeshes[0]))
        last_mbs = self._jitted["split_last"](self._ship(batch, self.submeshes[P - 1]))

        acc = [self._ensure_zero(k)(self.stage_params[k]) for k in range(P)]
        saved: List[dict] = [dict() for _ in range(P)]
        losses: List[Any] = []
        weights: List[Any] = []
        done = 0
        for m in range(M):
            self._forward_chain(m, first_mbs[m], saved)
            if m >= P - 1:
                self._backward_chain(done, last_mbs[done], saved, acc, losses, weights)
                done += 1
        while done < M:
            self._backward_chain(done, last_mbs[done], saved, acc, losses, weights)
            done += 1

        loss_mean, inv_w = self._jitted["finalize"](tuple(losses), tuple(weights))
        for k in range(P):
            w_k = self._ship(inv_w, self.submeshes[k])
            self.stage_params[k], self._opt_states[k] = self._ensure_update(k)(
                self.stage_params[k], self._opt_states[k], acc[k], w_k
            )
        fence_if_cpu(self.stage_params)
        return loss_mean

    def make_train_step(self, tx) -> Callable:
        """The step callable `Accelerator.train_step` wraps (TraceGuard,
        instrumentation). Initializes per-stage optimizer state on ``tx`` if
        not already done."""
        if self._opt_states is None:
            self.init_optimizer_state(tx)

        def step(batch):
            return self.train_step(batch)

        return step

    # ---------------------------------------------------------- introspection
    def compiled_program_counts(self) -> dict:
        """name -> jit cache size per program — the compiled-once-per-stage
        audit: every entry should be exactly 1 in steady state."""
        out = {}
        for name, fn in self._jitted.items():
            size = getattr(fn, "_cache_size", None)
            out[name] = int(size()) if callable(size) else -1
        return out

    def live_per_chip_bytes(self) -> dict:
        """Measured per-chip param/opt bytes off the LIVE shardings, busiest
        stage — comparable to ``plan.cost.per_chip_param_bytes`` (the
        predicted-vs-live pin the bench asserts)."""
        import jax

        def per_chip(tree):
            total = 0
            for leaf in jax.tree_util.tree_leaves(tree):
                if hasattr(leaf, "addressable_shards") and leaf.addressable_shards:
                    shard = leaf.addressable_shards[0]
                    total += shard.data.nbytes
                elif hasattr(leaf, "nbytes"):
                    total += leaf.nbytes
            return total

        params = max(per_chip(p) for p in self.stage_params)
        opt = (
            max(per_chip(s) for s in self._opt_states) if self._opt_states else 0
        )
        return {"per_chip_param_bytes": params, "per_chip_opt_bytes": opt}

    def measure_stage_times(self, batch, repeats: int = 3) -> List[float]:
        """Per-microbatch fwd+bwd wall seconds per stage, off the COMPILED
        programs (best of ``repeats``). One microbatch flows the full chain so
        every stage's backward sees a real carry; each program is timed in
        isolation with a sync. Feed the result to
        `planner.pipeline_bubble_terms` for the measured-vs-predicted bubble
        account the bench pins. NOT on the step path — it synchronizes the
        host per program, the exact thing the 1F1B schedule exists to avoid.
        Run it outside the TraceGuard window; shapes match `train_step`'s
        microbatches, so the program caches stay at one entry each."""
        import time

        import jax

        P = self.num_stages
        args = self.batch_to_args(batch)
        first_mbs = self._jitted["split_first"](self._ship(args, self.submeshes[0]))
        last_mbs = self._jitted["split_last"](self._ship(batch, self.submeshes[P - 1]))
        best = [float("inf")] * P
        for _ in range(max(1, repeats)):
            fwd_t = [0.0] * P
            bwd_t = [0.0] * P
            saved: List[Any] = [None] * P
            saved[0] = first_mbs[0]
            for k in range(P - 1):
                t0 = time.perf_counter()
                carry = self._jitted[f"fwd{k}"](self.stage_params[k], saved[k])
                # Deliberate host sync: this is measurement code, not schedule
                # code — the timed program must retire before the clock stops.
                jax.block_until_ready(carry)  # tpu-lint: disable=TPU121
                fwd_t[k] = time.perf_counter() - t0
                saved[k + 1] = self._ship(carry, self.submeshes[k + 1])
            # The last stage has no standalone forward: its fwd+loss+bwd fuse
            # into one program (`_make_last`), which is exactly its stage time.
            acc = [self._ensure_zero(k)(self.stage_params[k]) for k in range(P)]
            last = P - 1
            diff, static, spec = _partition_carry(saved[last])
            t0 = time.perf_counter()
            _, _, acc[last], g = self._ensure_bwd(last, spec)(
                self.stage_params[last], static, diff, last_mbs[0], acc[last]
            )
            jax.block_until_ready(g)
            bwd_t[last] = time.perf_counter() - t0
            for k in range(P - 2, 0, -1):
                g = self._ship(g, self.submeshes[k])
                diff, static, spec = _partition_carry(saved[k])
                t0 = time.perf_counter()
                acc[k], g = self._ensure_bwd(k, spec)(
                    self.stage_params[k], static, diff, g, acc[k]
                )
                jax.block_until_ready(g)
                bwd_t[k] = time.perf_counter() - t0
            g = self._ship(g, self.submeshes[0])
            t0 = time.perf_counter()
            acc[0] = self._ensure_bwd_first()(self.stage_params[0], saved[0], g, acc[0])
            jax.block_until_ready(acc[0])
            bwd_t[0] = time.perf_counter() - t0
            for k in range(P):
                best[k] = min(best[k], fwd_t[k] + bwd_t[k])
        return best

    # ------------------------------------------------------------ state views
    def merged_params(self):
        """Re-join the per-stage trees into the original params structure
        (checkpoint-time view; NOT on the step path)."""
        plan = self.plan.stage_plan
        prelude = self.stage_params[0]["prelude"]
        tail = self.stage_params[self.num_stages - 1]["tail"]
        layers = [None] * plan.num_layers
        for k in range(self.num_stages):
            for i in plan.stage_layers(k):
                layers[i] = self.stage_params[k][f"layer_{i}"]
        return self.layered.join(prelude, layers, tail)

    @property
    def params(self):
        return self.merged_params()

    def state_dict(self):
        import jax

        return jax.device_get(self.merged_params())

    def load_state_dict(self, state):
        prelude, layers, tail = self.layered.split(state)
        plan = self.plan.stage_plan
        self.stage_params = []
        self._param_shardings = []
        for k in range(self.num_stages):
            self._place_stage(k, build_stage_tree(prelude, layers, tail, plan, k))

    def num_parameters(self) -> int:
        import jax

        return sum(
            int(leaf.size)
            for tree in self.stage_params
            for leaf in jax.tree_util.tree_leaves(tree)
        )

    def _ensure_eval_fwd(self, k: int):
        """Eval forward for stage k — DISTINCT program names from the training
        fwd{k}s on purpose: eval pushes the FULL batch where training pushes
        microbatch shapes, and sharing the function object would add a second
        cache entry per stage (breaking the compiled-once audit and reading
        as a recompile under an armed TraceGuard when eval interleaves with
        training)."""
        name = f"eval_fwd{k}"
        if name not in self._jitted:
            import jax

            self._jitted[name] = jax.jit(self._stage_forward_fn(k))
        return self._jitted[name]

    def __call__(self, batch):
        """Forward-only over the pipeline (eval view): full batch through every
        stage, logits returned from the last stage's mesh."""
        args = self.batch_to_args(batch)
        carry = self._ensure_eval_fwd(0)(self.stage_params[0], self._ship(args, self.submeshes[0]))
        for k in range(1, self.num_stages):
            carry = self._ship(carry, self.submeshes[k])
            carry = self._ensure_eval_fwd(k)(self.stage_params[k], carry)
        return carry


def prepare_mpmd_pipeline(
    model,
    layered=None,
    mesh=None,
    plan: Optional[MPMDTrainPlan] = None,
    *,
    batch: Optional[int] = None,
    seq: Optional[int] = None,
    num_microbatches: Optional[int] = None,
    logits_loss: Optional[Callable] = None,
    batch_to_args: Optional[Callable] = None,
    compute_dtype=None,
    autocast: bool = True,
) -> MPMDPipelinedModel:
    """Plan (if needed) and build the MPMD pipeline executor for ``model``.

    When ``plan`` is None, runs `plan_mpmd_train_sharding` over the model's
    `LayeredApply.split` — ``batch`` and ``seq`` are then required (they size
    the microbatch schedule and the per-stage workload)."""
    from ..models import layered_for_model
    from .planner import plan_mpmd_train_sharding

    if mesh is None:
        from ..state import AcceleratorState

        mesh = AcceleratorState().mesh
    if layered is None:
        layered = layered_for_model(model)
    if plan is None:
        if batch is None or seq is None:
            raise ValueError("prepare_mpmd_pipeline needs batch= and seq= to plan")
        prelude, layers, tail = layered.split(model.params)
        plan = plan_mpmd_train_sharding(
            prelude,
            layers,
            tail,
            mesh,
            batch=batch,
            seq=seq,
            num_microbatches=num_microbatches,
        )
    return MPMDPipelinedModel(
        model,
        layered,
        mesh,
        plan,
        logits_loss=logits_loss,
        batch_to_args=batch_to_args,
        compute_dtype=compute_dtype,
        autocast=autocast,
    )

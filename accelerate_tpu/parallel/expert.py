"""Expert parallelism (EP): top-k routing + capacity-based einsum dispatch over an
"expert" mesh axis.

The reference has no in-tree MoE machinery — EP exists only as DeepSpeed-MoE
leaf-module passthrough (dataclasses.py:992-1010, commands/launch.py:499-505), with
routing/all-to-all delegated to DeepSpeed's CUDA kernels. Here EP is first-class and
TPU-native (SURVEY §2.5 "expert-axis sharding + all-to-all dispatch"): the GShard-style
dense dispatch/combine einsums are XLA's preferred MoE formulation — with expert-major
tensors sharded over the "expert" axis and tokens over "data", GSPMD lowers the
dispatch einsum to an all-to-all over ICI, exactly the comm pattern DeepSpeed implements
by hand.

Shapes (per jit program, global):  tokens T = B*S, experts E, capacity C, hidden H.
  dispatch [T, E, C] one-hot   — token t goes to slot c of expert e
  combine  [T, E, C] float     — same support, weighted by the renormalized router gate
  expert_in  = einsum('tec,th->ech', dispatch, x)     (all-to-all under GSPMD)
  expert_out = vmapped_ffn(expert_in)                 (fully expert-parallel)
  y          = einsum('tec,ech->th', combine, expert_out)  (all-to-all back)
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# Appended to a model's TP rules: expert FFN kernels are [E, in, out]; dim 0 shards
# over "expert", the contraction dims keep Megatron column/row layout over "model".
EXPERT_SHARDING_RULES = [
    (r"experts/(w_gate|w_up)/kernel", ("expert", None, "model")),
    (r"experts/w_down/kernel", ("expert", "model", None)),
]


def expert_capacity(num_tokens: int, num_experts: int, top_k: int, capacity_factor: float) -> int:
    """Per-expert slot count: even share × top_k × slack (GShard capacity rule)."""
    return max(1, int(np.ceil(num_tokens * top_k / num_experts * capacity_factor)))


def top_k_routing(router_logits, top_k: int, capacity: int):
    """Compute dispatch/combine tensors for top-k token→expert routing.

    Args:
        router_logits: [T, E] raw router scores.
        top_k: experts per token.
        capacity: max tokens per expert; overflow tokens are dropped (their combine
            weight is zero — the residual connection carries them through unchanged).

    Returns:
        (dispatch [T,E,C] same-dtype one-hot, combine [T,E,C], aux) where aux is a dict
        with `load_balance_loss` (Switch-style E·Σ f_e·P_e) and `router_z_loss`.
    """
    T, E = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)  # [T, E]

    # top-k expert ids per token, processed in priority order so a token's k-th choice
    # only takes a slot after every token's (k-1)-th choice (GShard ordering).
    gate_vals, expert_ids = jax.lax.top_k(probs, top_k)  # [T, k]
    # renormalize the kept gates (Mixtral normalizes over the top-k set)
    gate_vals = gate_vals / jnp.maximum(gate_vals.sum(axis=-1, keepdims=True), 1e-9)

    onehot = jax.nn.one_hot(expert_ids, E, dtype=jnp.float32)  # [T, k, E]

    # Slot assignment: within each (priority, expert), tokens take slots in order;
    # priorities stack — choice j starts after all slots used by choices < j.
    position_in_expert = jnp.zeros((T, top_k), dtype=jnp.int32)
    used = jnp.zeros((E,), dtype=jnp.float32)
    positions = []
    keep = []
    for j in range(top_k):
        oh = onehot[:, j, :]  # [T, E]
        pos_j = (jnp.cumsum(oh, axis=0) - 1.0) + used[None, :]  # [T, E] slot index
        pos_tok = jnp.sum(pos_j * oh, axis=-1)  # [T]
        within = pos_tok < capacity
        positions.append(pos_tok.astype(jnp.int32))
        keep.append(within)
        used = used + jnp.sum(oh, axis=0)
    position_in_expert = jnp.stack(positions, axis=1)  # [T, k]
    keep = jnp.stack(keep, axis=1)  # [T, k]

    slot_onehot = jax.nn.one_hot(position_in_expert, capacity, dtype=jnp.float32)  # [T,k,C]
    keep_f = keep.astype(jnp.float32)[..., None]  # [T,k,1]
    # [T,k,E,C] → reduce the k axis
    dispatch = jnp.einsum("tke,tkc->tec", onehot * keep_f, slot_onehot)
    combine = jnp.einsum("tke,tkc->tec", onehot * keep_f * gate_vals[..., None], slot_onehot)

    # aux losses (computed on ALL tokens' router probs, not just kept ones)
    # f_e: fraction of token-choices routed to e; P_e: mean router prob for e.
    f = jnp.mean(onehot.sum(axis=1), axis=0)  # [E]
    P = jnp.mean(probs, axis=0)  # [E]
    load_balance_loss = E * jnp.sum(f * P) / top_k
    z = jax.scipy.special.logsumexp(router_logits.astype(jnp.float32), axis=-1)
    router_z_loss = jnp.mean(jnp.square(z))
    aux = {"load_balance_loss": load_balance_loss, "router_z_loss": router_z_loss}
    return dispatch, combine, aux


class ExpertMLP(nn.Module):
    """SwiGLU FFN with a leading expert axis on every kernel ([E, ...], sharded over
    the "expert" mesh axis by EXPERT_SHARDING_RULES)."""

    hidden_size: int
    intermediate_size: int
    num_experts: int

    @nn.compact
    def __call__(self, x):  # x: [E, C, H]
        E, H, F = self.num_experts, self.hidden_size, self.intermediate_size
        init = nn.initializers.lecun_normal()
        w_gate = self.param("w_gate/kernel", lambda k, s: init(k, s), (E, H, F))
        w_up = self.param("w_up/kernel", lambda k, s: init(k, s), (E, H, F))
        w_down = self.param("w_down/kernel", lambda k, s: init(k, s), (E, F, H))
        gate = jnp.einsum("ech,ehf->ecf", x, w_gate)
        up = jnp.einsum("ech,ehf->ecf", x, w_up)
        return jnp.einsum("ecf,efh->ech", nn.silu(gate) * up, w_down)


class MoEBlock(nn.Module):
    """Router + expert-parallel FFN (the in-tree Mixtral/Switch FFN replacement for the
    reference's DeepSpeed-MoE passthrough)."""

    hidden_size: int
    intermediate_size: int
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, hidden):  # [B, S, H]
        B, S, H = hidden.shape
        T = B * S
        x = hidden.reshape(T, H)
        router_logits = nn.Dense(self.num_experts, use_bias=False, name="router")(
            x.astype(jnp.float32)
        )
        C = expert_capacity(T, self.num_experts, self.top_k, self.capacity_factor)
        dispatch, combine, aux = top_k_routing(router_logits, self.top_k, C)
        dispatch = dispatch.astype(hidden.dtype)
        combine = combine.astype(jnp.float32)

        expert_in = jnp.einsum("tec,th->ech", dispatch, x)  # a2a under GSPMD
        expert_out = ExpertMLP(
            self.hidden_size, self.intermediate_size, self.num_experts, name="experts"
        )(expert_in)
        y = jnp.einsum("tec,ech->th", combine, expert_out.astype(jnp.float32))
        return y.reshape(B, S, H).astype(hidden.dtype), aux

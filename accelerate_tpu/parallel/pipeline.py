"""Pipeline parallelism over the "stage" mesh axis.

The reference reaches pipeline parallelism only through external native runtimes:
Megatron-LM's 1F1B schedule for training (reference utils/megatron_lm.py:1004-1010) and
PiPPy's fx-traced stages + c10d send/recv for inference (reference inference.py:126).
Here PP is in-tree and TPU-native: stages live on the "stage" axis of the one global
mesh, activations hop between stages with `lax.ppermute` over ICI, and the microbatch
schedule is a `lax.scan` over pipeline ticks inside one jitted SPMD program — XLA
overlaps each stage's matmuls with the neighbor DMA, and autodiff through the scan
produces the backward schedule (GPipe-style, rematerialized per tick so activation
memory stays O(microbatches), not O(microbatches × layers)).

Layout: a model's stack decomposes via the `LayeredApply` protocol
(accelerate_tpu.big_modeling) into prelude / N homogeneous layers / tail. Layer params
are stacked on a leading [L] axis sharded over "stage" (each stage holds L/S layers and
scans them locally); prelude and tail are replicated — only their owning stage computes
them (a `lax.cond` gates the FLOPs) and shard_map's transpose inserts the psum that
makes their gradients globally correct.

Schedule: tick t ∈ [0, M+S-1): stage 0 injects microbatch min(t, M-1), every stage runs
its local layer chunk, the last stage folds microbatch t-(S-1) into the loss, and the
carry rotates +1 stage. Injections after t=M-1 are duplicates that never reach the tail
inside the loop — they occupy the same slots the pipeline bubble would leave idle.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import numpy as np

# Path rules consumed by parallel/sharding.py: stacked layer params (and their optimizer
# moments, whose paths nest under e.g. "0/mu/layers/...") shard dim 0 over "stage".
# enc_layers/dec_layers are the encoder-decoder pipeline's two stacked bodies.
PIPELINE_SHARDING_RULES = [(r"(^|/)(enc_|dec_)?layers(/|$)", ("stage",))]


def _shard_map():
    from .sharding import compat_shard_map

    return compat_shard_map


def stack_layer_params(layers):
    """Stack a list of per-layer param pytrees into one pytree with leading [L] axes."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layer_params(stacked, num_layers: int):
    import jax

    return [jax.tree_util.tree_map(lambda x: x[i], stacked) for i in range(num_layers)]


def stack_layer_params_sharded(layers, sharding_tree):
    """Stack per-layer param pytrees directly into stage-sharded [L, ...] buffers,
    assembling each device's [L/S, ...] slice individually so the full stacked model
    never materializes on one device.

    Deliberately NOT `jit(stack_layer_params, out_shardings=...)`: on jax 0.4.37's
    forced-host-device CPU backend the GSPMD-partitioned concatenate reads its input
    with a stride equal to the size of the replicated mesh axes (out.flat[k] ==
    ref.flat[data_size * k]), silently corrupting every stacked buffer — the root
    cause of the pipeline parity drift."""
    import jax
    import numpy as np

    num_layers = len(layers)

    def per_leaf(shard, *leaves):
        shape = (num_layers,) + tuple(leaves[0].shape)
        host = [np.asarray(x) for x in leaves]

        def cb(idx):
            rows = range(*idx[0].indices(num_layers))
            return np.stack([host[i][idx[1:]] for i in rows])

        return jax.make_array_from_callback(shape, shard, cb)

    return jax.tree_util.tree_map(per_leaf, sharding_tree, *layers)


def _dict_path_get(tree, path):
    for k in path:
        tree = tree[k]
    return tree


def _dict_path_set(tree, path, value):
    """Copy-on-write set: returns a new nested dict with `path` replaced by `value`,
    creating intermediate dicts as needed (tied paths are pruned from the stored tail)."""
    if not path:
        return value
    out = dict(tree)
    out[path[0]] = _dict_path_set(tree.get(path[0], {}), path[1:], value)
    return out


def _dict_path_del(tree, path):
    out = dict(tree)
    if len(path) == 1:
        del out[path[0]]
        return out
    out[path[0]] = _dict_path_del(tree[path[0]], path[1:])
    if not out[path[0]]:
        del out[path[0]]
    return out


def find_tied_leaves(prelude, tail):
    """Tail leaves sharing a buffer with a prelude leaf (tied weights, e.g. a tied
    lm head — reference finds these via data_ptr maps, utils/modeling.py:606). Returns
    [(tail_path, prelude_path)] with paths as tuples of dict keys."""
    import jax

    def _paths(tree):
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        return [(tuple(getattr(k, "key", k) for k in path), leaf) for path, leaf in flat]

    prelude_by_id = {id(leaf): path for path, leaf in _paths(prelude)}
    return [
        (path, prelude_by_id[id(leaf)])
        for path, leaf in _paths(tail)
        if id(leaf) in prelude_by_id
    ]


def default_causal_lm_logits_loss(logits, batch):
    """Shifted next-token cross-entropy on a microbatch, as a `(loss_sum, weight)` pair
    (mirrors models.llama.causal_lm_loss but from logits — the tail output — instead of
    params). Returning the unnormalized pair lets the pipeline produce the globally
    token-weighted mean even when label masking is uneven across microbatches/shards."""
    import jax
    import jax.numpy as jnp

    labels = batch.get("labels", batch["input_ids"])
    shift_logits = logits[:, :-1].astype(jnp.float32)
    shift_labels = labels[:, 1:]
    logp = jax.nn.log_softmax(shift_logits, axis=-1)
    valid = (shift_labels >= 0).astype(jnp.float32)
    safe_labels = jnp.maximum(shift_labels, 0)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    return (nll * valid).sum(), valid.sum()


def _default_batch_to_args(batch):
    if isinstance(batch, dict):
        return (batch["input_ids"], batch.get("attention_mask"))
    return (batch,)


def default_seq2seq_logits_loss(logits, batch):
    """Teacher-forced cross-entropy on decoder targets from logits, as a
    `(loss_sum, weight)` pair (mirrors models.t5.seq2seq_lm_loss; labels align
    with decoder positions — no shift)."""
    import jax
    import jax.numpy as jnp

    labels = batch["labels"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    valid = (labels >= 0).astype(jnp.float32)
    safe = jnp.maximum(labels, 0)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return (nll * valid).sum(), valid.sum()


def _default_seq2seq_batch_to_args(batch):
    return (batch["input_ids"], batch["decoder_input_ids"], batch.get("attention_mask"))


from ..modeling import _cast_floating


class PipelineSpec:
    """Stage functions for one model: an adapter over the `LayeredApply` protocol plus a
    logits-level loss. This is the PiPPy `Pipe.from_tracing` replacement — models declare
    their stage decomposition instead of being fx-traced."""

    def __init__(
        self,
        layered,
        loss_on_logits: Optional[Callable] = None,
        batch_to_args: Optional[Callable] = None,
    ):
        self.layered = layered
        self.loss_on_logits = loss_on_logits or default_causal_lm_logits_loss
        self.batch_to_args = batch_to_args or _default_batch_to_args

    def prelude(self, prelude_params, batch):
        return self.layered.apply_prelude(prelude_params, *self.batch_to_args(batch))

    def layer(self, layer_params, carry):
        return self.layered.apply_layer(layer_params, carry)

    def tail(self, tail_params, carry):
        return self.layered.apply_tail(tail_params, carry)


class EncoderDecoderPipelineSpec(PipelineSpec):
    """Stage functions for a two-stack (encoder-decoder) model, over the
    `T5PipelineApply`-shaped protocol: split -> (prelude, enc_layers, dec_layers,
    tail), apply_prelude/apply_enc_layer/apply_promote/apply_dec_layer/apply_tail.
    The reference reaches this only through Megatron's T5 schedule
    (utils/megatron_lm.py:702,1004-1010)."""

    def __init__(
        self,
        layered,
        loss_on_logits: Optional[Callable] = None,
        batch_to_args: Optional[Callable] = None,
    ):
        super().__init__(
            layered,
            loss_on_logits or default_seq2seq_logits_loss,
            batch_to_args or _default_seq2seq_batch_to_args,
        )

    def promote(self, prelude_params, carry):
        return self.layered.apply_promote(prelude_params, carry)

    def enc_layer(self, layer_params, carry):
        return self.layered.apply_enc_layer(layer_params, carry)

    def dec_layer(self, layer_params, carry):
        return self.layered.apply_dec_layer(layer_params, carry)

    def static_carry(self, prelude_params, batch):
        """Input-independent carry entries (e.g. T5's relative-position biases):
        computed once per stage from the replicated prelude, merged into the carry
        before each layer application, and NEVER rotated over ICI."""
        fn = getattr(self.layered, "apply_static_carry", None)
        if fn is None:
            return {}
        return fn(prelude_params, *self.batch_to_args(batch))


def _split_microbatches(batch, num_microbatches: int):
    import jax

    def _split(x):
        if x.shape[0] % num_microbatches != 0:
            raise ValueError(
                f"Local batch {x.shape[0]} not divisible by num_microbatches={num_microbatches}"
            )
        return x.reshape((num_microbatches, x.shape[0] // num_microbatches) + x.shape[1:])

    return jax.tree_util.tree_map(_split, batch)


def _build_local_fns(
    spec, num_microbatches: int, compute_dtype=None, remat: bool = True, encoder_decoder: bool = False
):
    """Per-device (shard_map-level) pipelined loss and forward — ONE implementation
    for both schedules, parameterized by the tick body:

    - single-body (decoder-only): one stream; a microbatch rides the ring once
      (drain S-1, schedule M + S - 1 ticks), each stage scanning its local chunk
      of the one stacked layer body.
    - encoder-decoder (`encoder_decoder=True`): every stage holds a chunk of BOTH
      stacks and two streams are in flight; a microbatch rides the ring twice —
      encoder chunks on hops [0, S), `spec.promote` (the encoder final norm) as it
      re-enters stage 0, decoder chunks with cross-attention on hops [S, 2S) — so
      the drain is 2S-1 and the schedule M + 2S - 1 ticks. The carry pytree holds
      both hidden streams, making it uniform across every hop.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    M = num_microbatches

    if encoder_decoder:
        enc_fn, dec_fn = spec.enc_layer, spec.dec_layer
        if remat:
            enc_fn, dec_fn = jax.checkpoint(spec.enc_layer), jax.checkpoint(spec.dec_layer)
    else:
        layer_fn = jax.checkpoint(spec.layer) if remat else spec.layer

    def _prep(params, batch):
        if compute_dtype is not None:
            params = _cast_floating(params, compute_dtype)
            batch = _cast_floating(batch, compute_dtype)
        return params, batch

    def _index_mb(mbs, i):
        return jax.tree_util.tree_map(
            lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False), mbs
        )

    def _pipeline_scan(params, batch, fold_output):
        """Builds (tick, init_streams, total_ticks); `fold_output(acc, tail_p, x,
        out_mb, out_i, valid)` folds the last stage's finished carry into an
        accumulator. The scan carry is (streams_tuple, acc)."""
        prelude_p, tail_p = params["prelude"], params["tail"]
        from .ring_attention import _axis_size

        S = _axis_size("stage")
        idx = lax.axis_index("stage")
        mbs = _split_microbatches(batch, M)
        mb0 = _index_mb(mbs, jnp.int32(0))
        # Input-independent carry entries (spec.static_carry, e.g. T5's relative
        # biases): every stage computes them locally from the replicated prelude;
        # they merge into the carry before each layer application and never ride
        # the ppermute ring.
        static = {}
        if encoder_decoder and hasattr(spec, "static_carry"):
            static = spec.static_carry(prelude_p, mb0)

        def _strip(c):
            return {k: v for k, v in c.items() if k not in static} if static else c

        def _merge(c):
            return {**c, **static} if static else c

        carry_struct = jax.eval_shape(lambda p, m: _strip(spec.prelude(p, m)), prelude_p, mb0)
        zeros = jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), carry_struct)
        perm = [(i, (i + 1) % S) for i in range(S)]
        drain = (2 * S - 1) if encoder_decoder else (S - 1)

        def rotate(x):
            return jax.tree_util.tree_map(lambda a: lax.ppermute(a, "stage", perm), x)

        def tick(carry, t):
            streams, acc = carry
            mb = _index_mb(mbs, jnp.clip(t, 0, M - 1))
            if encoder_decoder:
                s0, s1 = streams
                # Stage 0 retires both incoming carries: the enc-stream carry that
                # just completed its S encoder chunks promotes into the dec stream
                # (replacing the dec carry that folded last tick), and a fresh
                # microbatch injects into the enc stream.
                x1 = lax.cond(
                    idx == 0, lambda s: _strip(spec.promote(prelude_p, _merge(s))), lambda s: s1, s0
                )
                x0 = lax.cond(
                    idx == 0, lambda s: _strip(spec.prelude(prelude_p, mb)), lambda s: s, s0
                )
                x0, _ = lax.scan(
                    lambda h, lp: (_strip(enc_fn(lp, _merge(h))), None), x0, params["enc_layers"]
                )
                x1, _ = lax.scan(
                    lambda h, lp: (_strip(dec_fn(lp, _merge(h))), None), x1, params["dec_layers"]
                )
                out_x, new_streams = _merge(x1), (rotate(x0), rotate(x1))
            else:
                (s0,) = streams
                # Only stage 0 pays the prelude FLOPs; everyone else keeps the
                # carry it received last tick.
                x = lax.cond(idx == 0, lambda s: spec.prelude(prelude_p, mb), lambda s: s, s0)
                x, _ = lax.scan(lambda h, lp: (layer_fn(lp, h), None), x, params["layers"])
                out_x, new_streams = x, (rotate(x),)
            out_i = jnp.clip(t - drain, 0, M - 1)
            valid = jnp.logical_and(t >= drain, idx == S - 1)
            acc = fold_output(acc, tail_p, out_x, _index_mb(mbs, out_i), out_i, valid)
            return (new_streams, acc), None

        init_streams = (zeros, zeros) if encoder_decoder else (zeros,)
        return tick, init_streams, M + drain, (prelude_p, tail_p)

    def _loss_pair(tail_p, carry, mb):
        """Normalize loss_on_logits output to a (loss_sum, weight) pair: fns returning a
        plain scalar (a microbatch mean) get weight 1 — equal-weight averaging; pair
        returns give exact token-weighted parity with the unpipelined loss.

        Both entries are shape (1,), NOT 0-d: every float scalar in this body risks
        becoming a 0-d residual of the differentiated shard_map, and jax 0.4.37's
        partial-eval misses scalar-residual promotion for forwarded residuals — the
        transpose then fails _check_names (leading-axis sharding on a 0-d aval)."""
        out = spec.loss_on_logits(spec.tail(tail_p, carry), mb)
        if isinstance(out, tuple):
            s, w = out
            return s.astype(jnp.float32).reshape(1), w.astype(jnp.float32).reshape(1)
        return out.astype(jnp.float32).reshape(1), jnp.ones((1,), jnp.float32)

    def local_loss(params, batch):
        params, batch = _prep(params, batch)

        def fold(acc, tail_p, x, out_mb, out_i, valid):
            # Only the last stage pays the tail (lm_head) FLOPs.
            s, w = lax.cond(
                valid,
                lambda c: _loss_pair(tail_p, c, out_mb),
                lambda c: (jnp.zeros((1,), jnp.float32), jnp.zeros((1,), jnp.float32)),
                x,
            )
            return (acc[0] + s, acc[1] + w)

        tick, init_streams, total, _ = _pipeline_scan(params, batch, fold)
        (_, (loss_sum, weight)), _ = lax.scan(
            tick,
            (init_streams, (jnp.zeros((1,), jnp.float32), jnp.zeros((1,), jnp.float32))),
            jnp.arange(total),
        )
        axes = ("stage", "data", "fsdp")
        loss_sum = lax.psum(loss_sum, axes)
        weight = lax.psum(weight, axes)
        # Return the unreduced (loss_sum, weight) pair; the caller divides OUTSIDE the
        # shard_map. Keeping the division inside makes `weight` a 0-d float residual of
        # the differentiated body, and jax 0.4.37's shard_map partial-eval under remat
        # skips its scalar-residual promotion — the transpose then dies with a
        # _SpecError (leading-axis names on a 0-d aval).
        return loss_sum, weight

    def local_forward(params, batch):
        params, batch = _prep(params, batch)
        prelude_p, tail_p = params["prelude"], params["tail"]
        mbs = _split_microbatches(batch, M)
        mb0 = _index_mb(mbs, np.int32(0))
        carry_struct = jax.eval_shape(spec.prelude, prelude_p, mb0)
        out_struct = jax.eval_shape(spec.tail, tail_p, carry_struct)
        buf0 = jax.tree_util.tree_map(
            lambda s: jnp.zeros((M,) + s.shape, s.dtype), out_struct
        )

        def fold(buf, tail_p, x, out_mb, out_i, valid):
            out = lax.cond(
                valid,
                lambda c: spec.tail(tail_p, c),
                lambda c: jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), out_struct),
                x,
            )
            return jax.tree_util.tree_map(
                lambda b, o: lax.cond(
                    valid,
                    lambda args: lax.dynamic_update_index_in_dim(args[0], args[1], out_i, 0),
                    lambda args: args[0],
                    (b, o),
                ),
                buf,
                out,
            )

        tick, init_streams, total, _ = _pipeline_scan(params, batch, fold)
        (_, buf), _ = lax.scan(tick, (init_streams, buf0), jnp.arange(total))
        # Outputs live on the last stage only; psum broadcasts them (zeros elsewhere).
        buf = jax.tree_util.tree_map(lambda b: lax.psum(b, "stage"), buf)
        return jax.tree_util.tree_map(lambda b: b.reshape((-1,) + b.shape[2:]), buf)

    return local_loss, local_forward


class PipelinedModel:
    """A model placed on the mesh's "stage" axis, quacking like `PreparedModel` so it
    slots into `Accelerator.backward`/`AcceleratedOptimizer` unchanged.

    params = {"prelude": replicated, "layers": [L, ...] stacked & stage-sharded,
    "tail": replicated}. `loss(params, batch)` is the pipelined scan; `__call__(batch)`
    is the pipelined forward returning logits.
    """

    is_pipelined = True
    # Pipeline params always live in device memory (stage-sharded HBM); the
    # host-offload tiers (modeling.py:145-161) don't compose with the stage scan.
    offload_params = False

    def to_compute_memory(self, params):
        """PreparedModel protocol (modeling.py:145): identity — never offloaded."""
        return params

    def to_storage_memory(self, params):
        """PreparedModel protocol (modeling.py:154): identity — never offloaded."""
        return params

    def __init__(
        self,
        model,
        layered,
        mesh,
        num_microbatches: int = 4,
        loss_on_logits: Optional[Callable] = None,
        batch_to_args: Optional[Callable] = None,
        compute_dtype=None,
        autocast: bool = True,
        remat: bool = True,
    ):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if mesh.shape.get("model", 1) > 1 or mesh.shape.get("seq", 1) > 1:
            raise NotImplementedError(
                "Pipeline parallelism currently composes with data/fsdp axes only "
                "(tp/sp inside pipeline stages needs manual-collective layers)."
            )
        self.mesh = mesh
        self.module = getattr(model, "module", None)
        self.layered = layered
        self.compute_dtype = compute_dtype
        self.autocast_enabled = autocast and compute_dtype is not None
        self.num_microbatches = num_microbatches
        # Two-stack (encoder-decoder) decompositions implement the
        # T5PipelineApply-shaped protocol and run the two-phase ring schedule.
        self.is_encoder_decoder = hasattr(layered, "apply_enc_layer")
        self.spec = (
            EncoderDecoderPipelineSpec(layered, loss_on_logits, batch_to_args)
            if self.is_encoder_decoder
            else PipelineSpec(layered, loss_on_logits, batch_to_args)
        )

        import jax

        # Stage assignment is planner-emitted (plan_pipeline_stages balances
        # contiguous ranges on per-layer bytes); the SPMD runner below stacks
        # layer params into one [L, ...] buffer sharded P("stage") on the
        # leading dim, which can only EXECUTE the uniform (equal-count) shape —
        # non-uniform balanced plans need an MPMD runner.
        from .planner import plan_pipeline_stages

        def _stage_plan(stack, kind):
            if len(stack) % n_stages != 0:
                raise ValueError(
                    f"{len(stack)} {kind} layers not divisible by {n_stages} pipeline "
                    f"stages (the SPMD stage runner scans equal-count stages only; "
                    f"non-uniform plans run on the MPMD runner — build the mesh with "
                    f"a 'pipeline' axis and use parallel.mpmd.prepare_mpmd_pipeline "
                    f"or Accelerator.prepare(sharding_rules='auto'))"
                )
            plan = plan_pipeline_stages(stack, n_stages)
            if not plan.uniform:
                raise ValueError(
                    f"{plan.num_layers} {kind} layers not divisible by {n_stages} "
                    f"pipeline stages (the planner's byte-balanced assignment "
                    f"{plan.assignment} is non-uniform; the SPMD stage runner "
                    f"scans equal-count stages only — non-uniform plans run on the "
                    f"MPMD runner: build the mesh with a 'pipeline' axis and use "
                    f"parallel.mpmd.prepare_mpmd_pipeline or "
                    f"Accelerator.prepare(sharding_rules='auto'))"
                )
            return plan

        n_stages = mesh.shape["stage"]
        if self.is_encoder_decoder:
            prelude, enc_layers, dec_layers, tail = layered.split(model.params)
            self.num_layers = (len(enc_layers), len(dec_layers))
            self.stage_plans = {
                "enc_layers": _stage_plan(enc_layers, "encoder"),
                "dec_layers": _stage_plan(dec_layers, "decoder"),
            }
            self.stage_plan = self.stage_plans["dec_layers"]
            layer_groups = {"enc_layers": enc_layers, "dec_layers": dec_layers}
        else:
            prelude, layers, tail = layered.split(model.params)
            self.num_layers = len(layers)
            # Stages scan ONE layer body, so every layer entry must share a pytree
            # structure. Mixed-structure streaming decompositions (T5LayeredApply)
            # can't scan — point at the pipeline protocol instead.
            structures = {jax.tree_util.tree_structure(lp) for lp in layers}
            if len(structures) > 1:
                raise NotImplementedError(
                    "Pipeline parallelism requires homogeneous layer blocks (one "
                    "scanned body); this LayeredApply yields mixed structures "
                    "(encoder-decoder). Use the two-stack pipeline protocol instead "
                    "(e.g. models.t5.T5PipelineApply), or tier-streamed execution: "
                    "accelerate_tpu.big_modeling.dispatch_model/cpu_offload with the "
                    "same LayeredApply."
                )
            self.stage_plan = _stage_plan(layers, "transformer")
            self.stage_plans = {"layers": self.stage_plan}
            layer_groups = {"layers": layers}
        self.sharding_rules = list(self.stage_plan.rules)
        # Tied weights (e.g. embed_tokens reused by a tied lm head) appear in both the
        # prelude and the tail after split. Store them ONCE (in the prelude) and
        # re-inject the prelude's copy into the tail view inside the differentiated
        # functions — otherwise the two copies would receive independent partial
        # gradients and silently diverge under the optimizer.
        self._ties = find_tied_leaves(prelude, tail)
        for tail_path, _ in self._ties:
            tail = _dict_path_del(tail, tail_path)
        # Stack the per-layer pytrees directly into stage-sharded buffers, one
        # device-local [L/S, ...] slice at a time (stack_layer_params_sharded) so the
        # full stacked model never materializes on one device.
        self.param_sharding = {
            "prelude": jax.tree_util.tree_map(lambda _: NamedSharding(mesh, P()), prelude),
            "tail": jax.tree_util.tree_map(lambda _: NamedSharding(mesh, P()), tail),
        }
        stacked_groups = {}
        for group_name, stack in layer_groups.items():
            stacked_struct = jax.eval_shape(stack_layer_params, stack)
            group_sharding = jax.tree_util.tree_map(
                lambda _: NamedSharding(mesh, P("stage")), stacked_struct
            )
            stacked_groups[group_name] = stack_layer_params_sharded(stack, group_sharding)
            self.param_sharding[group_name] = group_sharding
        from .sharding import place_params

        placed = place_params(
            {"prelude": prelude, "tail": tail},
            {"prelude": self.param_sharding["prelude"], "tail": self.param_sharding["tail"]},
        )
        self.params = {"prelude": placed["prelude"], "tail": placed["tail"], **stacked_groups}

        local_loss, local_forward = _build_local_fns(
            self.spec,
            num_microbatches,
            compute_dtype=compute_dtype if self.autocast_enabled else None,
            remat=remat,
            encoder_decoder=self.is_encoder_decoder,
        )
        from .sharding import data_spec as _data_spec

        shard_map = _shard_map()
        data_spec = _data_spec(mesh)
        param_specs = {
            "prelude": P(),
            "tail": P(),
            **{name: P("stage") for name in layer_groups},
        }
        # check_vma off: the scan carry deliberately mixes device-varying values (the
        # rotating activations) with unvarying zeros at t=0, which the VMA type system
        # rejects; correctness is covered by the parity tests.
        smap_kwargs = dict(mesh=mesh, in_specs=(param_specs, data_spec), check_vma=False)

        def _with_ties(fn):
            if not self._ties:
                return fn
            ties = self._ties

            def inner(params, batch):
                tail = params["tail"]
                for tail_path, prelude_path in ties:
                    tail = _dict_path_set(
                        tail, tail_path, _dict_path_get(params["prelude"], prelude_path)
                    )
                return fn({**params, "tail": tail}, batch)

            return inner

        _loss_pair_fn = shard_map(
            _with_ties(local_loss), out_specs=(P(), P()), **smap_kwargs
        )

        def _loss(params, batch):
            import jax.numpy as jnp

            loss_sum, weight = _loss_pair_fn(params, batch)
            return (loss_sum / jnp.maximum(weight, 1e-9))[0]

        self._loss_fn = _loss
        self._forward_fn = shard_map(_with_ties(local_forward), out_specs=data_spec, **smap_kwargs)
        self._jit_forward = None
        # Accelerator.autocast toggles clear this on every registered model; the
        # pipeline's compute dtype is baked into the shard_map fns at construction, so
        # clearing it is a harmless no-op here.
        self._jit_cache: dict = {}

    # -- PreparedModel-compatible surface ---------------------------------------------
    def loss(self, params, batch):
        """Differentiable pipelined loss — the canonical argument to Accelerator.backward."""
        return self._loss_fn(params, batch)

    def __call__(self, batch):
        import jax

        if self._jit_forward is None:
            self._jit_forward = jax.jit(self._forward_fn)
        return self._jit_forward(self.params, batch)

    def eval_apply(self, batch):
        return self(batch)

    def state_dict(self):
        return self.params

    def load_state_dict(self, params):
        from .sharding import place_params

        # place_params (not device_put): loaded buffers must not alias the caller's
        # arrays — the optimizer's donated update deletes ours every step.
        self.params = place_params(params, self.param_sharding)

    def merged_params(self):
        """Params back in the original (unstacked) model layout — for saving checkpoints
        interchangeable with the non-pipelined model."""
        if self.is_encoder_decoder:
            n_enc, n_dec = self.num_layers
            enc = unstack_layer_params(self.params["enc_layers"], n_enc)
            dec = unstack_layer_params(self.params["dec_layers"], n_dec)
            return self.layered.join(self.params["prelude"], enc, dec, self.params["tail"])
        layers = unstack_layer_params(self.params["layers"], self.num_layers)
        return self.layered.join(self.params["prelude"], layers, self.params["tail"])

    @property
    def num_parameters(self) -> int:
        import jax

        return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(self.params))

    def __repr__(self):
        return (
            f"PipelinedModel(layers={self.num_layers}, stages={self.mesh.shape['stage']}, "
            f"microbatches={self.num_microbatches}, params={self.num_parameters:,})"
        )


def prepare_pipeline(
    model,
    layered,
    mesh=None,
    num_microbatches: int = 4,
    loss_on_logits: Optional[Callable] = None,
    batch_to_args: Optional[Callable] = None,
    compute_dtype=None,
    remat: bool = True,
) -> PipelinedModel:
    """Build a PipelinedModel from a Model bundle + its LayeredApply decomposition
    (the user-facing PP entry, Megatron `pp_degree` / PiPPy `prepare_pippy` parity)."""
    from ..state import AcceleratorState, PartialState

    if mesh is None:
        mesh = AcceleratorState().mesh
    # FSDP sync_module_states applies to pipelined models too (prepare_model's
    # broadcast can't reach them — they arrive at Accelerator.prepare already
    # placed): rank 0's initial weights win BEFORE stage placement.
    shared = AcceleratorState._shared_state
    fsdp = shared.get("fsdp_plugin") if shared else None
    if (
        fsdp is not None
        and getattr(fsdp, "sync_module_states", False)
        and PartialState._shared_state
        and PartialState().num_processes > 1
    ):
        from ..utils.operations import broadcast

        model.params = broadcast(model.params, from_process=0)
    if compute_dtype is None:
        # Inherit the Accelerator's mixed-precision policy (prepare_model parity —
        # accelerator.py sets compute_dtype from state for non-pipelined models).
        shared = AcceleratorState._shared_state
        if shared and shared.get("_mixed_precision") in ("bf16", "fp16", "fp8"):
            compute_dtype = AcceleratorState().compute_dtype
    return PipelinedModel(
        model,
        layered,
        mesh,
        num_microbatches=num_microbatches,
        loss_on_logits=loss_on_logits,
        batch_to_args=batch_to_args,
        compute_dtype=compute_dtype,
        remat=remat,
    )

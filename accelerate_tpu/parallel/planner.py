"""Automatic sharding-strategy search: the cost-model planner (ROADMAP item 6).

Replaces the hand-written partition-rule tables as the SOURCE of sharding
decisions (AMP, arXiv:2210.07297; executed by the GSPMD partitioner,
arXiv:2105.04663): enumerate candidate PartitionSpecs per parameter from layer
shapes + mesh topology, score each full plan with an analytic cost model —
per-chip HBM bytes (params + optimizer state + KV pools at the live cache
dtype), collective bytes over ICI implied by the spec transitions (all-reduce
for row-parallel outputs, all-gather for replicated reads), and estimated
step/dispatch time from FLOPs + bytes at configurable chip bandwidths — then
beam-search to a plan and emit a rules table in the exact ``(pattern, spec)``
shape ``spec_for_param`` / ``derive_tp_param_shardings`` already consume. The
planner therefore slots in behind every existing seam (`Accelerator` training
shardings, ``ContinuousBatcher(tp=N, sharding_rules="auto")``, the
Router/fleet) with zero new placement machinery; the hand tables shipped by
``accelerate_tpu.models`` remain as parity ORACLES, not sources.

Structure discovery is shape-first: the residual width is inferred as the most
common dimension across 2-D kernels, Megatron blocks are grouped by path
prefix, and the block's output projection (the row-parallel end of a
column->row chain) is identified structurally (its input dim is another
kernel's output dim and differs from the residual width) with a conventional
name-hint tie-break for square attention projections. Weights the planner
cannot place in a dataflow role are costed conservatively — sharding them is
charged a per-step all-gather of the weight itself — so unknown layers
replicate rather than silently eating collectives (the planner analogue of
TPU118's "no silent replication").

``refine_plans`` is the measure-and-refine half: the cost model proposes the
top-k plans, the hardware disposes — each candidate's params are placed by its
emitted rules and a one-token forward is compiled and timed.
"""

from __future__ import annotations

import re
from collections import Counter
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "ChipSpec",
    "Workload",
    "LeafPlan",
    "PlanCost",
    "ShardingPlan",
    "StagePlan",
    "CHIPS",
    "default_chip",
    "candidate_specs",
    "emit_rules",
    "plan_sharding",
    "plan_serving_sharding",
    "plan_train_sharding",
    "plan_pipeline_stages",
    "score_rules",
    "MPMDTrainPlan",
    "build_stage_tree",
    "default_num_microbatches",
    "pipeline_bubble_terms",
    "plan_mpmd_train_sharding",
    "search_train_meshes",
    "measure_forward_step",
    "measure_train_step",
    "refine_plans",
    "resolve_sharding_rules",
]


# --------------------------------------------------------------------- chips
@dataclass(frozen=True)
class ChipSpec:
    """Per-chip bandwidth/compute constants the cost model prices against.

    The defaults are public TPU figures at the right order of magnitude —
    the planner ranks PLANS against each other on one chip, so only the
    RATIOS (HBM vs ICI vs FLOPs) matter; override per generation for honest
    absolute step-time predictions."""

    name: str = "tpu-v4"
    hbm_bytes: float = 32e9
    hbm_gbps: float = 1200.0  # HBM read bandwidth, GB/s
    ici_gbps: float = 300.0  # effective all-reduce bandwidth over ICI, GB/s
    tflops: float = 275.0  # bf16 matmul peak, TFLOP/s


CHIPS: Dict[str, ChipSpec] = {
    "tpu-v4": ChipSpec(),
    "tpu-v5e": ChipSpec("tpu-v5e", 16e9, 819.0, 180.0, 197.0),
    "tpu-v5p": ChipSpec("tpu-v5p", 95e9, 2765.0, 600.0, 459.0),
    # CPU smoke constants: only used so predicted-vs-measured numbers in the
    # bench are the right ballpark on the forced-device test meshes.
    "cpu-smoke": ChipSpec("cpu-smoke", 8e9, 10.0, 4.0, 0.05),
}


def default_chip() -> ChipSpec:
    """Chip constants for the CURRENT backend: real TPU generations price as
    tpu-v4 unless overridden; the CPU interpret/smoke backend gets CPU-ish
    constants so bench predictions are comparable to measurements."""
    import jax

    return CHIPS["cpu-smoke"] if jax.default_backend() == "cpu" else CHIPS["tpu-v4"]


@dataclass(frozen=True)
class Workload:
    """What one dispatch looks like, for the cost model.

    ``batch``/``seq`` size the activation collectives (decode: slots x 1
    token; training: tokens per microbatch); ``kv_pool_bytes`` is the LOGICAL
    slot-cache footprint at the live cache dtype (sharded by KV head when
    ``kv_shardable``); ``opt_bytes_per_param`` adds optimizer state to the
    per-chip HBM account (Adam fp32 moments: 8.0; serving: 0)."""

    batch: int = 8
    seq: int = 1
    act_bytes: int = 2
    kv_pool_bytes: float = 0.0
    kv_shardable: bool = True
    opt_bytes_per_param: float = 0.0

    @property
    def is_training(self) -> bool:
        """Optimizer state in the account means a TRAINING dispatch: the step
        reads/writes moments and syncs gradients, both of which the cost model
        then prices (serving dispatches carry neither)."""
        return self.opt_bytes_per_param > 0.0


# --------------------------------------------------------------- plan output
@dataclass
class LeafPlan:
    """One parameter's chosen placement and its modeled contributions."""

    path: str
    shape: Tuple[int, ...]
    nbytes: float
    spec: Tuple
    local_bytes: float
    collective_bytes: float
    role: str  # "column-parallel" | "row-parallel" | "replicated" | ...
    # Optimizer-state placement for this leaf's moments (ZeRO weight-update
    # sharding: may shard along "data" even where the param replicates).
    # Equal to `spec` when the moments simply follow the parameter.
    opt_spec: Tuple = ()
    opt_local_bytes: float = 0.0


@dataclass
class PlanCost:
    """Analytic account of one full plan on one chip of the mesh."""

    per_chip_param_bytes: float
    per_chip_opt_bytes: float
    per_chip_kv_bytes: float
    collective_bytes: float  # ICI bytes per dispatch
    flop_time_s: float
    hbm_time_s: float
    ici_time_s: float
    step_time_s: float
    hbm_overflow_bytes: float

    @property
    def per_chip_total_bytes(self) -> float:
        return self.per_chip_param_bytes + self.per_chip_opt_bytes + self.per_chip_kv_bytes

    @property
    def total(self) -> float:
        """The beam-search objective: dispatch time (compute/HBM/ICI overlap
        as a max on TPU), a small additive bytes+traffic term so strictly
        smaller footprints win ties, and a dominating penalty for plans that
        do not fit per-chip HBM."""
        overflow_penalty = self.hbm_overflow_bytes * 1e3
        return self.step_time_s + 1e-3 * (self.hbm_time_s + self.ici_time_s) + overflow_penalty


@dataclass
class ShardingPlan:
    """The planner's product: a rules table in the shape every existing
    consumer (`spec_for_param`, `derive_tp_param_shardings`) already eats,
    plus the per-leaf placements and the modeled cost behind it."""

    rules: List[Tuple[str, Tuple]]
    leaves: List[LeafPlan]
    cost: PlanCost
    mesh_axes: Dict[str, int]
    chip: ChipSpec
    workload: Workload
    measured_step_s: Optional[float] = None
    #: Optimizer-state rules table, same `(pattern, spec)` shape, consumed by
    #: `derive_opt_state_shardings(..., opt_rules=...)`. Patterns are anchored
    #: `(^|/)` (not `^`) so they match the param path nested inside a moment
    #: path like ``0/mu/<param path>``. Empty when moments follow the params.
    opt_rules: List[Tuple[str, Tuple]] = field(default_factory=list)

    @property
    def leaf_specs(self) -> Dict[str, Tuple]:
        return {leaf.path: leaf.spec for leaf in self.leaves}

    @property
    def leaf_opt_specs(self) -> Dict[str, Tuple]:
        return {leaf.path: leaf.opt_spec for leaf in self.leaves}

    def describe(self) -> str:
        """Human-readable plan: per-leaf specs, the emitted rules table, and
        the predicted per-chip bytes / collective traffic / step time."""
        training = self.workload.is_training
        opt_col = f" {'opt spec':<22}" if training else ""
        lines = [
            f"sharding plan over mesh {self.mesh_axes} (chip model: {self.chip.name})",
            "",
            f"{'parameter':<52} {'shape':<18} {'spec':<22}{opt_col} {'role':<16} {'per-chip':>10}",
        ]
        for leaf in sorted(self.leaves, key=lambda l: l.path):
            opt_cell = f" {str(leaf.opt_spec):<22}" if training else ""
            lines.append(
                f"{leaf.path:<52} {str(tuple(leaf.shape)):<18} "
                f"{str(leaf.spec):<22}{opt_cell} {leaf.role:<16} {_fmt_bytes(leaf.local_bytes):>10}"
            )
        lines.append("")
        lines.append("emitted rules table (first match wins):")
        for pattern, spec in self.rules:
            lines.append(f"  ({pattern!r}, {spec!r})")
        if not self.rules:
            lines.append("  (empty — everything replicates)")
        if self.opt_rules:
            lines.append("")
            lines.append("emitted optimizer-state rules table (ZeRO weight-update sharding):")
            for pattern, spec in self.opt_rules:
                lines.append(f"  ({pattern!r}, {spec!r})")
        cost = self.cost
        lines += [
            "",
            f"predicted per-chip HBM: params {_fmt_bytes(cost.per_chip_param_bytes)}"
            + (f" + opt {_fmt_bytes(cost.per_chip_opt_bytes)}" if cost.per_chip_opt_bytes else "")
            + (f" + kv {_fmt_bytes(cost.per_chip_kv_bytes)}" if cost.per_chip_kv_bytes else "")
            + f" = {_fmt_bytes(cost.per_chip_total_bytes)}",
            f"predicted ICI traffic: {_fmt_bytes(cost.collective_bytes)}/dispatch",
            f"predicted step time: {cost.step_time_s * 1e6:.2f} us "
            f"(flops {cost.flop_time_s * 1e6:.2f} / hbm {cost.hbm_time_s * 1e6:.2f} / "
            f"ici {cost.ici_time_s * 1e6:.2f})",
        ]
        if self.measured_step_s is not None:
            lines.append(f"measured step time: {self.measured_step_s * 1e6:.2f} us")
        if cost.hbm_overflow_bytes:
            lines.append(
                f"WARNING: plan overflows per-chip HBM by {_fmt_bytes(cost.hbm_overflow_bytes)}"
            )
        return "\n".join(lines)

    def to_json(self) -> Dict[str, Any]:
        return {
            "mesh_axes": dict(self.mesh_axes),
            "chip": self.chip.name,
            "rules": [[pattern, list(spec)] for pattern, spec in self.rules],
            "opt_rules": [[pattern, list(spec)] for pattern, spec in self.opt_rules],
            "leaves": [
                {
                    "path": leaf.path,
                    "shape": list(leaf.shape),
                    "spec": list(leaf.spec),
                    "opt_spec": list(leaf.opt_spec),
                    "role": leaf.role,
                    "per_chip_bytes": int(leaf.local_bytes),
                    "opt_per_chip_bytes": int(leaf.opt_local_bytes),
                    "collective_bytes": int(leaf.collective_bytes),
                }
                for leaf in self.leaves
            ],
            "predicted": {
                "per_chip_param_bytes": int(self.cost.per_chip_param_bytes),
                "per_chip_opt_bytes": int(self.cost.per_chip_opt_bytes),
                "per_chip_kv_bytes": int(self.cost.per_chip_kv_bytes),
                "collective_bytes_per_dispatch": int(self.cost.collective_bytes),
                "step_time_s": self.cost.step_time_s,
                "hbm_overflow_bytes": int(self.cost.hbm_overflow_bytes),
            },
            "measured_step_s": self.measured_step_s,
        }


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024.0 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GB"


# ----------------------------------------------------------- leaf harvesting
@dataclass
class _Leaf:
    path: str
    shape: Tuple[int, ...]
    nbytes: float
    elems: float


def _harvest_leaves(params, weight_dtype: str = "bf16") -> List[_Leaf]:
    """Flatten a params tree (arrays or ShapeDtypeStructs) into planner
    leaves. ``weight_dtype="int8"`` prices every floating 2-D ``kernel`` leaf
    at its POST-quantization footprint (int8 entries + fp32 per-output-channel
    scales, `ops/quantization.quantize_params_int8`), so predicted per-chip
    bytes track what the engine actually stores."""
    from .sharding import tree_paths_and_leaves

    flat, _ = tree_paths_and_leaves(params)
    leaves = []
    for path, leaf in flat:
        shape = tuple(int(d) for d in getattr(leaf, "shape", np.shape(leaf)))
        dtype = np.dtype(getattr(leaf, "dtype", np.float32))
        elems = float(np.prod(shape)) if shape else 1.0
        nbytes = elems * dtype.itemsize
        if (
            weight_dtype == "int8"
            and path.rsplit("/", 1)[-1] == "kernel"
            and len(shape) >= 2
            and np.issubdtype(dtype, np.floating)
        ):
            nbytes = elems * 1 + shape[-1] * 4  # int8 entries + fp32 scales
        leaves.append(_Leaf(path=path, shape=shape, nbytes=nbytes, elems=elems))
    return leaves


def _axis_sizes(mesh) -> Dict[str, int]:
    """Axis-name -> size for a real `jax.sharding.Mesh` OR a plain dict — the
    planner itself is pure shape arithmetic, so `accelerate-tpu plan` can
    search a 64-chip layout from a laptop with ``mesh={"model": 64}``."""
    if isinstance(mesh, dict):
        return {name: int(size) for name, size in mesh.items()}
    return {name: int(size) for name, size in dict(mesh.shape).items()}


# ----------------------------------------------------------- candidate space
def candidate_specs(path: str, shape: Sequence[int], mesh, axes: Sequence[str] = ("model",)):
    """All legal PartitionSpec tuples for one leaf: replicate, plus each
    single-axis placement on a divisible dim (column-parallel = last dim,
    row-parallel = first dim, and interior dims for stacked/conv weights).
    Divisibility-filtered with the same rule `_check_tp_divisible` enforces at
    placement time — a candidate this function returns can never hit the
    indivisible-rule hard error. 1-D leaves (norm scales, biases) only ever
    replicate: sharding them saves nothing and un-replicates the residual
    stream."""
    shape = tuple(int(d) for d in shape)
    cands: List[Tuple] = [()]
    if len(shape) < 2:
        return cands
    sizes = _axis_sizes(mesh)
    for axis in axes:
        n = sizes.get(axis, 1)
        if n <= 1:
            continue
        for dim, d in enumerate(shape):
            if d % n == 0 and d >= n:
                # Full-rank specs, trailing Nones KEPT: a row-parallel kernel
                # must emit (axis, None) — not (axis,) — because the
                # quantized-entry contract reads the rule's LAST entry as the
                # kernel's output axis (derive_tp_param_shardings: a
                # row-parallel kernel's per-output-channel scales replicate).
                spec = [None] * len(shape)
                spec[dim] = axis
                cand = tuple(spec)
                if cand not in cands:
                    cands.append(cand)
    return cands


def _spec_shard_factor(spec: Tuple, sizes: Dict[str, int]) -> int:
    factor = 1
    for entry in spec:
        if entry is None:
            continue
        parts = (entry,) if isinstance(entry, str) else tuple(entry)
        for axis in parts:
            factor *= sizes.get(axis, 1)
    return factor


# --------------------------------------------------------------- collectives
def _allreduce_bytes(payload: float, n: int) -> float:
    """Ring all-reduce wire bytes per chip: 2 (N-1)/N x payload."""
    return 2.0 * (n - 1) / n * payload if n > 1 else 0.0


def _allgather_bytes(payload: float, n: int) -> float:
    """Ring all-gather wire bytes per chip: (N-1)/N x payload."""
    return float(n - 1) / n * payload if n > 1 else 0.0


# --------------------------------------------------- structure (chains/roles)
#: Conventional output-projection names: the row-parallel end of a Megatron
#: column->row chain when shapes alone can't disambiguate (square attention
#: projections). Matched against the MODULE component of the path.
_OUT_PROJ_HINTS = (
    "wo",
    "w_down",
    "out_proj",
    "o_proj",
    "down_proj",
    "dense_4h_to_h",
    "fc_out",
    "fc2",
    "proj_out",
)

#: Input-side projections for the same convention (column-parallel end).
_IN_PROJ_HINTS = (
    "wq",
    "wk",
    "wv",
    "w_gate",
    "w_up",
    "q_proj",
    "k_proj",
    "v_proj",
    "query",
    "key",
    "value",
    "gate_proj",
    "up_proj",
    "dense_h_to_4h",
    "fc_in",
    "fc1",
)


def _module_name(path: str) -> str:
    parts = path.split("/")
    return parts[-2] if len(parts) >= 2 else parts[-1]


def _block_prefix(path: str) -> str:
    parts = path.split("/")
    return "/".join(parts[:-2]) if len(parts) >= 3 else ""


def _infer_hidden(leaves: Sequence[_Leaf]) -> Optional[int]:
    """The residual-stream width: the most common dimension across 2-D matmul
    kernels (it appears in every projection that reads or writes the
    residual)."""
    counts: Counter = Counter()
    for leaf in leaves:
        if len(leaf.shape) == 2 and leaf.path.rsplit("/", 1)[-1] == "kernel":
            counts.update(leaf.shape)
    if not counts:
        return None
    return counts.most_common(1)[0][0]


@dataclass
class _Cand:
    """One candidate for a group decision. ``opt_specs`` is the optimizer-state
    placement per leaf — ``None`` means the moments simply follow the param
    spec; a dict means the planner chose a distinct moment layout (ZeRO
    weight-update sharding along the data axis)."""

    label: str
    specs: Dict[str, Tuple]
    coll: float
    opt_specs: Optional[Dict[str, Tuple]] = None

    def opt_spec(self, path: str) -> Tuple:
        if self.opt_specs is not None:
            return self.opt_specs[path]
        return self.specs[path]


def _as_cand(candidate) -> _Cand:
    """Group builders construct plain (label, specs, coll) tuples; normalize
    them at the search boundary so opt-state-aware candidates and legacy
    3-tuples coexist."""
    if isinstance(candidate, _Cand):
        return candidate
    label, specs, coll = candidate
    return _Cand(label=label, specs=specs, coll=coll)


@dataclass
class _Group:
    """One beam-search decision: a Megatron chain (column producers + the row
    output projection), a lone matmul/embedding, or an unknown-role weight.
    ``candidates`` are (label, {path: spec}, collective_bytes) options (or
    `_Cand` objects once the training expansion has run)."""

    key: str
    leaves: List[_Leaf]
    candidates: List = field(default_factory=list)


def _build_groups(
    leaves: Sequence[_Leaf],
    mesh,
    axis: str,
    workload: Workload,
) -> List[_Group]:
    """Carve the parameter tree into independent decisions for the "model"
    axis: per-block Megatron chains, loner matmuls (lm_head), embedding
    tables, and conservative unknowns."""
    sizes = _axis_sizes(mesh)
    n = sizes.get(axis, 1)
    hidden = _infer_hidden(leaves)

    kernels_2d = [
        l for l in leaves if len(l.shape) == 2 and l.path.rsplit("/", 1)[-1] == "kernel"
    ]
    embeddings = [
        l for l in leaves if l.path.rsplit("/", 1)[-1] == "embedding" and len(l.shape) == 2
    ]
    known = {l.path for l in kernels_2d} | {l.path for l in embeddings}
    others = [l for l in leaves if l.path not in known]

    groups: List[_Group] = []
    by_block: Dict[str, List[_Leaf]] = {}
    for leaf in kernels_2d:
        by_block.setdefault(_block_prefix(leaf.path), []).append(leaf)

    loners: List[_Leaf] = []
    for block, members in sorted(by_block.items()):
        members = sorted(members, key=lambda l: l.path)
        out_proj = _pick_out_proj(members, hidden)
        if out_proj is None or len(members) < 2:
            loners.extend(members)
            continue
        columns = [l for l in members if l.path != out_proj.path]
        # Chain legality: every member divisible on its chain dim.
        legal = out_proj.shape[0] % n == 0 and all(c.shape[-1] % n == 0 for c in columns)
        cands: List[Tuple[str, Dict[str, Tuple], float]] = [
            ("replicate", {l.path: () for l in members}, 0.0)
        ]
        if n > 1 and legal:
            specs = {c.path: (None, axis) for c in columns}
            # (axis, None), full rank: the trailing None is load-bearing —
            # the quantized-scale derivation reads the rule's LAST entry as
            # the output axis, and a row-parallel kernel's scales replicate.
            specs[out_proj.path] = (axis, None)
            # One all-reduce of the block's residual write per dispatch: the
            # column outputs flow into the row contraction sharded, the row
            # output is partial-summed across the axis.
            residual_bytes = float(
                workload.batch * workload.seq * out_proj.shape[-1] * workload.act_bytes
            )
            cands.append(("megatron", specs, _allreduce_bytes(residual_bytes, n)))
        groups.append(_Group(key=f"chain:{block}", leaves=members, candidates=cands))

    for leaf in loners + embeddings:
        groups.append(_loner_group(leaf, mesh, axis, workload, hidden))

    for leaf in others:
        groups.append(_unknown_group(leaf, mesh, axis))
    return groups


def _pick_out_proj(members: List[_Leaf], hidden: Optional[int]) -> Optional[_Leaf]:
    """The block's row-parallel end: a kernel writing the residual (dout ==
    hidden) whose INPUT is another member's output. Structural match first
    (din != hidden pins it uniquely — MLP down-projections); the conventional
    name hints break the tie for square attention projections. None when the
    block has no recognizable chain — those weights are planned as loners."""
    if hidden is None:
        return None
    douts = {l.shape[-1] for l in members}
    structural = [
        l
        for l in members
        if l.shape[-1] == hidden and l.shape[0] != hidden and l.shape[0] in douts
    ]
    if len(structural) == 1:
        return structural[0]
    hinted = [
        l
        for l in members
        if l.shape[-1] == hidden
        and _module_name(l.path) in _OUT_PROJ_HINTS
        and l.shape[0] in douts
    ]
    if len(hinted) == 1 and all(
        _module_name(l.path) in _IN_PROJ_HINTS for l in members if l.path != hinted[0].path
    ):
        return hinted[0]
    return None


def _loner_group(leaf: _Leaf, mesh, axis: str, workload: Workload, hidden: Optional[int]) -> _Group:
    """A matmul/embedding with no chain partner. Column-parallel replays its
    output through an all-gather (the consumer reads replicated); row-parallel
    partial-sums through an all-reduce; an embedding GATHER sharded on the
    vocab dim all-reduces the masked lookup, sharded on the feature dim it
    all-gathers the rows."""
    sizes = _axis_sizes(mesh)
    n = sizes.get(axis, 1)
    tokens = float(workload.batch * workload.seq)
    is_embedding = leaf.path.rsplit("/", 1)[-1] == "embedding"
    cands: List[Tuple[str, Dict[str, Tuple], float]] = [("replicate", {leaf.path: ()}, 0.0)]
    if n > 1 and len(leaf.shape) == 2:
        din, dout = leaf.shape
        out_bytes = tokens * dout * workload.act_bytes
        if is_embedding:
            # [vocab, features]: dim 0 = gather dim, dim 1 = row features.
            feat_bytes = tokens * dout * workload.act_bytes
            if din % n == 0:
                cands.append(
                    ("row-parallel", {leaf.path: (axis, None)}, _allreduce_bytes(feat_bytes, n))
                )
            if dout % n == 0:
                cands.append(
                    ("column-parallel", {leaf.path: (None, axis)}, _allgather_bytes(feat_bytes, n))
                )
        else:
            if dout % n == 0:
                cands.append(
                    ("column-parallel", {leaf.path: (None, axis)}, _allgather_bytes(out_bytes, n))
                )
            if din % n == 0:
                cands.append(
                    ("row-parallel", {leaf.path: (axis, None)}, _allreduce_bytes(out_bytes, n))
                )
    return _Group(key=f"loner:{leaf.path}", leaves=[leaf], candidates=cands)


def _unknown_group(leaf: _Leaf, mesh, axis: str) -> _Group:
    """A weight the planner can't place in a dataflow role (conv filters,
    stacked expert tensors, 1-D scales). Sharding it is costed as one
    all-gather of the weight itself per dispatch — the GSPMD worst case for a
    replicated-activation read — so these replicate unless they are so large
    that even re-gathering beats holding N copies."""
    sizes = _axis_sizes(mesh)
    n = sizes.get(axis, 1)
    cands: List[Tuple[str, Dict[str, Tuple], float]] = [("replicate", {leaf.path: ()}, 0.0)]
    if n > 1 and len(leaf.shape) >= 2:
        dims = sorted(
            (d for d, size in enumerate(leaf.shape) if size % n == 0 and size >= n),
            key=lambda d: -leaf.shape[d],
        )
        if dims:
            dim = dims[0]
            spec = [None] * len(leaf.shape)
            spec[dim] = axis
            cands.append(("sharded-regather", {leaf.path: tuple(spec)}, _allgather_bytes(leaf.nbytes, n)))
    return _Group(key=f"unknown:{leaf.path}", leaves=[leaf], candidates=cands)


def _fsdp_groups(leaves: Sequence[_Leaf], mesh, workload: Workload) -> List[_Group]:
    """Per-leaf ZeRO-3 decisions on the "fsdp" axis: keep a full replica and
    all-reduce gradients, or shard the storage (params + moments 1/N) and
    pay per-step all-gathers (fwd + bwd) plus the reduce-scatter — the
    weight-update-sharding account from PAPERS.md."""
    from .sharding import _fsdp_dim

    sizes = _axis_sizes(mesh)
    n = sizes.get("fsdp", 1)
    groups = []
    for leaf in leaves:
        cands: List[Tuple[str, Dict[str, Tuple], float]] = [
            ("replicate", {leaf.path: ()}, _allreduce_bytes(leaf.nbytes, n))
        ]
        dim = _fsdp_dim(leaf.path, leaf.shape, n, set())
        if n > 1 and dim is not None:
            spec = [None] * len(leaf.shape)
            spec[dim] = "fsdp"
            cands.append(
                ("fsdp", {leaf.path: tuple(spec)}, 3.0 * _allgather_bytes(leaf.nbytes, n))
            )
        groups.append(_Group(key=f"fsdp:{leaf.path}", leaves=[leaf], candidates=cands))
    return groups


# ----------------------------------------------------- ZeRO (training) axis
#: Moments smaller than this replicate regardless: sharding a norm scale's
#: Adam state saves a few hundred bytes and costs a scattered layout. Smaller
#: than sharding._SMALL_PARAM_DEFAULT on purpose — the CPU test tier plans
#: tiny models whose kernels must still exercise the ZeRO path.
_ZERO_MIN_ELEMS = 1024


def _zero_opt_spec(
    path: str, shape: Tuple[int, ...], param_spec: Tuple, sizes: Dict[str, int], zero_axis: str
) -> Optional[Tuple]:
    """Extend a param spec with ``zero_axis`` for the MOMENT placement: grow an
    already-sharded dim when the finer grid still divides (keeps the moment
    shard nested inside the param shard), else take the same free dim
    `spec_for_param`'s fsdp extension would pick. Full-rank tuple (trailing
    Nones kept, planner canon); None when no dim divides."""
    from .sharding import _fsdp_dim

    n = sizes.get(zero_axis, 1)
    if n <= 1 or not shape:
        return None
    spec = list(param_spec) + [None] * (len(shape) - len(param_spec))
    taken = {i for i, s in enumerate(spec) if s is not None}
    for i in sorted(taken, reverse=True):
        axes = (spec[i],) if isinstance(spec[i], str) else tuple(spec[i])
        group = n * int(np.prod([sizes.get(a, 1) for a in axes]))
        if shape[i] % group == 0 and shape[i] >= group:
            spec[i] = tuple(axes) + (zero_axis,)
            return tuple(spec)
    dim = _fsdp_dim(path, shape, n, taken)
    if dim is None:
        return None
    spec[dim] = zero_axis
    return tuple(spec)


def _train_extend_candidates(
    group: _Group, sizes: Dict[str, int], workload: Workload, zero_axis: Optional[str]
) -> None:
    """Rewrite a group's candidates for a TRAINING mesh with a data axis:

    - every candidate is charged the per-step gradient synchronization over
      "data" (an all-reduce of the leaf's local gradient — grads carry the
      param sharding, so the payload is the param's per-chip bytes);
    - each candidate gains a "+zero" twin whose optimizer moments additionally
      shard along the data axis. The ZeRO update's reduce-scatter + updated-
      param all-gather moves exactly the same wire bytes as the plain grad
      all-reduce (2(N-1)/N each), so the ICI term is UNCHANGED — the twin wins
      purely on per-chip HBM, which is the Xu et al. weight-update-sharding
      account.
    """
    data_n = sizes.get("data", 1)
    out: List[_Cand] = []
    for candidate in group.candidates:
        cand = _as_cand(candidate)
        grad_sync = 0.0
        if data_n > 1 and workload.is_training:
            for leaf in group.leaves:
                local = leaf.nbytes / _spec_shard_factor(cand.specs[leaf.path], sizes)
                grad_sync += _allreduce_bytes(local, data_n)
        base = _Cand(cand.label, cand.specs, cand.coll + grad_sync, cand.opt_specs)
        out.append(base)
        if zero_axis is None:
            continue
        opt_specs: Dict[str, Tuple] = {}
        changed = False
        for leaf in group.leaves:
            pspec = cand.specs[leaf.path]
            zspec = None
            if leaf.elems >= _ZERO_MIN_ELEMS:
                zspec = _zero_opt_spec(leaf.path, leaf.shape, pspec, sizes, zero_axis)
            if zspec is not None and zspec != tuple(pspec):
                opt_specs[leaf.path] = zspec
                changed = True
            else:
                opt_specs[leaf.path] = pspec
        if changed:
            out.append(_Cand(base.label + "+zero", cand.specs, base.coll, opt_specs))
    group.candidates = out


# --------------------------------------------------------------- beam search
def _score(
    local_param_bytes: float,
    local_elems: float,
    ici_bytes: float,
    chip: ChipSpec,
    workload: Workload,
    kv_factor: int,
    local_opt_bytes: Optional[float] = None,
) -> PlanCost:
    per_chip_kv = workload.kv_pool_bytes / max(kv_factor, 1)
    # Spec-DEPENDENT optimizer-state account: the beam search passes the bytes
    # implied by each candidate's moment placement (ZeRO shards may divide the
    # data axis where the param replicates). The None default prices moments
    # as following the param sharding — the pre-2D behavior, and what a rules
    # table without an opt-rules twin actually places.
    per_chip_opt = (
        local_opt_bytes if local_opt_bytes is not None
        else local_elems * workload.opt_bytes_per_param
    )
    flop_time = 2.0 * local_elems * workload.batch * workload.seq / (chip.tflops * 1e12)
    # A training step reads AND writes the moments next to the params; serving
    # dispatches (opt == 0) price exactly as before.
    hbm_time = (local_param_bytes + per_chip_kv + per_chip_opt) / (chip.hbm_gbps * 1e9)
    ici_time = ici_bytes / (chip.ici_gbps * 1e9)
    step = max(flop_time, hbm_time, ici_time)
    total_bytes = local_param_bytes + per_chip_opt + per_chip_kv
    overflow = max(0.0, total_bytes - chip.hbm_bytes)
    return PlanCost(
        per_chip_param_bytes=local_param_bytes,
        per_chip_opt_bytes=per_chip_opt,
        per_chip_kv_bytes=per_chip_kv,
        collective_bytes=ici_bytes,
        flop_time_s=flop_time,
        hbm_time_s=hbm_time,
        ici_time_s=ici_time,
        step_time_s=step,
        hbm_overflow_bytes=overflow,
    )


@dataclass
class _Partial:
    choices: Tuple[int, ...]
    local_bytes: float
    local_elems: float
    ici_bytes: float
    local_opt_bytes: float = 0.0


def _beam_search(
    groups: List[_Group],
    sizes: Dict[str, int],
    chip: ChipSpec,
    workload: Workload,
    kv_factor: int,
    beam_width: int,
    top_k: int,
) -> List[Tuple[Dict[str, Tuple], Dict[str, Tuple], Dict[str, str], float, PlanCost]]:
    """Beam over group decisions (largest groups first so early pruning sees
    the decisions that matter). Returns up to ``top_k`` distinct complete
    (param assignment, opt assignment, roles, ici, cost) tuples ranked by
    modeled cost."""
    for group in groups:
        group.candidates = [_as_cand(c) for c in group.candidates]
    order = sorted(range(len(groups)), key=lambda i: -sum(l.nbytes for l in groups[i].leaves))
    beam = [_Partial(choices=(), local_bytes=0.0, local_elems=0.0, ici_bytes=0.0)]
    opt_bpp = workload.opt_bytes_per_param
    for gi in order:
        group = groups[gi]
        nxt: List[_Partial] = []
        for partial in beam:
            for ci, cand in enumerate(group.candidates):
                add_bytes = 0.0
                add_elems = 0.0
                add_opt = 0.0
                for leaf in group.leaves:
                    factor = _spec_shard_factor(cand.specs[leaf.path], sizes)
                    add_bytes += leaf.nbytes / factor
                    add_elems += leaf.elems / factor
                    if opt_bpp:
                        opt_factor = _spec_shard_factor(cand.opt_spec(leaf.path), sizes)
                        add_opt += leaf.elems * opt_bpp / opt_factor
                nxt.append(
                    _Partial(
                        choices=partial.choices + (ci,),
                        local_bytes=partial.local_bytes + add_bytes,
                        local_elems=partial.local_elems + add_elems,
                        ici_bytes=partial.ici_bytes + cand.coll,
                        local_opt_bytes=partial.local_opt_bytes + add_opt,
                    )
                )
        nxt.sort(
            key=lambda p: _score(
                p.local_bytes, p.local_elems, p.ici_bytes, chip, workload, kv_factor,
                local_opt_bytes=p.local_opt_bytes if opt_bpp else None,
            ).total
        )
        beam = nxt[: max(beam_width, top_k)]

    results = []
    seen = set()
    for partial in beam:
        assignment: Dict[str, Tuple] = {}
        opt_assignment: Dict[str, Tuple] = {}
        roles: Dict[str, str] = {}
        for pos, gi in enumerate(order):
            cand = groups[gi].candidates[partial.choices[pos]]
            for leaf in groups[gi].leaves:
                spec = cand.specs[leaf.path]
                opt_spec = cand.opt_spec(leaf.path)
                assignment[leaf.path] = spec
                opt_assignment[leaf.path] = opt_spec
                if spec:
                    roles[leaf.path] = cand.label
                elif opt_spec and opt_spec != tuple(spec):
                    roles[leaf.path] = "zero-opt"
                else:
                    roles[leaf.path] = "replicated"
        key = tuple(sorted(assignment.items())) + tuple(sorted(opt_assignment.items()))
        if key in seen:
            continue
        seen.add(key)
        cost = _score(
            partial.local_bytes, partial.local_elems, partial.ici_bytes, chip, workload,
            kv_factor, local_opt_bytes=partial.local_opt_bytes if opt_bpp else None,
        )
        results.append((assignment, opt_assignment, roles, partial.ici_bytes, cost))
        if len(results) >= top_k:
            break
    return results


# ------------------------------------------------------------- rule emission
#: Suffix components that are storage details of a leaf, not module identity:
#: patterns anchor on the MODULE component so quantized {"q","scale"} entries
#: keep riding their kernel's rule (`derive_tp_param_shardings` contract).
def _rule_suffix(path: str) -> str:
    parts = path.split("/")
    return "/".join(parts[-2:]) if len(parts) >= 2 else path


def emit_rules(assignment: Dict[str, Tuple], path_anchor: str = "^") -> List[Tuple[str, Tuple]]:
    """Collapse per-leaf spec choices into a `(pattern, spec)` table in the
    exact shape `spec_for_param` / `derive_tp_param_shardings` consume.

    Sharded leaves group by their last-two-component suffix (``wq/kernel``)
    when every leaf sharing that suffix agrees on the spec — the emitted
    pattern ``(^|/)wq/kernel(/|$)`` then also covers the quantized
    ``.../kernel/q`` / ``.../kernel/scale`` entries, exactly like the hand
    tables. Conflicting suffixes fall back to full-path anchored rules,
    emitted FIRST so first-match-wins keeps them authoritative. Replicated
    leaves need no rule: unmatched leaves replicate by construction.

    ``path_anchor`` is the full-path rules' start anchor: the default ``^``
    for param tables; optimizer-state tables pass ``(^|/)`` so the pattern
    still matches the param path nested inside a moment path (``0/mu/<path>``)."""
    by_suffix: Dict[str, Dict[str, Tuple]] = {}
    for path, spec in assignment.items():
        by_suffix.setdefault(_rule_suffix(path), {})[path] = spec

    exact: List[Tuple[str, Tuple]] = []
    grouped: List[Tuple[str, Tuple]] = []
    for suffix in sorted(by_suffix):
        specs = by_suffix[suffix]
        chosen = set(specs.values())
        sharded = {p: s for p, s in specs.items() if any(e is not None for e in s)}
        if not sharded:
            continue
        if len(chosen) == 1:
            grouped.append((f"(^|/){re.escape(suffix)}(/|$)", next(iter(chosen))))
        else:
            for path in sorted(sharded):
                exact.append((f"{path_anchor}{re.escape(path)}(/|$)", sharded[path]))
    return exact + grouped


# ------------------------------------------------------------------ planning
def plan_sharding(
    params,
    mesh,
    *,
    axes: Optional[Sequence[str]] = None,
    chip: Optional[ChipSpec] = None,
    workload: Optional[Workload] = None,
    weight_dtype: str = "bf16",
    beam_width: int = 8,
    top_k: int = 1,
):
    """Search a sharding strategy for ``params`` on ``mesh``.

    Returns the best `ShardingPlan` (or the ranked top-k list when
    ``top_k > 1`` — feed those to `refine_plans` for measure-and-refine).
    ``axes`` defaults to every supported mesh axis with size > 1: "model"
    gets the Megatron chain/loner dataflow model, "fsdp" the ZeRO-3
    storage-vs-regather account, and "data" (with a TRAINING workload, i.e.
    ``opt_bytes_per_param > 0``) the ZeRO weight-update-sharding account —
    per-leaf optimizer-moment placement along the data axis, priced
    spec-dependently in HBM while the grad-sync ICI bytes stay those of the
    plain all-reduce (reduce-scatter + all-gather moves the same wire bytes).
    `params` may be real arrays or `ShapeDtypeStruct`s (`jax.eval_shape`) —
    the planner only reads shapes and dtypes.

    Binding semantics: sharded decisions bind everywhere (an emitted rule
    always wins in `spec_for_param`); REPLICATE decisions bind except where
    an `fsdp_plugin` explicitly requests parameter sharding — the deriver's
    fsdp policy governs rule-unmatched leaves, which is why the Accelerator
    seam plans ``axes=("model",)`` and leaves ZeRO to the plugin the user
    configured. Plan the "fsdp" axis directly only for plugin-free placement
    (rules consumed on their own)."""
    if isinstance(chip, str):
        chip = CHIPS[chip]
    chip = chip or default_chip()
    workload = workload or Workload()
    sizes = _axis_sizes(mesh)
    if axes is None:
        axes = [a for a in ("data", "model", "fsdp") if sizes.get(a, 1) > 1]

    leaves = _harvest_leaves(params, weight_dtype=weight_dtype)
    groups: List[_Group] = []
    if "model" in axes:
        groups += _build_groups(leaves, mesh, "model", workload)
    if "fsdp" in axes and "model" not in axes:
        groups += _fsdp_groups(leaves, mesh, workload)
    elif "fsdp" in axes:
        # Megatron + ZeRO composition rides the existing spec_for_param
        # extension (the rule's dim grows ("model","fsdp")) — the planner
        # decides the model-axis layout and leaves the fsdp extension to the
        # deriver rather than double-counting it here.
        pass
    if not groups:
        groups = [_Group(key=f"leaf:{l.path}", leaves=[l], candidates=[("replicate", {l.path: ()}, 0.0)]) for l in leaves]

    # Training meshes with a data axis: charge every candidate the grad sync
    # and enumerate the ZeRO optimizer-state twin (moments sharded over
    # "data" even where params replicate).
    if "data" in axes and sizes.get("data", 1) > 1 and workload.is_training:
        for group in groups:
            _train_extend_candidates(group, sizes, workload, zero_axis="data")

    kv_factor = sizes.get("model", 1) if workload.kv_shardable else 1
    ranked = _beam_search(groups, sizes, chip, workload, kv_factor, beam_width, top_k)

    opt_bpp = workload.opt_bytes_per_param
    plans = []
    for assignment, opt_assignment, roles, ici_bytes, cost in ranked:
        leaf_plans = [
            LeafPlan(
                path=leaf.path,
                shape=leaf.shape,
                nbytes=leaf.nbytes,
                spec=assignment[leaf.path],
                local_bytes=leaf.nbytes / _spec_shard_factor(assignment[leaf.path], sizes),
                collective_bytes=0.0,
                role=roles[leaf.path],
                opt_spec=opt_assignment[leaf.path],
                opt_local_bytes=(
                    leaf.elems * opt_bpp
                    / _spec_shard_factor(opt_assignment[leaf.path], sizes)
                ),
            )
            for leaf in leaves
        ]
        # The opt-rules table covers EVERY sharded moment (including the ones
        # that just follow a sharded param): derive_opt_state_shardings treats
        # it as authoritative when present, so an omitted follow-the-param
        # rule would silently replicate that moment and reshard every step.
        opt_rules = (
            emit_rules(opt_assignment, path_anchor="(^|/)")
            if any(opt_assignment[l.path] != assignment[l.path] for l in leaves)
            else []
        )
        plans.append(
            ShardingPlan(
                rules=emit_rules(assignment),
                leaves=leaf_plans,
                cost=cost,
                mesh_axes=sizes,
                chip=chip,
                workload=workload,
                opt_rules=opt_rules,
            )
        )
    if not plans:
        raise ValueError("planner produced no candidate plans (empty params tree?)")
    return plans[0] if top_k == 1 else plans


def score_rules(
    params,
    mesh,
    rules: Sequence[Tuple[str, Tuple]],
    *,
    chip: Optional[ChipSpec] = None,
    workload: Optional[Workload] = None,
    weight_dtype: str = "bf16",
) -> ShardingPlan:
    """Price an EXISTING rules table (e.g. a hand-written family table) with
    the same cost model the planner uses — the apples-to-apples comparison
    behind `accelerate-tpu plan --against-rules` and the planner-vs-hand
    bench A/B. Collective bytes are modeled by re-deriving each rule-matched
    leaf's role through the planner's group structure."""
    if isinstance(chip, str):
        chip = CHIPS[chip]
    chip = chip or default_chip()
    workload = workload or Workload()
    sizes = _axis_sizes(mesh)
    leaves = _harvest_leaves(params, weight_dtype=weight_dtype)

    assignment: Dict[str, Tuple] = {}
    for leaf in leaves:
        spec: Tuple = ()
        for pattern, rule_spec in rules or []:
            if re.search(pattern, leaf.path):
                # Normalize to the planner's full-rank canonical form so hand
                # rules like ("model",) and ("model", None) price identically.
                padded = tuple(rule_spec)[: len(leaf.shape)]
                padded = padded + (None,) * (len(leaf.shape) - len(padded))
                spec = () if all(e is None for e in padded) else padded
                break
        assignment[leaf.path] = spec

    # Reuse the group construction to price collectives for this assignment:
    # each group contributes the candidate whose specs match the assignment,
    # or a conservative regather when the assignment is not one the model
    # recognizes.
    groups = _build_groups(leaves, mesh, "model", workload)
    ici_bytes = 0.0
    roles: Dict[str, str] = {p: "replicated" for p in assignment}
    local_bytes = 0.0
    local_elems = 0.0
    for leaf in leaves:
        factor = _spec_shard_factor(assignment[leaf.path], sizes)
        local_bytes += leaf.nbytes / factor
        local_elems += leaf.elems / factor
    for group in groups:
        matched = None
        for candidate in group.candidates:
            cand = _as_cand(candidate)
            if all(assignment.get(p, ()) == s for p, s in cand.specs.items()):
                matched = (cand.label, cand.coll)
                break
        if matched is None:
            # Off-model assignment: conservative regather of each sharded leaf.
            coll = sum(
                _allgather_bytes(l.nbytes, _spec_shard_factor(assignment[l.path], sizes))
                for l in group.leaves
                if assignment[l.path]
            )
            matched = ("off-model", coll)
        label, coll = matched
        ici_bytes += coll
        for leaf in group.leaves:
            roles[leaf.path] = label if assignment[leaf.path] else "replicated"

    # Training dispatches sync gradients over "data" — price the hand table's
    # all-reduce the same way _train_extend_candidates prices the planner's
    # candidates, or the comparison silently favors whichever side skipped it.
    data_n = sizes.get("data", 1)
    if data_n > 1 and workload.is_training:
        for leaf in leaves:
            local = leaf.nbytes / _spec_shard_factor(assignment[leaf.path], sizes)
            ici_bytes += _allreduce_bytes(local, data_n)

    kv_factor = sizes.get("model", 1) if workload.kv_shardable else 1
    cost = _score(local_bytes, local_elems, ici_bytes, chip, workload, kv_factor)
    leaf_plans = [
        LeafPlan(
            path=leaf.path,
            shape=leaf.shape,
            nbytes=leaf.nbytes,
            spec=assignment[leaf.path],
            local_bytes=leaf.nbytes / _spec_shard_factor(assignment[leaf.path], sizes),
            collective_bytes=0.0,
            role=roles[leaf.path],
            # A bare rules table carries no opt-state twin: moments follow the
            # param placement, which is how _score priced them above.
            opt_spec=assignment[leaf.path],
            opt_local_bytes=(
                leaf.elems * workload.opt_bytes_per_param
                / _spec_shard_factor(assignment[leaf.path], sizes)
            ),
        )
        for leaf in leaves
    ]
    return ShardingPlan(
        rules=list(rules or []),
        leaves=leaf_plans,
        cost=cost,
        mesh_axes=sizes,
        chip=chip,
        workload=workload,
    )


# ------------------------------------------------------------------- serving
def plan_serving_sharding(
    params,
    mesh,
    config,
    *,
    num_slots: int,
    padded_length: int,
    paged: bool,
    page_size: int = 0,
    num_pages: int = 0,
    kv_cache_dtype: str = "bf16",
    weight_dtype: str = "bf16",
    chip: Optional[ChipSpec] = None,
    beam_width: int = 8,
    top_k: int = 1,
):
    """Plan the tensor-parallel decode layout for a serving engine: the
    "model"-axis search over the params tree with the engine's KV pool priced
    into per-chip HBM at the LIVE cache dtype (quantized pools add their
    per-page-per-head scale arrays). This is what
    ``ContinuousBatcher(tp=N, sharding_rules="auto")`` calls."""
    kv_heads = getattr(config, "num_key_value_heads", None) or config.num_attention_heads
    head_dim = getattr(config, "head_dim", None) or (
        config.hidden_size // config.num_attention_heads
    )
    layers = config.num_hidden_layers
    kv_bytes_per_elem = {"bf16": 2.0, "int8": 1.0, "fp8_e4m3": 1.0}.get(kv_cache_dtype, 2.0)
    if paged:
        kv_elems = 2.0 * layers * num_pages * page_size * kv_heads * head_dim
        scale_bytes = (
            2.0 * layers * num_pages * kv_heads * 4.0 if kv_cache_dtype != "bf16" else 0.0
        )
    else:
        kv_elems = 2.0 * layers * num_slots * padded_length * kv_heads * head_dim
        scale_bytes = 0.0
    workload = Workload(
        batch=num_slots,
        seq=1,
        act_bytes=2,
        kv_pool_bytes=kv_elems * kv_bytes_per_elem + scale_bytes,
        kv_shardable=kv_heads % max(_axis_sizes(mesh).get("model", 1), 1) == 0,
        opt_bytes_per_param=0.0,
    )
    return plan_sharding(
        params,
        mesh,
        axes=("model",),
        chip=chip,
        workload=workload,
        weight_dtype=weight_dtype,
        beam_width=beam_width,
        top_k=top_k,
    )


# ------------------------------------------------------------------ training
def plan_train_sharding(
    params,
    mesh,
    *,
    batch: int,
    seq: int,
    act_bytes: int = 2,
    opt_bytes_per_param: float = 8.0,
    weight_dtype: str = "bf16",
    chip: Optional[ChipSpec] = None,
    beam_width: int = 8,
    top_k: int = 1,
    layered_split=None,
    num_microbatches: Optional[int] = None,
):
    """Plan the training layout for ``mesh``.

    On a 2D ("data", "model") mesh: the params tree searched over both axes
    with gradient all-reduce priced per candidate and a ZeRO-style twin per
    candidate whose optimizer moments shard along "data" even where the params
    replicate (Xu et al.: reduce-scatter + all-gather moves the same ICI bytes
    as the all-reduce, so the twin wins purely on per-chip HBM). This is what
    ``Accelerator.prepare(sharding_rules="auto")`` calls on a training mesh.

    On a mesh with a "pipeline" axis of size > 1: dispatches to
    `plan_mpmd_train_sharding` — per-stage 2D plans over the pipeline
    submeshes plus the pipeline-bubble step-time term — and returns an
    `MPMDTrainPlan`. The pipeline route needs ``layered_split`` (the model's
    ``LayeredApply.split(params)`` output: ``(prelude, layers, tail)``) so the
    plan's per-stage rules tables are emitted against the exact stage-tree
    paths the MPMD runtime places (`build_stage_tree`)."""
    sizes = _axis_sizes(mesh)
    if sizes.get("pipeline", 1) > 1:
        if layered_split is None:
            raise ValueError(
                "plan_train_sharding on a mesh with a pipeline axis needs "
                "layered_split=(prelude, layers, tail) — the model's "
                "LayeredApply.split(params) output (models.layered_for_model "
                "builds the LayeredApply for a registered family)"
            )
        prelude, layers, tail = layered_split
        return plan_mpmd_train_sharding(
            prelude,
            layers,
            tail,
            mesh,
            batch=batch,
            seq=seq,
            act_bytes=act_bytes,
            opt_bytes_per_param=opt_bytes_per_param,
            weight_dtype=weight_dtype,
            chip=chip,
            beam_width=beam_width,
            num_microbatches=num_microbatches,
        )
    axes = tuple(a for a in ("data", "model") if sizes.get(a, 1) > 1) or ("model",)
    workload = Workload(
        batch=batch,
        seq=seq,
        act_bytes=act_bytes,
        opt_bytes_per_param=opt_bytes_per_param,
    )
    return plan_sharding(
        params,
        mesh,
        axes=axes,
        chip=chip,
        workload=workload,
        weight_dtype=weight_dtype,
        beam_width=beam_width,
        top_k=top_k,
    )


# ------------------------------------------------------------------ pipeline
@dataclass
class StagePlan:
    """Planner-emitted pipeline stage assignment: contiguous layer ranges
    balanced on per-layer parameter bytes (the hand partitioner's equal-count
    split is the special case where every layer weighs the same)."""

    num_stages: int
    num_layers: int
    assignment: List[int]  # layer index -> stage index, non-decreasing
    per_stage_bytes: List[float]
    rules: List[Tuple[str, Tuple]]

    @property
    def uniform(self) -> bool:
        """True when every stage holds the same number of layers — the only
        shape the SPMD stage runner (stacked layer params, P("stage") leading
        dim) can execute today."""
        counts = [self.assignment.count(s) for s in range(self.num_stages)]
        return len(set(counts)) == 1

    @property
    def imbalance(self) -> float:
        """max/mean per-stage bytes — 1.0 is perfectly balanced."""
        mean = sum(self.per_stage_bytes) / max(len(self.per_stage_bytes), 1)
        return max(self.per_stage_bytes) / mean if mean else 1.0

    def stage_layers(self, stage: int) -> List[int]:
        return [i for i, s in enumerate(self.assignment) if s == stage]


def _layer_nbytes(layer_params, weight_dtype: str = "bf16") -> float:
    return sum(leaf.nbytes for leaf in _harvest_leaves(layer_params, weight_dtype))


def plan_pipeline_stages(
    layer_params_list: Sequence[Any],
    num_stages: int,
    *,
    weight_dtype: str = "bf16",
) -> StagePlan:
    """Assign ``len(layer_params_list)`` layers to ``num_stages`` contiguous
    stages minimizing the max per-stage parameter bytes (classic linear
    partition DP). Accepts real arrays or ShapeDtypeStructs per layer. Emits
    the same rules table shape the pipeline seam consumes."""
    n = len(layer_params_list)
    if num_stages <= 0:
        raise ValueError(f"num_stages must be positive, got {num_stages}")
    if n < num_stages:
        raise ValueError(f"cannot split {n} layers across {num_stages} stages")
    weights = [_layer_nbytes(lp, weight_dtype) for lp in layer_params_list]
    prefix = [0.0]
    for w in weights:
        prefix.append(prefix[-1] + w)

    def span(i: int, j: int) -> float:  # bytes of layers [i, j)
        return prefix[j] - prefix[i]

    INF = float("inf")
    # dp[s][j] = minimal max-stage-bytes splitting the first j layers into s stages
    dp = [[INF] * (n + 1) for _ in range(num_stages + 1)]
    cut = [[0] * (n + 1) for _ in range(num_stages + 1)]
    dp[0][0] = 0.0
    for s in range(1, num_stages + 1):
        for j in range(s, n + 1):
            for i in range(s - 1, j):
                cand = max(dp[s - 1][i], span(i, j))
                if cand < dp[s][j]:
                    dp[s][j] = cand
                    cut[s][j] = i
    bounds = [n]
    j = n
    for s in range(num_stages, 0, -1):
        j = cut[s][j]
        bounds.append(j)
    bounds.reverse()  # [0, ..., n], num_stages + 1 entries
    assignment = [0] * n
    per_stage = []
    for s in range(num_stages):
        lo, hi = bounds[s], bounds[s + 1]
        for i in range(lo, hi):
            assignment[i] = s
        per_stage.append(span(lo, hi))
    return StagePlan(
        num_stages=num_stages,
        num_layers=n,
        assignment=assignment,
        per_stage_bytes=per_stage,
        rules=[(r"(^|/)(enc_|dec_)?layers(/|$)", ("stage",))],
    )


# ----------------------------------------------------- MPMD pipeline planning
def build_stage_tree(prelude, layers, tail, stage_plan: StagePlan, stage: int):
    """The canonical per-stage params subtree — THE path contract between the
    planner's per-stage rules tables and the MPMD runtime's stage placement.

    Stage ``k`` holds ``{"layer_<i>": layers[i]}`` for its assigned layers,
    stage 0 additionally ``{"prelude": ...}`` and the last stage
    ``{"tail": ...}``. `plan_mpmd_train_sharding` harvests/emits rules against
    these paths and `parallel.mpmd` derives shardings for the SAME structure,
    so a rule like ``(^|/)wq/kernel(/|$)`` means the same leaf on both sides."""
    tree = {f"layer_{i}": layers[i] for i in stage_plan.stage_layers(stage)}
    if stage == 0:
        tree["prelude"] = prelude
    if stage == stage_plan.num_stages - 1:
        tree["tail"] = tail
    return tree


def default_num_microbatches(batch: int, num_stages: int) -> int:
    """Largest divisor of the global batch ≤ 2·stages: enough microbatches to
    keep the 1F1B bubble ≤ (P-1)/(3P-1) ≈ 1/3 without shrinking per-dispatch
    work further than the schedule needs."""
    candidates = [d for d in range(1, batch + 1) if batch % d == 0 and d <= 2 * num_stages]
    return max(candidates) if candidates else 1


def pipeline_bubble_terms(
    stage_times: Sequence[float], num_microbatches: int, p2p_time_s: float = 0.0
) -> Tuple[float, float]:
    """The pipeline-bubble step-time term: 1F1B wall-clock and idle fraction
    from per-microbatch stage times.

    ``wall = (M + P - 1) · max_k τ_k + t_p2p`` (M microbatches drain through P
    stages paced by the slowest stage, plus the activation/grad hop time that
    does not hide under compute), and ``bubble = 1 - Σ_k M·τ_k / (P · wall)``
    — the fraction of stage-seconds spent idle. Uniform stages with free hops
    recover the classic ``(P - 1) / (M + P - 1)``; stage imbalance grows the
    bubble because every stage paces on ``τ_max``."""
    num_stages = len(stage_times)
    if num_stages == 0:
        return 0.0, 0.0
    tau_max = max(stage_times)
    wall = (num_microbatches + num_stages - 1) * tau_max + p2p_time_s
    if wall <= 0.0:
        return 0.0, 0.0
    busy = num_microbatches * sum(stage_times)
    bubble = max(0.0, 1.0 - busy / (num_stages * wall))
    return wall, bubble


@dataclass
class MPMDTrainPlan:
    """The 3D ("data", "model", "pipeline") training plan: a byte-balanced
    (possibly NON-uniform) stage assignment plus one full 2D `ShardingPlan`
    per stage submesh — each stage carries its own rules + ZeRO opt-rules
    tables — and the pipeline-bubble account that prices the whole schedule.
    Executed by `parallel.mpmd.MPMDPipelinedModel`."""

    stage_plan: StagePlan
    stages: List[ShardingPlan]
    mesh_axes: Dict[str, int]
    chip: ChipSpec
    workload: Workload
    num_microbatches: int
    bubble_fraction: float
    p2p_bytes_per_microbatch: float
    p2p_time_s: float
    cost: PlanCost
    measured_step_s: Optional[float] = None

    @property
    def num_stages(self) -> int:
        return self.stage_plan.num_stages

    def stage_rules(self, stage: int) -> List[Tuple[str, Tuple]]:
        return self.stages[stage].rules

    def stage_opt_rules(self, stage: int) -> List[Tuple[str, Tuple]]:
        return self.stages[stage].opt_rules

    def describe(self) -> str:
        plan = self.stage_plan
        counts = [plan.assignment.count(s) for s in range(plan.num_stages)]
        lines = [
            f"MPMD pipeline plan over mesh {self.mesh_axes} (chip model: {self.chip.name})",
            f"stages: {plan.num_stages} over {plan.num_layers} layers, "
            f"layer counts {counts} (imbalance {plan.imbalance:.3f})",
            f"schedule: 1F1B, {self.num_microbatches} microbatches, predicted "
            f"bubble {self.bubble_fraction:.3f}, p2p "
            f"{_fmt_bytes(self.p2p_bytes_per_microbatch)}/microbatch-hop",
            f"predicted step time: {self.cost.step_time_s * 1e6:.2f} us "
            f"(busiest stage per-chip {_fmt_bytes(self.cost.per_chip_total_bytes)})",
            "",
        ]
        for k, stage in enumerate(self.stages):
            lines.append(f"--- stage {k} (layers {plan.stage_layers(k)}) ---")
            lines.append(stage.describe())
            lines.append("")
        return "\n".join(lines)

    def to_json(self) -> Dict[str, Any]:
        plan = self.stage_plan
        return {
            "mesh_axes": dict(self.mesh_axes),
            "chip": self.chip.name,
            "pipeline": {
                "num_stages": plan.num_stages,
                "num_layers": plan.num_layers,
                "assignment": list(plan.assignment),
                "stage_layer_counts": [
                    plan.assignment.count(s) for s in range(plan.num_stages)
                ],
                "per_stage_bytes": [int(b) for b in plan.per_stage_bytes],
                "imbalance": plan.imbalance,
                "num_microbatches": self.num_microbatches,
                "bubble_fraction": self.bubble_fraction,
                "p2p_bytes_per_microbatch": int(self.p2p_bytes_per_microbatch),
                "p2p_time_s": self.p2p_time_s,
            },
            "stages": [stage.to_json() for stage in self.stages],
            "predicted": {
                "per_chip_param_bytes": int(self.cost.per_chip_param_bytes),
                "per_chip_opt_bytes": int(self.cost.per_chip_opt_bytes),
                "collective_bytes_per_step": int(self.cost.collective_bytes),
                "step_time_s": self.cost.step_time_s,
                "hbm_overflow_bytes": int(self.cost.hbm_overflow_bytes),
            },
            "measured_step_s": self.measured_step_s,
        }


def plan_mpmd_train_sharding(
    prelude,
    layers,
    tail,
    mesh,
    *,
    batch: int,
    seq: int,
    act_bytes: int = 2,
    opt_bytes_per_param: float = 8.0,
    weight_dtype: str = "bf16",
    chip: Optional[ChipSpec] = None,
    beam_width: int = 8,
    num_microbatches: Optional[int] = None,
) -> MPMDTrainPlan:
    """Plan 3D MPMD pipeline training: byte-balance the layers onto the
    "pipeline" axis (`plan_pipeline_stages` — assignments may be non-uniform),
    run the full 2D ("data", "model") search independently per stage submesh
    (each stage gets its own rules + ZeRO opt-rules tables, sized to ITS
    subtree), and price the schedule with the pipeline-bubble term: per-stage
    per-microbatch dispatch times from the existing HBM/ICI cost model, 1F1B
    wall-clock paced by the slowest stage, plus the P2P activation/gradient
    hop bytes between stage submeshes.

    Grad-sync note: the MPMD runtime all-reduces each stage's gradients over
    its submesh's "data" axis once per MICROBATCH (every backward program
    carries its own psum), so pricing the stage workload at the microbatch
    size charges the grad sync exactly as many times as the runtime pays it."""
    if isinstance(chip, str):
        chip = CHIPS[chip]
    chip = chip or default_chip()
    sizes = _axis_sizes(mesh)
    num_stages = sizes.get("pipeline", 1)
    if num_stages < 2:
        raise ValueError(
            f"plan_mpmd_train_sharding needs a pipeline axis of size >= 2, got "
            f"mesh axes {sizes}"
        )
    stage_plan = plan_pipeline_stages(list(layers), num_stages, weight_dtype=weight_dtype)
    M = num_microbatches or default_num_microbatches(batch, num_stages)
    if batch % M != 0:
        raise ValueError(f"global batch {batch} not divisible by num_microbatches={M}")
    microbatch = batch // M

    if isinstance(mesh, dict):
        # Abstract planning (the CLI's deviceless path): every pipeline slice
        # of an {axis: size} mesh is the same {data, model} sub-dict, and the
        # per-stage 2D search only ever reads axis sizes.
        sub = {a: s for a, s in sizes.items() if a != "pipeline"}
        submeshes = [sub] * num_stages
    else:
        from .mesh import slice_mesh

        submeshes = slice_mesh(mesh, "pipeline")
    axes = tuple(a for a in ("data", "model") if sizes.get(a, 1) > 1) or ("model",)
    workload = Workload(
        batch=microbatch,
        seq=seq,
        act_bytes=act_bytes,
        opt_bytes_per_param=opt_bytes_per_param,
    )
    stage_plans: List[ShardingPlan] = []
    for k in range(num_stages):
        tree = build_stage_tree(prelude, layers, tail, stage_plan, k)
        stage_plans.append(
            plan_sharding(
                tree,
                submeshes[k],
                axes=axes,
                chip=chip,
                workload=workload,
                weight_dtype=weight_dtype,
                beam_width=beam_width,
            )
        )

    # P2P term: each stage boundary ships one residual-stream microbatch
    # forward and its gradient back — 2 · mb · seq · hidden · act_bytes per
    # microbatch per boundary, never through host (d2d over ICI).
    full_tree = {"prelude": prelude, "tail": tail}
    full_tree.update({f"layer_{i}": lp for i, lp in enumerate(layers)})
    hidden = _infer_hidden(_harvest_leaves(full_tree, weight_dtype)) or 0
    p2p_mb = float(microbatch * seq * hidden * act_bytes)
    p2p_total = 2.0 * p2p_mb * (num_stages - 1) * M
    p2p_time = p2p_total / (chip.ici_gbps * 1e9)

    taus = [sp.cost.step_time_s for sp in stage_plans]
    wall, bubble = pipeline_bubble_terms(taus, M, p2p_time)
    collective = M * sum(sp.cost.collective_bytes for sp in stage_plans) + p2p_total
    # The busiest stage is the binding per-chip HBM constraint; overflow is
    # per-stage-local so any overflowing stage poisons the plan.
    worst = max(stage_plans, key=lambda sp: sp.cost.per_chip_total_bytes)
    cost = PlanCost(
        per_chip_param_bytes=worst.cost.per_chip_param_bytes,
        per_chip_opt_bytes=worst.cost.per_chip_opt_bytes,
        per_chip_kv_bytes=0.0,
        collective_bytes=collective,
        flop_time_s=M * max(sp.cost.flop_time_s for sp in stage_plans),
        hbm_time_s=M * max(sp.cost.hbm_time_s for sp in stage_plans),
        ici_time_s=collective / (chip.ici_gbps * 1e9),
        step_time_s=wall,
        hbm_overflow_bytes=max(sp.cost.hbm_overflow_bytes for sp in stage_plans),
    )
    return MPMDTrainPlan(
        stage_plan=stage_plan,
        stages=stage_plans,
        mesh_axes=sizes,
        chip=chip,
        workload=workload,
        num_microbatches=M,
        bubble_fraction=bubble,
        p2p_bytes_per_microbatch=p2p_mb,
        p2p_time_s=p2p_time,
        cost=cost,
    )


def search_train_meshes(
    params,
    devices,
    *,
    batch: int,
    seq: int,
    layered_split=None,
    act_bytes: int = 2,
    opt_bytes_per_param: float = 8.0,
    weight_dtype: str = "bf16",
    chip: Optional[ChipSpec] = None,
    beam_width: int = 8,
    max_pipeline: Optional[int] = None,
):
    """Search the full ("data", "model", "pipeline") mesh product: enumerate
    every factorization of the device count, plan each candidate mesh with
    `plan_train_sharding` (2D plans at pipeline=1, MPMD pipeline plans
    otherwise — both priced by the same cost model, pipeline candidates with
    the bubble term on top), and return ``[(mesh_axes, plan)]`` ranked by
    modeled cost. Pipeline candidates need ``layered_split``; without it only
    the 2D slice of the product is searched (AMP-style 3D search degrades to
    the PR-16 2D search)."""
    from ..utils.dataclasses import ParallelismConfig
    from .mesh import build_mesh

    devices = list(devices)
    n = len(devices)
    num_layers = len(layered_split[1]) if layered_split is not None else 0
    results = []
    for pipe in (d for d in range(1, n + 1) if n % d == 0):
        if pipe > 1 and (layered_split is None or pipe > num_layers):
            continue
        if max_pipeline is not None and pipe > max_pipeline:
            continue
        rem = n // pipe
        for model_deg in (d for d in range(1, rem + 1) if rem % d == 0):
            data_deg = rem // model_deg
            mesh = build_mesh(
                ParallelismConfig(data=data_deg, model=model_deg, pipeline=pipe),
                devices=devices,
            )
            try:
                plan = plan_train_sharding(
                    params,
                    mesh,
                    batch=batch,
                    seq=seq,
                    act_bytes=act_bytes,
                    opt_bytes_per_param=opt_bytes_per_param,
                    weight_dtype=weight_dtype,
                    chip=chip,
                    beam_width=beam_width,
                    layered_split=layered_split,
                )
            except ValueError:
                continue
            results.append(
                ({"data": data_deg, "model": model_deg, "pipeline": pipe}, plan)
            )
    results.sort(key=lambda pair: pair[1].cost.total)
    return results


# ---------------------------------------------------------- measure & refine
def measure_forward_step(
    apply_fn: Callable,
    params,
    mesh,
    rules: Sequence[Tuple[str, Tuple]],
    *,
    batch: int = 1,
    repeats: int = 3,
) -> float:
    """Wall-time one compiled single-token forward with ``params`` placed by
    ``rules`` on ``mesh`` — the default measurement `refine_plans` uses.
    Returns best-of-``repeats`` seconds (best-of, not mean: scheduling noise
    only ever ADDS time)."""
    import time

    import jax
    import jax.numpy as jnp

    from .sharding import derive_tp_param_shardings

    shardings = derive_tp_param_shardings(params, mesh, list(rules))
    placed = jax.device_put(params, shardings)
    ids = jnp.zeros((batch, 1), jnp.int32)

    fwd = jax.jit(lambda p, t: apply_fn(p, t))
    jax.block_until_ready(fwd(placed, ids))  # compile outside the timed region
    best = float("inf")
    for _ in range(max(1, repeats)):
        start = time.perf_counter()
        jax.block_until_ready(fwd(placed, ids))
        best = min(best, time.perf_counter() - start)
    return best


def measure_train_step(
    apply_fn: Callable,
    params,
    mesh,
    rules: Sequence[Tuple[str, Tuple]],
    *,
    opt_rules: Optional[Sequence[Tuple[str, Tuple]]] = None,
    tx=None,
    batch: int = 1,
    seq: int = 16,
    repeats: int = 3,
) -> float:
    """The training twin of `measure_forward_step`: wall-time one compiled
    fused train step (loss + grad + optimizer update) with ``params`` placed by
    ``rules`` and optimizer state placed by ``opt_rules`` on ``mesh``.

    A forward measurement can't rank training plans — a rule table that wins on
    decode may lose on the grad all-reduce it forces, and ZeRO moment sharding
    (``opt_rules``) never shows up in a forward pass at all. This compiles the
    real thing: `value_and_grad` of a causal-LM-shaped loss plus a ``tx.update``
    + apply, params and opt state donated, so the measured seconds include
    grad-sync collectives and the optimizer's HBM traffic. Returns
    best-of-``repeats`` seconds, same discipline as the forward twin."""
    import time

    import jax
    import jax.numpy as jnp
    import optax

    from .sharding import derive_opt_state_shardings, derive_tp_param_shardings

    if tx is None:
        tx = optax.adam(1e-3)

    shardings = derive_tp_param_shardings(params, mesh, list(rules))
    placed = jax.device_put(params, shardings)
    state_shapes = jax.eval_shape(tx.init, placed)
    opt_shardings = derive_opt_state_shardings(
        state_shapes, mesh, None, list(rules),
        opt_rules=list(opt_rules) if opt_rules else None,
    )
    opt_state = jax.jit(tx.init, out_shardings=opt_shardings)(placed)
    ids = jnp.zeros((batch, seq), jnp.int32)

    def loss_fn(p, tokens):
        logits = apply_fn(p, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tokens
        ).mean()

    def _step(p, opt, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(p, tokens)
        updates, new_opt = tx.update(grads, opt, p)
        return optax.apply_updates(p, updates), new_opt, loss

    step = jax.jit(_step, donate_argnums=(0, 1))
    placed, opt_state, loss = step(placed, opt_state, ids)
    jax.block_until_ready(loss)  # compile + first dispatch outside the timer
    best = float("inf")
    for _ in range(max(1, repeats)):
        start = time.perf_counter()
        placed, opt_state, loss = step(placed, opt_state, ids)
        jax.block_until_ready(loss)
        best = min(best, time.perf_counter() - start)
    return best


def refine_plans(
    plans: Sequence[ShardingPlan],
    measure_fn: Callable[[ShardingPlan], float],
    *,
    repeats: int = 1,
) -> Tuple[ShardingPlan, List[Tuple[ShardingPlan, float]]]:
    """Measure-and-refine: the cost model proposes (`top_k` candidates from
    `plan_sharding`), the hardware disposes. ``measure_fn(plan) -> seconds``
    compiles and times one candidate (see `measure_forward_step`); the
    measured-best plan is returned with ``measured_step_s`` stamped, plus the
    full (plan, seconds) list for reporting."""
    if not plans:
        raise ValueError("refine_plans needs at least one candidate plan")
    measured: List[Tuple[ShardingPlan, float]] = []
    for plan in plans:
        seconds = min(measure_fn(plan) for _ in range(max(1, repeats)))
        plan.measured_step_s = seconds
        measured.append((plan, seconds))
    best = min(measured, key=lambda pair: pair[1])[0]
    return best, measured


# ------------------------------------------------------------------ the seam
def resolve_sharding_rules(
    sharding_rules,
    params,
    mesh,
    *,
    plan_kwargs: Optional[Dict[str, Any]] = None,
):
    """The sentinel seam every consumer shares — `Accelerator.prepare_model`
    and `ContinuousBatcher` accept the same value set: a list/tuple passes
    through, ``None`` / ``"rules"`` stay ``None`` (caller falls back to the
    model family table), and ``"auto"`` runs the planner. Returns
    (rules, plan-or-None)."""
    if sharding_rules is None or sharding_rules == "rules":
        return None, None
    if isinstance(sharding_rules, (list, tuple)):
        return list(sharding_rules), None
    if sharding_rules == "auto":
        plan = plan_sharding(params, mesh, **(plan_kwargs or {}))
        return plan.rules, plan
    raise ValueError(
        f"sharding_rules must be a rules list, None, 'rules' or 'auto'; got "
        f"{sharding_rules!r}"
    )

from .mesh import build_mesh, get_default_mesh, mesh_axis_size
from .pipeline import PipelinedModel, prepare_pipeline

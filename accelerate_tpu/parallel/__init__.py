from .mesh import build_mesh, get_default_mesh, mesh_axis_size
from .pipeline import PipelinedModel, prepare_pipeline
from .expert import EXPERT_SHARDING_RULES, ExpertMLP, MoEBlock, expert_capacity, top_k_routing
from .planner import (
    ChipSpec,
    ShardingPlan,
    Workload,
    plan_serving_sharding,
    plan_sharding,
    refine_plans,
    score_rules,
)
from .ring_attention import ring_attention

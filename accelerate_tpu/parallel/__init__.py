from .mesh import build_mesh, get_default_mesh, mesh_axis_size, slice_mesh
from .pipeline import PipelinedModel, prepare_pipeline
from .mpmd import MPMDPipelinedModel, prepare_mpmd_pipeline
from .expert import EXPERT_SHARDING_RULES, ExpertMLP, MoEBlock, expert_capacity, top_k_routing
from .planner import (
    ChipSpec,
    MPMDTrainPlan,
    ShardingPlan,
    Workload,
    plan_mpmd_train_sharding,
    plan_serving_sharding,
    plan_sharding,
    refine_plans,
    score_rules,
    search_train_meshes,
)
from .ring_attention import ring_attention

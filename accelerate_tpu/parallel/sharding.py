"""Sharding-spec derivation: the strategy layer (L4).

This module replaces all four of the reference's parallelism backends (DDP wrap
accelerator.py:1414, torch-FSDP wrap :1431-1545, DeepSpeed engine :1563-1785, Megatron
TP/PP glue utils/megatron_lm.py) with ONE mechanism: derive a `NamedSharding` for every
parameter / gradient / optimizer-state leaf, then let GSPMD insert the collectives.

  - DP: replicated params; batch axis on ("data","fsdp") — gradients reduce
    automatically (the psum appears in the backward of the sharded-batch loss).
  - FSDP/ZeRO-3 (`FULL_SHARD`): params sharded over the "fsdp" axis on their largest
    divisible dim; XLA all-gathers weights per-layer in fwd/bwd and reduce-scatters
    grads — exactly torch-FSDP's choreography, but compiler-scheduled.
  - ZeRO-2 (`SHARD_GRAD_OP`): params replicated, optimizer state sharded over "fsdp"
    (weight-update sharding; see PAPERS.md "Automatic Cross-Replica Sharding").
  - TP: path-regex rules map module-specific weights onto the "model" axis
    (column/row-parallel Megatron layout as specs, not layer rewrites).

Rules are `(path_regex, partition_spec_tuple)` pairs; the first match wins. Model
families in `accelerate_tpu.models` ship their own rule tables.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence, Tuple

import numpy as np

_SMALL_PARAM_DEFAULT = 2**16  # below this, sharding costs more than it saves


def tree_paths_and_leaves(tree):
    """[(path_str, leaf)] with '/'-joined readable paths."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for key_path, leaf in flat:
        parts = []
        for k in key_path:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            elif hasattr(k, "name"):
                parts.append(str(k.name))
            else:
                parts.append(str(k))
        out.append(("/".join(parts), leaf))
    return out, treedef


def _axes_free(spec: Sequence, mesh) -> set:
    used = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


def _fsdp_dim(path: str, shape, fsdp_size: int, taken_dims: set) -> Optional[int]:
    """Pick the dim to shard over "fsdp", keeping CONTRACTION dims replicated.

    A contraction-dim-sharded weight makes GSPMD propagate hidden-sharded layouts
    into the residual stream ("Involuntary full rematerialization", round-2 verdict
    weak #3) because the weight's gradient then demands hidden-sharded cotangents.
    So: embedding tables shard dim 0 (vocab — the gather dim routes whole rows);
    kernels shard the LAST (output) dim, whose gradient is a batch contraction that
    XLA lowers to the natural ZeRO reduce-scatter; otherwise the largest free dim.
    """
    candidates = [
        i for i, d in enumerate(shape) if i not in taken_dims and d % fsdp_size == 0 and d >= fsdp_size
    ]
    if not candidates:
        return None
    if ("embedding" in path.rsplit("/", 1)[-1] or "embed" in path) and 0 in candidates:
        return 0
    if len(shape) >= 2 and (len(shape) - 1) in candidates:
        return len(shape) - 1
    return max(candidates, key=lambda i: shape[i])


def spec_for_param(
    path: str,
    shape: Tuple[int, ...],
    mesh,
    fsdp_plugin=None,
    rules: Optional[Sequence] = None,
    min_shard_size: Optional[int] = None,
):
    """PartitionSpec for one parameter: TP rules first, then FSDP on a free dim."""
    from jax.sharding import PartitionSpec

    if isinstance(rules, str):
        raise ValueError(
            f"rules={rules!r} reached spec derivation unresolved — the 'auto' "
            "sentinel must be lowered to a table first (parallel.planner."
            "plan_sharding, or the Accelerator/ContinuousBatcher seams that "
            "call it)"
        )
    size = int(np.prod(shape)) if shape else 1
    spec = [None] * len(shape)
    matched = False
    if rules:
        for pattern, rule_spec in rules:
            if re.search(pattern, path):
                rule_spec = tuple(rule_spec)[: len(shape)]
                spec = list(rule_spec) + [None] * (len(shape) - len(rule_spec))
                matched = True
                break

    fsdp_size = mesh.shape.get("fsdp", 1)
    shards_params = fsdp_plugin is not None and fsdp_plugin.shards_params
    # auto_wrap_policy decides WHICH params join the fsdp shard group (the GSPMD
    # reading of reference set_auto_wrap_policy, dataclasses.py:1173-1203):
    #   SIZE_BASED_WRAP / None — size threshold (min_num_params);
    #   TRANSFORMER_BASED_WRAP — only params whose path matches one of
    #     transformer_cls_names_to_wrap (path regexes, e.g. "layer_"); the rest
    #     (embeddings/head/norms) stay replicated, exactly like unwrapped root
    #     modules in the reference;
    #   NO_WRAP — one root unit: every divisible param shards, no threshold.
    policy = getattr(fsdp_plugin, "auto_wrap_policy", None) if fsdp_plugin else None
    threshold = min_shard_size
    if threshold is None:
        threshold = fsdp_plugin.min_num_params if (fsdp_plugin and fsdp_plugin.min_num_params) else _SMALL_PARAM_DEFAULT
    if policy == "NO_WRAP":
        threshold = 1
    elif policy == "TRANSFORMER_BASED_WRAP" and shards_params:
        wrap_names = getattr(fsdp_plugin, "transformer_cls_names_to_wrap", None) or []
        if not any(re.search(pat, path) for pat in wrap_names):
            shards_params = False
    if fsdp_size > 1 and shards_params and size >= threshold and "fsdp" not in _axes_free(spec, mesh):
        taken = {i for i, s in enumerate(spec) if s is not None}
        extended = False
        if matched and taken:
            # A TP rule already shards this param: extend the rule's dim with
            # "fsdp" (Megatron+ZeRO convention — dp further shards the tp shard)
            # rather than grabbing a free dim, which for Megatron-layout kernels
            # is the contraction dim and would reshard the residual stream.
            for i in sorted(taken, reverse=True):
                axes = (spec[i],) if isinstance(spec[i], str) else tuple(spec[i])
                group = fsdp_size * int(np.prod([mesh.shape.get(a, 1) for a in axes]))
                if shape[i] % group == 0 and shape[i] >= group:
                    spec[i] = axes + ("fsdp",)
                    extended = True
                    break
        if not extended:
            dim = _fsdp_dim(path, shape, fsdp_size, taken)
            if dim is not None and spec[dim] is None:
                spec[dim] = "fsdp"
    # Drop trailing Nones for a canonical spec
    while spec and spec[-1] is None:
        spec.pop()
    return PartitionSpec(*spec)


def derive_param_shardings(params, mesh, fsdp_plugin=None, rules=None):
    """Pytree of NamedSharding for `params` (the FSDP auto-wrap-policy replacement,
    reference dataclasses.py:1173-1203 — size/module-class policies become a size
    threshold + path rules)."""
    import jax
    from jax.sharding import NamedSharding

    flat, treedef = tree_paths_and_leaves(params)
    shardings = [
        NamedSharding(mesh, spec_for_param(path, np.shape(leaf), mesh, fsdp_plugin, rules)) for path, leaf in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, shardings)


def _spec_legal(spec: Tuple, shape: Tuple[int, ...], mesh) -> bool:
    """True when every sharded dim of ``shape`` divides evenly by the product
    of its mesh-axis sizes (GSPMD would pad otherwise; the planner never emits
    padded placements, so an indivisible match means the rule was written for a
    different tree)."""
    sizes = dict(getattr(mesh, "shape", {}) or {})
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        axes = (entry,) if isinstance(entry, str) else tuple(entry)
        group = 1
        for a in axes:
            group *= int(sizes.get(a, 1))
        if group > 1 and (dim >= len(shape) or shape[dim] % group != 0):
            return False
    return True


def derive_opt_state_shardings(opt_state_shapes, mesh, fsdp_plugin=None, rules=None, opt_rules=None):
    """Shardings for optimizer state, by the same path+shape rules.

    Adam moments mirror parameter shapes, so the same derivation yields matching
    shardings; for `SHARD_GRAD_OP` (ZeRO-2) the optimizer state shards over "fsdp" even
    though params stay replicated — that's the weight-update-sharding trick. Scalars
    (step counts) replicate.

    ``opt_rules`` is the planner-emitted ZeRO table (``ShardingPlan.opt_rules``):
    when given it is AUTHORITATIVE for any moment whose path matches — the
    planner already enumerated every sharded moment, so matched paths take the
    table's spec verbatim (legality re-checked against the mesh) and unmatched
    non-scalar leaves fall through to the ordinary param-rule derivation.
    Patterns in the table anchor ``(^|/)`` because moment paths nest the param
    path (``0/mu/<param path>``).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    compiled_opt_rules = [(re.compile(pat), spec) for pat, spec in (opt_rules or [])]

    def _opt_rule_spec(path, shape):
        for pat, spec in compiled_opt_rules:
            if pat.search(path):
                full = tuple(spec) + (None,) * (len(shape) - len(spec))
                if _spec_legal(full, shape, mesh):
                    return PartitionSpec(*full)
                return PartitionSpec()  # illegal on this tree: replicate, never crash
        return None

    shards_opt = fsdp_plugin is not None and fsdp_plugin.shards_opt_state
    # For opt-state derivation under ZeRO-2, treat params as sharded — but carry
    # the wrap-policy knobs through, so a moment shards exactly when its
    # parameter would (mismatched param/moment shardings would insert a reshard
    # collective into every update step).
    class _OptPlugin:
        shards_params = True
        min_num_params = getattr(fsdp_plugin, "min_num_params", 0) if fsdp_plugin else 0
        auto_wrap_policy = getattr(fsdp_plugin, "auto_wrap_policy", None) if fsdp_plugin else None
        transformer_cls_names_to_wrap = (
            getattr(fsdp_plugin, "transformer_cls_names_to_wrap", None) if fsdp_plugin else None
        )

    plugin = _OptPlugin() if shards_opt else None

    flat, treedef = tree_paths_and_leaves(opt_state_shapes)
    out = []
    for path, leaf in flat:
        shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
        if len(shape) == 0:
            out.append(NamedSharding(mesh, PartitionSpec()))
            continue
        planned = _opt_rule_spec(path, shape)
        if planned is not None:
            out.append(NamedSharding(mesh, planned))
        else:
            out.append(NamedSharding(mesh, spec_for_param(path, shape, mesh, plugin, rules)))
    return jax.tree_util.tree_unflatten(treedef, out)


def with_memory_kind(shardings, memory_kind: str):
    """Rebuild a NamedSharding pytree with a different memory kind (the host-offload
    tier lever: `pinned_host` holds ZeRO-offload state, reference accelerator.py:1563+)."""
    import jax
    from jax.sharding import NamedSharding

    return jax.tree_util.tree_map(
        lambda s: NamedSharding(s.mesh, s.spec, memory_kind=memory_kind), shardings
    )


#: Memory kinds that live in host RAM, preferred order. Accelerator backends
#: expose a distinct "pinned_host" space next to device HBM; CPU backends
#: (jax >= 0.4.3x) expose only "unpinned_host", which IS their default memory
#: — offload placement there is a no-op by construction, which keeps the
#: offload code paths (kind-stamped shardings, streaming device_puts, chunked
#: group programs) fully exercisable on the CPU test tier.
HOST_MEMORY_KINDS = ("pinned_host", "unpinned_host")


def host_memory_kind() -> Optional[str]:
    """The memory kind the host-offload tier lowers to on this backend:
    "pinned_host" where a distinct host space exists, the backend's host-side
    default ("unpinned_host" on CPU) otherwise, None when the backend exposes
    no host-addressable space at all."""
    import jax

    try:
        kinds = {m.kind for m in jax.devices()[0].addressable_memories()}
    except Exception:
        return None
    for kind in HOST_MEMORY_KINDS:
        if kind in kinds:
            return kind
    return None


def device_memory_kind() -> Optional[str]:
    """The backend's default (compute-tier) memory kind — "device" on
    TPU/GPU, "unpinned_host" on CPU where the two tiers coincide."""
    import jax

    try:
        return jax.devices()[0].default_memory().kind
    except Exception:
        return None


def host_memory_available() -> bool:
    """Whether the backend exposes a host-tier memory space the offload
    machinery can place state into (see `host_memory_kind`)."""
    return host_memory_kind() is not None


def place_params(tree, shardings=None):
    """Place a param pytree onto the mesh with GUARANTEED fresh buffers.

    `jax.device_put` aliases the source buffer when a shard lands where the input
    already lives (even with may_alias=False) — and the optimizer's donated update
    deletes prepared buffers every step, which would tear down the user's original
    arrays through the alias. A non-donating jit identity always materializes new
    output buffers. `shardings=None` keeps default placement but still copies.
    """
    import jax

    if shardings is None:
        return jax.jit(lambda t: t)(tree)
    flat = jax.tree_util.tree_leaves(shardings)
    # Host-TIER shardings route through eager device_put. Membership is
    # "a host kind that is NOT this backend's default": on CPU every
    # sharding resolves to unpinned_host (the only memory space), so plain
    # placements must keep the jit path; on accelerators both host kinds
    # are a distinct tier and take the eager path.
    host_kinds = {k for k in HOST_MEMORY_KINDS if k != device_memory_kind()}
    if any(getattr(s, "memory_kind", None) in host_kinds for s in flat):
        # jit out_shardings with memory kinds trips the SPMD partitioner on some
        # backends, so host placement goes through eager device_put. device_put
        # aliases a source already committed to the identical sharding — break the
        # alias with a host materialization so the fresh-buffer guarantee holds.
        def _fresh(x, s):
            if (
                isinstance(x, jax.Array)
                and x.is_fully_addressable
                and getattr(x, "committed", False)
                and x.sharding == s
            ):
                x = np.asarray(x)
            return jax.device_put(x, s)

        return jax.tree_util.tree_map(_fresh, tree, shardings)
    return jax.jit(lambda t: t, out_shardings=shardings)(tree)


import contextlib
import contextvars

# Mesh for in-model activation constraints. Scoped (not read from global state) so
# the constraints are inert wherever they would be illegal or wrong — inside the
# pipeline's shard_map (manual axes), in user code tracing models off-mesh, and in
# tests that build models without an Accelerator.
_ACTIVATION_MESH: contextvars.ContextVar = contextvars.ContextVar("activation_mesh", default=None)


@contextlib.contextmanager
def activation_sharding_scope(mesh):
    """Enable `constrain_activation` with this mesh for the duration (trace time)."""
    token = _ACTIVATION_MESH.set(mesh)
    try:
        yield
    finally:
        _ACTIVATION_MESH.reset(token)


def constrain_activation(x):
    """Pin a [batch, seq, ...] activation to the canonical layout: batch over
    ("data","fsdp"), seq over "seq", trailing dims replicated.

    Without this, GSPMD propagates layouts backward from fsdp-sharded weights —
    e.g. a q_proj kernel sharded on its contraction dim makes XLA reshard the whole
    residual stream hidden-over-fsdp ("Involuntary full rematerialization", round-2
    verdict weak #3). ZeRO-3 semantics are the opposite: weights all-gather to the
    compute layout; activations stay batch-sharded. Models call this at residual
    seams; it is a no-op unless inside `activation_sharding_scope`.
    """
    mesh = _ACTIVATION_MESH.get()
    if mesh is None or getattr(x, "ndim", 0) < 2:
        return x
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    batch_axes = tuple(a for a in ("data", "fsdp") if mesh.shape.get(a, 1) > 1)
    seq_axis = "seq" if mesh.shape.get("seq", 1) > 1 else None
    if not batch_axes and seq_axis is None:
        return x
    spec = [batch_axes if batch_axes else None, seq_axis] + [None] * (x.ndim - 2)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, PartitionSpec(*spec)))


# --------------------------------------------------------------- serving TP
# Tensor-parallel DECODE (serving.ContinuousBatcher(tp=N)): one engine spans a
# submesh whose single "model" axis carries the Megatron column/row-parallel
# layout the model families' rule tables already describe. Everything here is
# spec derivation — XLA/GSPMD inserts the collectives once params, KV pools
# and scale pools are placed with these NamedShardings.


def compat_shard_map(fn, **kwargs):
    """`shard_map` across jax versions — the ONE compat shim (pipeline, ring
    flash, and the TP paged-attention wrap all route here): current jax
    exposes `jax.shard_map`, older versions `jax.experimental.shard_map`;
    the replication-checking kwarg renamed `check_rep` -> `check_vma` along
    the way. Callers pass the current spelling (`check_vma`); exactly one
    retry swaps the kwarg on TypeError, so an unrelated TypeError from the
    wrapped call still propagates."""
    try:
        from jax import shard_map
    except ImportError:  # older jax keeps it under experimental
        from jax.experimental.shard_map import shard_map

    if "check_vma" in kwargs:
        try:
            return shard_map(fn, **kwargs)
        except TypeError:
            kwargs = dict(kwargs)
            kwargs["check_rep"] = kwargs.pop("check_vma")
    return shard_map(fn, **kwargs)


def serving_tp_mesh(tp: int, devices=None, group: int = 0):
    """A 1-axis ("model",) submesh over `tp` devices for a mesh-spanning
    serving engine. `devices` picks the group explicitly; otherwise `group`
    selects the g-th disjoint `tp`-device block of `jax.devices()` (the
    router assigns one group per replica), wrapping around when the topology
    has fewer than ``(group+1)*tp`` devices — CPU smoke meshes oversubscribe
    harmlessly."""
    import jax
    from jax.sharding import Mesh

    tp = int(tp)
    if tp < 1:
        raise ValueError("tp must be >= 1")
    if devices is None:
        all_devices = jax.devices()
        groups = max(len(all_devices) // tp, 1)
        g = int(group)
        if g >= groups:
            # The wrap exists for CPU smoke meshes (oversubscription is
            # harmless there); on real hardware sharing chips between groups
            # silently halves their throughput — be loud about it.
            from ..logging import get_logger

            get_logger(__name__).warning(
                "serving_tp_mesh: group %d wraps onto device block %d — only "
                "%d disjoint %d-device group(s) exist across %d visible "
                "device(s), so this submesh SHARES chips with group %d. Fine "
                "for CPU smoke meshes; on real hardware shrink replicas or tp.",
                g, g % groups, groups, tp, len(all_devices), g % groups,
            )
        start = (g % groups) * tp
        devices = all_devices[start : start + tp]
    devices = list(devices)
    if len(devices) != tp:
        raise ValueError(
            f"tensor-parallel degree {tp} needs exactly {tp} devices, got "
            f"{len(devices)} (of {len(jax.devices())} visible)"
        )
    return Mesh(np.asarray(devices), ("model",))


def _check_tp_divisible(path: str, shape, spec, mesh):
    """A rule-sharded dim must divide by its axis group — silently dropping
    the axis would be exactly the full-replication fallback TPU118 warns
    about, so an indivisible rule is a hard error naming the leaf."""
    for i, entry in enumerate(spec):
        if entry is None:
            continue
        axes = (entry,) if isinstance(entry, str) else tuple(entry)
        group = int(np.prod([mesh.shape.get(a, 1) for a in axes]))
        if group > 1 and shape[i] % group:
            raise ValueError(
                f"TP rule shards {path} dim {i} (size {shape[i]}) over axes "
                f"{axes} (group size {group}), which does not divide — pick a "
                f"tp that divides the model's head/hidden dims"
            )


def derive_tp_param_shardings(params, mesh, rules):
    """NamedSharding pytree for a serving params tree: Megatron TP rules only
    (no fsdp/data axes — decode batches are slot batches, replicated).

    Quantized kernel entries (`ops/quantization.quantize_params_int8`:
    ``{"q": int8 [K, N], "scale": f32 [N]}`` dict leaves under the kernel
    path) ride their kernel's rule — ``q`` shards exactly like the kernel it
    replaced, and the per-output-channel ``scale`` vector follows the
    kernel's OUTPUT dim (the rule's last entry): column-parallel kernels
    shard their scales, row-parallel kernels replicate them. Unmatched
    leaves (norms, biases) replicate."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    rules = list(rules or [])
    flat, treedef = tree_paths_and_leaves(params)
    out = []
    for path, leaf in flat:
        shape = tuple(np.shape(leaf))
        if path.endswith("kernel/scale") and len(shape) == 1:
            # The quantized entry's scale vector: align with the kernel's
            # output (last) dim instead of rule-from-the-front truncation,
            # which would silently replicate column-parallel scales.
            axis = None
            for pattern, rule_spec in rules:
                if re.search(pattern, path):
                    rule_spec = tuple(rule_spec)
                    axis = rule_spec[-1] if rule_spec else None
                    break
            spec = PartitionSpec(axis) if axis is not None else PartitionSpec()
        else:
            spec = spec_for_param(path, shape, mesh, None, rules)
        _check_tp_divisible(path, shape, tuple(spec), mesh)
        out.append(NamedSharding(mesh, spec))
    return jax.tree_util.tree_unflatten(treedef, out)


def _tp_cache_spec(path: str, ndim: int, axis: str = "model"):
    """PartitionSpec for one slot-cache leaf, by leaf name: K/V pools/rows
    ([..., heads, head_dim]) shard their HEADS dim; the quantized pools'
    per-page-per-head scale arrays ([..., num_pages, heads]) shard their
    trailing heads dim; everything else (cache_index scalars, pad masks)
    replicates. Name-based so the dense per-slot rows, the page pools, AND
    scan-stacked ([layers, ...]) variants all derive the same layout."""
    from jax.sharding import PartitionSpec

    leaf = path.rsplit("/", 1)[-1]
    if leaf in ("cached_key", "cached_value") and ndim >= 2:
        spec = [None] * ndim
        spec[ndim - 2] = axis
        return PartitionSpec(*spec)
    if leaf in ("key_scale", "value_scale") and ndim >= 1:
        spec = [None] * ndim
        spec[ndim - 1] = axis
        return PartitionSpec(*spec)
    return PartitionSpec()


def derive_tp_cache_shardings(cache, mesh, axis: str = "model"):
    """NamedSharding pytree for a serving slot cache (dense rows or page
    pools): K/V shard by KV head over `axis`, scale pools by head, scalars
    replicate. Shapes may be real arrays or ShapeDtypeStructs."""
    import jax
    from jax.sharding import NamedSharding

    flat, treedef = tree_paths_and_leaves(cache)
    out = []
    for path, leaf in flat:
        shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
        spec = _tp_cache_spec(path, len(shape), axis)
        _check_tp_divisible(path, shape, tuple(spec), mesh)
        out.append(NamedSharding(mesh, spec))
    return jax.tree_util.tree_unflatten(treedef, out)


def constrain_tp_cache(cache, mesh, axis: str = "model"):
    """`with_sharding_constraint` every cache leaf to its TP layout — applied
    INSIDE the serving programs on the returned (donated) cache so the pool
    round-trips every dispatch with one stable sharding: without the pin,
    GSPMD is free to pick a different output layout per program, which would
    (a) silently replicate the pool and (b) change the next dispatch's input
    signature — a recompile the serving discipline forbids."""
    import jax
    from jax.sharding import NamedSharding

    if mesh is None:
        return cache

    def pin(path, leaf):
        parts = []
        for k in path:
            parts.append(str(getattr(k, "key", getattr(k, "name", getattr(k, "idx", k)))))
        spec = _tp_cache_spec("/".join(parts), getattr(leaf, "ndim", 0), axis)
        return jax.lax.with_sharding_constraint(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(pin, cache)


def tree_device_nbytes(tree, device) -> int:
    """Stored bytes of `tree` resident on ONE device — the honest per-chip
    HBM figure for a sharded params/KV tree (a replicated leaf counts its
    full size, a sharded leaf only its local shard), read off the LIVE
    arrays' shardings rather than computed from specs."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            total += int(np.size(leaf)) * np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
            continue
        total += sum(int(s.data.nbytes) for s in shards if s.device == device)
    return total


def data_spec(mesh, extra_seq_axis: bool = False):
    """PartitionSpec for input batches: batch over ("data","fsdp"), optionally sequence
    over "seq" (sequence parallelism; the capability gap called out in SURVEY §5)."""
    from jax.sharding import PartitionSpec

    if extra_seq_axis and mesh.shape.get("seq", 1) > 1:
        return PartitionSpec(("data", "fsdp"), "seq")
    return PartitionSpec(("data", "fsdp"))

"""Model bundles and the prepared-model wrapper (L5 support).

The reference wraps `torch.nn.Module`s in backend wrappers (DDP/FSDP/XLA MpModelWrapper,
accelerator.py:1414-1550). Under GSPMD there is exactly one wrapper: `PreparedModel`,
which binds (apply_fn, params) to a mesh with derived parameter shardings and a
mixed-precision policy. Forward passes are jitted with input/output shardings; parameter
"wrapping" is just placement.

`Model` is the unprepared bundle users hand to `Accelerator.prepare` — flax modules
don't carry their parameters, so the bundle is the JAX equivalent of a torch Module's
(structure + state) pairing.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np

from .logging import get_logger

logger = get_logger(__name__)


def _cast_floating(tree, dtype):
    import jax
    import jax.numpy as jnp

    def _cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(_cast, tree)


@dataclass
class Model:
    """Unprepared model bundle: apply_fn + params (+ the flax module, when there is one).

    Build with `Model.from_flax(module, params)`, `Model.from_fn(apply_fn, params)`, or
    via the in-tree `accelerate_tpu.models` constructors. `loss_fn(params, batch)` is
    optional sugar used by `Accelerator.backward` when the user doesn't pass their own.
    """

    apply_fn: Callable
    params: Any
    module: Any = None
    loss_fn: Optional[Callable] = None
    # Sharding hints: pytree-path-regex -> PartitionSpec tuples, consumed by
    # parallel/sharding.py rule derivation (the TP "module rules" equivalent).
    sharding_rules: Optional[list] = None
    # Planner-emitted ZeRO table for optimizer state (ShardingPlan.opt_rules):
    # moments shard along "data" even where params replicate. Stamped by
    # Accelerator.prepare_model under sharding_rules="auto", read by
    # AcceleratedOptimizer when deriving opt_state_sharding.
    opt_sharding_rules: Optional[list] = None

    @classmethod
    def from_flax(cls, module, params, loss_fn=None, sharding_rules=None) -> "Model":
        return cls(
            apply_fn=module.apply,
            params=params,
            module=module,
            loss_fn=loss_fn,
            sharding_rules=sharding_rules or getattr(module, "sharding_rules", None),
        )

    @classmethod
    def from_fn(cls, apply_fn, params, loss_fn=None, sharding_rules=None) -> "Model":
        return cls(apply_fn=apply_fn, params=params, loss_fn=loss_fn, sharding_rules=sharding_rules)

    def init_weights(self, rng, *sample_args):
        """(Re)initialize params from the flax module."""
        if self.module is None:
            raise ValueError("init_weights requires a flax module")
        self.params = self.module.init(rng, *sample_args)
        return self.params


class PreparedModel:
    """A model placed on the mesh (the single GSPMD 'wrapper'; replaces reference
    DDP/FSDP/MpModelWrapper wrapping accelerator.py:1414-1550).

    - `params` live as global jax.Arrays with derived NamedShardings (replicated for
      DP, sharded over "fsdp"/"model" axes per plugin/rules).
    - `__call__` runs the jitted forward under the mixed-precision policy: params and
      float inputs cast to the compute dtype, float outputs upcast to fp32 (the
      `convert_outputs_to_fp32` contract, reference accelerator.py:1356-1365).
    - `state_dict()`/`load_state_dict()` expose a checkpointable view.
    """

    def __init__(
        self,
        model: Model,
        mesh=None,
        param_sharding=None,
        compute_dtype=None,
        autocast: bool = True,
        fp8_recipe=None,
        offload_params: bool = False,
        param_dtype=None,
        reduce_dtype=None,
        remat_policy: Optional[str] = None,
    ):
        import jax
        import jax.numpy as jnp

        self.module = model.module
        self.apply_fn = model.apply_fn
        self.loss_fn = model.loss_fn
        self.sharding_rules = model.sharding_rules
        self.opt_sharding_rules = getattr(model, "opt_sharding_rules", None)
        self.mesh = mesh
        self.compute_dtype = compute_dtype
        self.autocast_enabled = autocast and compute_dtype is not None
        self.fp8_recipe = fp8_recipe
        if fp8_recipe is not None and getattr(fp8_recipe, "scaling", "dynamic") == "delayed":
            # The prepared-model apply path has no mutable fp8_meta channel, so
            # delayed histories would stay frozen at the cold scale (1.0)
            # FOREVER — a silent ~25% quantization error, worse than dynamic in
            # every way. Surface it rather than let a ported TE config degrade.
            logger.warning(
                "FP8RecipeKwargs(scaling='delayed') through the prepared-model "
                "path keeps amax histories frozen at their init scale (the "
                "apply has no mutable 'fp8_meta' channel). Use the default "
                "dynamic scaling (tighter on TPU — see docs/limitations.md), "
                "or thread meta explicitly via ops.fp8.fp8_matmul_delayed / "
                "fp8_autocast with apply(..., mutable=['fp8_meta'])."
            )
        # FSDP MixedPrecision parity (reference accelerator.py:1486-1540 +
        # dataclasses MixedPrecision fields), GSPMD semantics:
        #   param_dtype — STORAGE dtype of the parameters. Under jax.grad the
        #     gradient (and therefore the on-wire grad reduction XLA inserts)
        #     carries the parameter dtype, so this is also the reduce dtype of
        #     the implicit cross-device psum.
        #   reduce_dtype — arithmetic dtype of explicit gradient accumulation
        #     (the microbatch scan buffer in FusedTrainStep and the eager
        #     accumulate path), where bf16 roll-off across many adds is the
        #     real hazard.
        self.param_dtype = jnp.dtype(param_dtype) if param_dtype is not None else None
        self.reduce_dtype = jnp.dtype(reduce_dtype) if reduce_dtype is not None else None
        # Per-layer activation checkpointing (reference accelerator.py:1460-1474):
        # forward traces under remat_scope, so every in-tree model's layer stack
        # recomputes instead of saving intermediates.
        self.remat_policy = remat_policy
        self._jit_cache: dict = {}

        # Host-offloaded parameters (ZeRO-offload param tier): weights live in
        # pinned host memory and stream to HBM inside each jitted program.
        self.offload_params = False
        self.param_compute_sharding = param_sharding
        if offload_params and param_sharding is not None:
            from .parallel.sharding import host_memory_available, host_memory_kind, with_memory_kind

            if host_memory_available():
                self.offload_params = True
                param_sharding = with_memory_kind(param_sharding, host_memory_kind())
            else:
                logger.warning(
                    "offload_params requested but this backend exposes no host-tier "
                    "memory space (pinned_host/unpinned_host); parameters stay in "
                    "device memory."
                )
        self.param_sharding = param_sharding

        from .parallel.sharding import place_params

        params = model.params
        if self.param_dtype is not None:
            params = _cast_floating(params, self.param_dtype)
        if param_sharding is not None:
            params = place_params(params, param_sharding)
        elif mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            params = place_params(params, jax.tree_util.tree_map(lambda _: NamedSharding(mesh, PartitionSpec()), params))
        else:
            # Still copy: the donated optimizer update would otherwise delete the
            # user's original arrays through the alias.
            params = place_params(params)
        self.params = params
        self._rng = jax.random.key(np.random.randint(0, 2**31 - 1))

    def to_compute_memory(self, params):
        """Traceable: stream host-offloaded params into device memory (identity when
        not offloaded). Call OUTSIDE a grad closure so gradients are device-resident."""
        import jax

        if self.offload_params:
            return jax.device_put(params, self.param_compute_sharding)
        return params

    def to_storage_memory(self, params):
        """Eager: place updated params back on their storage tier (pinned host when
        offloaded, identity otherwise). The write-back half of to_compute_memory."""
        import jax

        if self.offload_params and self.param_sharding is not None:
            return jax.device_put(params, self.param_sharding)
        return params

    # -- forward -----------------------------------------------------------------------
    def _mp_apply(self, params, *args, **kwargs):
        import contextlib

        import jax.numpy as jnp

        from .parallel.sharding import activation_sharding_scope

        # fp8: Dense matmuls run through the fp8 interceptor during tracing
        # (ops/fp8.py, the TE convert_model replacement); other ops stay bf16.
        ctx = contextlib.nullcontext()
        if self.fp8_recipe is not None:
            from .ops.fp8 import fp8_autocast

            ctx = fp8_autocast(self.fp8_recipe)
        # Activation constraints (constrain_activation at the models' residual
        # seams) are active only when the model actually sits on a mesh.
        act_ctx = activation_sharding_scope(self.mesh) if self.mesh is not None else contextlib.nullcontext()
        remat_ctx = contextlib.nullcontext()
        if self.remat_policy is not None:
            from .ops.remat import remat_scope

            remat_ctx = remat_scope(self.remat_policy)
        with ctx, act_ctx, remat_ctx:
            if self.autocast_enabled:
                params = _cast_floating(params, self.compute_dtype)
                args = _cast_floating(args, self.compute_dtype)
                out = self.apply_fn(params, *args, **kwargs)
                return _cast_floating(out, jnp.float32)
            return self.apply_fn(params, *args, **kwargs)

    @property
    def jitted_apply(self):
        import jax

        if "apply" not in self._jit_cache:

            def _fwd(params, *args, **kwargs):
                return self._mp_apply(self.to_compute_memory(params), *args, **kwargs)

            self._jit_cache["apply"] = jax.jit(_fwd)
        return self._jit_cache["apply"]

    def __call__(self, *args, **kwargs):
        return self.jitted_apply(self.params, *args, **kwargs)

    def eval_apply(self, *args, **kwargs):
        return self(*args, **kwargs)

    def apply(self, params, *args, **kwargs):
        """Traceable forward under the mixed-precision policy — use inside loss
        functions and custom jitted steps."""
        return self._mp_apply(params, *args, **kwargs)

    def loss(self, params, batch):
        """The bundled loss under this model's precision policy: differentiable
        `loss(params, batch)`, the canonical argument to `Accelerator.backward`."""
        if self.loss_fn is None:
            raise ValueError("This model bundle has no loss_fn; pass your own loss to backward()")
        return self.loss_fn(params, batch, self._mp_apply)

    # -- rng ---------------------------------------------------------------------------
    def next_rng_key(self):
        import jax

        self._rng, sub = jax.random.split(self._rng)
        return sub

    # -- checkpoint view ---------------------------------------------------------------
    def state_dict(self):
        return self.params

    def load_state_dict(self, params):
        from .parallel.sharding import place_params

        # place_params (not device_put): loaded buffers must not alias the caller's
        # arrays — the optimizer's donated update deletes ours every step.
        self.params = place_params(params, self.param_sharding)

    # -- introspection -----------------------------------------------------------------
    @property
    def num_parameters(self) -> int:
        import jax

        return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(self.params))

    def parameter_bytes(self) -> int:
        import jax

        return sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(self.params))

    def __repr__(self):
        shard_desc = "custom" if self.param_sharding is not None else "replicated"
        return (
            f"PreparedModel(params={self.num_parameters:,}, sharding={shard_desc}, "
            f"compute_dtype={self.compute_dtype}, mesh={dict(self.mesh.shape) if self.mesh else None})"
        )

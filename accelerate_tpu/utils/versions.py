"""Version comparison helpers (parity: reference utils/versions.py:26,46)."""

import importlib.metadata
import operator

from packaging.version import Version, parse

STR_OPERATION_TO_FUNC = {
    ">": operator.gt,
    ">=": operator.ge,
    "==": operator.eq,
    "!=": operator.ne,
    "<=": operator.le,
    "<": operator.lt,
}


def compare_versions(library_or_version, operation: str, requirement_version: str) -> bool:
    """Compare a library version (by name or `Version`) against `requirement_version`."""
    if operation not in STR_OPERATION_TO_FUNC:
        raise ValueError(f"`operation` must be one of {list(STR_OPERATION_TO_FUNC)}, got {operation}")
    if isinstance(library_or_version, str):
        library_or_version = parse(importlib.metadata.version(library_or_version))
    return STR_OPERATION_TO_FUNC[operation](library_or_version, parse(requirement_version))


def is_jax_version(operation: str, version: str) -> bool:
    """Compare the installed jax version against `version`."""
    import jax

    return compare_versions(parse(jax.__version__), operation, version)

"""Pytree-recursive collectives and tensor utilities (L2).

TPU-native redesign of reference utils/operations.py. Two planes:

  - **Data plane** (arrays): across *hosts* via `jax.experimental.multihost_utils`
    (which compiles to XLA collectives over ICI/DCN — the NCCL replacement,
    reference operations.py:308-358,727-765). Inside jit, sharded global arrays make
    most per-rank collectives unnecessary: a "gathered" metric is just the global
    array fetched to host.
  - **Object plane** (arbitrary picklables): pickle → uint8 arrays → XLA broadcast /
    allgather. Notably `gather_object` works here; the reference raises
    NotImplementedError on XLA (operations.py:462-463).

Debug mode (`ACCELERATE_TPU_DEBUG_MODE=1`) wraps every collective in a cross-process
shape/dtype verification (parity: reference `verify_operation` operations.py:361-421),
which catches the classic mismatched-shape distributed hang before it happens.
"""

from __future__ import annotations

import functools
import pickle
from typing import Any, Callable, Mapping

import numpy as np


class DistributedOperationException(Exception):
    """Raised when ranks call a collective with mismatched shapes (reference
    operations.py:30)."""


def is_jax_array(x) -> bool:
    import jax

    return isinstance(x, jax.Array)


def is_array_like(x) -> bool:
    return is_jax_array(x) or isinstance(x, (np.ndarray, np.generic))


def honor_type(obj, generator):
    """Rebuild `obj`'s container type from `generator` (reference operations.py:73)."""
    try:
        if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # namedtuple
            return type(obj)(*list(generator))
        return type(obj)(generator)
    except TypeError:
        # Some objects (e.g. flax structs) may not accept a generator; fall back to list.
        return list(generator)


def recursively_apply(
    func: Callable,
    data: Any,
    *args,
    test_type: Callable = is_array_like,
    error_on_other_type: bool = False,
    **kwargs,
):
    """Apply `func` to every array leaf of a nested list/tuple/namedtuple/Mapping
    (reference operations.py:84)."""
    if isinstance(data, (tuple, list)):
        return honor_type(
            data,
            (
                recursively_apply(
                    func, o, *args, test_type=test_type, error_on_other_type=error_on_other_type, **kwargs
                )
                for o in data
            ),
        )
    elif isinstance(data, Mapping):
        return type(data)(
            {
                k: recursively_apply(
                    func, v, *args, test_type=test_type, error_on_other_type=error_on_other_type, **kwargs
                )
                for k, v in data.items()
            }
        )
    elif test_type(data):
        return func(data, *args, **kwargs)
    elif error_on_other_type:
        raise TypeError(
            f"Unsupported type {type(data)} passed to collective: only nested "
            "list/tuple/dicts of arrays are supported."
        )
    return data


def send_to_device(tensor, device=None, non_blocking: bool = True, skip_keys=None):
    """Recursive host→device transfer (reference operations.py:135).

    `device` may be a jax.Device, a Sharding, or None (default device). Torch tensors
    are converted through numpy so torch dataloaders feed TPU arrays transparently.
    """
    import jax

    if skip_keys is None:
        skip_keys = []
    elif isinstance(skip_keys, str):
        skip_keys = [skip_keys]

    def _to_numpy(t):
        if hasattr(t, "detach") and hasattr(t, "numpy"):  # torch tensor
            return t.detach().cpu().numpy()
        return t

    def _send(t):
        t = _to_numpy(t)
        if not is_array_like(t):
            return t
        return jax.device_put(t, device)

    if isinstance(tensor, Mapping):
        return type(tensor)(
            {k: (v if k in skip_keys else send_to_device(v, device, non_blocking, skip_keys)) for k, v in tensor.items()}
        )
    if isinstance(tensor, (tuple, list)):
        # Recurse through ourselves so skip_keys is honored at any Mapping depth
        # (reference operations.py:135 recurses the same way).
        return honor_type(tensor, (send_to_device(t, device, non_blocking, skip_keys) for t in tensor))

    def _test(t):
        return is_array_like(t) or (hasattr(t, "detach") and hasattr(t, "numpy"))

    return recursively_apply(_send, tensor, test_type=_test)


def get_data_structure(data):
    """Shape/dtype skeleton of a pytree (reference operations.py:174)."""

    def _info(t):
        return {"shape": tuple(np.shape(t)), "dtype": str(np.asarray(t).dtype) if not is_jax_array(t) else str(t.dtype)}

    return recursively_apply(_info, data)


def find_batch_size(data) -> int | None:
    """First dimension of the first array leaf (reference operations.py:240)."""
    if isinstance(data, (tuple, list)):
        for d in data:
            result = find_batch_size(d)
            if result is not None:
                return result
        return None
    elif isinstance(data, Mapping):
        for v in data.values():
            result = find_batch_size(v)
            if result is not None:
                return result
        return None
    elif is_array_like(data) and np.ndim(data) > 0:
        return np.shape(data)[0]
    return None


def listify(data):
    """Arrays → nested python lists (reference operations.py:257)."""

    def _listify(t):
        return np.asarray(t).tolist()

    return recursively_apply(_listify, data)


def slice_tensors(data, tensor_slice, process_index=None, num_processes=None):
    """Slice every array leaf (reference operations.py:272)."""

    def _slice(t, s):
        return t[s]

    return recursively_apply(_slice, data, tensor_slice)


def concatenate(data, dim: int = 0):
    """Concatenate a list of same-structure pytrees leafwise (reference operations.py:600)."""
    import jax.numpy as jnp

    if isinstance(data[0], (tuple, list)):
        return honor_type(data[0], (concatenate([d[i] for d in data], dim=dim) for i in range(len(data[0]))))
    elif isinstance(data[0], Mapping):
        return type(data[0])({k: concatenate([d[k] for d in data], dim=dim) for k in data[0].keys()})
    elif not is_array_like(data[0]):
        raise TypeError(f"Can only concatenate arrays but got {type(data[0])}")
    if isinstance(data[0], np.ndarray):
        return np.concatenate(data, axis=dim)
    return jnp.concatenate(data, axis=dim)


# --------------------------------------------------------------------------------------
# Debug-mode operation verification (reference operations.py:361-421)
# --------------------------------------------------------------------------------------


def verify_operation(function):
    """Cross-process shape check before a collective when debug mode is on."""

    @functools.wraps(function)
    def wrapper(*args, **kwargs):
        from ..state import PartialState

        state = PartialState()
        if not state.debug or state.num_processes == 1:
            return function(*args, **kwargs)
        operation = f"{function.__module__}.{function.__name__}"
        tensor = kwargs.get("tensor", args[0] if args else None)
        shapes = get_data_structure(tensor)
        output = gather_object([shapes])
        if output[0] is not None and not all(x == output[0] for x in output):
            process_shape_str = "\n  - ".join([f"Process {i}: {s}" for i, s in enumerate(output)])
            raise DistributedOperationException(
                f"Cannot apply desired operation due to shape mismatches. All shapes across devices must be valid.\n\n"
                f"Operation: `{operation}`\nInput shapes:\n  - {process_shape_str}"
            )
        return function(*args, **kwargs)

    return wrapper


def chained_operation(function):
    """Re-raise collective errors with context (reference operations.py:405)."""

    @functools.wraps(function)
    def wrapper(*args, **kwargs):
        try:
            return function(*args, **kwargs)
        except DistributedOperationException as e:
            operation = f"{function.__module__}.{function.__name__}"
            raise DistributedOperationException(
                f"Error found while calling `{operation}`. Please see the earlier error for more details."
            ) from e

    return wrapper


# --------------------------------------------------------------------------------------
# Data-plane collectives
# --------------------------------------------------------------------------------------


def _num_processes() -> int:
    import jax

    return jax.process_count()


def _fetch_global(t):
    """Materialize a (possibly sharded) jax.Array on host as numpy.

    For fully-addressable arrays this is a device_get; for multi-host global arrays the
    non-addressable shards are fetched via an allgather.
    """
    import jax

    if is_jax_array(t):
        if t.is_fully_addressable:
            return np.asarray(jax.device_get(t))
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(t, tiled=True))
    return np.asarray(t)


def fetch_global(t):
    """Public alias of `_fetch_global`: materialize a (possibly multi-host sharded)
    jax.Array on host as numpy — the portable way to read a global batch/output."""
    return _fetch_global(t)


@verify_operation
def gather(tensor):
    """All-gather along dim 0 across processes (reference operations.py:425).

    Host-local arrays: every process contributes its array; all receive the dim-0
    concatenation (reference `_tpu_gather`/`_gpu_gather` semantics). Global sharded
    arrays: returns the full global value (the SPMD equivalent — the array already *is*
    the gathered batch).
    """

    def _gather_one(t):
        if is_jax_array(t) and not t.is_fully_addressable:
            return _fetch_global(t)
        if _num_processes() == 1:
            return _fetch_global(t)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(np.asarray(t), tiled=True))

    return recursively_apply(_gather_one, tensor, error_on_other_type=True)


@chained_operation
def gather_object(object: Any):
    """Gather arbitrary picklables from all processes into a list (reference
    operations.py:451 — which is NotImplemented on XLA; supported here via the
    byte-array object plane)."""
    if _num_processes() == 1:
        return list(object) if isinstance(object, list) else [object]
    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(object), dtype=np.uint8)
    sizes = multihost_utils.process_allgather(np.array([payload.size], dtype=np.int64))
    sizes = np.asarray(sizes).reshape(-1)
    max_size = int(sizes.max())
    padded = np.zeros((max_size,), dtype=np.uint8)
    padded[: payload.size] = payload
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    out = []
    for i, size in enumerate(sizes):
        obj = pickle.loads(gathered[i, :size].tobytes())
        if isinstance(obj, list):
            out.extend(obj)
        else:
            out.append(obj)
    return out


@verify_operation
def broadcast(tensor, from_process: int = 0):
    """Broadcast array pytree from one process (reference operations.py:545).

    XLA's broadcast_one_to_all always sources process 0; for other sources we route
    through the object plane."""

    def _broadcast_one(t):
        t = np.asarray(_fetch_global(t))
        if _num_processes() == 1:
            return t
        from jax.experimental import multihost_utils

        if from_process == 0:
            return np.asarray(multihost_utils.broadcast_one_to_all(t))
        # Rare path: non-zero source. Object-plane relay via process 0.
        gathered = gather_object([t])
        return np.asarray(gathered[from_process])

    return recursively_apply(_broadcast_one, tensor, error_on_other_type=True)


@chained_operation
def broadcast_object_list(object_list: list, from_process: int = 0):
    """Broadcast a list of picklables from `from_process` (reference operations.py:566)."""
    if _num_processes() == 1:
        return object_list
    from jax.experimental import multihost_utils

    import jax

    if from_process != 0:
        # gather_object extends lists, so wrap each process's list once more: the result
        # is one sublist per process, indexed directly by rank.
        gathered = gather_object([[list(object_list)]])
        src = gathered[from_process]
        for i in range(len(object_list)):
            object_list[i] = src[i]
        return object_list

    payload = np.frombuffer(pickle.dumps(list(object_list)), dtype=np.uint8)
    size = multihost_utils.broadcast_one_to_all(np.array([payload.size], dtype=np.int64))
    buf = np.zeros((int(size[0]),), dtype=np.uint8)
    if jax.process_index() == from_process:
        buf[:] = payload
    buf = np.asarray(multihost_utils.broadcast_one_to_all(buf))
    result = pickle.loads(buf.tobytes())
    for i in range(len(object_list)):
        object_list[i] = result[i]
    return object_list


@verify_operation
def reduce(tensor, reduction: str = "mean", scale: float = 1.0):
    """Cross-process reduce (reference operations.py:727-765 with its XLA `scale` arg)."""

    def _reduce_one(t):
        # A non-addressable global array is already a single cross-host value; summing
        # per-host copies would over-count by num_processes (gather() has the same branch).
        if is_jax_array(t) and not t.is_fully_addressable:
            return _fetch_global(t) * scale
        arr = _fetch_global(t)
        if _num_processes() > 1:
            from jax.experimental import multihost_utils

            stacked = np.asarray(multihost_utils.process_allgather(np.asarray(arr)))
            arr = stacked.sum(axis=0)
            if reduction == "mean":
                arr = arr / _num_processes()
        arr = arr * scale
        return arr

    return recursively_apply(_reduce_one, tensor, error_on_other_type=True)


@verify_operation
def pad_across_processes(tensor, dim: int = 0, pad_index: int = 0, pad_first: bool = False):
    """Pad each process's array to the max size along `dim` (reference operations.py:634)."""

    def _pad_one(t):
        arr = np.asarray(_fetch_global(t))
        if arr.ndim == 0 or dim >= arr.ndim:
            return arr
        size = np.array(arr.shape, dtype=np.int64)
        if _num_processes() == 1:
            return arr
        from jax.experimental import multihost_utils

        sizes = np.asarray(multihost_utils.process_allgather(size))
        max_size = int(sizes[:, dim].max())
        if max_size == arr.shape[dim]:
            return arr
        old_size = arr.shape
        new_size = list(old_size)
        new_size[dim] = max_size
        new_tensor = np.full(new_size, pad_index, dtype=arr.dtype)
        if pad_first:
            indices = tuple(
                slice(max_size - old_size[dim], max_size) if i == dim else slice(None) for i in range(arr.ndim)
            )
        else:
            indices = tuple(slice(0, old_size[dim]) if i == dim else slice(None) for i in range(arr.ndim))
        new_tensor[indices] = arr
        return new_tensor

    return recursively_apply(_pad_one, tensor, error_on_other_type=True)


def pad_input_tensors(tensor, batch_size: int, num_processes: int, dim: int = 0):
    """Pad dim 0 so it divides num_processes (reference operations.py:686, used by the
    batch dispatcher and pipeline inference)."""

    def _pad_one(t):
        arr = np.asarray(t)
        remainder = arr.shape[dim] % num_processes
        if remainder == 0:
            return arr
        pad_count = num_processes - remainder
        pad_block = np.repeat(np.take(arr, [-1], axis=dim), pad_count, axis=dim)
        return np.concatenate([arr, pad_block], axis=dim)

    return recursively_apply(_pad_one, tensor, error_on_other_type=True)


# --------------------------------------------------------------------------------------
# Slot-row gather/scatter over cache pytrees (serving.py's continuous batcher)
# --------------------------------------------------------------------------------------

# The flax "cache" collection leaves and the axis their BATCH (slot) dimension
# lives at, counted from the BACK so the same rule covers plain stacks
# ([B, L, h, d]) and nn.scan-stacked layers ([layers, B, L, h, d]).
_SLOT_AXIS_FROM_BACK = {"cached_key": 4, "cached_value": 4, "pad_mask": 2}


def _key_name(entry) -> str:
    """DictKey/GetAttrKey/SequenceKey path entry -> plain string."""
    return str(getattr(entry, "key", getattr(entry, "name", entry)))


def _leaf_name(path) -> str:
    return _key_name(path[-1])


def tree_scatter_rows(dst, src, index):
    """Write `src`'s single slot row into `dst` at row `index` for every cache
    leaf: `dst.cached_*[..., index:index+1, :, :, :] = src.cached_*`. This is how
    a freshly-prefilled batch-1 KV cache is INSERTED into a slot of the shared
    `num_slots`-row serving cache without the model ever seeing a slot index —
    jit-traceable (`index` may be a traced scalar), so the whole insert program
    compiles once per prompt bucket.

    Leaves not in the slot-axis table (e.g. the scalar `cache_index`, meaningless
    per-slot) keep `dst`'s value; leaves present only in `src` are dropped.
    """
    import jax
    import jax.numpy as jnp

    src_leaves = {
        tuple(_key_name(p) for p in path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(src)[0]
    }

    def _scatter(path, d):
        names = tuple(_key_name(p) for p in path)
        axis_back = _SLOT_AXIS_FROM_BACK.get(names[-1])
        s = src_leaves.get(names)
        if axis_back is None or s is None:
            return d
        axis = d.ndim - axis_back
        start = [jnp.int32(0)] * d.ndim
        start[axis] = jnp.asarray(index, jnp.int32)
        return jax.lax.dynamic_update_slice(d, s.astype(d.dtype), tuple(start))

    return jax.tree_util.tree_map_with_path(_scatter, dst)


def tree_gather_rows(tree, index):
    """Slice slot row `index` out of every cache leaf (the inverse of
    `tree_scatter_rows`): returns a batch-1 cache view for debugging/tests.
    Non-slot leaves (scalars like `cache_index`) pass through unchanged."""
    import jax
    import jax.numpy as jnp

    def _gather(path, t):
        axis_back = _SLOT_AXIS_FROM_BACK.get(_leaf_name(path))
        if axis_back is None:
            return t
        axis = t.ndim - axis_back
        return jax.lax.dynamic_slice_in_dim(t, jnp.asarray(index, jnp.int32), 1, axis=axis)

    return jax.tree_util.tree_map_with_path(_gather, tree)


# --------------------------------------------------------------------------------------
# Page-pool gather/scatter over cache pytrees (serving.py's paged KV cache)
# --------------------------------------------------------------------------------------

# K/V leaves of a PAGED slot cache are pool-shaped: [..., num_pages, page_size,
# heads, head_dim] — the page axis sits where the dense cache's batch axis sits
# (4 from the back), so the same rule covers plain stacks and nn.scan-stacked
# layers ([layers, num_pages, page_size, h, d]).
_PAGE_AXIS_FROM_BACK = {"cached_key": 4, "cached_value": 4}

# Per-page-per-head scale pools of a QUANTIZED paged cache
# (ops/quantization.py): [..., num_pages, heads] f32, page axis 2 from the
# back. `_SCALE_OF` maps a K/V pool leaf to its sibling scale leaf; the
# gather/scatter below dequantize/quantize through it so the insert path and
# the decode write path can never disagree about a page's scale.
_SCALE_AXIS_FROM_BACK = {"key_scale": 2, "value_scale": 2}
_SCALE_OF = {"cached_key": "key_scale", "cached_value": "value_scale"}
_KV_OF = {v: k for k, v in _SCALE_OF.items()}


def _path_names(path):
    return tuple(_key_name(p) for p in path)


def tree_gather_pages(pool, dense_struct, page_ids, cache_index):
    """Materialize a batch-1 DENSE decode cache from pool pages: for every
    `cached_key`/`cached_value` leaf, gather `pool_leaf[page_ids]`
    ([P, page_size, h, d]) and merge the page axes into one contiguous
    [1, P*page_size, h, d] row; fill `cache_index` leaves with the traced
    `cache_index` scalar (the number of tokens already valid in the gathered
    prefix). `dense_struct` is the eval_shape pytree of the dense prefill
    module's cache — it fixes the output tree layout and shapes.

    jit-traceable (`page_ids` [P] int32 and `cache_index` may be traced
    operands); the serving engine's paged insert uses this to give a suffix
    prefill an attention view over shared prefix pages without ever owning a
    dense per-slot cache."""
    import jax
    import jax.numpy as jnp

    pool_leaves = {
        _path_names(path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(pool)[0]
    }

    def _build(path, struct):
        names = _path_names(path)
        axis_back = _PAGE_AXIS_FROM_BACK.get(names[-1])
        if axis_back is None:
            if names[-1] == "cache_index":
                return jnp.full(struct.shape, jnp.asarray(cache_index, struct.dtype))
            if names[-1] == "pad_mask":
                return jnp.ones(struct.shape, struct.dtype)
            return jnp.zeros(struct.shape, struct.dtype)
        leaf = pool_leaves.get(names)
        if leaf is None:
            raise ValueError(f"pool cache has no leaf at {'/'.join(names)}")
        axis = leaf.ndim - axis_back
        pages = jnp.take(leaf, jnp.asarray(page_ids, jnp.int32), axis=axis)
        scale_leaf = pool_leaves.get(names[:-1] + (_SCALE_OF.get(names[-1], ""),))
        if scale_leaf is not None:
            # Quantized pool: dequantize the gathered pages with their
            # per-page-per-head scales so the dense prefill sees real values.
            scale_axis = scale_leaf.ndim - _SCALE_AXIS_FROM_BACK[_SCALE_OF[names[-1]]]
            pages_scale = jnp.take(
                scale_leaf, jnp.asarray(page_ids, jnp.int32), axis=scale_axis
            )
            # Insert the page_size axis after the page axis and the head_dim
            # axis at the end, then broadcast-multiply in fp32.
            scale_b = jnp.expand_dims(pages_scale, axis + 1)[..., None]
            pages = pages.astype(jnp.float32) * scale_b
        merged = pages.reshape(
            pages.shape[:axis]
            + (pages.shape[axis] * pages.shape[axis + 1],)
            + pages.shape[axis + 2 :]
        )
        dense = jnp.expand_dims(merged, axis)  # the batch-1 slot axis
        if dense.shape != struct.shape:
            raise ValueError(
                f"gathered pages for {'/'.join(names)} have shape {dense.shape}, "
                f"dense prefill cache expects {struct.shape} — page count x page "
                "size must equal the dense cache length"
            )
        return dense.astype(struct.dtype)

    return jax.tree_util.tree_map_with_path(_build, dense_struct)


def tree_zero_cache_tail(dense, valid_len):
    """Zero every `cached_key`/`cached_value` row of a dense cache at
    positions >= `valid_len` (a traced scalar). The paged insert runs this
    before `tree_scatter_pages`: the gathered dense cache carries STALE
    dequantized content from each private page's previous occupant beyond the
    prompt, and while the position mask keeps it unattended, a QUANTIZED
    scatter would fold it into the boundary page's amax scale — a prior
    occupant with larger K/V magnitudes would silently coarsen the new
    request's real rows past the half-step round-trip bound (and decode's
    scatter-max would keep the inflated scale alive). Zeros contribute
    nothing to amax, restoring the bound; on unquantized pools this is pure
    hygiene."""
    import jax
    import jax.numpy as jnp

    def _zero(path, leaf):
        name = _leaf_name(path)
        if name not in _PAGE_AXIS_FROM_BACK:  # cached_key / cached_value only
            return leaf
        seq_axis = leaf.ndim - 3  # [..., batch, L, heads, head_dim]
        cols = jnp.arange(leaf.shape[seq_axis])
        keep = (cols < jnp.asarray(valid_len, jnp.int32)).reshape(
            (leaf.shape[seq_axis],) + (1,) * (leaf.ndim - seq_axis - 1)
        )
        return jnp.where(keep, leaf, jnp.zeros((), leaf.dtype))

    return jax.tree_util.tree_map_with_path(_zero, dense)


def tree_scatter_pages(pool, dense, page_ids):
    """Write a batch-1 dense cache back into pool pages (the inverse of
    `tree_gather_pages`): every `cached_key`/`cached_value` leaf is split into
    [P, page_size] blocks and scattered to `pool_leaf[page_ids[j]]`. Leaves the
    pool has no entry for in `dense` (the dense path's `cache_index` scalar,
    meaningless pool-side) keep the pool's value.

    Callers that must not rewrite shared read-only prefix pages redirect those
    entries of `page_ids` to the reserved scratch page before calling (the
    serving engine's insert does exactly that), so a registered prefix page is
    written exactly once — at creation — for its whole lifetime.

    QUANTIZED pools (int8/fp8 K/V leaves with sibling `key_scale`/
    `value_scale` pool arrays): the dense float blocks are quantized whole-page
    (per-page-per-head amax scales, `ops.quantization.quantize_kv_pages`) and
    the scale leaves are scattered at the same `page_ids` — so an
    insert-written page round-trips within half a quantization step and the
    decode write path (`quantized_pool_write`) can grow its scale from there."""
    import jax
    import jax.numpy as jnp

    from ..ops.quantization import kv_spec_for_dtype, quantize_kv_pages

    dense_leaves = {
        _path_names(path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(dense)[0]
    }
    pool_leaves = {
        _path_names(path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(pool)[0]
    }
    ids = jnp.asarray(page_ids, jnp.int32)

    def _kv_blocks_front(names, kv_leaf):
        """Dense K/V leaf -> page blocks with the page axis at the FRONT
        ([P, ..., page_size, h, head_dim]), or None when absent in `dense`."""
        d = dense_leaves.get(names)
        if d is None:
            return None
        axis = kv_leaf.ndim - _PAGE_AXIS_FROM_BACK[names[-1]]
        d = jnp.squeeze(d, axis=axis)  # drop the batch-1 slot axis
        page_size = kv_leaf.shape[axis + 1]
        num = ids.shape[0]
        blocks = d.reshape(d.shape[:axis] + (num, page_size) + d.shape[axis + 1 :])
        return jnp.moveaxis(blocks, axis, 0)

    def _scatter(path, leaf):
        names = _path_names(path)
        name = names[-1]
        if name in _SCALE_AXIS_FROM_BACK:
            # Scale pool leaf: recompute the written pages' per-head scales
            # from the dense sibling K/V and scatter them alongside.
            kv_names = names[:-1] + (_KV_OF[name],)
            kv_leaf = pool_leaves.get(kv_names)
            spec = kv_spec_for_dtype(kv_leaf.dtype) if kv_leaf is not None else None
            blocks = _kv_blocks_front(kv_names, kv_leaf) if spec is not None else None
            if blocks is None:
                return leaf
            _, scales = quantize_kv_pages(blocks, spec)
            axis = leaf.ndim - _SCALE_AXIS_FROM_BACK[name]
            front = jnp.moveaxis(leaf, axis, 0)
            return jnp.moveaxis(front.at[ids].set(scales.astype(leaf.dtype)), 0, axis)
        axis_back = _PAGE_AXIS_FROM_BACK.get(name)
        if axis_back is None or names not in dense_leaves:
            return leaf
        axis = leaf.ndim - axis_back
        blocks_front = _kv_blocks_front(names, leaf)
        spec = (
            kv_spec_for_dtype(leaf.dtype)
            if names[:-1] + (_SCALE_OF[name],) in pool_leaves
            else None
        )
        if spec is not None:
            blocks_front, _ = quantize_kv_pages(blocks_front, spec)
        pool_front = jnp.moveaxis(leaf, axis, 0)
        out = pool_front.at[ids].set(blocks_front.astype(leaf.dtype))
        return jnp.moveaxis(out, 0, axis)

    return jax.tree_util.tree_map_with_path(_scatter, pool)


# --------------------------------------------------------------------------------------
# fp32 output conversion (reference operations.py:768-827)
# --------------------------------------------------------------------------------------


def convert_to_fp32(tensor):
    """Upcast float16/bfloat16 leaves to float32 (reference operations.py:768)."""
    import jax.numpy as jnp

    def _convert(t):
        return t.astype(jnp.float32) if is_jax_array(t) else np.asarray(t, dtype=np.float32)

    def _is_half(t):
        dt = t.dtype if hasattr(t, "dtype") else np.asarray(t).dtype
        return str(dt) in ("float16", "bfloat16")

    return recursively_apply(_convert, tensor, test_type=lambda t: is_array_like(t) and _is_half(t))


class ConvertOutputsToFp32:
    """Picklable forward-output fp32 converter (reference operations.py:802)."""

    def __init__(self, model_forward):
        self.model_forward = model_forward
        functools.update_wrapper(self, model_forward)

    def __call__(self, *args, **kwargs):
        return convert_to_fp32(self.model_forward(*args, **kwargs))

    def __getstate__(self):
        raise pickle.PicklingError(
            "Cannot pickle a prepared model with automatic mixed precision; unwrap it first with "
            "`extract_model_from_parallel`."
        )


convert_outputs_to_fp32 = ConvertOutputsToFp32

"""Environment parsing and hardware probing.

Parity: reference utils/environment.py (str_to_bool :58, get_int_from_env :73,
parse_flag_from_env :82, GPU probing :100-143, NUMA affinity :220-296). The hardware
probes here are TPU-shaped: ICI mesh topology from the JAX device list instead of
nvidia-smi, and host memory from /proc instead of pynvml.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from dataclasses import dataclass


def str_to_bool(value: str) -> int:
    """Convert a string (env-var) truth value to 1/0. Raises on unrecognized values."""
    value = value.lower()
    if value in ("y", "yes", "t", "true", "on", "1"):
        return 1
    if value in ("n", "no", "f", "false", "off", "0"):
        return 0
    raise ValueError(f"invalid truth value {value}")


def get_int_from_env(env_keys, default):
    """Return the first positive int found under any of `env_keys`."""
    for e in env_keys:
        val = int(os.environ.get(e, -1))
        if val >= 0:
            return val
    return default


def parse_flag_from_env(key: str, default: bool = False) -> bool:
    value = os.environ.get(key, str(default))
    return bool(str_to_bool(value))


def parse_choice_from_env(key: str, default: str = "no") -> str:
    return os.environ.get(key, str(default))


def get_host_memory_bytes() -> int:
    """Total host RAM in bytes (used by the big-model device-map planner)."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


def get_available_host_memory_bytes() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return get_host_memory_bytes() // 2


_FENCE_ON_CPU: bool | None = None


def fence_if_cpu(tree) -> None:
    """Host-sync `tree` when running on the XLA:CPU backend (the virtual-mesh
    dev/test surface); no-op on TPU/GPU.

    XLA:CPU deadlocks under async dispatch of partitioned programs: with K
    optimizer steps in flight, partitions of DIFFERENT steps hold the client's
    worker threads waiting on DIFFERENT channel-collective rendezvous, and on
    a small host the next step's partitions can starve the previous step's
    last participant forever (observed: 3/4 partitions joined, termination at
    the full rendezvous deadline on an idle box). One host sync per step caps
    in-flight programs at one step. Real TPU/GPU runtimes schedule per-device
    queues and need (and get) no such fence."""
    global _FENCE_ON_CPU
    if _FENCE_ON_CPU is None:
        import jax

        _FENCE_ON_CPU = jax.devices()[0].platform == "cpu"
    if _FENCE_ON_CPU:
        import jax

        jax.block_until_ready(tree)


@dataclass
class TpuTopology:
    """ICI topology discovered from the JAX device list (replaces nvidia-smi probing,
    reference utils/environment.py:100-143)."""

    num_devices: int
    num_hosts: int
    local_device_count: int
    device_kind: str
    coords: list | None = None

    @property
    def devices_per_host(self) -> int:
        return self.local_device_count


def get_tpu_topology() -> TpuTopology:
    import jax

    devices = jax.devices()
    coords = [getattr(d, "coords", None) for d in devices]
    return TpuTopology(
        num_devices=len(devices),
        num_hosts=jax.process_count(),
        local_device_count=jax.local_device_count(),
        device_kind=devices[0].device_kind if devices else "cpu",
        coords=coords if all(c is not None for c in coords) else None,
    )


# Peak dense-bf16 FLOP/s per chip, by device kind, for MFU accounting. Public numbers from
# cloud.google.com/tpu/docs (v4: 275e12, v5e: 197e12, v5p: 459e12, v6e "Trillium": 918e12).
DEVICE_PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v4 lite": 275e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v6e": 918e12,
    "TPU v6 lite": 918e12,
    "TPU7x": 2307e12,
}


def get_device_peak_flops(device_kind: str, dtype: str = "bf16") -> float:
    """Best-effort peak FLOP/s for a device kind; 0.0 when unknown (MFU then unreported).

    Longest name first, so "TPU v5 lite" matches its own entry rather than "TPU v5".
    """
    kind = device_kind.lower()
    for k in sorted(DEVICE_PEAK_FLOPS, key=len, reverse=True):
        if kind.startswith(k.lower()) or k.lower() in kind:
            return DEVICE_PEAK_FLOPS[k]
    return 0.0


def set_host_device_count_flag(flags: str, num_devices: int, override: bool = True) -> str:
    """Return XLA_FLAGS with `--xla_force_host_platform_device_count=N` set.
    `override=False` keeps an existing count (explicit-beats-inherited contract
    shared by the launch CLI and the test harness)."""
    import re

    if "--xla_force_host_platform_device_count" not in flags:
        return (flags + f" --xla_force_host_platform_device_count={num_devices}").strip()
    if not override:
        return flags
    return re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        f"--xla_force_host_platform_device_count={num_devices}",
        flags,
    )


@contextmanager
def clear_environment():
    """Temporarily empty os.environ (parity: reference utils/other.py:211)."""
    _old = os.environ.copy()
    os.environ.clear()
    try:
        yield
    finally:
        os.environ.clear()
        os.environ.update(_old)


@contextmanager
def patch_environment(**kwargs):
    """Temporarily set env vars (upper-cased keys); restores previous values on exit
    (parity: reference utils/other.py:246)."""
    existing = {}
    for key, value in kwargs.items():
        key = key.upper()
        if key in os.environ:
            existing[key] = os.environ[key]
        os.environ[key] = str(value)
    try:
        yield
    finally:
        for key in kwargs:
            key = key.upper()
            if key in existing:
                os.environ[key] = existing[key]
            else:
                os.environ.pop(key, None)

"""Seeding and cross-process RNG synchronization.

Parity: reference utils/random.py (set_seed :31, synchronize_rng_states :64-124). The JAX
twist: device-side randomness is explicit (threaded PRNG keys), so "synchronizing RNG"
means synchronizing the *host-side* generators that drive data order (python/numpy and
the sampler generator). Device keys are made identical across processes by construction —
every process folds the same seed — so no broadcast is needed for them.
"""

from __future__ import annotations

import random
from typing import Iterable, Optional

import numpy as np

from .dataclasses import RNGType


def set_seed(seed: int, device_specific: bool = False, deterministic: bool = False) -> int:
    """Seed python, numpy, and return a JAX PRNG seed.

    Args:
        seed: base seed.
        device_specific: fold in the process index so each host draws different data
            noise (parity: reference utils/random.py:45-47).
        deterministic: accepted for parity; XLA is deterministic by default on TPU.

    Returns the (possibly process-adjusted) seed, to be used for `jax.random.key`.
    """
    if device_specific:
        import jax

        seed += jax.process_index()
    random.seed(seed)
    np.random.seed(seed % (2**32))
    return seed


def synchronize_rng_state(rng_type: Optional[RNGType] = None, generator=None):
    """Broadcast rank-0's host RNG state to all processes (parity: reference
    utils/random.py:64-111).

    Host-side generators (python/numpy/sampler) must agree across processes so that every
    host shards the same global shuffle. States are serialized and broadcast through the
    object plane (multihost pickle broadcast); on a single host this is a no-op.
    """
    import jax

    if jax.process_count() == 1:
        return
    from .operations import broadcast_object_list

    if rng_type == RNGType.PYTHON:
        state = [random.getstate()]
        state = broadcast_object_list(state, from_process=0)
        random.setstate(state[0])
    elif rng_type == RNGType.NUMPY:
        state = [np.random.get_state()]
        state = broadcast_object_list(state, from_process=0)
        np.random.set_state(state[0])
    elif rng_type == RNGType.GENERATOR and generator is not None:
        # The pipeline's synchronized generator is a SeedableRandomSampler
        # (state_dict/load_state_dict); torch generators expose get_state/set_state.
        if hasattr(generator, "state_dict"):
            state = [generator.state_dict()]
            state = broadcast_object_list(state, from_process=0)
            generator.load_state_dict(state[0])
        else:
            state = [generator.get_state()]
            state = broadcast_object_list(state, from_process=0)
            generator.set_state(state[0])
    elif rng_type == RNGType.JAX:
        # JAX keys are value-identical across processes by construction; nothing to sync.
        return


def synchronize_rng_states(rng_types: Iterable[str], generator=None):
    for rng_type in rng_types:
        synchronize_rng_state(RNGType(rng_type), generator=generator)


class NumpyRNGState:
    """Checkpointable snapshot of host RNG streams (python+numpy), used by
    checkpointing.save_accelerator_state (parity: reference checkpointing.py:122-151)."""

    @staticmethod
    def capture() -> dict:
        return {"python": random.getstate(), "numpy": np.random.get_state()}

    @staticmethod
    def restore(state: dict):
        if "python" in state:
            random.setstate(state["python"])
        if "numpy" in state:
            np.random.set_state(state["numpy"])

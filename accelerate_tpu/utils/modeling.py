"""Model size accounting + device-map planning (parity: reference utils/modeling.py,
1826 LoC — the subtle core is `infer_auto_device_map` :1095-1395).

TPU-native re-targeting: the memory tiers are **HBM (per TPU device) → host DRAM →
disk**, and "module" granularity is pytree path prefixes (flax modules are name-scoped
dicts, so a block = everything under `params/layer_3/...`). The planner keeps the
reference's contract: greedy first-fit in declaration order, reserving room on compute
devices for the largest single block, tied weights co-located.
"""

from __future__ import annotations

import os
import re
from collections import OrderedDict, defaultdict
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from ..logging import get_logger
from .dataclasses import CustomDtype
from .environment import get_available_host_memory_bytes

logger = get_logger(__name__)

DTYPE_BYTES = {"float32": 4, "float64": 8, "bfloat16": 2, "float16": 2, "int64": 8, "int32": 4, "int8": 1, "uint8": 1, "bool": 1}


def dtype_byte_size(dtype) -> float:
    """Bytes per element, incl. sub-byte custom dtypes (reference modeling.py:124)."""
    if isinstance(dtype, CustomDtype):
        return {"int4": 0.5, "fp8": 1, "int8": 1}[dtype.value]
    name = getattr(dtype, "name", str(dtype))
    if name in DTYPE_BYTES:
        return DTYPE_BYTES[name]
    m = re.search(r"(\d+)$", name)
    if m:
        return int(m.group(1)) / 8
    raise ValueError(f"Unknown dtype {dtype}")


def named_parameter_shapes(params) -> "OrderedDict[str, tuple]":
    """path -> (shape, dtype) for every leaf, in declaration order."""
    from ..parallel.sharding import tree_paths_and_leaves

    flat, _ = tree_paths_and_leaves(params)
    out = OrderedDict()
    for path, leaf in flat:
        shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
        dtype = getattr(leaf, "dtype", np.asarray(leaf).dtype)
        out[path] = (shape, dtype)
    return out


def compute_module_sizes(params, dtype=None, special_dtypes: Optional[dict] = None) -> Dict[str, int]:
    """Size in bytes of every module (path prefix) incl. "" for the whole model
    (reference modeling.py:706)."""
    sizes = defaultdict(int)
    for path, (shape, leaf_dtype) in named_parameter_shapes(params).items():
        if special_dtypes is not None and path in special_dtypes:
            size = int(np.prod(shape) * dtype_byte_size(special_dtypes[path]))
        elif dtype is not None:
            size = int(np.prod(shape) * dtype_byte_size(dtype))
        else:
            size = int(np.prod(shape) * dtype_byte_size(leaf_dtype))
        parts = path.split("/")
        for i in range(len(parts) + 1):
            sizes["/".join(parts[:i])] += size
    return dict(sizes)


def group_into_blocks(params, no_split_prefixes: Optional[List[str]] = None, block_depth: int = 2) -> "OrderedDict[str, list]":
    """Block name -> [param paths]: the placement granularity.

    Blocks are path prefixes at `block_depth` (default: `params/<module>`), so each
    transformer layer is one block — the analogue of the reference's leaf-module
    iteration with no-split classes (reference modeling.py:1095 uses module classes; a
    pytree has no classes, so depth + explicit prefixes express the same thing).
    """
    blocks: "OrderedDict[str, list]" = OrderedDict()
    for path in named_parameter_shapes(params):
        parts = path.split("/")
        prefix = "/".join(parts[:block_depth]) if len(parts) > block_depth else path
        if no_split_prefixes:
            for nsp in no_split_prefixes:
                # '/'-boundary match: 'params/layer_1' must not capture layer_10..19.
                if path == nsp or path.startswith(nsp + "/"):
                    prefix = nsp
                    break
        blocks.setdefault(prefix, []).append(path)
    return blocks


def get_max_memory(max_memory: Optional[dict] = None) -> "OrderedDict[str, int]":
    """Tier budgets: one entry per accelerator device (by index), then "cpu" and "disk"
    (reference modeling.py:799 builds the same dict from torch.cuda probing).

    Values accept ints (bytes) or strings like "10GiB"/"200MB".
    """
    import jax

    if max_memory is not None:
        parsed = OrderedDict()
        for k, v in max_memory.items():
            if isinstance(v, str):
                parsed[k] = parse_memory_string(v)
            else:
                parsed[k] = v if v == float("inf") else int(v)
        return parsed
    out = OrderedDict()
    for i, dev in enumerate(jax.local_devices()):
        stats = {}
        try:
            stats = dev.memory_stats() or {}
        except Exception:
            pass
        limit = stats.get("bytes_limit")
        if limit is None:
            # CPU "devices" have no HBM; give them a nominal slice of host RAM.
            limit = get_available_host_memory_bytes() // max(1, len(jax.local_devices())) // 2
        out[i] = int(limit * 0.9)
    out["cpu"] = int(get_available_host_memory_bytes() * 0.9)
    out["disk"] = float("inf")
    return out


_MEMORY_UNITS = {"B": 1, "KB": 10**3, "MB": 10**6, "GB": 10**9, "TB": 10**12, "KIB": 2**10, "MIB": 2**20, "GIB": 2**30, "TIB": 2**40}


def parse_memory_string(value: str) -> int:
    m = re.fullmatch(r"\s*([\d.]+)\s*([KMGT]?I?B)\s*", value.upper())
    if not m:
        raise ValueError(f"Cannot parse memory string {value!r}")
    return int(float(m.group(1)) * _MEMORY_UNITS[m.group(2)])


def get_balanced_memory(params, max_memory: Optional[dict] = None, dtype=None, low_zero: bool = False) -> dict:
    """Even out per-device budgets so layers spread across all devices instead of
    first-fit filling device 0 (reference modeling.py:943-1074)."""
    max_memory = get_max_memory(max_memory)
    devices = [k for k in max_memory if k not in ("cpu", "disk")]
    if len(devices) <= 1:
        return max_memory
    sizes = compute_module_sizes(params, dtype=dtype)
    total = sizes[""]
    per_device = total // (len(devices) - (1 if low_zero else 0))
    blocks = group_into_blocks(params)
    # Leave room for the largest block on each device (the reference's buffer heuristic).
    largest_block = max(
        sum(
            int(np.prod(shape) * dtype_byte_size(dtype or leaf_dtype))
            for p2, (shape, leaf_dtype) in named_parameter_shapes(params).items()
            if p2 in paths
        )
        for paths in ({p: None for p in b} for b in blocks.values())
    )
    budget = per_device + largest_block
    out = OrderedDict()
    for k in max_memory:
        if k in ("cpu", "disk"):
            out[k] = max_memory[k]
        elif low_zero and k == devices[0]:
            out[k] = min(max_memory[k], largest_block)
        else:
            out[k] = min(max_memory[k], budget)
    return out


def find_tied_parameters(params) -> List[List[str]]:
    """Groups of paths sharing the same underlying buffer (reference modeling.py:606).

    Flax pytrees rarely alias, but converted checkpoints (tied embeddings) can; detect
    via id() of the leaf arrays."""
    from ..parallel.sharding import tree_paths_and_leaves

    flat, _ = tree_paths_and_leaves(params)
    by_id = defaultdict(list)
    for path, leaf in flat:
        if hasattr(leaf, "__array__") or hasattr(leaf, "shape"):
            by_id[id(leaf)].append(path)
    return [paths for paths in by_id.values() if len(paths) > 1]


def infer_auto_device_map(
    params,
    max_memory: Optional[dict] = None,
    no_split_prefixes: Optional[List[str]] = None,
    dtype=None,
    special_dtypes: Optional[dict] = None,
    verbose: bool = False,
) -> "OrderedDict[str, Union[int, str]]":
    """Greedy first-fit of blocks onto device(s) → cpu → disk
    (reference modeling.py:1095-1395).

    Returns block-path → tier ("cpu"/"disk"/device index). Contract kept from the
    reference: iterate blocks in declaration order; compute devices reserve headroom
    for the largest block (weights streamed in must coexist with the resident ones);
    tied params land with their first occurrence's block.
    """
    max_memory = get_max_memory(max_memory)
    shapes = named_parameter_shapes(params)
    blocks = group_into_blocks(params, no_split_prefixes)

    def block_size(paths) -> int:
        total = 0
        for p in paths:
            shape, leaf_dtype = shapes[p]
            if special_dtypes and p in special_dtypes:
                total += int(np.prod(shape) * dtype_byte_size(special_dtypes[p]))
            else:
                total += int(np.prod(shape) * dtype_byte_size(dtype or leaf_dtype))
        return total

    sizes = {name: block_size(paths) for name, paths in blocks.items()}
    largest = max(sizes.values())

    tiers: List[Tuple[Union[int, str], float]] = []
    for key, budget in max_memory.items():
        tiers.append((key, budget))

    device_map: "OrderedDict[str, Union[int, str]]" = OrderedDict()
    used = defaultdict(int)
    tier_order = [k for k, _ in tiers]

    tied_groups = find_tied_parameters(params)
    tied_home: Dict[str, str] = {}

    for name, paths in blocks.items():
        # tied params: if any path's buddy already placed, co-locate
        placed = None
        for group in tied_groups:
            if any(p in paths for p in group):
                for other in group:
                    for prev_block, tier in device_map.items():
                        if other in blocks.get(prev_block, []):
                            placed = tier
                            break
        if placed is not None:
            device_map[name] = placed
            used[placed] += sizes[name]
            continue
        size = sizes[name]
        chosen = None
        for tier in tier_order:
            budget = max_memory[tier]
            headroom = largest if not isinstance(tier, str) else 0  # devices keep stream room
            if used[tier] + size + headroom <= budget:
                chosen = tier
                break
        if chosen is None:
            chosen = "disk"
        device_map[name] = chosen
        used[chosen] += size
        if verbose:
            logger.info("block %s (%s bytes) -> %s", name, size, chosen)
    return device_map


def clean_device_map(device_map: dict) -> dict:
    """Collapse children mapped to the same tier onto their parent prefix
    (reference modeling.py:880)."""
    values = set(device_map.values())
    if len(values) == 1:
        return {"": next(iter(values))}
    out = dict(device_map)
    changed = True
    while changed:
        changed = False
        groups = defaultdict(list)
        for k in list(out):
            parts = k.split("/")
            if len(parts) > 1:
                groups["/".join(parts[:-1])].append(k)
        for parent, kids in groups.items():
            vals = {out[k] for k in kids}
            if len(vals) == 1 and len(kids) > 1:
                v = vals.pop()
                for k in kids:
                    del out[k]
                out[parent] = v
                changed = True
    return out


def calculate_maximum_sizes(params) -> Tuple[int, Tuple[int, str]]:
    """(total_bytes, (largest_block_bytes, name)) — reference modeling.py:1077."""
    sizes = compute_module_sizes(params)
    total = sizes[""]
    blocks = group_into_blocks(params)
    largest_name, largest = "", 0
    for name in blocks:
        if sizes.get(name, 0) > largest:
            largest, largest_name = sizes[name], name
    return total, (largest, largest_name)


def load_safetensors_state_dict(path: str) -> dict:
    """Flat name->np.ndarray from a .safetensors file (HF checkpoint ingestion,
    reference modeling.py:1424 load_state_dict)."""
    from safetensors import safe_open

    out = {}
    with safe_open(path, framework="np") as f:
        meta = f.metadata() or {}
        # Files written by old safetensors versions record bf16 tensors as U16 views
        # (see hf_loading.save_hf_checkpoint fallback); restore the real dtype.
        viewed = set(filter(None, meta.get("bfloat16_as_uint16", "").split(",")))
        for key in f.keys():
            t = f.get_tensor(key)
            if key in viewed:
                t = t.view("bfloat16")
            out[key] = t
    return out

"""Capability probes for optional dependencies.

The reference's plugin system is driven by ~30 import probes (utils/imports.py:49-402);
here the optional surface is the JAX ecosystem plus tracker/IO backends. Each probe is
cached and never raises.
"""

import importlib.util
import os
from functools import lru_cache


def _is_package_available(pkg_name: str) -> bool:
    return importlib.util.find_spec(pkg_name) is not None


@lru_cache
def is_flax_available() -> bool:
    return _is_package_available("flax")


@lru_cache
def is_optax_available() -> bool:
    return _is_package_available("optax")


@lru_cache
def is_orbax_available() -> bool:
    return _is_package_available("orbax")


@lru_cache
def is_torch_available() -> bool:
    """Torch (CPU) is used only as an optional data-loading / checkpoint-ingest frontend."""
    return _is_package_available("torch")


@lru_cache
def is_safetensors_available() -> bool:
    return _is_package_available("safetensors")


@lru_cache
def is_transformers_available() -> bool:
    return _is_package_available("transformers")


@lru_cache
def is_tensorboard_available() -> bool:
    return _is_package_available("tensorboardX") or _is_package_available("tensorboard")


@lru_cache
def is_wandb_available() -> bool:
    return _is_package_available("wandb")


@lru_cache
def is_mlflow_available() -> bool:
    return _is_package_available("mlflow")


@lru_cache
def is_comet_ml_available() -> bool:
    return _is_package_available("comet_ml")


@lru_cache
def is_aim_available() -> bool:
    return _is_package_available("aim")


@lru_cache
def is_clearml_available() -> bool:
    return _is_package_available("clearml")


@lru_cache
def is_dvclive_available() -> bool:
    return _is_package_available("dvclive")


@lru_cache
def is_rich_available() -> bool:
    return _is_package_available("rich")


@lru_cache
def is_tqdm_available() -> bool:
    return _is_package_available("tqdm")


@lru_cache
def is_pandas_available() -> bool:
    return _is_package_available("pandas")


@lru_cache
def is_datasets_available() -> bool:
    return _is_package_available("datasets")


@lru_cache
def is_tpu_available() -> bool:
    """True when the default JAX backend exposes TPU devices.

    Unlike the reference's `is_torch_xla_available(check_is_tpu=True)` (utils/imports.py:153),
    this initializes the JAX backend, so call it lazily (never at import time).
    """
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


def is_cpu_force_mode() -> bool:
    """True when tests force the host-CPU multi-device platform."""
    return os.environ.get("JAX_PLATFORMS", "") == "cpu"

"""Plugin and configuration dataclasses.

This is the configuration spine of the framework — the TPU-native counterpart of the
reference's utils/dataclasses.py. The key design change: the reference routes each
parallelism strategy to a different backend wrapper (DDP / torch-FSDP / DeepSpeed /
Megatron — dataclasses.py:739-1464); here EVERY strategy reduces to (a) a mesh shape
(`ParallelismConfig`) and (b) sharding-spec derivation rules (`FullyShardedDataParallelPlugin`
et al. in parallel/sharding.py). DeepSpeed/Megatron-shaped plugins are provided as
compatibility shims that translate themselves into those two primitives, so users of the
reference can bring their configs unchanged.

Env-var protocol parity: plugins read `ACCELERATE_TPU_*` env vars in __post_init__,
mirroring the reference's worker-side deserialization (dataclasses.py:659-669,739-830).
"""

from __future__ import annotations

import copy
import enum
import functools
import os
import warnings
from dataclasses import dataclass, field
from datetime import timedelta
from typing import Any, Callable, Iterable, Optional

from .constants import FSDP_AUTO_WRAP_POLICY, FSDP_SHARDING_STRATEGY, FSDP_STATE_DICT_TYPE, MESH_AXIS_NAMES
from .environment import parse_flag_from_env, str_to_bool


class KwargsHandler:
    """Base for kwargs dataclasses; `to_kwargs` diffs against defaults
    (parity: reference dataclasses.py:39-57)."""

    def to_dict(self):
        return copy.deepcopy(self.__dict__)

    def to_kwargs(self):
        default_dict = self.__class__().to_dict()
        this_dict = self.to_dict()
        return {k: v for k, v in this_dict.items() if default_dict[k] != v}


@dataclass
class AutocastKwargs(KwargsHandler):
    """Customize mixed-precision casting behavior (parity: reference AutocastKwargs).

    On TPU this selects the compute dtype policy rather than entering a torch autocast
    context: `enabled=False` keeps the module in its parameter dtype, `cache_enabled` is
    accepted for API parity and ignored (XLA caches compiled executables instead).
    """

    enabled: bool = True
    cache_enabled: Optional[bool] = None


@dataclass
class GradScalerKwargs(KwargsHandler):
    """Dynamic loss-scaling knobs for fp16 (parity: reference GradScalerKwargs →
    torch.cuda.amp.GradScaler). bf16 — the TPU default — needs no scaling; these apply
    only when mixed_precision='fp16'."""

    init_scale: float = 65536.0
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000
    enabled: bool = True


@dataclass
class InitProcessGroupKwargs(KwargsHandler):
    """Multi-host coordination-service init knobs (parity: reference InitProcessGroupKwargs
    → init_process_group; here they feed jax.distributed.initialize)."""

    backend: Optional[str] = "xla"
    init_method: Optional[str] = None
    timeout: Optional[timedelta] = None

    def __post_init__(self):
        if self.timeout is None:
            self.timeout = timedelta(seconds=1800)


@dataclass
class DistributedDataParallelKwargs(KwargsHandler):
    """Accepted for API parity with the reference's DDP kwargs (dataclasses.py:83).

    Under GSPMD there are no gradient buckets or process-group wrappers; the only field
    with a TPU meaning is `gradient_as_bucket_view` (ignored) and
    `static_graph` (ignored — jit graphs are always static). Kept so reference scripts
    run unmodified.
    """

    dim: int = 0
    broadcast_buffers: bool = True
    bucket_cap_mb: int = 25
    find_unused_parameters: bool = False
    check_reduction: bool = False
    gradient_as_bucket_view: bool = False
    static_graph: bool = False


class EnumWithContains(enum.EnumMeta):
    def __contains__(cls, item):
        try:
            cls(item)
        except ValueError:
            return False
        return True


class BaseEnum(str, enum.Enum, metaclass=EnumWithContains):
    def __str__(self):
        return self.value

    @classmethod
    def list(cls):
        return list(map(str, cls))


class DistributedType(BaseEnum):
    """Execution topology (parity: reference DistributedType, dataclasses.py).

    The reference enumerates one value per comm backend (MULTI_GPU/DEEPSPEED/FSDP/XLA/...).
    Under JAX, the compute data plane is always XLA-SPMD over a mesh, so the only real
    distinctions are: no acceleration, single-host SPMD, and multi-host SPMD.
    """

    NO = "NO"
    XLA_SPMD = "XLA_SPMD"
    MULTI_HOST = "MULTI_HOST"


class PrecisionType(BaseEnum):
    NO = "no"
    FP8 = "fp8"
    FP16 = "fp16"
    BF16 = "bf16"


class RNGType(BaseEnum):
    PYTHON = "python"
    NUMPY = "numpy"
    JAX = "jax"
    GENERATOR = "generator"


class CustomDtype(enum.Enum):
    """Sub-byte / non-native dtypes for size accounting (parity: reference
    dataclasses.py:475)."""

    FP8 = "fp8"
    INT4 = "int4"
    INT8 = "int8"


@dataclass
class ParallelismConfig:
    """Mesh shape: one axis size per parallelism kind. The single replacement for the
    reference's per-backend degree knobs (Megatron tp/pp degrees dataclasses.py:1256-1258,
    FSDP implicit world sharding, DeepSpeed zero stages).

    Sizes of -1 mean "absorb remaining devices" (at most one axis may be -1; defaults to
    the data axis). Axis order is DCN-outermost→ICI-innermost as laid out in
    `constants.MESH_AXIS_NAMES`: ("data", "fsdp", "model", "seq", "expert", "stage",
    "pipeline"). "stage" is the SPMD pipeline runner's axis; "pipeline" selects the MPMD
    runtime (per-stage submeshes, unequal layer counts allowed).
    """

    data: int = -1
    fsdp: int = 1
    model: int = 1
    seq: int = 1
    expert: int = 1
    stage: int = 1
    pipeline: int = 1

    def __post_init__(self):
        sizes = self.axis_sizes()
        if sum(1 for v in sizes.values() if v == -1) > 1:
            raise ValueError("At most one mesh axis may be -1 (auto), got " f"{sizes}")

    def axis_sizes(self) -> dict:
        return {name: getattr(self, name) for name in MESH_AXIS_NAMES}

    def resolve(self, num_devices: int) -> dict:
        """Concretize -1 axes against the device count; validates divisibility."""
        sizes = self.axis_sizes()
        fixed = 1
        auto_axis = None
        for name, v in sizes.items():
            if v == -1:
                auto_axis = name
            else:
                if v < 1:
                    raise ValueError(f"Axis {name} must be >=1 or -1, got {v}")
                fixed *= v
        if auto_axis is None:
            if fixed != num_devices:
                raise ValueError(f"Mesh of {fixed} devices does not match {num_devices} available devices")
            return sizes
        if num_devices % fixed != 0:
            raise ValueError(f"Fixed axes use {fixed} devices which does not divide {num_devices}")
        sizes[auto_axis] = num_devices // fixed
        return sizes

    @classmethod
    def from_env(cls) -> "ParallelismConfig":
        kw = {}
        for name in MESH_AXIS_NAMES:
            env = os.environ.get(f"ACCELERATE_TPU_MESH_{name.upper()}")
            if env is not None:
                kw[name] = int(env)
        return cls(**kw)


@dataclass
class GradientAccumulationPlugin(KwargsHandler):
    """Gradient accumulation config (parity: reference GradientAccumulationPlugin)."""

    num_steps: int = 1
    adjust_scheduler: bool = True
    sync_with_dataloader: bool = True
    sync_each_batch: bool = False


@dataclass
class ProjectConfiguration:
    """Checkpoint/logging directory layout (parity: reference ProjectConfiguration)."""

    project_dir: Optional[str] = None
    logging_dir: Optional[str] = None
    automatic_checkpoint_naming: bool = False
    total_limit: Optional[int] = None
    iteration: int = 0
    save_on_each_node: bool = False

    def set_directories(self, project_dir=None):
        self.project_dir = project_dir
        if self.logging_dir is None:
            self.logging_dir = project_dir

    def __post_init__(self):
        self.set_directories(self.project_dir)


@dataclass
class DataLoaderConfiguration:
    """Dataloader behavior knobs (parity: reference DataLoaderConfiguration).

    `dispatch_batches`: rank-0-reads-all + broadcast (DataLoaderDispatcher semantics,
    reference data_loader.py:562). `split_batches`: the loader's batch size is the global
    batch size and is sliced across processes, instead of each process loading
    `batch_size` samples. `even_batches`: pad the final global batch so every process
    receives the same count (required for jit-stable shapes; turning it off implies
    dropping to per-host ragged iteration). `use_seedable_sampler`: deterministic
    epoch-keyed shuffling.
    """

    split_batches: bool = False
    dispatch_batches: Optional[bool] = None
    even_batches: bool = True
    use_seedable_sampler: bool = True
    non_blocking: bool = True
    prefetch_size: int = 2
    drop_last: Optional[bool] = None


@dataclass
class CompilationConfig(KwargsHandler):
    """XLA compilation options — the TPU-native replacement for TorchDynamoPlugin
    (reference dataclasses.py:641). jit is always on; these tune it."""

    donate_params: bool = True
    remat_policy: Optional[str] = None  # None | "full" | "dots" | "dots_saveable" | "nothing_saveable"
    scan_layers: bool = False
    cache_dir: Optional[str] = None
    xla_flags: Optional[str] = None

    def __post_init__(self):
        if self.cache_dir is None:
            self.cache_dir = os.environ.get("ACCELERATE_TPU_COMPILATION_CACHE", None)


@dataclass
class FullyShardedDataParallelPlugin:
    """ZeRO/FSDP as sharding-spec derivation (replaces reference dataclasses.py:1121-1203 +
    accelerator.py:1431-1545 wrapping).

    Strategies map to GSPMD policies over the "fsdp" mesh axis:
      - FULL_SHARD (ZeRO-3): params, grads and optimizer state sharded; XLA all-gathers
        weights per-layer during fwd/bwd and reduce-scatters grads.
      - SHARD_GRAD_OP (ZeRO-2): params replicated, grads + optimizer state sharded
        (weight-update sharding / ZeRO-2 equivalent).
      - NO_SHARD: plain DP.
      - HYBRID_SHARD: shard over "fsdp" axis, replicate over "data" axis.
    `min_num_params`-style auto-wrap maps to a size threshold below which tensors stay
    replicated (small layernorm/bias arrays aren't worth a collective).
    """

    sharding_strategy: str = "FULL_SHARD"
    auto_wrap_policy: Optional[str] = None
    min_num_params: int = 0
    transformer_cls_names_to_wrap: Optional[list] = None
    cpu_offload: bool = False
    # Host-offload tiers (ZeRO-offload parity, reference accelerator.py:1563-1785 +
    # dataclasses.py:704-719): place optimizer state / parameters in pinned host
    # memory (`memory_kind="pinned_host"`), streamed to HBM inside the update step.
    # None -> follow cpu_offload.
    offload_optimizer_state: Optional[bool] = None
    offload_params: Optional[bool] = None
    # NVMe tier (DeepSpeed offload_optimizer device="nvme" parity): "disk"/"nvme"
    # puts optimizer state in a single-blob disk store with per-group streaming +
    # async prefetch; `offload_dir` picks the directory (tempdir default).
    offload_optimizer_device: Optional[str] = None
    offload_dir: Optional[str] = None
    state_dict_type: str = "SHARDED_STATE_DICT"
    activation_checkpointing: bool = False
    sync_module_states: bool = True
    param_dtype: Optional[str] = None
    reduce_dtype: Optional[str] = None
    use_orig_params: bool = True  # accepted for parity; meaningless under GSPMD

    def __post_init__(self):
        prefix = "ACCELERATE_TPU_FSDP_"
        env = os.environ
        if isinstance(self.sharding_strategy, int):
            self.sharding_strategy = FSDP_SHARDING_STRATEGY[self.sharding_strategy - 1]
        self.sharding_strategy = env.get(prefix + "SHARDING_STRATEGY", self.sharding_strategy)
        if self.sharding_strategy not in FSDP_SHARDING_STRATEGY:
            raise ValueError(
                f"sharding_strategy must be one of {FSDP_SHARDING_STRATEGY}, got {self.sharding_strategy}"
            )
        self.auto_wrap_policy = env.get(prefix + "AUTO_WRAP_POLICY", self.auto_wrap_policy)
        if self.auto_wrap_policy is not None and self.auto_wrap_policy not in FSDP_AUTO_WRAP_POLICY:
            raise ValueError(f"auto_wrap_policy must be one of {FSDP_AUTO_WRAP_POLICY}")
        if prefix + "TRANSFORMER_CLS_TO_WRAP" in env:
            self.transformer_cls_names_to_wrap = [
                s.strip() for s in env[prefix + "TRANSFORMER_CLS_TO_WRAP"].split(",") if s.strip()
            ]
        if self.auto_wrap_policy == "TRANSFORMER_BASED_WRAP" and not self.transformer_cls_names_to_wrap:
            raise ValueError(
                "auto_wrap_policy='TRANSFORMER_BASED_WRAP' requires transformer_cls_names_to_wrap "
                "(the layer-class/param-path names whose parameters shard over the fsdp axis)"
            )
        self.min_num_params = int(env.get(prefix + "MIN_NUM_PARAMS", self.min_num_params))
        self.param_dtype = env.get(prefix + "PARAM_DTYPE", self.param_dtype)
        self.reduce_dtype = env.get(prefix + "REDUCE_DTYPE", self.reduce_dtype)
        for fld in ("param_dtype", "reduce_dtype"):
            val = getattr(self, fld)
            if val is not None and val not in ("float32", "bfloat16", "float16"):
                raise ValueError(f"{fld} must be float32|bfloat16|float16, got {val!r}")
        self.sync_module_states = parse_flag_from_env(
            prefix + "SYNC_MODULE_STATES", self.sync_module_states
        )
        self.cpu_offload = parse_flag_from_env(prefix + "OFFLOAD_PARAMS", self.cpu_offload)
        if self.offload_optimizer_state is None:
            self.offload_optimizer_state = self.cpu_offload
        if self.offload_params is None:
            self.offload_params = self.cpu_offload
        self.offload_optimizer_device = env.get(
            prefix + "OFFLOAD_OPTIMIZER_DEVICE", self.offload_optimizer_device
        )
        if self.offload_optimizer_device is not None and self.offload_optimizer_device.lower() not in (
            "disk",
            "nvme",
            "cpu",
            "pinned_host",
        ):
            raise ValueError(
                f"offload_optimizer_device must be disk|nvme|cpu|pinned_host, got "
                f"{self.offload_optimizer_device!r}"
            )
        if self.offload_optimizer_device is not None and self.offload_optimizer_device.lower() in (
            "cpu",
            "pinned_host",
        ):
            # The host tier is the boolean knob's behavior; normalize.
            self.offload_optimizer_state = True
            self.offload_optimizer_device = None
        self.offload_dir = env.get(prefix + "OFFLOAD_DIR", self.offload_dir)
        self.state_dict_type = env.get(prefix + "STATE_DICT_TYPE", self.state_dict_type)
        if self.state_dict_type not in FSDP_STATE_DICT_TYPE:
            raise ValueError(f"state_dict_type must be one of {FSDP_STATE_DICT_TYPE}")
        self.activation_checkpointing = parse_flag_from_env(
            prefix + "ACTIVATION_CHECKPOINTING", self.activation_checkpointing
        )

    @property
    def shards_params(self) -> bool:
        return self.sharding_strategy in ("FULL_SHARD", "HYBRID_SHARD")

    @property
    def shards_opt_state(self) -> bool:
        return self.sharding_strategy != "NO_SHARD"


@dataclass
class DeepSpeedPlugin:
    """Compatibility shim: a DeepSpeed-shaped config that lowers to GSPMD sharding +
    host offload (replaces reference dataclasses.py:704-1010 + accelerator.py:1563-1785).

    zero_stage 0 → NO_SHARD, 1/2 → SHARD_GRAD_OP (optimizer/gradient sharding), 3 →
    FULL_SHARD. NVMe offload maps to the disk tier of the big-model planner; CPU offload
    to pinned-host placement.
    """

    hf_ds_config: Any = None
    gradient_accumulation_steps: int = 1
    gradient_clipping: Optional[float] = None
    zero_stage: int = 2
    offload_optimizer_device: Optional[str] = None  # none|cpu|nvme
    offload_param_device: Optional[str] = None
    zero3_init_flag: bool = False
    zero3_save_16bit_model: bool = False
    train_micro_batch_size_per_gpu: Optional[int] = None

    def __post_init__(self):
        env = os.environ
        self.zero_stage = int(env.get("ACCELERATE_TPU_ZERO_STAGE", self.zero_stage))
        self.gradient_accumulation_steps = int(
            env.get("ACCELERATE_TPU_GRADIENT_ACCUMULATION_STEPS", self.gradient_accumulation_steps)
        )
        if isinstance(self.hf_ds_config, dict):
            cfg = self.hf_ds_config
            zero = cfg.get("zero_optimization", {})
            self.zero_stage = zero.get("stage", self.zero_stage)
            if "offload_optimizer" in zero:
                self.offload_optimizer_device = zero["offload_optimizer"].get("device")
            if "offload_param" in zero:
                self.offload_param_device = zero["offload_param"].get("device")
            if "gradient_accumulation_steps" in cfg and cfg["gradient_accumulation_steps"] != "auto":
                self.gradient_accumulation_steps = cfg["gradient_accumulation_steps"]
            if "gradient_clipping" in cfg and cfg["gradient_clipping"] != "auto":
                self.gradient_clipping = cfg["gradient_clipping"]

    def to_fsdp_plugin(self) -> FullyShardedDataParallelPlugin:
        stage_map = {0: "NO_SHARD", 1: "SHARD_GRAD_OP", 2: "SHARD_GRAD_OP", 3: "FULL_SHARD"}
        if self.zero_stage not in stage_map:
            raise ValueError(
                f"zero_stage must be one of {sorted(stage_map)}, got {self.zero_stage!r} "
                "(note: 'auto' is not resolvable without a training context; set an explicit stage)"
            )
        strategy = stage_map[self.zero_stage]
        return FullyShardedDataParallelPlugin(
            sharding_strategy=strategy,
            cpu_offload=self.offload_param_device in ("cpu", "nvme")
            or self.offload_optimizer_device in ("cpu", "nvme"),
            offload_optimizer_state=self.offload_optimizer_device in ("cpu", "nvme"),
            offload_params=self.offload_param_device in ("cpu", "nvme"),
            # DeepSpeed NVMe offload -> the disk tier (per-group blob streaming).
            offload_optimizer_device="disk" if self.offload_optimizer_device == "nvme" else None,
        )


@dataclass
class MegatronLMPlugin:
    """Compatibility shim: Megatron-shaped degrees that lower to a ParallelismConfig
    (replaces reference dataclasses.py:1230-1464 + utils/megatron_lm.py glue)."""

    tp_degree: int = 1
    pp_degree: int = 1
    num_micro_batches: int = 1
    sequence_parallelism: bool = False
    sequence_parallel_degree: int = 1
    expert_parallel_degree: int = 1
    recompute_activations: bool = False

    def __post_init__(self):
        env = os.environ
        self.tp_degree = int(env.get("ACCELERATE_TPU_MEGATRON_TP_DEGREE", self.tp_degree))
        self.pp_degree = int(env.get("ACCELERATE_TPU_MEGATRON_PP_DEGREE", self.pp_degree))
        if self.sequence_parallelism and self.sequence_parallel_degree == 1:
            # Megatron SP shards over the TP group; mirror that default here.
            self.sequence_parallel_degree = self.tp_degree

    def to_parallelism_config(self) -> ParallelismConfig:
        return ParallelismConfig(
            data=-1,
            model=self.tp_degree,
            stage=self.pp_degree,
            seq=self.sequence_parallel_degree if self.sequence_parallelism else 1,
            expert=self.expert_parallel_degree,
        )


@dataclass
class SequenceParallelPlugin:
    """First-class sequence/context parallelism — the capability the reference lacks
    natively (SURVEY §5: only a Megatron passthrough flag, dataclasses.py:1262-1265).

    `mode="ring"`: ring attention — KV blocks rotate around the "seq" axis via ppermute
    while queries stay resident (communication overlaps with blockwise attention compute).
    `mode="allgather"`: all-gather KV (cheaper at short context, more HBM).
    """

    seq_degree: int = 1
    mode: str = "ring"
    block_size: int = 512

    def __post_init__(self):
        if self.mode not in ("ring", "allgather"):
            raise ValueError(f"mode must be ring|allgather, got {self.mode}")


@dataclass
class FP8RecipeKwargs(KwargsHandler):
    """fp8 policy (parity: reference FP8RecipeKwargs → TransformerEngine DelayedScaling).
    On TPU this selects XLA fp8 dot dtypes (e4m3 fwd / e5m2 bwd); `scaling`
    picks per-tensor dynamic amax (default — the in-graph reduction fuses into
    the producer on TPU and tracks every tensor exactly) or TE-parity
    "delayed" (rolling amax-history window of `amax_history_len` steps,
    `ops/fp8.py` fp8_matmul_delayed / fp8_autocast)."""

    margin: int = 0
    interval: int = 1
    fp8_format: str = "HYBRID"  # E4M3 | HYBRID
    scaling: str = "dynamic"  # dynamic | delayed
    amax_history_len: int = 1024
    amax_compute_algo: str = "most_recent"
    override_linear_precision: tuple = (False, False, False)

    def __post_init__(self):
        self.fp8_format = self.fp8_format.upper()
        if self.fp8_format not in ("E4M3", "HYBRID"):
            raise ValueError("fp8_format must be E4M3 or HYBRID")
        self.scaling = self.scaling.lower()
        if self.scaling not in ("dynamic", "delayed"):
            raise ValueError("scaling must be dynamic or delayed")
        if self.amax_compute_algo not in ("max", "most_recent"):
            raise ValueError("amax_compute_algo must be max or most_recent")

"""Weight quantization: int8 / int4 / nf4, TPU-native.

Parity target: the reference's bitsandbytes integration (utils/bnb.py —
`load_and_quantize_model` :44, `replace_with_bnb_layers` :274, `BnbQuantizationConfig`
dataclasses.py:1624), which swaps nn.Linear for CUDA-kernel-backed bnb layers.

TPU redesign: there are no custom kernels to swap in — and none are needed. Quantized
kernels live in HBM as int8 (or packed int4 nibbles) plus scales; the dequantize
(`scale * q`) is an elementwise op XLA fuses into the consuming matmul, so weights
stream from HBM at 2×/4× effective bandwidth and the MXU still computes in bf16. The
module tree is untouched — quantization is a *params transform* plus an apply wrapper,
not a layer swap:

    qmodel = load_and_quantize_model(model, QuantizationConfig(load_in_4bit=True))
    logits = qmodel.apply_fn(qmodel.params, input_ids)     # dequant fused by XLA

Quantized leaves are `QuantTensor` pytree nodes (arrays as children, metadata static),
so the whole params tree stays jit/device_put/checkpoint-friendly. nf4 follows QLoRA's
NormalFloat-4 codebook with per-block absmax scaling; int4 is symmetric linear with
per-block scales; int8 is per-output-channel symmetric.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import numpy as np

import jax

# QLoRA NF4 codebook (16 quantiles of a standard normal, normalized to [-1, 1]).
NF4_CODE = np.array(
    [
        -1.0, -0.6961928009986877, -0.5250730514526367, -0.39491748809814453,
        -0.28444138169288635, -0.18477343022823334, -0.09105003625154495, 0.0,
        0.07958029955625534, 0.16093020141124725, 0.24611230194568634, 0.33791524171829224,
        0.44070982933044434, 0.5626170039176941, 0.7229568362236023, 1.0,
    ],
    dtype=np.float32,
)


@jax.tree_util.register_pytree_node_class
class QuantTensor:
    """A quantized weight: (q, scale) arrays + static metadata. Quacks enough like an
    array (shape/dtype/size refer to the LOGICAL dequantized tensor) for size
    accounting, and flattens to its buffers for jit/device_put/serialization."""

    def __init__(self, kind: str, q, scale, shape: Tuple[int, ...], pad: int = 0, block_size: int = 0):
        self.kind = kind
        self.q = q
        self.scale = scale
        self.shape = tuple(shape)
        self.pad = pad
        self.block_size = block_size

    # pytree protocol: buffers are children, metadata is static structure
    def tree_flatten(self):
        return (self.q, self.scale), (self.kind, self.shape, self.pad, self.block_size)

    @classmethod
    def tree_unflatten(cls, aux, children):
        kind, shape, pad, block_size = aux
        q, scale = children
        return cls(kind, q, scale, shape, pad, block_size)

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def nbytes_quantized(self) -> int:
        total = 0
        for buf in (self.q, self.scale):
            total += buf.size * np.dtype(buf.dtype).itemsize
        return total

    def dequantize(self, dtype=None):
        return dequantize_entry(self, dtype or "bfloat16")

    def __repr__(self):
        return f"QuantTensor({self.kind}, shape={self.shape}, stored={self.nbytes_quantized}B)"


@dataclass
class QuantizationConfig:
    """Parity: reference BnbQuantizationConfig (dataclasses.py:1624) minus the
    CUDA-specific knobs; `quant_type` covers bnb's fp4/nf4 choice."""

    load_in_8bit: bool = False
    load_in_4bit: bool = False
    quant_type: str = "nf4"  # "nf4" | "int4" (4-bit only)
    block_size: int = 64  # per-block scaling granularity for 4-bit
    compute_dtype: Any = None  # dtype weights dequantize to (default bf16)
    skip_modules: List[str] = field(default_factory=list)  # path substrings to keep dense
    min_dims: int = 2  # only quantize kernels with >= this many dims

    def __post_init__(self):
        if self.load_in_8bit and self.load_in_4bit:
            raise ValueError("Pick one of load_in_8bit / load_in_4bit")
        if self.load_in_4bit and self.quant_type not in ("nf4", "int4"):
            raise ValueError(f"Unknown 4-bit quant_type {self.quant_type!r}")

    @property
    def enabled(self) -> bool:
        return self.load_in_8bit or self.load_in_4bit


# ---- int8: per-output-channel symmetric ---------------------------------------------
def quantize_int8(w) -> QuantTensor:
    import jax.numpy as jnp

    w = jnp.asarray(w)
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=tuple(range(w.ndim - 1)), keepdims=True)
    scale = absmax / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / jnp.maximum(scale, 1e-12)), -127, 127).astype(
        jnp.int8
    )
    return QuantTensor("int8", q, scale.astype(jnp.float32), w.shape)


# ---- 4-bit: per-block, packed two nibbles per byte ----------------------------------
def _block_view(w, block_size: int):
    import jax.numpy as jnp

    flat = jnp.ravel(w)
    pad = (-flat.size) % block_size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, block_size), pad


def quantize_int4(w, block_size: int = 64) -> QuantTensor:
    import jax.numpy as jnp

    w = jnp.asarray(w)
    blocks, pad = _block_view(w.astype(jnp.float32), block_size)
    absmax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scale = absmax / 7.0
    q = jnp.clip(jnp.round(blocks / jnp.maximum(scale, 1e-12)), -7, 7).astype(jnp.int8) + 8  # [0,15]
    packed = (q[:, ::2] | (q[:, 1::2] << 4)).astype(jnp.uint8)
    return QuantTensor("int4", packed, scale.astype(jnp.float32), w.shape, pad, block_size)


def quantize_nf4(w, block_size: int = 64) -> QuantTensor:
    import jax.numpy as jnp

    w = jnp.asarray(w)
    blocks, pad = _block_view(w.astype(jnp.float32), block_size)
    absmax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12)
    normed = blocks / scale  # [-1, 1]
    code = jnp.asarray(NF4_CODE)
    idx = jnp.argmin(jnp.abs(normed[..., None] - code[None, None, :]), axis=-1).astype(jnp.uint8)
    packed = (idx[:, ::2] | (idx[:, 1::2] << 4)).astype(jnp.uint8)
    return QuantTensor("nf4", packed, absmax.astype(jnp.float32), w.shape, pad, block_size)


def _unpack_nibbles(packed):
    import jax.numpy as jnp

    lo = (packed & 0x0F).astype(jnp.int32)
    hi = (packed >> 4).astype(jnp.int32)
    return jnp.stack([lo, hi], axis=-1).reshape(packed.shape[0], -1)


def dequantize_entry(entry: QuantTensor, dtype="bfloat16"):
    import jax.numpy as jnp

    if entry.kind == "int8":
        return (entry.q.astype(jnp.float32) * entry.scale).astype(dtype)
    vals = _unpack_nibbles(entry.q)
    if entry.kind == "nf4":
        blocks = jnp.asarray(NF4_CODE)[vals] * entry.scale
    elif entry.kind == "int4":
        # stored scale is already absmax/7 (one quantization step)
        blocks = (vals - 8).astype(jnp.float32) * entry.scale
    else:
        raise ValueError(f"Unknown quant kind {entry.kind!r}")
    flat = blocks.reshape(-1)
    if entry.pad:
        flat = flat[: flat.size - entry.pad]
    return flat.reshape(entry.shape).astype(dtype)


def is_quant_entry(x) -> bool:
    return isinstance(x, QuantTensor)


# ---- params-level transform ----------------------------------------------------------
def quantize_params(params, config: QuantizationConfig):
    """Replace eligible kernels with QuantTensors (the `replace_with_bnb_layers`
    equivalent, reference utils/bnb.py:274 — operating on params, not modules)."""

    def convert(path: str, leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim < config.min_dims:
            return leaf
        if not np.issubdtype(np.asarray(leaf).dtype, np.floating):
            return leaf
        if any(skip in path for skip in config.skip_modules):
            return leaf
        if config.load_in_8bit:
            return quantize_int8(leaf)
        if config.quant_type == "nf4":
            return quantize_nf4(leaf, config.block_size)
        return quantize_int4(leaf, config.block_size)

    def rec(tree, path=""):
        if isinstance(tree, dict):
            return {k: rec(v, f"{path}/{k}") for k, v in tree.items()}
        return convert(path, tree)

    return rec(params)


def dequantize_params(qparams, dtype=None):
    """Inverse transform; inside jit the per-leaf dequant fuses into consumers."""

    def rec(tree):
        if is_quant_entry(tree):
            return dequantize_entry(tree, dtype or "bfloat16")
        if isinstance(tree, dict):
            return {k: rec(v) for k, v in tree.items()}
        return tree

    return rec(qparams)


def quantized_nbytes(qparams) -> int:
    """HBM footprint of the quantized params tree (scales included)."""
    total = 0

    def rec(tree):
        nonlocal total
        if is_quant_entry(tree):
            total += tree.nbytes_quantized
            return
        if isinstance(tree, dict):
            for v in tree.values():
                rec(v)
            return
        if hasattr(tree, "size"):
            total += tree.size * np.dtype(tree.dtype).itemsize

    rec(qparams)
    return total


def load_and_quantize_model(model, config: QuantizationConfig):
    """Quantize a Model bundle's params and wrap its apply with fused dequant
    (reference load_and_quantize_model utils/bnb.py:44).

    Returns a new `Model` whose params are the quantized pytree; the apply wrapper
    dequantizes lazily so XLA keeps the int8/packed buffers in HBM and fuses
    `scale * q` into each consuming matmul.
    """
    import jax.numpy as jnp

    from ..modeling import Model

    if not config.enabled:
        return model
    compute_dtype = config.compute_dtype or jnp.bfloat16
    base_apply = model.apply_fn
    base_loss = model.loss_fn
    qparams = quantize_params(model.params, config)

    def apply_fn(params, *args, **kwargs):
        return base_apply(dequantize_params(params, compute_dtype), *args, **kwargs)

    loss_fn = None
    if base_loss is not None:

        def loss_fn(params, batch, apply_fn_=None):
            return base_loss(params, batch, apply_fn_ or apply_fn)

    quantized = Model.from_fn(apply_fn, qparams, loss_fn=loss_fn, sharding_rules=None)
    quantized.module = getattr(model, "module", None)
    quantized.quantization_config = config
    return quantized

"""Main-process-only tqdm wrapper (parity: reference utils/tqdm.py:26)."""

from .imports import is_tqdm_available


def tqdm(*args, main_process_only: bool = True, **kwargs):
    """A tqdm that renders only on the main process by default."""
    if not is_tqdm_available():
        raise ImportError("tqdm is required for `accelerate_tpu.utils.tqdm`")
    import tqdm as _tqdm

    from ..state import PartialState

    disable = kwargs.pop("disable", False)
    if main_process_only and not disable:
        disable = PartialState().local_process_index != 0
    return _tqdm.tqdm(*args, disable=disable, **kwargs)

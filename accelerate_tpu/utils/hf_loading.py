"""HF checkpoint interchange: load torch-layout Llama/Mixtral checkpoints into the
in-tree flax models, and export back.

The reference consumes HF checkpoints natively because it IS torch
(`load_checkpoint_in_model` utils/modeling.py:1565, `load_state_dict` :1424,
`shard_checkpoint` :206). Here the torch↔flax seam needs an explicit name/layout map:

  - torch `nn.Linear.weight` is [out, in]; flax `Dense.kernel` is [in, out] → transpose.
  - HF llama: `model.layers.N.self_attn.q_proj.weight` → `layer_N/attention/wq/kernel`.
  - HF mixtral experts are per-expert modules (`block_sparse_moe.experts.E.w1`);
    ours are stacked [E, in, out] (parallel/expert.py) → stack + transpose.

Supports single-file `.safetensors`, HF sharded checkpoints
(`model.safetensors.index.json`), and torch `.bin` (pickle) files.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, Optional

import numpy as np


# --------------------------------------------------------------------- file readers
def _read_torch_bin(path: str) -> Dict[str, np.ndarray]:
    import torch

    state = torch.load(path, map_location="cpu", weights_only=True)
    out = {}
    for k, v in state.items():
        t = v.detach()
        if t.dtype == torch.bfloat16:
            out[k] = t.view(torch.uint16).numpy().view("bfloat16")
        else:
            out[k] = t.numpy()
    return out


def load_hf_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Flat name->array from a checkpoint file, sharded-index dir, or directory."""
    from .modeling import load_safetensors_state_dict

    if os.path.isdir(path):
        index = os.path.join(path, "model.safetensors.index.json")
        bin_index = os.path.join(path, "pytorch_model.bin.index.json")
        if os.path.exists(index):
            with open(index) as f:
                weight_map = json.load(f)["weight_map"]
            state: Dict[str, np.ndarray] = {}
            for shard in sorted(set(weight_map.values())):
                state.update(load_safetensors_state_dict(os.path.join(path, shard)))
            return state
        if os.path.exists(bin_index):
            with open(bin_index) as f:
                weight_map = json.load(f)["weight_map"]
            state = {}
            for shard in sorted(set(weight_map.values())):
                state.update(_read_torch_bin(os.path.join(path, shard)))
            return state
        for name in ("model.safetensors", "pytorch_model.bin"):
            p = os.path.join(path, name)
            if os.path.exists(p):
                return load_hf_state_dict(p)
        raise FileNotFoundError(f"No checkpoint found in {path}")
    if path.endswith(".safetensors"):
        return load_safetensors_state_dict(path)
    return _read_torch_bin(path)


# --------------------------------------------------------------------- llama mapping
def _llama_from_hf(flat: Dict[str, np.ndarray], config) -> dict:
    def T(name):
        return np.ascontiguousarray(flat[name].T)

    inner: dict = {
        "embed_tokens": {"embedding": np.asarray(flat["model.embed_tokens.weight"])},
        "final_norm": {"scale": np.asarray(flat["model.norm.weight"])},
    }
    for i in range(config.num_hidden_layers):
        p = f"model.layers.{i}."
        inner[f"layer_{i}"] = {
            "attention": {
                "wq": {"kernel": T(p + "self_attn.q_proj.weight")},
                "wk": {"kernel": T(p + "self_attn.k_proj.weight")},
                "wv": {"kernel": T(p + "self_attn.v_proj.weight")},
                "wo": {"kernel": T(p + "self_attn.o_proj.weight")},
            },
            "mlp": {
                "w_gate": {"kernel": T(p + "mlp.gate_proj.weight")},
                "w_up": {"kernel": T(p + "mlp.up_proj.weight")},
                "w_down": {"kernel": T(p + "mlp.down_proj.weight")},
            },
            "input_norm": {"scale": np.asarray(flat[p + "input_layernorm.weight"])},
            "post_attn_norm": {"scale": np.asarray(flat[p + "post_attention_layernorm.weight"])},
        }
    if not config.tie_word_embeddings:
        inner["lm_head"] = {"kernel": T("lm_head.weight")}
    return {"params": inner}


def _llama_to_hf(params: dict, config) -> Dict[str, np.ndarray]:
    inner = params["params"]

    def T(x):
        return np.ascontiguousarray(np.asarray(x).T)

    flat = {
        "model.embed_tokens.weight": np.asarray(inner["embed_tokens"]["embedding"]),
        "model.norm.weight": np.asarray(inner["final_norm"]["scale"]),
    }
    for i in range(config.num_hidden_layers):
        lp = inner[f"layer_{i}"]
        p = f"model.layers.{i}."
        flat[p + "self_attn.q_proj.weight"] = T(lp["attention"]["wq"]["kernel"])
        flat[p + "self_attn.k_proj.weight"] = T(lp["attention"]["wk"]["kernel"])
        flat[p + "self_attn.v_proj.weight"] = T(lp["attention"]["wv"]["kernel"])
        flat[p + "self_attn.o_proj.weight"] = T(lp["attention"]["wo"]["kernel"])
        flat[p + "mlp.gate_proj.weight"] = T(lp["mlp"]["w_gate"]["kernel"])
        flat[p + "mlp.up_proj.weight"] = T(lp["mlp"]["w_up"]["kernel"])
        flat[p + "mlp.down_proj.weight"] = T(lp["mlp"]["w_down"]["kernel"])
        flat[p + "input_layernorm.weight"] = np.asarray(lp["input_norm"]["scale"])
        flat[p + "post_attention_layernorm.weight"] = np.asarray(lp["post_attn_norm"]["scale"])
    if "lm_head" in inner:
        flat["lm_head.weight"] = T(inner["lm_head"]["kernel"])
    return flat


# -------------------------------------------------------------------- mixtral mapping
def _mixtral_from_hf(flat: Dict[str, np.ndarray], config) -> dict:
    def T(name):
        return np.ascontiguousarray(flat[name].T)

    inner: dict = {
        "embed_tokens": {"embedding": np.asarray(flat["model.embed_tokens.weight"])},
        "final_norm": {"scale": np.asarray(flat["model.norm.weight"])},
        "lm_head": {"kernel": T("lm_head.weight")},
    }
    E = config.num_local_experts
    for i in range(config.num_hidden_layers):
        p = f"model.layers.{i}."
        moe = p + "block_sparse_moe."
        # HF mixtral expert module: w1 = gate, w3 = up, w2 = down (all [out, in])
        w_gate = np.stack([flat[f"{moe}experts.{e}.w1.weight"].T for e in range(E)])
        w_up = np.stack([flat[f"{moe}experts.{e}.w3.weight"].T for e in range(E)])
        w_down = np.stack([flat[f"{moe}experts.{e}.w2.weight"].T for e in range(E)])
        inner[f"layer_{i}"] = {
            "attention": {
                "wq": {"kernel": T(p + "self_attn.q_proj.weight")},
                "wk": {"kernel": T(p + "self_attn.k_proj.weight")},
                "wv": {"kernel": T(p + "self_attn.v_proj.weight")},
                "wo": {"kernel": T(p + "self_attn.o_proj.weight")},
            },
            "moe": {
                "router": {"kernel": T(moe + "gate.weight")},
                "experts": {
                    "w_gate/kernel": np.ascontiguousarray(w_gate),
                    "w_up/kernel": np.ascontiguousarray(w_up),
                    "w_down/kernel": np.ascontiguousarray(w_down),
                },
            },
            "input_norm": {"scale": np.asarray(flat[p + "input_layernorm.weight"])},
            "post_attn_norm": {"scale": np.asarray(flat[p + "post_attention_layernorm.weight"])},
        }
    return {"params": inner}


def _mixtral_to_hf(params: dict, config) -> Dict[str, np.ndarray]:
    inner = params["params"]

    def T(x):
        return np.ascontiguousarray(np.asarray(x).T)

    flat = {
        "model.embed_tokens.weight": np.asarray(inner["embed_tokens"]["embedding"]),
        "model.norm.weight": np.asarray(inner["final_norm"]["scale"]),
        "lm_head.weight": T(inner["lm_head"]["kernel"]),
    }
    for i in range(config.num_hidden_layers):
        lp = inner[f"layer_{i}"]
        p = f"model.layers.{i}."
        moe = p + "block_sparse_moe."
        for ours, theirs in [("wq", "q_proj"), ("wk", "k_proj"), ("wv", "v_proj"), ("wo", "o_proj")]:
            flat[p + f"self_attn.{theirs}.weight"] = T(lp["attention"][ours]["kernel"])
        flat[moe + "gate.weight"] = T(lp["moe"]["router"]["kernel"])
        experts = lp["moe"]["experts"]
        for e in range(config.num_local_experts):
            flat[f"{moe}experts.{e}.w1.weight"] = T(np.asarray(experts["w_gate/kernel"])[e])
            flat[f"{moe}experts.{e}.w3.weight"] = T(np.asarray(experts["w_up/kernel"])[e])
            flat[f"{moe}experts.{e}.w2.weight"] = T(np.asarray(experts["w_down/kernel"])[e])
        flat[p + "input_layernorm.weight"] = np.asarray(lp["input_norm"]["scale"])
        flat[p + "post_attention_layernorm.weight"] = np.asarray(lp["post_attn_norm"]["scale"])
    return flat


# ---------------------------------------------------------------------- gptj mapping
def _gptj_from_hf(flat: Dict[str, np.ndarray], config) -> dict:
    def T(name):
        return np.ascontiguousarray(flat[name].T)

    inner: dict = {
        "wte": {"embedding": np.asarray(flat["transformer.wte.weight"])},
        "ln_f": {
            "scale": np.asarray(flat["transformer.ln_f.weight"]),
            "bias": np.asarray(flat["transformer.ln_f.bias"]),
        },
        "lm_head": {"kernel": T("lm_head.weight"), "bias": np.asarray(flat["lm_head.bias"])},
    }
    for i in range(config.num_hidden_layers):
        p = f"transformer.h.{i}."
        inner[f"layer_{i}"] = {
            "ln_1": {
                "scale": np.asarray(flat[p + "ln_1.weight"]),
                "bias": np.asarray(flat[p + "ln_1.bias"]),
            },
            "attention": {
                "wq": {"kernel": T(p + "attn.q_proj.weight")},
                "wk": {"kernel": T(p + "attn.k_proj.weight")},
                "wv": {"kernel": T(p + "attn.v_proj.weight")},
                "wo": {"kernel": T(p + "attn.out_proj.weight")},
            },
            "mlp": {
                "fc_in": {"kernel": T(p + "mlp.fc_in.weight"), "bias": np.asarray(flat[p + "mlp.fc_in.bias"])},
                "fc_out": {"kernel": T(p + "mlp.fc_out.weight"), "bias": np.asarray(flat[p + "mlp.fc_out.bias"])},
            },
        }
    return {"params": inner}


def _gptj_to_hf(params: dict, config) -> Dict[str, np.ndarray]:
    inner = params["params"]

    def T(x):
        return np.ascontiguousarray(np.asarray(x).T)

    flat = {
        "transformer.wte.weight": np.asarray(inner["wte"]["embedding"]),
        "transformer.ln_f.weight": np.asarray(inner["ln_f"]["scale"]),
        "transformer.ln_f.bias": np.asarray(inner["ln_f"]["bias"]),
        "lm_head.weight": T(inner["lm_head"]["kernel"]),
        "lm_head.bias": np.asarray(inner["lm_head"]["bias"]),
    }
    for i in range(config.num_hidden_layers):
        lp = inner[f"layer_{i}"]
        p = f"transformer.h.{i}."
        flat[p + "ln_1.weight"] = np.asarray(lp["ln_1"]["scale"])
        flat[p + "ln_1.bias"] = np.asarray(lp["ln_1"]["bias"])
        for ours, theirs in [("wq", "q_proj"), ("wk", "k_proj"), ("wv", "v_proj"), ("wo", "out_proj")]:
            flat[p + f"attn.{theirs}.weight"] = T(lp["attention"][ours]["kernel"])
        flat[p + "mlp.fc_in.weight"] = T(lp["mlp"]["fc_in"]["kernel"])
        flat[p + "mlp.fc_in.bias"] = np.asarray(lp["mlp"]["fc_in"]["bias"])
        flat[p + "mlp.fc_out.weight"] = T(lp["mlp"]["fc_out"]["kernel"])
        flat[p + "mlp.fc_out.bias"] = np.asarray(lp["mlp"]["fc_out"]["bias"])
    return flat


# ------------------------------------------------------------------ gpt_neox mapping
def _gpt_neox_from_hf(flat: Dict[str, np.ndarray], config) -> dict:
    """HF GPT-NeoX fuses QKV as `query_key_value` with a PER-HEAD [h, 3, d] layout;
    ours are separate wq/wk/wv — split by reshaping [3H, H] -> [h, 3, d, H]."""
    h, d = config.num_attention_heads, config.head_dim

    def T(name):
        return np.ascontiguousarray(flat[name].T)

    def ln(name):
        return {"scale": np.asarray(flat[name + ".weight"]), "bias": np.asarray(flat[name + ".bias"])}

    inner: dict = {
        "embed_in": {"embedding": np.asarray(flat["gpt_neox.embed_in.weight"])},
        "final_norm": ln("gpt_neox.final_layer_norm"),
        "embed_out": {"kernel": T("embed_out.weight")},
    }
    for i in range(config.num_hidden_layers):
        p = f"gpt_neox.layers.{i}."
        qkv_w = flat[p + "attention.query_key_value.weight"].reshape(h, 3, d, config.hidden_size)
        qkv_b = flat[p + "attention.query_key_value.bias"].reshape(h, 3, d)

        def proj(j):
            w = np.ascontiguousarray(qkv_w[:, j].reshape(h * d, config.hidden_size).T)
            b = np.ascontiguousarray(qkv_b[:, j].reshape(h * d))
            return {"kernel": w, "bias": b}

        inner[f"layer_{i}"] = {
            "input_norm": ln(p + "input_layernorm"),
            "post_attn_norm": ln(p + "post_attention_layernorm"),
            "attention": {
                "wq": proj(0),
                "wk": proj(1),
                "wv": proj(2),
                "wo": {
                    "kernel": T(p + "attention.dense.weight"),
                    "bias": np.asarray(flat[p + "attention.dense.bias"]),
                },
            },
            "mlp": {
                "dense_h_to_4h": {
                    "kernel": T(p + "mlp.dense_h_to_4h.weight"),
                    "bias": np.asarray(flat[p + "mlp.dense_h_to_4h.bias"]),
                },
                "dense_4h_to_h": {
                    "kernel": T(p + "mlp.dense_4h_to_h.weight"),
                    "bias": np.asarray(flat[p + "mlp.dense_4h_to_h.bias"]),
                },
            },
        }
    return {"params": inner}


def _gpt_neox_to_hf(params: dict, config) -> Dict[str, np.ndarray]:
    inner = params["params"]
    h, d = config.num_attention_heads, config.head_dim

    def T(x):
        return np.ascontiguousarray(np.asarray(x).T)

    flat = {
        "gpt_neox.embed_in.weight": np.asarray(inner["embed_in"]["embedding"]),
        "gpt_neox.final_layer_norm.weight": np.asarray(inner["final_norm"]["scale"]),
        "gpt_neox.final_layer_norm.bias": np.asarray(inner["final_norm"]["bias"]),
        "embed_out.weight": T(inner["embed_out"]["kernel"]),
    }
    for i in range(config.num_hidden_layers):
        lp = inner[f"layer_{i}"]
        p = f"gpt_neox.layers.{i}."
        for ours, theirs in [("input_norm", "input_layernorm"), ("post_attn_norm", "post_attention_layernorm")]:
            flat[p + theirs + ".weight"] = np.asarray(lp[ours]["scale"])
            flat[p + theirs + ".bias"] = np.asarray(lp[ours]["bias"])
        # Re-fuse QKV into HF's per-head [h, 3, d] layout.
        w = np.stack(
            [np.asarray(lp["attention"][k]["kernel"]).T.reshape(h, d, config.hidden_size) for k in ("wq", "wk", "wv")],
            axis=1,
        )  # [h, 3, d, H]
        b = np.stack([np.asarray(lp["attention"][k]["bias"]).reshape(h, d) for k in ("wq", "wk", "wv")], axis=1)
        flat[p + "attention.query_key_value.weight"] = np.ascontiguousarray(
            w.reshape(3 * config.hidden_size, config.hidden_size)
        )
        flat[p + "attention.query_key_value.bias"] = np.ascontiguousarray(b.reshape(3 * config.hidden_size))
        flat[p + "attention.dense.weight"] = T(lp["attention"]["wo"]["kernel"])
        flat[p + "attention.dense.bias"] = np.asarray(lp["attention"]["wo"]["bias"])
        for name in ("dense_h_to_4h", "dense_4h_to_h"):
            flat[p + f"mlp.{name}.weight"] = T(lp["mlp"][name]["kernel"])
            flat[p + f"mlp.{name}.bias"] = np.asarray(lp["mlp"][name]["bias"])
    return flat


# ----------------------------------------------------------------------- opt mapping
def _opt_from_hf(flat: Dict[str, np.ndarray], config) -> dict:
    def T(name):
        return np.ascontiguousarray(flat[name].T)

    def dense(name):
        return {"kernel": T(name + ".weight"), "bias": np.asarray(flat[name + ".bias"])}

    def ln(name):
        return {"scale": np.asarray(flat[name + ".weight"]), "bias": np.asarray(flat[name + ".bias"])}

    inner: dict = {
        "embed_tokens": {"embedding": np.asarray(flat["model.decoder.embed_tokens.weight"])},
        "embed_positions": {"embedding": np.asarray(flat["model.decoder.embed_positions.weight"])},
        "final_norm": ln("model.decoder.final_layer_norm"),
    }
    for i in range(config.num_hidden_layers):
        p = f"model.decoder.layers.{i}."
        inner[f"layer_{i}"] = {
            "self_attn_norm": ln(p + "self_attn_layer_norm"),
            "final_norm": ln(p + "final_layer_norm"),
            "attention": {
                "wq": dense(p + "self_attn.q_proj"),
                "wk": dense(p + "self_attn.k_proj"),
                "wv": dense(p + "self_attn.v_proj"),
                "wo": dense(p + "self_attn.out_proj"),
            },
            "fc1": dense(p + "fc1"),
            "fc2": dense(p + "fc2"),
        }
    return {"params": inner}


def _opt_to_hf(params: dict, config) -> Dict[str, np.ndarray]:
    inner = params["params"]

    def T(x):
        return np.ascontiguousarray(np.asarray(x).T)

    flat = {
        "model.decoder.embed_tokens.weight": np.asarray(inner["embed_tokens"]["embedding"]),
        "model.decoder.embed_positions.weight": np.asarray(inner["embed_positions"]["embedding"]),
        "model.decoder.final_layer_norm.weight": np.asarray(inner["final_norm"]["scale"]),
        "model.decoder.final_layer_norm.bias": np.asarray(inner["final_norm"]["bias"]),
        "lm_head.weight": np.asarray(inner["embed_tokens"]["embedding"]),  # tied
    }
    for i in range(config.num_hidden_layers):
        lp = inner[f"layer_{i}"]
        p = f"model.decoder.layers.{i}."
        for ours, theirs in [("self_attn_norm", "self_attn_layer_norm"), ("final_norm", "final_layer_norm")]:
            flat[p + theirs + ".weight"] = np.asarray(lp[ours]["scale"])
            flat[p + theirs + ".bias"] = np.asarray(lp[ours]["bias"])
        for ours, theirs in [("wq", "q_proj"), ("wk", "k_proj"), ("wv", "v_proj"), ("wo", "out_proj")]:
            flat[p + f"self_attn.{theirs}.weight"] = T(lp["attention"][ours]["kernel"])
            flat[p + f"self_attn.{theirs}.bias"] = np.asarray(lp["attention"][ours]["bias"])
        for name in ("fc1", "fc2"):
            flat[p + f"{name}.weight"] = T(lp[name]["kernel"])
            flat[p + f"{name}.bias"] = np.asarray(lp[name]["bias"])
    return flat


# ------------------------------------------------------------------------ t5 mapping
def _t5_from_hf(flat: Dict[str, np.ndarray], config) -> dict:
    """HF T5 layout: per-stack blocks with numbered sublayers (0=self-attn,
    [1=cross-attn decoder-only], last=FF); the relative-bias table lives on block 0
    of each stack. Our modules share ONE bias module per stack — same weight.

    Both generations load (reference load_checkpoint_in_model utils/modeling.py:1565
    accepts any layout): v1.1 (un-tied lm_head, gated wi_0/wi_1 — t5-v1_1-*, T0pp,
    flan-t5) and v1.0 (tied head inside the shared embedding, single relu `wi` —
    t5-small/base/large). The config must match the checkpoint's generation
    (`tie_word_embeddings` / `feed_forward_proj`) — checked here so a mismatch is
    one clear error instead of a missing-key crash three frames deep."""
    # The FFN keys identify the generation unambiguously (wi vs wi_0/wi_1).
    # Head-tying is taken from the CONFIG: .bin files and in-memory state
    # dicts keep a tied lm_head.weight VIEW while safetensors drops shared
    # tensors, so lm_head's presence alone proves nothing about tying.
    ckpt_gated = "encoder.block.0.layer.1.DenseReluDense.wi_0.weight" in flat
    cfg_gated = getattr(config, "feed_forward_proj", "gated-gelu") != "relu"
    cfg_tied = bool(getattr(config, "tie_word_embeddings", False))
    if ckpt_gated != cfg_gated:
        raise ValueError(
            f"T5 checkpoint/config generation mismatch: checkpoint has a "
            f"{'gated wi_0/wi_1 (v1.1)' if ckpt_gated else 'single relu wi (v1.0)'} "
            f"FFN but the config says feed_forward_proj="
            f"{getattr(config, 'feed_forward_proj', 'gated-gelu')!r}. Use a "
            f"t5_small_v1_0()-style config (tie_word_embeddings=True, relu) for "
            f"v1.0 checkpoints (t5-small/base/large) and the default T5Config "
            f"for v1.1 (t5-v1_1-*, T0pp, flan-t5)."
        )
    if not cfg_tied and "lm_head.weight" not in flat:
        raise ValueError(
            "config says tie_word_embeddings=False but the checkpoint has no "
            "lm_head.weight — this is a tied-head (v1.0) checkpoint; load it "
            "with a tie_word_embeddings=True config (e.g. t5_small_v1_0())."
        )

    def T(name):
        return np.ascontiguousarray(flat[name].T)

    def attn(prefix):
        return {
            "wq": {"kernel": T(prefix + ".q.weight")},
            "wk": {"kernel": T(prefix + ".k.weight")},
            "wv": {"kernel": T(prefix + ".v.weight")},
            "wo": {"kernel": T(prefix + ".o.weight")},
        }

    def ff(prefix):
        if not ckpt_gated:
            return {
                "wi": {"kernel": T(prefix + ".wi.weight")},
                "wo_ff": {"kernel": T(prefix + ".wo.weight")},
            }
        return {
            "wi_0": {"kernel": T(prefix + ".wi_0.weight")},
            "wi_1": {"kernel": T(prefix + ".wi_1.weight")},
            "wo_ff": {"kernel": T(prefix + ".wo.weight")},
        }

    def norm(name):
        return {"scale": np.asarray(flat[name])}

    inner: dict = {
        "shared": {"embedding": np.asarray(flat["shared.weight"])},
        "enc_final_norm": norm("encoder.final_layer_norm.weight"),
        "dec_final_norm": norm("decoder.final_layer_norm.weight"),
        "enc_bias": {
            "rel_embedding": np.asarray(
                flat["encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"]
            )
        },
        "dec_bias": {
            "rel_embedding": np.asarray(
                flat["decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"]
            )
        },
    }
    if not cfg_tied:
        inner["lm_head"] = {"kernel": T("lm_head.weight")}
    # cfg_tied with lm_head.weight present (a .bin's tied view): ignored — the
    # head IS shared.weight, already loaded above.
    for i in range(config.num_layers):
        p = f"encoder.block.{i}."
        inner[f"enc_blocks_{i}"] = {
            "attention": attn(p + "layer.0.SelfAttention"),
            "input_norm": norm(p + "layer.0.layer_norm.weight"),
            "ff": ff(p + "layer.1.DenseReluDense"),
            "ff_norm": norm(p + "layer.1.layer_norm.weight"),
        }
    for i in range(config.num_decoder_layers):
        p = f"decoder.block.{i}."
        inner[f"dec_blocks_{i}"] = {
            "self_attention": attn(p + "layer.0.SelfAttention"),
            "input_norm": norm(p + "layer.0.layer_norm.weight"),
            "cross_attention": attn(p + "layer.1.EncDecAttention"),
            "cross_norm": norm(p + "layer.1.layer_norm.weight"),
            "ff": ff(p + "layer.2.DenseReluDense"),
            "ff_norm": norm(p + "layer.2.layer_norm.weight"),
        }
    return {"params": inner}


def _t5_to_hf(params: dict, config) -> Dict[str, np.ndarray]:
    inner = params["params"]

    def T(x):
        return np.ascontiguousarray(np.asarray(x).T)

    flat = {
        "shared.weight": np.asarray(inner["shared"]["embedding"]),
        "encoder.embed_tokens.weight": np.asarray(inner["shared"]["embedding"]),
        "decoder.embed_tokens.weight": np.asarray(inner["shared"]["embedding"]),
        "encoder.final_layer_norm.weight": np.asarray(inner["enc_final_norm"]["scale"]),
        "decoder.final_layer_norm.weight": np.asarray(inner["dec_final_norm"]["scale"]),
        "encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight": np.asarray(
            inner["enc_bias"]["rel_embedding"]
        ),
        "decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight": np.asarray(
            inner["dec_bias"]["rel_embedding"]
        ),
    }
    if "lm_head" in inner:  # v1.0 ties the head into shared.weight — nothing to write
        flat["lm_head.weight"] = T(inner["lm_head"]["kernel"])

    def put_attn(prefix, sub):
        for ours, theirs in [("wq", "q"), ("wk", "k"), ("wv", "v"), ("wo", "o")]:
            flat[f"{prefix}.{theirs}.weight"] = T(sub[ours]["kernel"])

    def put_ff(prefix, sub):
        pairs = (
            [("wi", "wi"), ("wo_ff", "wo")]
            if "wi" in sub
            else [("wi_0", "wi_0"), ("wi_1", "wi_1"), ("wo_ff", "wo")]
        )
        for ours, theirs in pairs:
            flat[f"{prefix}.{theirs}.weight"] = T(sub[ours]["kernel"])

    for i in range(config.num_layers):
        lp = inner[f"enc_blocks_{i}"]
        p = f"encoder.block.{i}."
        put_attn(p + "layer.0.SelfAttention", lp["attention"])
        flat[p + "layer.0.layer_norm.weight"] = np.asarray(lp["input_norm"]["scale"])
        put_ff(p + "layer.1.DenseReluDense", lp["ff"])
        flat[p + "layer.1.layer_norm.weight"] = np.asarray(lp["ff_norm"]["scale"])
    for i in range(config.num_decoder_layers):
        lp = inner[f"dec_blocks_{i}"]
        p = f"decoder.block.{i}."
        put_attn(p + "layer.0.SelfAttention", lp["self_attention"])
        flat[p + "layer.0.layer_norm.weight"] = np.asarray(lp["input_norm"]["scale"])
        put_attn(p + "layer.1.EncDecAttention", lp["cross_attention"])
        flat[p + "layer.1.layer_norm.weight"] = np.asarray(lp["cross_norm"]["scale"])
        put_ff(p + "layer.2.DenseReluDense", lp["ff"])
        flat[p + "layer.2.layer_norm.weight"] = np.asarray(lp["ff_norm"]["scale"])
    return flat


_FROM_HF = {
    "llama": _llama_from_hf,
    "mixtral": _mixtral_from_hf,
    "gptj": _gptj_from_hf,
    "gpt_neox": _gpt_neox_from_hf,
    "opt": _opt_from_hf,
    "t5": _t5_from_hf,
}
_TO_HF = {
    "llama": _llama_to_hf,
    "mixtral": _mixtral_to_hf,
    "gptj": _gptj_to_hf,
    "gpt_neox": _gpt_neox_to_hf,
    "opt": _opt_to_hf,
    "t5": _t5_to_hf,
}


def convert_hf_state_dict(flat: Dict[str, np.ndarray], model_type: str, config) -> dict:
    """Flat HF state dict -> our nested params pytree."""
    if model_type not in _FROM_HF:
        raise ValueError(f"Unsupported model_type {model_type!r}; known: {sorted(_FROM_HF)}")
    return _FROM_HF[model_type](flat, config)


def export_hf_state_dict(params: dict, model_type: str, config) -> Dict[str, np.ndarray]:
    """Our params pytree -> flat HF-layout state dict (torch [out, in] kernels)."""
    if model_type not in _TO_HF:
        raise ValueError(f"Unsupported model_type {model_type!r}; known: {sorted(_TO_HF)}")
    return _TO_HF[model_type](params, config)


def load_hf_checkpoint_in_model(model, checkpoint_path: str, model_type: str, config=None):
    """Load an HF torch checkpoint into a Model bundle in place (reference
    load_checkpoint_in_model utils/modeling.py:1565). Returns the model."""
    config = config or getattr(getattr(model, "module", None), "config", None)
    if config is None:
        raise ValueError("Pass config= when the model bundle has no flax module config")
    flat = load_hf_state_dict(checkpoint_path)
    params = convert_hf_state_dict(flat, model_type, config)
    if hasattr(model, "load_state_dict"):
        model.load_state_dict(params)
    else:
        model.params = params
    return model


def save_hf_checkpoint(params: dict, model_type: str, config, save_path: str):
    """Write params as a single HF-layout .safetensors file."""
    from safetensors.numpy import save_file

    flat = export_hf_state_dict(params, model_type, config)
    os.makedirs(os.path.dirname(os.path.abspath(save_path)), exist_ok=True)
    try:
        # safetensors >= 0.4 writes ml_dtypes bfloat16 arrays as real BF16, so the
        # file round-trips through HF transformers and load_hf_state_dict.
        save_file(dict(flat), save_path)
    except (TypeError, ValueError):
        # Old safetensors without numpy-bf16 support: record the view in metadata
        # so readers can restore the dtype.
        clean, viewed = {}, []
        for k, v in flat.items():
            if v.dtype.name == "bfloat16":
                clean[k] = v.view(np.uint16)
                viewed.append(k)
            else:
                clean[k] = v
        save_file(clean, save_path, metadata={"bfloat16_as_uint16": ",".join(viewed)})

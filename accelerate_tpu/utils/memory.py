"""OOM-retry helpers (parity: reference utils/memory.py:29,87-158).

On TPU the OOM signal is an XlaRuntimeError mentioning RESOURCE_EXHAUSTED (HBM OOM at
compile or run time) rather than torch's CUDA OOM. `find_executable_batch_size` halves
the batch size until the wrapped function stops OOMing — same decorator contract as the
reference so training scripts port unchanged.
"""

from __future__ import annotations

import functools
import gc
import inspect


def release_memory(*objects):
    """Drop references and force a GC pass; live jax.Arrays are deleted explicitly.

    Parity: reference utils/memory.py:29 (which calls torch.cuda.empty_cache)."""
    import jax

    if not isinstance(objects, list):
        objects = list(objects)
    for i in range(len(objects)):
        obj = objects[i]
        try:
            leaves = jax.tree_util.tree_leaves(obj)
            for leaf in leaves:
                if isinstance(leaf, jax.Array):
                    leaf.delete()
        except Exception:
            pass
        objects[i] = None
    gc.collect()
    return objects


def is_oom_exception(exception: Exception) -> bool:
    """True when an exception is an XLA out-of-memory condition."""
    msg = str(exception)
    markers = [
        "RESOURCE_EXHAUSTED",
        "Out of memory",
        "out of memory",
        "Resource exhausted",
        "Attempting to reserve",
        "exceeds the amount of memory available",
    ]
    return any(m in msg for m in markers)


def find_executable_batch_size(function=None, starting_batch_size: int = 128):
    """Decorator: retries `function(batch_size, *args, **kwargs)` halving batch_size on
    HBM OOM (parity: reference utils/memory.py:87-158).

    Example:
        @find_executable_batch_size(starting_batch_size=512)
        def train(batch_size, ...): ...
    """
    if function is None:
        return functools.partial(find_executable_batch_size, starting_batch_size=starting_batch_size)

    batch_size = [starting_batch_size]

    @functools.wraps(function)
    def decorator(*args, **kwargs):
        params = list(inspect.signature(function).parameters.keys())
        if len(params) < (len(args) + 1):
            arg_str = ", ".join([f"{arg}={value}" for arg, value in zip(params[1:], args[1:])])
            raise TypeError(
                f"Batch size was passed into `{function.__name__}` as the first argument when called."
                f"Remove this as the decorator already does so: `{function.__name__}({arg_str})`"
            )
        while True:
            if batch_size[0] == 0:
                raise RuntimeError("No executable batch size found, reached zero.")
            try:
                return function(batch_size[0], *args, **kwargs)
            except Exception as e:
                if is_oom_exception(e):
                    gc.collect()
                    batch_size[0] //= 2
                else:
                    raise

    return decorator

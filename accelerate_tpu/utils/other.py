"""Miscellaneous helpers (parity: reference utils/other.py).

`extract_model_from_parallel` and `save` keep their reference semantics
(other.py:56,176); environment context managers live in utils/environment.py.
"""

from __future__ import annotations

import os
import socket
from typing import Any

import numpy as np


def extract_model_from_parallel(model, keep_fp32_wrapper: bool = True):
    """Unwrap a prepared model back to the user's module (parity: reference
    utils/other.py:56 which unwraps DDP/FSDP/DeepSpeed/compiled wrappers).

    Under GSPMD there is exactly one wrapper type: `PreparedModel`."""
    try:
        from ..modeling import PreparedModel
    except ImportError:
        return model

    if isinstance(model, PreparedModel):
        return model.module if model.module is not None else model
    return model


def save(obj: Any, f, save_on_each_node: bool = False, safe_serialization: bool = True):
    """Save `obj` on the main process only (parity: reference utils/other.py:176).

    Arrays are saved via numpy `.npz`/msgpack-style flat dict when `obj` is a pytree of
    arrays; arbitrary picklables fall back to pickle.
    """
    import pickle

    from ..state import PartialState

    state = PartialState()
    if state.is_main_process or save_on_each_node:
        f = str(f)
        os.makedirs(os.path.dirname(f) or ".", exist_ok=True)
        import jax

        leaves, _ = jax.tree_util.tree_flatten(obj)
        if leaves and all(isinstance(x, (jax.Array, np.ndarray, np.generic, int, float)) for x in leaves):
            from ..checkpointing import save_pytree

            save_pytree(obj, f)
            return
        with open(f, "wb") as fh:
            pickle.dump(obj, fh)


def is_port_in_use(port: int = 29500) -> bool:
    """(parity: reference utils/other.py:313)"""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        return s.connect_ex(("localhost", port)) == 0


def convert_bytes(size: float) -> str:
    """Human-readable byte size (parity: reference utils/other.py:324)."""
    for unit in ["bytes", "KB", "MB", "GB", "TB"]:
        if size < 1024.0:
            return f"{round(size, 2)} {unit}"
        size /= 1024.0
    return f"{round(size, 2)} PB"


def check_os_kernel():
    """Warn on Linux kernels with poor multiprocess host performance (parity:
    reference utils/other.py:334 warns on <5.5)."""
    import platform
    import warnings

    info = platform.uname()
    if info.system != "Linux":
        return
    try:
        version = tuple(int(v) for v in info.release.split(".")[:2])
    except ValueError:
        return
    if version < (5, 5):
        warnings.warn(
            f"Detected kernel version {info.release}, which is below the recommended minimum of 5.5.0; "
            "this can cause the process to hang.",
            UserWarning,
        )


def merge_dicts(source: dict, destination: dict) -> dict:
    """Recursive dict merge; `source` wins (used by config layering)."""
    for key, value in source.items():
        if isinstance(value, dict):
            node = destination.setdefault(key, {})
            merge_dicts(value, node)
        else:
            destination[key] = value
    return destination

"""Optional rich console integration (parity: reference utils/rich.py — installs a
rich traceback handler when the package is available)."""

from .imports import is_rich_available

if is_rich_available():
    from rich.traceback import install

    install(show_locals=False)
else:
    raise ModuleNotFoundError(
        "To use the rich extension, install rich with `pip install rich`"
    )

"""Constants shared across the framework.

Parity notes: the reference keeps its constant tables in utils/constants.py (sharding
strategies at constants.py:33, deepspeed multinode launchers at constants.py:39). Here the
tables are TPU-shaped: sharding strategies name GSPMD axis policies instead of torch-FSDP
enum values, and the launcher table names TPU pod mechanisms instead of pdsh/mpirun.
"""

MODEL_NAME = "model"
OPTIMIZER_NAME = "optimizer"
SCHEDULER_NAME = "scheduler"
SAMPLER_NAME = "sampler"
RNG_STATE_NAME = "random_states"
SCALER_NAME = "scaler"
PARAMS_NAME = "params"

SAFE_WEIGHTS_NAME = "model.safetensors"
SAFE_WEIGHTS_INDEX_NAME = "model.safetensors.index.json"
WEIGHTS_NAME = "model.msgpack"
WEIGHTS_INDEX_NAME = "model.msgpack.index.json"
SHARDED_STATE_DIR = "sharded_state"

# GSPMD sharding strategies (the FSDP/ZeRO replacement — reference constants.py:33 lists the
# five torch-FSDP strategies; these are their mesh-axis equivalents).
FSDP_SHARDING_STRATEGY = ["FULL_SHARD", "SHARD_GRAD_OP", "NO_SHARD", "HYBRID_SHARD", "HYBRID_SHARD_ZERO2"]
FSDP_STATE_DICT_TYPE = ["FULL_STATE_DICT", "SHARDED_STATE_DICT"]
FSDP_AUTO_WRAP_POLICY = ["TRANSFORMER_BASED_WRAP", "SIZE_BASED_WRAP", "NO_WRAP"]

# TPU pod launch mechanisms (replaces the deepspeed pdsh/openmpi table, constants.py:39).
TPU_POD_LAUNCHERS = ["gcloud", "ssh", "manual"]

# Mesh axis names, in canonical (outer→inner, DCN→ICI) order. Data goes on ("data","fsdp"),
# parameters shard over "fsdp" (ZeRO-3) and "model" (tensor parallel), activations'
# sequence dim over "seq" (ring attention), experts over "expert". Two pipeline axes exist:
# "stage" is the SPMD runner's axis (stacked [L,...] params, lax.ppermute ring, equal layer
# counts), "pipeline" is the MPMD runtime's axis (parallel/mpmd.py: the mesh is sliced into
# per-stage submeshes so stages may hold unequal layer counts).
MESH_AXIS_NAMES = ("data", "fsdp", "model", "seq", "expert", "stage", "pipeline")
DATA_AXES = ("data", "fsdp")

ELASTIC_LOG_PREFIX = "accelerate_tpu.launch"

# RNG stream names checkpointed per process (reference checkpointing.py:122-151 saves
# python/numpy/torch/cuda/xla states; JAX needs python/numpy plus the explicit jax key).
RNG_TYPES = ["python", "numpy", "jax"]

# Environment-variable protocol prefix (reference uses ACCELERATE_* — utils/launch.py:100-148).
ENV_PREFIX = "ACCELERATE_TPU_"

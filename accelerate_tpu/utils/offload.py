"""Disk-backed weight store (parity: reference utils/offload.py:25-192).

Each tensor is one raw `.npy` saved with `np.save` and re-opened `mmap_mode="r"`, plus
an `index.json` of name → {filename, shape, dtype}; `OffloadedWeightsLoader` is the lazy
Mapping over (disk index + in-memory state dicts) that the streamed executor reads
blocks from. bfloat16 round-trips via a uint16 view (npy has no bf16)."""

from __future__ import annotations

import json
import os
from collections.abc import Mapping
from typing import Dict, Optional

import numpy as np


def offload_weight(weight, weight_name: str, offload_folder: str, index: Optional[dict] = None) -> dict:
    """(reference offload.py:25)"""
    import jax

    arr = np.asarray(jax.device_get(weight)) if not isinstance(weight, np.ndarray) else weight
    dtype_name = arr.dtype.name
    save_arr = arr.view(np.uint16) if dtype_name == "bfloat16" else arr
    os.makedirs(offload_folder, exist_ok=True)
    fname = weight_name.replace("/", "--") + ".npy"
    np.save(os.path.join(offload_folder, fname), save_arr)
    if index is None:
        index = {}
    index[weight_name] = {"filename": fname, "shape": list(arr.shape), "dtype": dtype_name}
    return index


def save_offload_index(index: dict, offload_folder: str):
    with open(os.path.join(offload_folder, "index.json"), "w") as f:
        json.dump(index, f, indent=2)


def load_offload_index(offload_folder: str) -> dict:
    path = os.path.join(offload_folder, "index.json")
    if not os.path.isfile(path):
        return {}
    with open(path) as f:
        return json.load(f)


def load_offloaded_weight(offload_folder: str, weight_info: dict):
    """mmap-read one tensor (reference offload.py:79); bf16 restored from uint16.

    The bf16 view stays on the memmap (no np.asarray!) so disk weights are only paged
    in when a block is actually device_put — the whole point of the disk tier."""
    arr = np.load(os.path.join(offload_folder, weight_info["filename"]), mmap_mode="r")
    if weight_info["dtype"] == "bfloat16":
        import jax.numpy as jnp

        return arr.view(jnp.bfloat16)
    return arr


class OffloadedWeightsLoader(Mapping):
    """Lazy Mapping over disk-offloaded + in-memory weights (reference offload.py:127)."""

    def __init__(self, state_dict: Optional[Dict] = None, save_folder: Optional[str] = None, index: Optional[dict] = None):
        if state_dict is None and save_folder is None:
            raise ValueError("Need either a state_dict or a save_folder")
        self.state_dict = state_dict or {}
        self.save_folder = save_folder
        if index is None and save_folder is not None:
            index = load_offload_index(save_folder)
        self.index = index or {}
        self.all_keys = list(self.state_dict.keys()) + [k for k in self.index if k not in self.state_dict]

    def __getitem__(self, key: str):
        if key in self.state_dict:
            return self.state_dict[key]
        weight_info = self.index[key]
        return load_offloaded_weight(self.save_folder, weight_info)

    def __iter__(self):
        return iter(self.all_keys)

    def __len__(self):
        return len(self.all_keys)


class PrefixedDataset(Mapping):
    """View of a Mapping with a key prefix stripped/applied (reference offload.py:174)."""

    def __init__(self, dataset: Mapping, prefix: str):
        self.dataset = dataset
        self.prefix = prefix

    def __getitem__(self, key):
        return self.dataset[f"{self.prefix}{key}"]

    def __iter__(self):
        return iter([key for key in self.dataset if key.startswith(self.prefix)])

    def __len__(self):
        return len([key for key in self.dataset if key.startswith(self.prefix)])


def extract_submodule_state(params, prefix: str) -> dict:
    """Flat {path: leaf} for every param under a block prefix."""
    from ..parallel.sharding import tree_paths_and_leaves

    flat, _ = tree_paths_and_leaves(params)
    return {path: leaf for path, leaf in flat if path.startswith(prefix)}

"""Host-side page-pool allocator and shared-prefix cache for paged KV serving.

The device side of the paged cache is dumb on purpose: one pool of fixed-size
KV pages per layer (`ops/attention.update_slot_cache` paged mode) plus per-slot
page tables riding as traced int32 operands, so the single decode executable
and the per-bucket insert executables never retrace. ALL policy lives here, on
the host, between dispatches:

  - **PagePool** — a free-list allocator with per-page refcounts over pages
    `1..num_pages-1` (page 0 is the reserved SCRATCH page: inactive slots'
    table rows point at it so their discarded writes can never corrupt a live
    request, and shared-prefix table entries are redirected to it at insert so
    a registered read-only page is written exactly once, at creation).
  - **Prefix cache** — chain hashes of prompt token prefixes at page
    granularity (`chain_hashes`): the digest for page i covers tokens
    `[0, (i+1)*page_size)`, so a hash match implies bitwise-identical KV
    content (K/V at position j depends only on tokens `<= j` under causal
    attention, and rotary embeddings are absolute-position aligned). Matched
    pages are shared read-only across requests with refcount pins; a released
    shared page stays CACHED (refcount 0, evictable LRU) rather than free, so
    the next request with the same system prompt pays zero prefill FLOPs and
    zero duplicate HBM for it.

Admission is reserve-on-admit: the engine reserves the request's whole
worst-case footprint `ceil((prompt + max_new_tokens) / page_size)` pages
(minus matched prefix pages) before the insert dispatch, so a request that
admits can always run to completion — no mid-flight pool exhaustion, no
preemption machinery — while capacity stays proportional to each request's
ACTUAL footprint instead of the engine-wide `max_length` worst case.

Pure host Python (no jax imports): allocator calls sit on the serving hot path
between dispatches and must never touch the device.
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

#: Pool page 0 — never allocated; absorbs writes the engine wants discarded.
SCRATCH_PAGE = 0

#: KV page storage dtypes the device pool supports ("bf16" = unquantized, the
#: model compute dtype; mirrors ops/quantization.KV_CACHE_DTYPES without the
#: jax import — this module stays pure host Python). Bytes-per-value is never
#: tabulated here: the live pool leaf's itemsize
#: (`ContinuousBatcher.kv_pool_itemsize`) is the one source of truth.
KV_CACHE_DTYPES = ("bf16", "int8", "fp8_e4m3")


def pages_for(num_tokens: int, page_size: int) -> int:
    """Pages covering `num_tokens` cache positions (ceil division) — the
    admission footprint formula. Speculative engines pass
    `prompt + max_new + draft_tokens`: the draft window's rejected writes land
    through the slot's own page table, so the window counts against the
    reservation like real tokens (positions past the table's last entry fall
    through to the scratch page and are discarded)."""
    return -(-int(num_tokens) // int(page_size))


def chain_hashes(tokens, page_size: int) -> List[str]:
    """Chain digest per FULL page of a token sequence: entry i is the SHA-256
    over tokens `[0, (i+1)*page_size)` (running hash, so a page's digest commits
    to its whole prefix — two prompts share page i iff they agree on every token
    through page i). Partial trailing pages get no hash: prefix sharing is
    page-granular by design."""
    ids = np.asarray(tokens, np.int32).reshape(-1)
    digest = hashlib.sha256()
    out: List[str] = []
    for i in range(ids.size // page_size):
        digest.update(ids[i * page_size : (i + 1) * page_size].tobytes())
        out.append(digest.hexdigest())
    return out


class PagePool:
    """Refcounted page allocator + page-granular prefix cache (host side).

    Page states (mutually exclusive):
      - **free**: on the free list, content meaningless.
      - **in use**: refcount >= 1 — owned by one request (private pages) or
        pinned by every request currently sharing it (registered prefix pages).
      - **cached**: refcount == 0 but registered in the prefix cache — content
        is a valid shared prefix awaiting its next hit; evicted LRU only when
        `reserve` finds the free list short.

    `pages_in_use + pages_free + pages_cached == pages_total` always (the
    scratch page is outside the ledger); `check_consistency()` verifies the
    invariants and is pinned by the chaos page-ledger check.
    """

    def __init__(
        self,
        num_pages: int,
        page_size: int,
        on_evict: Optional[Callable[[int], None]] = None,
        kv_cache_dtype: str = "bf16",
    ):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the reserved scratch page)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        if kv_cache_dtype not in KV_CACHE_DTYPES:
            raise ValueError(
                f"unknown kv_cache_dtype {kv_cache_dtype!r}; expected one of {KV_CACHE_DTYPES}"
            )
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        #: Device-pool storage dtype this allocator fronts. Pure bookkeeping
        #: host-side (allocation is dtype-blind), but carried here so capacity
        #: math / stats / the bench derive bytes from ONE source of truth.
        self.kv_cache_dtype = str(kv_cache_dtype)
        self.on_evict = on_evict
        self.evictions = 0
        self._init_state()

    def _init_state(self):
        self._refcount = np.zeros(self.num_pages, np.int64)
        # LIFO free list: a just-freed (hot) page is reused first.
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._page_of_hash: Dict[str, int] = {}
        self._hash_of_page: Dict[int, str] = {}
        self._lru: Dict[int, int] = {}  # cached page -> last-touch tick (dict = insertion order fallback)
        self._tick = 0

    # ------------------------------------------------------------------ ledger
    @property
    def pages_total(self) -> int:
        """Usable pages (the scratch page is not allocatable)."""
        return self.num_pages - 1

    @property
    def pages_in_use(self) -> int:
        return int((self._refcount[1:] > 0).sum())

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_cached(self) -> int:
        """Unreferenced prefix pages held for reuse (evictable)."""
        return len(self._lru)

    @property
    def prefix_entries(self) -> int:
        return len(self._page_of_hash)

    def check_consistency(self) -> List[str]:
        """Structural invariants; every violation is a leak or a
        use-after-free in the making. Empty list == healthy."""
        problems: List[str] = []
        if SCRATCH_PAGE in self._free or SCRATCH_PAGE in self._lru:
            problems.append("scratch page entered the allocatable set")
        free_set = set(self._free)
        if len(free_set) != len(self._free):
            problems.append("duplicate pages on the free list")
        for page in free_set:
            if self._refcount[page] != 0:
                problems.append(f"free page {page} has refcount {self._refcount[page]}")
            if page in self._hash_of_page:
                problems.append(f"free page {page} still registered in the prefix cache")
        for page in self._lru:
            if self._refcount[page] != 0:
                problems.append(f"cached page {page} has refcount {self._refcount[page]}")
            if page not in self._hash_of_page:
                problems.append(f"cached page {page} has no prefix registration")
            if page in free_set:
                problems.append(f"page {page} is both cached and free")
        for digest, page in self._page_of_hash.items():
            if self._hash_of_page.get(page) != digest:
                problems.append(f"hash map asymmetry for page {page}")
        accounted = self.pages_in_use + self.pages_free + self.pages_cached
        if accounted != self.pages_total:
            problems.append(
                f"ledger mismatch: in_use {self.pages_in_use} + free {self.pages_free} "
                f"+ cached {self.pages_cached} != total {self.pages_total}"
            )
        return problems

    # -------------------------------------------------------------- allocation
    def reserve(self, count: int) -> Optional[List[int]]:
        """Take `count` pages (refcount 1 each), evicting LRU cached prefix
        pages if the free list runs short. Returns None — reserving NOTHING —
        when even eviction cannot cover the request, so a failed admission
        never partially drains the pool."""
        if count < 0:
            raise ValueError("count must be >= 0")
        if count > len(self._free) + len(self._lru):
            return None
        taken: List[int] = []
        for _ in range(count):
            if self._free:
                page = self._free.pop()
            else:
                page = min(self._lru, key=self._lru.__getitem__)  # oldest tick
                del self._lru[page]
                digest = self._hash_of_page.pop(page)
                self._page_of_hash.pop(digest, None)
                self.evictions += 1
                if self.on_evict is not None:
                    self.on_evict(1)
            self._refcount[page] = 1
            taken.append(page)
        return taken

    def release(self, pages: Sequence[int]):
        """Drop one reference per page. A page at refcount 0 returns to the
        free list — unless it is a registered prefix page, which stays CACHED
        (content intact, LRU-evictable) for the next shared-prompt hit.

        Processed in REVERSE caller order: callers pass a slot's pages in
        chain order (prefix head first), so the reversal hands the chain TAIL
        the oldest LRU tick. Under pool pressure eviction then trims cached
        prefixes from the deep end — the next same-prefix request still
        matches the surviving head pages — instead of evicting the head and
        making every deeper cached page of the chain unmatchable at once."""
        for page in reversed(list(pages)):
            if page == SCRATCH_PAGE:
                raise ValueError("the scratch page is never reference-counted")
            if self._refcount[page] <= 0:
                raise ValueError(f"release of page {page} with refcount {self._refcount[page]}")
            self._refcount[page] -= 1
            if self._refcount[page] == 0:
                if page in self._hash_of_page:
                    self._tick += 1
                    self._lru[page] = self._tick
                else:
                    self._free.append(page)

    # ------------------------------------------------------------ prefix cache
    def match_prefix(self, hashes: Sequence[str], max_pages: int) -> List[int]:
        """Longest chain of already-cached prefix pages for `hashes` (capped at
        `max_pages`; the engine caps below the full prompt so at least one
        suffix token always runs through the model to produce first-token
        logits). Each matched page is PINNED (+1 refcount) — the caller owns
        the release."""
        matched: List[int] = []
        for digest in list(hashes)[: max(max_pages, 0)]:
            page = self._page_of_hash.get(digest)
            if page is None:
                break
            if self._refcount[page] == 0:
                self._lru.pop(page, None)
            self._refcount[page] += 1
            matched.append(page)
        return matched

    def register_prefix(self, hashes: Sequence[str], pages: Sequence[int], start: int = 0):
        """Attach chain hashes to pages `start..len(hashes)-1` after a
        successful insert wrote them (the first `start` entries were matched,
        already-registered pages). First writer wins: if another request
        registered the same digest concurrently, the later page stays a
        private, unregistered page — content is identical either way."""
        for i in range(start, len(hashes)):
            digest, page = hashes[i], pages[i]
            if page == SCRATCH_PAGE:
                raise ValueError("cannot register the scratch page as a prefix page")
            if digest in self._page_of_hash or page in self._hash_of_page:
                continue
            self._page_of_hash[digest] = page
            self._hash_of_page[page] = digest

    # ---------------------------------------------------------------- recovery
    def reset(self):
        """Blast-radius recovery: the device pool was rebuilt from zeros, so
        every page's CONTENT is gone — drop all refcounts, all prefix
        registrations (a stale hash->page mapping would serve zeroed KV as a
        'cached' prefix), and refill the free list. Cumulative counters
        (`evictions`) survive; they are telemetry, not state."""
        self._init_state()

    def stats(self) -> Dict[str, Any]:
        return {
            "kv_cache_dtype": self.kv_cache_dtype,
            "pages_total": self.pages_total,
            "pages_in_use": self.pages_in_use,
            "pages_free": self.pages_free,
            "pages_cached": self.pages_cached,
            "prefix_entries": self.prefix_entries,
            "evictions": self.evictions,
        }

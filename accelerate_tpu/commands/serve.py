"""`accelerate-tpu serve` — drive a replicated serving fleet from the CLI.

Builds a `router.Router` over `--replicas` in-process engines (the
`ContinuousBatcher` slot/paged machinery behind a health-routed front-end:
least-loaded routing, bounded per-replica backpressure, never-streamed retry,
`finish_reason=replica_lost` for streamed requests on a dead replica, rolling
`swap_weights` — docs/serving.md "Replication") and serves a batch of requests
through it, emitting one JSON line per finished request on stdout::

    accelerate-tpu serve --model llama-tiny --replicas 3 --requests 16 \
        --max-new 32 --deadline-s 60

Prompts are synthetic token ids by default (`--requests N --seed S`, the bench
workload shape); ``--prompts-file FILE`` reads one JSON object per line with a
``"tokens": [int, ...]`` field instead. Exit code 0 when every request reached
a normal terminal reason, 1 when any finished `error`/`replica_lost`/`timeout`.

`--replicas` defaults to the launch env protocol
(``ACCELERATE_TPU_SERVE_REPLICAS``, exported by ``accelerate-tpu launch
--replicas N``), so a supervised serving job sizes its fleet from the launcher.

``--out-of-process`` runs each replica as a real subprocess engine worker
(`accelerate_tpu.worker`) — process-level fault domains with warm
restart/rejoin; ``--min-replicas``/``--max-replicas`` arm the queue/TTFT
autoscaler, and ``--hedge-quantile`` derives the hedge threshold from the
live TTFT histogram (docs/serving.md "Out-of-process workers").

``--transport socket`` carries the same worker frames over TCP with
reconnect-with-backoff (a torn link reconnects and resumes streams; only an
exhausted ``--reconnect-deadline`` budget respawns the worker), and
``--connect HOST:PORT[,...]`` adopts externally launched listener workers
(``python -m accelerate_tpu.worker --listen HOST:PORT``) — one replica per
address (docs/serving.md "Socket transport").
"""

from __future__ import annotations

import json
import sys


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "serve",
        help="Serve a batch of requests through a replicated (health-routed) engine fleet",
        description=__doc__,
    )
    parser.add_argument("--model", default="llama-tiny", help="Named model (accelerate_tpu.models)")
    parser.add_argument(
        "--replicas", type=int, default=None,
        help="Engine fleet size (default: $ACCELERATE_TPU_SERVE_REPLICAS, else 2)",
    )
    parser.add_argument("--num-slots", type=int, default=4, help="Slots per replica engine")
    parser.add_argument("--chunk-size", type=int, default=8, help="Decode tokens per dispatch")
    parser.add_argument("--max-length", type=int, default=None, help="Per-slot cache length")
    parser.add_argument(
        "--max-queue", type=int, default=64,
        help="Bounded wait queue PER REPLICA (backpressure surfaces as queue_full)",
    )
    parser.add_argument(
        "--deadline-s", type=float, default=120.0,
        help="Default per-request wall-clock deadline (finish_reason=timeout past it)",
    )
    parser.add_argument(
        "--hedge-after-s", type=float, default=None,
        help="TTFT hedging: duplicate a still-queued request onto a second replica "
        "after this many seconds (default: disabled)",
    )
    parser.add_argument(
        "--hedge-quantile", type=float, default=None,
        help="derive the hedge threshold from the live TTFT histogram at this "
        "quantile instead of a static --hedge-after-s (enabled once enough "
        "samples exist; mutually exclusive with --hedge-after-s)",
    )
    parser.add_argument(
        "--out-of-process", action="store_true",
        help="run each replica as a REAL subprocess engine worker "
        "(accelerate_tpu.worker IPC): process-level fault domains — a worker "
        "SIGKILL/hang ejects one replica, never the fleet",
    )
    parser.add_argument(
        "--transport", default="pipe", choices=["pipe", "socket"],
        help="out-of-process worker transport: 'pipe' = stdio frames on the "
        "spawned child, 'socket' = the same frames over TCP loopback with "
        "reconnect-with-backoff on torn links (a healed partition reconnects "
        "and resumes streams instead of respawning the worker)",
    )
    parser.add_argument(
        "--connect", default=None, metavar="HOST:PORT[,HOST:PORT...]",
        help="adopt EXTERNALLY launched listener workers (python -m "
        "accelerate_tpu.worker --listen HOST:PORT) instead of spawning: one "
        "replica per address, socket transport implied; the model's params "
        "path must be reachable on each worker's host (digest-verified)",
    )
    parser.add_argument(
        "--reconnect-deadline", type=float, default=None, dest="reconnect_deadline_s",
        help="socket-transport reconnect budget in seconds before a torn link "
        "escalates to the worker-death/respawn path (default: 10.0)",
    )
    parser.add_argument(
        "--min-replicas", type=int, default=None,
        help="autoscaler floor (with --max-replicas): the fleet never shrinks below this",
    )
    parser.add_argument(
        "--max-replicas", type=int, default=None,
        help="autoscaler ceiling: enables traffic-adaptive scaling between "
        "--min-replicas (default: --replicas) and this on queue-depth/TTFT pressure",
    )
    parser.add_argument("--requests", type=int, default=8, help="Synthetic request count")
    parser.add_argument("--max-new", type=int, default=32, help="max_new_tokens per request")
    parser.add_argument("--prompt-min", type=int, default=4)
    parser.add_argument("--prompt-max", type=int, default=32)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--prompts-file", default=None,
        help='JSONL with one {"tokens": [...], "max_new_tokens": N?} object per line '
        "(replaces the synthetic workload)",
    )
    parser.add_argument("--no-paged", action="store_true", help="Contiguous per-slot KV layout")
    parser.add_argument(
        "--weight-dtype", default="bf16", choices=["bf16", "int8"],
        help="weight storage dtype: int8 quantizes per-output-channel at load "
        "time and runs every Dense through the fused int8-epilogue matmul "
        "(ops/quantization.py) — ~2x less weight HBM traffic per decode step",
    )
    parser.add_argument(
        "--kv-cache-dtype", default="bf16", choices=["bf16", "int8", "fp8_e4m3"],
        help="KV page-pool storage dtype (paged cache only): int8/fp8_e4m3 "
        "store pages quantized with per-page-per-head scales, cutting "
        "cache-read bytes 2x vs bf16 and multiplying pool capacity",
    )
    parser.add_argument(
        "--tp", type=int, default=1,
        help="tensor-parallel degree PER ENGINE: each replica spans its own "
        "tp-device submesh (weights Megatron-sharded, the KV pool sharded by "
        "KV head — docs/serving.md \"Tensor-parallel engines\"); replicas "
        "get disjoint device groups when the topology allows, so "
        "--replicas R --tp N uses R*N chips",
    )
    parser.add_argument(
        "--sharding", default="rules", choices=["rules", "auto"],
        help="tensor-parallel partition source: \"rules\" = the model "
        "family's hand-written table, \"auto\" = the cost-model planner "
        "searches the layout and emits an equivalent table "
        "(accelerate-tpu plan shows what it would pick)",
    )
    parser.set_defaults(func=serve_command)
    return parser


def _load_requests(args, vocab_size):
    import numpy as np

    from ..serving import Request

    if args.prompts_file:
        requests = []
        with open(args.prompts_file) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                record = json.loads(line)
                requests.append(Request(
                    i, np.asarray(record["tokens"], np.int32),
                    max_new_tokens=int(record.get("max_new_tokens", args.max_new)),
                ))
        return requests
    rng = np.random.default_rng(args.seed)
    lo, hi = args.prompt_min, max(args.prompt_min, args.prompt_max)
    return [
        Request(
            i,
            rng.integers(1, vocab_size, (int(rng.integers(lo, hi + 1)),)).astype(np.int32),
            max_new_tokens=args.max_new,
        )
        for i in range(args.requests)
    ]


def serve_command(args):
    from ..models import create_named_model, get_model_family
    from ..router import Router

    if args.no_paged and args.kv_cache_dtype != "bf16":
        print(
            "accelerate-tpu serve: --kv-cache-dtype requires the paged KV cache "
            "(drop --no-paged)",
            file=sys.stderr,
        )
        raise SystemExit(2)
    if args.sharding == "auto" and args.tp <= 1:
        print(
            "accelerate-tpu serve: --sharding auto plans a tensor-parallel "
            "layout — pass --tp N (N > 1); a single-device engine has nothing "
            "to partition",
            file=sys.stderr,
        )
        raise SystemExit(2)
    if args.tp > 1 and args.out_of_process:
        print(
            "accelerate-tpu serve: --tp composes with in-process replicas only "
            "for now — subprocess workers pin their own device view (multi-host "
            "TP workers are ROADMAP item 2)",
            file=sys.stderr,
        )
        raise SystemExit(2)
    connect = (
        [a.strip() for a in args.connect.split(",") if a.strip()]
        if args.connect else None
    )
    if connect:
        # Adopting external listeners IS the out-of-process socket path.
        args.out_of_process = True
        args.transport = "socket"
        if args.replicas is None:
            args.replicas = len(connect)
    if args.transport == "socket" and not args.out_of_process:
        print(
            "accelerate-tpu serve: --transport socket needs worker processes — "
            "pass --out-of-process (or --connect for external workers)",
            file=sys.stderr,
        )
        raise SystemExit(2)
    worker_kwargs = {}
    if args.out_of_process:
        worker_kwargs["transport"] = args.transport
        if connect:
            worker_kwargs["connect"] = connect
        if args.reconnect_deadline_s is not None:
            worker_kwargs["reconnect_deadline_s"] = args.reconnect_deadline_s
    _fam, cfg = get_model_family(args.model)
    requests = _load_requests(args, cfg.vocab_size)
    if not requests:
        print("accelerate-tpu serve: no requests to serve", file=sys.stderr)
        raise SystemExit(2)
    longest = max(int(len(r.input_ids)) + r.max_new_tokens for r in requests)
    max_length = args.max_length or min(cfg.max_position_embeddings, longest)
    model = create_named_model(args.model, seq_len=min(128, max_length))
    router = Router(
        model,
        replicas=args.replicas,
        num_slots=args.num_slots,
        max_length=max_length,
        chunk_size=args.chunk_size,
        max_queue=args.max_queue,
        default_deadline_s=args.deadline_s,
        hedge_after_s=args.hedge_after_s,
        hedge_quantile=args.hedge_quantile,
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        out_of_process=args.out_of_process,
        worker_kwargs=worker_kwargs or None,
        paged=not args.no_paged,
        weight_dtype=args.weight_dtype,
        kv_cache_dtype=args.kv_cache_dtype,
        tp=args.tp,
        sharding_rules=args.sharding,
    )
    print(
        f"[serve] model {args.model} | "
        f"{f'out-of-process ({args.transport}), ' if args.out_of_process else ''}"
        f"{router.num_replicas} replica(s) x "
        f"{args.num_slots} slots, chunk {args.chunk_size}, cache {max_length}"
        + (f", tp {args.tp}" if args.tp > 1 else "")
        + f" | {len(requests)} request(s)",
        file=sys.stderr, flush=True,
    )
    # Pace submissions against the fleet's backpressure: a workload larger
    # than replicas * max_queue must wait for capacity, not crash on the
    # QueueFull signal the bounded queues exist to raise.
    from collections import deque

    from ..serving import QueueFull

    pending = deque(requests)
    while pending or router.pending:
        while pending:
            try:
                router.submit(pending[0])
            except QueueFull:
                break
            pending.popleft()
        router.step()
    results = router.drain()
    abnormal = 0
    for rid in sorted(results):
        result = results[rid]
        if result.finish_reason not in ("eos", "length"):
            abnormal += 1
        print(json.dumps({
            "request_id": rid,
            "finish_reason": result.finish_reason,
            "tokens": [int(t) for t in result.tokens],
        }))
    stats = router.stats
    print(
        f"[serve] done: {len(results)} finished ({abnormal} abnormal) | "
        f"retries {stats['retries']} hedges {stats['hedges']} "
        f"ejected {stats['ejected']} | states {stats['replica_states']}",
        file=sys.stderr, flush=True,
    )
    router.close()
    raise SystemExit(0 if abnormal == 0 else 1)

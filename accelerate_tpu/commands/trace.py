"""`accelerate-tpu trace` — dump, stitch, and summarize flight-recorder traces.

Subcommands:

  - ``trace dump --dir DIR`` — request a dump from live processes (touches
    ``DIR/DUMP``, served at their next step/chunk boundary) and stitch every
    span stream already in the dir into one Perfetto-loadable trace JSON.
    Exit 0 with the artifact path on stdout; 1 when the dir holds no spans
    yet (the touch file is still left armed); 2 on usage errors.
  - ``trace export FILES... --out OUT`` — convert streamed span JSONL files
    (``spans_<pid>.jsonl``) into one Chrome/Perfetto trace-event JSON,
    stitching across processes (a supervisor + its restarted workers land on
    one timeline, ordered by their unix-anchored timestamps).
  - ``trace report FILE`` — text summary of a span JSONL or trace dir: span
    counts by name, trace ids, crash boundaries, wall-clock extent.

Everything here is host-side file plumbing over `telemetry.tracing` /
`telemetry.export` — no backend is initialized, so it runs on the machine you
ssh'd into to find out why the run is stuck (open the JSON in
https://ui.perfetto.dev or chrome://tracing).
"""

from __future__ import annotations

import os
import sys
import time


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "trace",
        help="Dump/stitch flight-recorder traces into Perfetto-loadable JSON",
        description=__doc__,
    )
    sub = parser.add_subparsers(dest="trace_command")

    dump = sub.add_parser("dump", help="Trigger + stitch a trace dump from a trace dir")
    dump.add_argument(
        "--dir", dest="trace_dir", default=None,
        help="Trace dir (default: $ACCELERATE_TPU_TRACE_DIR) — the --trace_dir "
        "passed to launch / chaos run",
    )
    dump.add_argument("--out", default=None, help="Output JSON path (default: DIR/trace.json)")
    dump.add_argument(
        "--wait", type=float, default=0.0,
        help="Seconds to wait for live processes to serve the touch-file trigger "
        "before stitching (default: stitch immediately)",
    )
    dump.set_defaults(func=trace_dump_command)

    export = sub.add_parser("export", help="Convert span JSONL files to trace-event JSON")
    export.add_argument("inputs", nargs="+", help="spans_*.jsonl files (or trace dirs)")
    export.add_argument("--out", required=True, help="Output trace-event JSON path")
    export.set_defaults(func=trace_export_command)

    report = sub.add_parser("report", help="Summarize a span JSONL file or trace dir")
    report.add_argument("input", help="A spans_*.jsonl file or a trace dir")
    report.set_defaults(func=trace_report_command)

    parser.set_defaults(func=lambda args: parser.print_help() or sys.exit(2))
    return parser


def _collect(path: str):
    from ..telemetry.flight_recorder import collect_trace_dir, read_span_jsonl

    if os.path.isdir(path):
        return collect_trace_dir(path)
    if os.path.isfile(path):
        return read_span_jsonl(path)
    print(f"accelerate-tpu trace: no such file or directory: {path}", file=sys.stderr)
    raise SystemExit(2)


def trace_dump_command(args):
    from ..telemetry.export import write_trace_events
    from ..telemetry.flight_recorder import DUMP_TOUCH_FILE

    trace_dir = args.trace_dir or os.environ.get("ACCELERATE_TPU_TRACE_DIR")
    if not trace_dir:
        print(
            "accelerate-tpu trace dump: no trace dir (--dir or ACCELERATE_TPU_TRACE_DIR)",
            file=sys.stderr,
        )
        raise SystemExit(2)
    if not os.path.isdir(trace_dir):
        print(f"accelerate-tpu trace dump: not a directory: {trace_dir}", file=sys.stderr)
        raise SystemExit(2)
    # Arm the touch file first: any live process polls it at its next step or
    # decode-chunk boundary and writes its own trace_<pid>_NNN.json next to
    # the span streams (the profiler's CAPTURE pattern).
    touch = os.path.join(trace_dir, DUMP_TOUCH_FILE)
    with open(touch, "w"):
        pass
    if args.wait > 0:
        deadline = time.monotonic() + args.wait
        while time.monotonic() < deadline and os.path.exists(touch):
            time.sleep(0.05)
    records = _collect(trace_dir)
    if not records:
        print(
            f"accelerate-tpu trace dump: no spans in {trace_dir} yet (touch file left "
            "armed for live processes)",
            file=sys.stderr,
        )
        raise SystemExit(1)
    out = args.out or os.path.join(trace_dir, "trace.json")
    write_trace_events(records, out)
    print(out)
    raise SystemExit(0)


def trace_export_command(args):
    from ..telemetry.export import write_trace_events

    records = []
    for path in args.inputs:
        records.extend(_collect(path))
    if not records:
        print("accelerate-tpu trace export: inputs contain no spans", file=sys.stderr)
        raise SystemExit(1)
    records.sort(key=lambda r: r.get("start_unix", r.get("t_unix", 0.0)))
    write_trace_events(records, args.out)
    print(args.out)
    raise SystemExit(0)


def trace_report_command(args):
    records = _collect(args.input)
    if not records:
        print("accelerate-tpu trace report: no spans", file=sys.stderr)
        raise SystemExit(1)
    by_name = {}
    times = []
    for record in records:
        key = (record.get("kind", "span"), record.get("name", "?"))
        by_name[key] = by_name.get(key, 0) + 1
        times.append(record.get("start_unix", record.get("t_unix", 0.0)))
    trace_ids = sorted({r.get("trace_id") for r in records if r.get("trace_id")})
    pids = sorted({r.get("pid") for r in records})
    print(f"records: {len(records)}  processes: {pids}  trace ids: {trace_ids}")
    print(f"wall-clock extent: {max(times) - min(times):.3f}s")
    for (kind, name), count in sorted(by_name.items()):
        print(f"  {kind:<11} {name:<28} x{count}")
    crashes = [r for r in records if r.get("name") in ("chaos.crash_boundary", "supervisor.child_exit")]
    if crashes:
        print(f"  crash/exit boundaries: {len(crashes)}")
    raise SystemExit(0)

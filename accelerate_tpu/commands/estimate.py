"""`accelerate-tpu estimate-memory` — model-memory estimator (parity: reference
commands/estimate.py:63-299).

Like the reference, the primary path builds a REAL meta-model — `transformers`
AutoConfig + `AutoModel.from_config` on the torch meta device (the reference's
`create_empty_model`, estimate.py:63-137) — so the numbers are measured from actual
parameter shapes for any architecture transformers knows, config-only, no weights
download. Hub names resolve when a network/cache is available and fail with a clear
offline message otherwise; local checkpoint dirs and in-tree model names always work
(zero-egress path). Closed-form estimation from a raw config.json remains the
fallback for configs transformers can't instantiate."""

import argparse
import json
import os

from ..utils.other import convert_bytes

DTYPE_BYTES = {"float32": 4, "bf16": 2, "bfloat16": 2, "float16": 2, "int8": 1, "int4": 0.5}


def register_subcommand(subparsers):
    parser = subparsers.add_parser("estimate-memory", help="Estimate model memory usage")
    parser.add_argument("model_name", help="Hub model id, local HF config/model dir, or in-tree model name")
    parser.add_argument("--dtypes", nargs="+", default=["float32", "bf16", "int8", "int4"])
    parser.add_argument(
        "--trust_remote_code",
        action="store_true",
        help="Allow custom modeling code shipped with the Hub repo (reference estimate.py flag)",
    )
    parser.set_defaults(func=estimate_command)
    return parser


def create_empty_model(model_name: str, trust_remote_code: bool = False):
    """Meta-device model from a config (reference create_empty_model estimate.py:63-137):
    AutoConfig resolves the name (local dir or Hub), AutoModel materializes shapes on
    `torch.device("meta")` — exact parameter accounting, zero weight bytes."""
    import torch
    import transformers

    try:
        config = transformers.AutoConfig.from_pretrained(model_name, trust_remote_code=trust_remote_code)
    except OSError as e:
        raise RuntimeError(
            f"Could not resolve `{model_name}`: not a local path and the Hub is unreachable "
            f"from this host (offline?). Pass a local checkpoint/config dir instead. [{e}]"
        ) from e
    # Pick the task-specific Auto class from the architecture name (the concrete
    # classes don't implement from_config; only Auto* do). Substring -> Auto map,
    # most specific first; AutoModel covers the rest.
    auto_by_task = [
        ("ForCausalLM", "AutoModelForCausalLM"),
        ("ForSeq2SeqLM", "AutoModelForSeq2SeqLM"),
        ("ForConditionalGeneration", "AutoModelForSeq2SeqLM"),
        ("ForSequenceClassification", "AutoModelForSequenceClassification"),
        ("ForTokenClassification", "AutoModelForTokenClassification"),
        ("ForQuestionAnswering", "AutoModelForQuestionAnswering"),
        ("ForMaskedLM", "AutoModelForMaskedLM"),
        ("ForImageClassification", "AutoModelForImageClassification"),
    ]
    cls = transformers.AutoModel
    for arch in getattr(config, "architectures", None) or []:
        for marker, auto_name in auto_by_task:
            if marker in arch and hasattr(transformers, auto_name):
                cls = getattr(transformers, auto_name)
                break
        else:
            continue
        break
    with torch.device("meta"):
        model = cls.from_config(config, trust_remote_code=trust_remote_code)
    return model


def sizes_from_meta_model(model) -> tuple:
    """(total_params, largest_layer_params) measured from a torch meta model —
    the reference's calculate_maximum_sizes/get_max_layer_size over real modules."""
    import torch.nn as nn

    total = sum(p.numel() for p in model.parameters()) + sum(b.numel() for b in model.buffers())
    candidates = [0]
    for module in model.modules():
        if isinstance(module, nn.ModuleList) and len(module):
            candidates.extend(sum(p.numel() for p in child.parameters()) for child in module)
        elif isinstance(module, nn.Embedding):
            candidates.append(module.weight.numel())
    largest = max(candidates)
    if largest == 0:  # no repeated blocks found: fall back to the whole model
        largest = total
    return total, largest


def estimate_parameters_from_hf_config(cfg: dict) -> tuple:
    """(total_params, largest_layer_params) from a transformer config.json."""
    vocab = cfg.get("vocab_size", 32000)
    hidden = cfg.get("hidden_size", cfg.get("n_embd", cfg.get("d_model", 768)))
    layers = cfg.get("num_hidden_layers", cfg.get("n_layer", cfg.get("num_layers", 12)))
    inter = cfg.get("intermediate_size", cfg.get("n_inner") or cfg.get("d_ff") or 4 * hidden)
    heads = cfg.get("num_attention_heads", cfg.get("n_head") or cfg.get("num_heads") or hidden // 64)
    kv_heads = cfg.get("num_key_value_heads", heads)
    head_dim = cfg.get("head_dim", cfg.get("d_kv") or hidden // heads)
    attn = hidden * heads * head_dim + 2 * hidden * kv_heads * head_dim + heads * head_dim * hidden
    gated = (
        "llama" in str(cfg.get("model_type", "")).lower()
        or cfg.get("hidden_act", "") in ("silu", "swiglu")
        or "gated" in str(cfg.get("feed_forward_proj", ""))
    )
    mlp = (3 if gated else 2) * hidden * inter
    per_layer = attn + mlp + 2 * hidden
    embed = vocab * hidden
    if cfg.get("is_encoder_decoder"):
        # Encoder layers: 1 attention; decoder layers: self + cross attention and
        # a third norm (T5-family accounting — t0pp-11b is within ~2%). In real HF
        # T5 configs `num_layers` IS the encoder count (decoder has its own key).
        enc_layers = cfg.get("num_encoder_layers") or cfg.get("num_layers") or layers // 2
        dec_layers = cfg.get("num_decoder_layers", enc_layers)
        enc_per_layer = attn + mlp + 2 * hidden
        dec_per_layer = 2 * attn + mlp + 3 * hidden
        total = embed + enc_layers * enc_per_layer + dec_layers * dec_per_layer + 2 * hidden
        per_layer = max(enc_per_layer, dec_per_layer)
    else:
        total = embed + layers * per_layer + hidden
    if not cfg.get("tie_word_embeddings", True):
        total += vocab * hidden
    largest_layer = max(per_layer, embed)
    return total, largest_layer


def gather_data(args):
    path = args.model_name
    total = largest = None
    cfg = None
    if os.path.isdir(path) and os.path.isfile(os.path.join(path, "config.json")):
        path = os.path.join(path, "config.json")
    if os.path.isfile(path):
        with open(path) as f:
            cfg = json.load(f)
    else:
        try:
            from ..models import get_model_config

            cfg = get_model_config(path)
        except ValueError:
            cfg = None  # not an in-tree name: treat as a Hub id below
    if cfg is None or os.path.isfile(str(args.model_name)) or os.path.isdir(str(args.model_name)):
        # Primary path: measured sizes from a real meta-model (any transformers arch).
        try:
            meta = create_empty_model(args.model_name, trust_remote_code=args.trust_remote_code)
            total, largest = sizes_from_meta_model(meta)
        except RuntimeError:
            if cfg is None:
                raise
        except Exception as e:
            # transformers can't build this config: closed-form fallback below —
            # but only if we actually have a config to fall back to.
            if cfg is None:
                raise RuntimeError(
                    f"transformers could not instantiate `{args.model_name}` ({e!r}) and no "
                    "local config is available for closed-form estimation."
                ) from e
    if total is None:
        total, largest = estimate_parameters_from_hf_config(cfg)
    rows = []
    for dtype in args.dtypes:
        bytes_per = DTYPE_BYTES[dtype]
        rows.append(
            {
                "dtype": dtype,
                "largest_layer": largest * bytes_per,
                "total_size": total * bytes_per,
                # Adam training ≈ params + grads + 2 optimizer moments in fp32 master
                # (reference uses the 4× heuristic, estimate.py:250-299).
                "training_size": total * bytes_per * 4,
            }
        )
    return total, rows


def estimate_command(args):
    total, rows = gather_data(args)
    print(f"Memory usage for loading `{args.model_name}` ({total / 1e9:.2f}B params):")
    header = f"| {'dtype':8} | {'Largest Layer':>14} | {'Total Size':>12} | {'Training (Adam)':>16} |"
    print(header)
    print("|" + "-" * (len(header) - 2) + "|")
    for row in rows:
        print(
            f"| {row['dtype']:8} | {convert_bytes(row['largest_layer']):>14} "
            f"| {convert_bytes(row['total_size']):>12} | {convert_bytes(row['training_size']):>16} |"
        )
    return rows

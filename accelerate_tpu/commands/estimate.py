"""`accelerate-tpu estimate-memory` — model-memory estimator (parity: reference
commands/estimate.py:63-299).

The reference pulls meta-models from the Hub; this estimator works offline from (a) a
local HF `config.json`, or (b) a named in-tree model family (`models/` registry), and
prints the dtype table of total / largest-layer size plus the ≈4× training footprint
heuristic (reference estimate.py:250-299)."""

import argparse
import json
import os

from ..utils.other import convert_bytes

DTYPE_BYTES = {"float32": 4, "bf16": 2, "bfloat16": 2, "float16": 2, "int8": 1, "int4": 0.5}


def register_subcommand(subparsers):
    parser = subparsers.add_parser("estimate-memory", help="Estimate model memory usage")
    parser.add_argument("model_name", help="Path to a HF config.json / model dir, or in-tree model name")
    parser.add_argument("--dtypes", nargs="+", default=["float32", "bf16", "int8", "int4"])
    parser.set_defaults(func=estimate_command)
    return parser


def estimate_parameters_from_hf_config(cfg: dict) -> tuple:
    """(total_params, largest_layer_params) from a transformer config.json."""
    vocab = cfg.get("vocab_size", 32000)
    hidden = cfg.get("hidden_size", cfg.get("n_embd", cfg.get("d_model", 768)))
    layers = cfg.get("num_hidden_layers", cfg.get("n_layer", cfg.get("num_layers", 12)))
    inter = cfg.get("intermediate_size", cfg.get("n_inner") or 4 * hidden)
    heads = cfg.get("num_attention_heads", cfg.get("n_head", hidden // 64))
    kv_heads = cfg.get("num_key_value_heads", heads)
    head_dim = cfg.get("head_dim", hidden // heads)
    attn = hidden * heads * head_dim + 2 * hidden * kv_heads * head_dim + heads * head_dim * hidden
    gated = "llama" in str(cfg.get("model_type", "")).lower() or cfg.get("hidden_act", "") in ("silu", "swiglu")
    mlp = (3 if gated else 2) * hidden * inter
    per_layer = attn + mlp + 2 * hidden
    embed = vocab * hidden
    total = embed + layers * per_layer + hidden
    if not cfg.get("tie_word_embeddings", True):
        total += vocab * hidden
    largest_layer = max(per_layer, embed)
    return total, largest_layer


def gather_data(args):
    path = args.model_name
    cfg = None
    if os.path.isdir(path) and os.path.isfile(os.path.join(path, "config.json")):
        path = os.path.join(path, "config.json")
    if os.path.isfile(path):
        with open(path) as f:
            cfg = json.load(f)
    else:
        from ..models import get_model_config

        cfg = get_model_config(path)
    total, largest = estimate_parameters_from_hf_config(cfg)
    rows = []
    for dtype in args.dtypes:
        bytes_per = DTYPE_BYTES[dtype]
        rows.append(
            {
                "dtype": dtype,
                "largest_layer": largest * bytes_per,
                "total_size": total * bytes_per,
                # Adam training ≈ params + grads + 2 optimizer moments in fp32 master
                # (reference uses the 4× heuristic, estimate.py:250-299).
                "training_size": total * bytes_per * 4,
            }
        )
    return total, rows


def estimate_command(args):
    total, rows = gather_data(args)
    print(f"Memory usage for loading `{args.model_name}` ({total / 1e9:.2f}B params):")
    header = f"| {'dtype':8} | {'Largest Layer':>14} | {'Total Size':>12} | {'Training (Adam)':>16} |"
    print(header)
    print("|" + "-" * (len(header) - 2) + "|")
    for row in rows:
        print(
            f"| {row['dtype']:8} | {convert_bytes(row['largest_layer']):>14} "
            f"| {convert_bytes(row['total_size']):>12} | {convert_bytes(row['training_size']):>16} |"
        )
    return rows

"""`accelerate-tpu convert` + `accelerate-tpu merge` — checkpoint tooling around
the two formats the framework speaks:

- convert: HF torch layout (safetensors / sharded index / .bin) <-> the native
  pytree checkpoint written by `save_pytree` (npz + structure manifest), using
  the per-family interchange maps (utils/hf_loading.py). The reference never
  needs this because it IS torch; a TPU framework whose users arrive with HF
  checkpoints does. `to_hf` writes real HF-layout safetensors.
- merge: consolidate a SHARDED_STATE_DICT checkpoint directory (one file per
  host + manifest, checkpointing.save_sharded) into a single-file native
  checkpoint for serving/export.
"""

import os


def register_subcommand(subparsers):
    parser = subparsers.add_parser("convert", help="Convert between HF torch and native checkpoint layouts")
    parser.add_argument("input", help="Input checkpoint (file or HF sharded dir)")
    parser.add_argument("output", help="Output path (native: .npz + manifest; to_hf: .safetensors)")
    parser.add_argument(
        "--model_type",
        required=True,
        choices=["llama", "mixtral", "gptj", "gpt_neox", "opt", "t5"],
        help="Interchange family",
    )
    parser.add_argument(
        "--model",
        required=True,
        help="In-tree config name (e.g. llama-1b, gptj-6b, t5-tiny) the layout is validated against",
    )
    parser.add_argument(
        "--direction",
        default="from_hf",
        choices=["from_hf", "to_hf"],
        help="from_hf: HF torch layout -> native pytree; to_hf: native -> HF layout",
    )
    parser.set_defaults(func=convert_command)

    merge = subparsers.add_parser("merge", help="Consolidate a sharded native checkpoint into one file")
    merge.add_argument("input_dir", help="Directory written by sharded save (manifest + shards)")
    merge.add_argument("output", help="Output path (native .npz + manifest)")
    merge.set_defaults(func=merge_command)
    return parser


def convert_command(args):
    from ..checkpointing import load_pytree, save_pytree
    from ..models import get_model_family
    from ..utils.hf_loading import (
        convert_hf_state_dict,
        load_hf_state_dict,
        save_hf_checkpoint,
    )

    family, config = get_model_family(args.model)
    if family != args.model_type:
        raise ValueError(f"--model {args.model} is a {family!r} config, not {args.model_type!r}")
    if args.direction == "from_hf":
        flat = load_hf_state_dict(args.input)
        params = convert_hf_state_dict(flat, args.model_type, config)
        save_pytree(params, args.output)
        written = args.output if args.output.endswith(".npz") else args.output + ".npz"
    else:
        params = load_pytree(args.input)
        save_hf_checkpoint(params, args.model_type, config, args.output)
        written = args.output
    print(f"Wrote {written} ({os.path.getsize(written) / 1e6:.1f} MB, {args.direction}, {args.model_type})")


def merge_command(args):
    from ..checkpointing import load_sharded, save_pytree

    tree = load_sharded(args.input_dir)
    save_pytree(tree, args.output)
    written = args.output if args.output.endswith(".npz") else args.output + ".npz"
    print(f"Merged {args.input_dir} -> {written} ({os.path.getsize(written) / 1e6:.1f} MB)")

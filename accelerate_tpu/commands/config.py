"""`accelerate-tpu config` — write/inspect the launch config YAML (parity: reference
commands/config/ questionnaire, ~1600 LoC; here: `--default` quick-write plus an
interactive prompt loop; the YAML keys mirror `ClusterConfig` reference
commands/config/config_args.py:175-244 with TPU-pod fields first-class).
"""

import argparse
import os

from .env import default_config_file

DEFAULT_CONFIG = {
    "compute_environment": "LOCAL_MACHINE",
    "distributed_type": "XLA_SPMD",
    "mixed_precision": "bf16",
    "num_processes": 1,
    "mesh": {"data": -1, "fsdp": 1, "model": 1, "seq": 1, "expert": 1, "stage": 1},
    "gradient_accumulation_steps": 1,
    "coordinator_address": None,
    "tpu_name": None,
    "tpu_zone": None,
    "tpu_use_cluster": False,
    "downcast_bf16": False,
}


def register_subcommand(subparsers):
    parser = subparsers.add_parser("config", help="Create the launch config file")
    parser.add_argument("--config_file", default=None, help="Path to write the config YAML")
    parser.add_argument("--default", action="store_true", help="Write the default config without prompting")
    parser.set_defaults(func=config_command)
    return parser


def _ask(prompt, default, cast=str):
    raw = input(f"{prompt} [{default}]: ").strip()
    if not raw:
        return default
    if cast is bool:
        return raw.lower() in ("1", "true", "yes", "y")
    return cast(raw)


def write_basic_config(config_file=None, mixed_precision="bf16", **overrides):
    """Programmatic quick-config (parity: reference commands/config/default.py
    write_basic_config)."""
    import yaml

    config = dict(DEFAULT_CONFIG)
    config["mixed_precision"] = mixed_precision
    config.update(overrides)
    config_file = config_file or default_config_file()
    os.makedirs(os.path.dirname(config_file), exist_ok=True)
    with open(config_file, "w") as f:
        yaml.safe_dump(config, f, sort_keys=False)
    return config_file


def load_config_file(config_file=None) -> dict:
    import yaml

    config_file = config_file or default_config_file()
    if not os.path.isfile(config_file):
        return {}
    with open(config_file) as f:
        return yaml.safe_load(f) or {}


def config_command(args):
    if args.default:
        path = write_basic_config(args.config_file)
        print(f"accelerate-tpu configuration saved at {path}")
        return
    config = dict(DEFAULT_CONFIG)
    config["mixed_precision"] = _ask("Mixed precision (no/bf16/fp16/fp8)", "bf16")
    config["num_processes"] = _ask("Number of host processes", 1, int)
    if config["num_processes"] > 1:
        config["coordinator_address"] = _ask("Coordinator address (host:port)", "localhost:8476")
    mesh = {}
    for axis in ("data", "fsdp", "model", "seq", "expert", "stage"):
        default = -1 if axis == "data" else 1
        mesh[axis] = _ask(f"Mesh axis size `{axis}` (-1 = remaining devices)", default, int)
    config["mesh"] = mesh
    config["gradient_accumulation_steps"] = _ask("Gradient accumulation steps", 1, int)
    path = write_basic_config(args.config_file, **config)
    print(f"accelerate-tpu configuration saved at {path}")

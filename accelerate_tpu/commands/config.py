"""`accelerate-tpu config` — write/inspect the launch config YAML (parity: reference
commands/config/ questionnaire, ~1600 LoC; here: `--default` quick-write plus an
interactive prompt loop; the YAML keys mirror `ClusterConfig` reference
commands/config/config_args.py:175-244 with TPU-pod fields first-class).
"""

import argparse
import os

from .env import default_config_file

DEFAULT_CONFIG = {
    "compute_environment": "LOCAL_MACHINE",
    "distributed_type": "XLA_SPMD",
    "mixed_precision": "bf16",
    "num_processes": 1,
    "mesh": {"data": -1, "fsdp": 1, "model": 1, "seq": 1, "expert": 1, "stage": 1},
    "gradient_accumulation_steps": 1,
    "coordinator_address": None,
    "tpu_name": None,
    "tpu_zone": None,
    "tpu_use_cluster": False,
    "downcast_bf16": False,
}


def register_subcommand(subparsers):
    parser = subparsers.add_parser("config", help="Create the launch config file")
    parser.add_argument("--config_file", default=None, help="Path to write the config YAML")
    parser.add_argument("--default", action="store_true", help="Write the default config without prompting")
    parser.set_defaults(func=config_command)
    return parser


def _ask(prompt, default, cast=str):
    while True:
        raw = input(f"{prompt} [{default}]: ").strip()
        if not raw:
            return default
        if cast is bool:
            return raw.lower() in ("1", "true", "yes", "y")
        try:
            return cast(raw)
        except ValueError:
            print(f"Please enter a {cast.__name__}")


def write_basic_config(config_file=None, mixed_precision="bf16", **overrides):
    """Programmatic quick-config (parity: reference commands/config/default.py
    write_basic_config)."""
    import yaml

    config = dict(DEFAULT_CONFIG)
    config["mixed_precision"] = mixed_precision
    config.update(overrides)
    config_file = config_file or default_config_file()
    os.makedirs(os.path.dirname(config_file), exist_ok=True)
    with open(config_file, "w") as f:
        yaml.safe_dump(config, f, sort_keys=False)
    return config_file


def load_config_file(config_file=None) -> dict:
    import yaml

    config_file = config_file or default_config_file()
    if not os.path.isfile(config_file):
        return {}
    with open(config_file) as f:
        return yaml.safe_load(f) or {}


def run_questionnaire() -> dict:
    """The full interactive flow (parity: reference commands/config/cluster.py, 717 LoC
    + config_args.py:175-244 ClusterConfig field set, re-shaped around a TPU mesh).

    Sections: compute environment -> topology (hosts/coordinator, TPU pod fields) ->
    mesh axes -> parallelism plugins (FSDP/ZeRO, sequence parallel, pipeline) ->
    precision -> runtime knobs (grad accumulation, compile cache, debug).
    """
    from .menu import select_value

    config = dict(DEFAULT_CONFIG)

    # -- compute environment ---------------------------------------------------------
    env_choice = select_value(
        "In which environment are you running?",
        [
            "This machine (single TPU host / CPU)",
            "TPU pod (multi-host slice)",
            "GCP Cloud TPU (provision on demand)",
        ],
    )
    pod = env_choice.startswith("TPU pod")
    cloud = env_choice.startswith("GCP Cloud")
    config["compute_environment"] = "TPU_POD" if pod else ("GCP_CLOUD" if cloud else "LOCAL_MACHINE")
    config["distributed_type"] = "XLA_SPMD"

    if cloud:
        # Managed-cloud block (parity: reference sagemaker questionnaire
        # commands/config/sagemaker.py — GCP-shaped, consumed by commands/cloud.py).
        cc = {}
        cc["name"] = _ask("Job/slice name", "accelerate-tpu-job")
        cc["project"] = _ask("GCP project", "my-project")
        cc["zone"] = _ask("Zone", "us-central2-b")
        cc["accelerator_type"] = _ask("Accelerator type (e.g. v5litepod-8)", "v5litepod-8")
        cc["runtime_version"] = _ask("TPU runtime version", "tpu-ubuntu2204-base")
        cc["use_queued_resource"] = _ask("Provision via queued resource (vs direct create)?", True, bool)
        cc["spot"] = _ask("Use spot (preemptible) capacity?", False, bool)
        out = _ask("GCS output prefix to sync results to (empty for none)", "")
        if out:
            cc["output_gcs"] = out
        cc["teardown"] = _ask("Tear the slice down when the job exits?", True, bool)
        config["cloud_config"] = cc

    if pod:
        config["num_processes"] = _ask("How many host processes (pod workers)?", 4, int)
        config["coordinator_address"] = _ask(
            "Coordinator address (host:port of worker 0)", "localhost:8476"
        )
        config["tpu_use_cluster"] = _ask(
            "Launch on every pod worker via gcloud ssh (tpu_use_cluster)?", True, bool
        )
        if config["tpu_use_cluster"]:
            config["tpu_name"] = _ask("TPU name", "my-tpu") or None
            config["tpu_zone"] = _ask("TPU zone", "us-central2-b") or None
            cmds = _ask(
                "Setup commands to run on each worker before launch (`;`-separated, empty for none)",
                "",
            )
            config["tpu_commands"] = [c.strip() for c in cmds.split(";") if c.strip()] or None
    else:
        config["num_processes"] = 1

    # -- mesh ------------------------------------------------------------------------
    mesh = {}
    if _ask("Customize the device mesh axes?", False, bool):
        for axis in ("data", "fsdp", "model", "seq", "expert", "stage"):
            default = -1 if axis == "data" else 1
            mesh[axis] = _ask(f"Mesh axis size `{axis}` (-1 = remaining devices)", default, int)
    else:
        mesh = dict(DEFAULT_CONFIG["mesh"])
    config["mesh"] = mesh

    # -- FSDP / ZeRO -----------------------------------------------------------------
    if _ask("Use FSDP/ZeRO parameter sharding?", False, bool):
        fsdp = {}
        fsdp["sharding_strategy"] = select_value(
            "Sharding strategy",
            ["FULL_SHARD", "SHARD_GRAD_OP", "NO_SHARD", "HYBRID_SHARD"],
            "FULL_SHARD",
        )
        fsdp["min_num_params"] = _ask("Min parameter count to shard (auto-wrap threshold)", 1024, int)
        fsdp["cpu_offload"] = _ask("Offload params/optimizer state to host memory?", False, bool)
        fsdp["activation_checkpointing"] = _ask("Activation checkpointing (remat)?", False, bool)
        fsdp["state_dict_type"] = select_value(
            "Checkpoint state-dict type", ["SHARDED_STATE_DICT", "FULL_STATE_DICT"], "SHARDED_STATE_DICT"
        )
        config["fsdp_config"] = fsdp
        if mesh.get("fsdp", 1) == 1 and fsdp["sharding_strategy"] != "NO_SHARD":
            print("note: set mesh axis `fsdp` > 1 (or leave data=-1, fsdp=1 for pure DP) to shard across devices")

    # -- sequence parallelism --------------------------------------------------------
    if mesh.get("seq", 1) != 1 or _ask("Enable sequence/context parallelism (long sequences)?", False, bool):
        sp = {}
        sp["mode"] = select_value("Sequence-parallel attention", ["ring", "allgather"], "ring")
        sp["block_size"] = _ask("Ring attention block size", 512, int)
        config["sequence_parallel_config"] = sp
        if mesh.get("seq", 1) == 1:
            mesh["seq"] = _ask("Mesh axis size `seq`", 2, int)

    # -- precision & runtime ---------------------------------------------------------
    config["mixed_precision"] = select_value(
        "Mixed precision", ["bf16", "no", "fp16", "fp8"], "bf16"
    )
    if config["mixed_precision"] == "bf16":
        config["downcast_bf16"] = _ask("Downcast fp64->bf16 aggressively (downcast_bf16)?", False, bool)
    config["gradient_accumulation_steps"] = _ask("Gradient accumulation steps", 1, int)
    cache = _ask("Persistent XLA compilation cache dir (empty to disable)", "")
    if cache:
        config["compilation_cache"] = cache
    config["debug"] = _ask("Enable debug-mode collective verification?", False, bool)
    return config


def config_command(args):
    if args.default:
        path = write_basic_config(args.config_file)
        print(f"accelerate-tpu configuration saved at {path}")
        return
    config = run_questionnaire()
    path = write_basic_config(args.config_file, **config)
    print(f"accelerate-tpu configuration saved at {path}")

"""Managed-cloud job launch: provision a Cloud TPU slice, sync the code, run the
training job on it, optionally tear it down — the TPU-native equivalent of the
reference's managed SageMaker path (commands/launch.py:880 sagemaker_launcher +
commands/config/sagemaker.py questionnaire), re-shaped around GCP primitives:

  SageMaker estimator + EC2 instance type  ->  Cloud TPU queued resource / tpu-vm
  estimator.fit() job submission           ->  gcloud create + scp workdir + ssh run
  spot instances                           ->  --spot (preemptible queued resource)
  job artifacts on S3                      ->  --output_gcs bucket sync after the run

Everything funnels through `plan_cloud_job`, which returns the ordered list of
gcloud commands; `--dry_run` prints them instead of executing (tests drive this —
no gcloud/network in CI, same pattern as commands/tpu.py)."""

import os
import shlex
import subprocess
import time

GCLOUD_TPU = ["gcloud", "compute", "tpus"]


class CloudJobConfig:
    """Field set mirroring the reference's SageMakerConfig (config_args.py:228-244),
    GCP-shaped. Populated from the `cloud_config` block of the config YAML and/or
    launch CLI flags; CLI wins."""

    FIELDS = {
        "name": "accelerate-tpu-job",
        "project": None,
        "zone": "us-central2-b",
        "accelerator_type": "v5litepod-8",
        "runtime_version": "tpu-ubuntu2204-base",
        "spot": False,
        "use_queued_resource": True,
        "reserved": False,
        "setup_commands": None,  # list[str] run on every worker before the job
        "output_gcs": None,  # gs:// prefix to sync the project dir to after the run
        "teardown": True,  # delete the slice when the job exits
        "poll_seconds": 30,  # queued-resource readiness poll interval
        "max_wait_seconds": 3600,
    }

    def __init__(self, config: dict, args):
        block = (config.get("cloud_config") or {}) if config else {}
        for field, default in self.FIELDS.items():
            cli = getattr(args, f"cloud_{field}", None)
            setattr(self, field, cli if cli is not None else block.get(field, default))
        if not self.project:
            raise ValueError(
                "Cloud launch needs a GCP project: set cloud_config.project in the config "
                "file (accelerate-tpu config) or pass --cloud_project"
            )


def add_cloud_args(parser):
    parser.add_argument(
        "--cloud",
        action="store_true",
        help="Provision a Cloud TPU slice and run the job on it (managed-cloud launch)",
    )
    parser.add_argument("--cloud_name", default=None, help="Name for the TPU slice / queued resource")
    parser.add_argument("--cloud_project", default=None)
    parser.add_argument("--cloud_zone", default=None)
    parser.add_argument("--cloud_accelerator_type", default=None, help="e.g. v5litepod-8, v5litepod-256")
    parser.add_argument("--cloud_runtime_version", default=None)
    parser.add_argument("--cloud_spot", action="store_true", default=None, help="Use a preemptible (spot) slice")
    parser.add_argument("--cloud_output_gcs", default=None, help="gs:// prefix to sync results to after the run")
    parser.add_argument(
        "--cloud_no_teardown",
        dest="cloud_teardown",
        action="store_false",
        default=None,
        help="Keep the slice alive after the job exits",
    )
    parser.add_argument("--dry_run", action="store_true", help="Print the gcloud commands, don't run them")
    return parser


def _scope(cfg):
    return ["--zone", cfg.zone, "--project", cfg.project]


def plan_cloud_job(cfg: CloudJobConfig, launch_argv: list) -> list:
    """The ordered command plan for one managed job. Returns `(tag, argv)` pairs;
    tags let the executor treat provisioning/polling/teardown differently and let
    tests assert the sequence without parsing argv."""
    plan = []
    if cfg.use_queued_resource:
        create = GCLOUD_TPU + [
            "queued-resources",
            "create",
            cfg.name,
            "--node-id",
            cfg.name,
            "--accelerator-type",
            cfg.accelerator_type,
            "--runtime-version",
            cfg.runtime_version,
        ] + _scope(cfg)
        if cfg.spot:
            create.append("--spot")
        if cfg.reserved:
            create.append("--reserved")
        plan.append(("provision", create))
        plan.append(
            (
                "poll",
                GCLOUD_TPU
                + ["queued-resources", "describe", cfg.name, "--format", "value(state.state)"]
                + _scope(cfg),
            )
        )
    else:
        create = GCLOUD_TPU + [
            "tpu-vm",
            "create",
            cfg.name,
            "--accelerator-type",
            cfg.accelerator_type,
            "--version",
            cfg.runtime_version,
        ] + _scope(cfg)
        if cfg.spot:
            create.append("--preemptible")
        plan.append(("provision", create))

    ssh_base = GCLOUD_TPU + ["tpu-vm", "ssh", cfg.name] + _scope(cfg) + ["--worker", "all", "--command"]
    # Clear any previous run's tree first: scp -r into an EXISTING ~/job would
    # nest the new copy under it and the run step would execute stale code.
    plan.append(("clean", ssh_base + ["rm -rf ~/job"]))
    scp = GCLOUD_TPU + [
        "tpu-vm",
        "scp",
        "--recurse",
        os.getcwd(),
        f"{cfg.name}:~/job",
    ] + _scope(cfg) + ["--worker", "all"]
    plan.append(("sync", scp))
    for setup in cfg.setup_commands or []:
        plan.append(("setup", ssh_base + [setup]))
    # ACCELERATE_TPU_MULTIHOST=1 makes each worker join the jax.distributed
    # coordination service (same prefix as the pod launcher, commands/tpu.py):
    # on a multi-worker slice the N ssh invocations must form ONE job.
    run = "cd ~/job && ACCELERATE_TPU_MULTIHOST=1 " + shlex.join(
        ["python", "-m", "accelerate_tpu.commands.launch"] + launch_argv
    )
    plan.append(("run", ssh_base + [run]))
    if cfg.output_gcs:
        plan.append(("collect", ssh_base + [f"gsutil -m rsync -r ~/job {shlex.quote(cfg.output_gcs)}"]))
    if cfg.teardown:
        if cfg.use_queued_resource:
            delete = GCLOUD_TPU + ["queued-resources", "delete", cfg.name, "--force", "--quiet"] + _scope(cfg)
        else:
            delete = GCLOUD_TPU + ["tpu-vm", "delete", cfg.name, "--quiet"] + _scope(cfg)
        plan.append(("teardown", delete))
    return plan


def _wait_active(cfg, describe_cmd):
    """Poll the queued resource until it is ACTIVE (provisioned and running).
    Transient describe failures (network blips over an up-to-1h wait) are retried;
    only 5 consecutive failures abort — aborting tears the slice down, losing the
    user's place in the capacity queue."""
    deadline = time.time() + cfg.max_wait_seconds
    consecutive_failures = 0
    while True:
        try:
            state = subprocess.run(
                describe_cmd, capture_output=True, text=True, check=True
            ).stdout.strip()
            consecutive_failures = 0
        except subprocess.SubprocessError as exc:
            consecutive_failures += 1
            if consecutive_failures >= 5:
                raise RuntimeError(f"describe failed {consecutive_failures}x in a row: {exc}") from exc
            print(f"[cloud] describe failed ({exc}); retrying", flush=True)
            time.sleep(cfg.poll_seconds)
            continue
        if state == "ACTIVE":
            return
        if state in ("FAILED", "SUSPENDED"):
            raise RuntimeError(f"queued resource {cfg.name} entered state {state}")
        if time.time() > deadline:
            raise TimeoutError(
                f"queued resource {cfg.name} not ACTIVE after {cfg.max_wait_seconds}s (state {state})"
            )
        print(f"[cloud] {cfg.name}: {state}; waiting {cfg.poll_seconds}s...", flush=True)
        time.sleep(cfg.poll_seconds)


STAGED_CONFIG = ".accelerate_tpu_job_config.yaml"


def build_remote_config(args, config: dict) -> dict:
    """The launch config the job runs with ON the slice: the local config minus the
    cloud block (the remote must not re-provision), with local CLI launch flags
    folded in so `--mixed_precision`/`--mesh_*`/etc. aren't silently dropped."""
    remote = {k: v for k, v in (config or {}).items() if k not in ("cloud_config", "compute_environment")}
    for key in (
        "mixed_precision",
        "gradient_accumulation_steps",
        "num_processes",
        "coordinator_address",
        "profile_dir",
        "grace_period",
    ):
        val = getattr(args, key, None)
        if val is not None:
            remote[key] = val
    if getattr(args, "max_restarts", 0):
        remote["max_restarts"] = args.max_restarts
    mesh_overrides = {
        axis: getattr(args, f"mesh_{axis}")
        for axis in ("data", "fsdp", "model", "seq", "expert", "stage")
        if getattr(args, f"mesh_{axis}", None) is not None
    }
    if mesh_overrides:
        remote["mesh"] = {**(remote.get("mesh") or {}), **mesh_overrides}
    if getattr(args, "debug", False):
        remote["debug"] = True
    return remote


def cloud_launcher(args, config: dict):
    """Provision → sync → run → collect → teardown. Teardown runs even when the job
    fails (billing), unless --cloud_no_teardown."""
    import yaml

    cfg = CloudJobConfig(config, args)
    remote_config = build_remote_config(args, config)
    launch_argv = ["--config_file", STAGED_CONFIG, args.training_script] + list(args.training_script_args)
    plan = plan_cloud_job(cfg, launch_argv)
    if args.dry_run:
        for tag, cmd in plan:
            print(f"[{tag}] {shlex.join(cmd)}")
        return plan
    # Stage the effective config inside the synced workdir so the remote launch
    # sees the same settings as a local one would (removed again on exit).
    staged_path = os.path.join(os.getcwd(), STAGED_CONFIG)
    with open(staged_path, "w") as f:
        yaml.safe_dump(remote_config, f, sort_keys=False)
    steps = [(tag, cmd) for tag, cmd in plan if tag not in ("collect", "teardown")]
    collect = next((cmd for tag, cmd in plan if tag == "collect"), None)
    teardown = next((cmd for tag, cmd in plan if tag == "teardown"), None)
    provisioned = False
    collect_failed = None
    try:
        for tag, cmd in steps:
            if tag == "provision":
                # Flag BEFORE executing: `gcloud ... create` can create the queued
                # resource/tpu-vm and still exit non-zero (client timeout, transient
                # API error after creation) — the partially-created billing slice
                # must still be torn down below.
                provisioned = True
            if tag == "poll":
                _wait_active(cfg, cmd)
            else:
                print(f"[cloud] {tag}: {shlex.join(cmd)}", flush=True)
                subprocess.run(cmd, check=True)
    finally:
        try:
            os.unlink(staged_path)
        except OSError:
            pass
        # Artifacts first, then the slice: a FAILED run's checkpoints/logs are
        # exactly the ones needed for diagnosis and resume, so the gsutil sync
        # runs on any exit once the slice exists — before teardown deletes the
        # only copy of ~/job. A failed sync must not prevent teardown (billing),
        # but it must be LOUD and fail the launcher on the success path below.
        if collect is not None and provisioned:
            print(f"[cloud] collect: {shlex.join(collect)}", flush=True)
            rc = subprocess.run(collect, check=False).returncode
            if rc != 0:
                collect_failed = rc
                print(
                    f"[cloud] WARNING: artifact sync failed (exit {rc}) — "
                    f"~/job will be lost with the slice",
                    flush=True,
                )
        # A billing slice must come down on ANY exit — job failure, Ctrl-C,
        # SystemExit — once provisioning was attempted.
        if teardown is not None and provisioned:
            print(f"[cloud] teardown: {shlex.join(teardown)}", flush=True)
            subprocess.run(teardown, check=False)
    if collect_failed is not None:
        raise RuntimeError(f"cloud job ran but artifact collection failed (exit {collect_failed})")

"""`accelerate-tpu chaos` — deterministic fault-injection runs with invariant
reports.

Subcommands (exit codes mirror `analyze`'s CI contract):

  - ``chaos run`` — execute a train or serve workload under a fault plan and
    check the end-to-end recovery invariants. Exit 0 when every invariant
    holds, 1 when any is violated (the report says which), 2 on usage errors.
  - ``chaos list-faults`` — print the injector catalog (fault kind + effect).
  - ``chaos report FILE`` — re-render a saved invariant report; exits with the
    report's verdict, so a stored artifact gates CI the same way a live run
    does.

``--plan`` takes a JSON plan file or a builtin name (``smoke-train``,
``smoke-serve``, ``smoke-router``, ``smoke-fleet``, ``partition-fleet``,
``seeded-regression``). The seeded-regression fixture MUST
exit non-zero: it scripts a broken digest layer, and a green report there means
the harness can no longer detect regressions.
"""

from __future__ import annotations

import os
import sys
import tempfile


def register_subcommand(subparsers):
    parser = subparsers.add_parser(
        "chaos",
        help="Run train/serve workloads under deterministic fault injection and check recovery invariants",
        description=__doc__,
    )
    sub = parser.add_subparsers(dest="chaos_command")

    run = sub.add_parser("run", help="Execute a workload under a fault plan")
    run.add_argument(
        "--plan",
        default="smoke-train",
        help="Fault plan: a JSON file path or a builtin name (smoke-train, smoke-serve, "
        "seeded-regression). Default: smoke-train",
    )
    run.add_argument(
        "--workload",
        default=None,
        choices=(None, "train", "async-train", "serve", "supervised-train", "router",
                 "fleet"),
        help="Workload to drive (default: the plan's own `workload` field, else inferred "
        "from its fault kinds; `async-train` saves through the background committer; "
        "`router` drives a replicated serving fleet under per-replica faults; `fleet` "
        "drives an OUT-OF-PROCESS fleet — real subprocess workers, real SIGKILLs)",
    )
    run.add_argument("--base-dir", default=None, help="Checkpoint/journal dir (default: a temp dir)")
    run.add_argument(
        "--trace-dir",
        default=None,
        help="Stream flight-recorder spans (workload lifecycle + injected faults) into "
        "this dir; render with `accelerate-tpu trace dump --dir DIR`. Default: "
        "$ACCELERATE_TPU_TRACE_DIR, else in-memory only",
    )
    run.add_argument("--steps", type=int, default=6, help="Train steps (train workloads)")
    run.add_argument("--requests", type=int, default=8, help="Requests (serve/router workloads)")
    run.add_argument("--replicas", type=int, default=None,
                     help="Fleet size (default: 3 for the router workload, 2 subprocess "
                     "workers for the fleet workload)")
    run.add_argument(
        "--transport",
        default=None,
        choices=(None, "pipe", "socket"),
        help="Fleet workload worker transport (default: socket when the plan "
        "carries net.* faults, else pipe). net.* faults require socket — they "
        "partition/delay the TCP link at the transport seam",
    )
    run.add_argument(
        "--reconnect-deadline",
        type=float,
        default=8.0,
        dest="reconnect_deadline_s",
        help="Socket-fleet reconnect budget in seconds before a torn link "
        "escalates to worker respawn (default: 8.0)",
    )
    run.add_argument("--json", action="store_true", dest="as_json", help="Emit the report as JSON")
    run.add_argument("--report-out", default=None, help="Also save the report JSON to this path")
    run.set_defaults(func=chaos_run_command)

    list_faults = sub.add_parser("list-faults", help="Print the fault-kind catalog")
    list_faults.set_defaults(func=chaos_list_faults_command)

    report = sub.add_parser("report", help="Re-render a saved invariant report")
    report.add_argument("report_file", help="Path to a report JSON written by `chaos run --report-out`")
    report.add_argument("--json", action="store_true", dest="as_json")
    report.set_defaults(func=chaos_report_command)

    parser.set_defaults(func=lambda args: parser.print_help() or sys.exit(2))
    return parser


def _load_plan(spec: str):
    from ..chaos import FaultPlan, builtin_plans

    plans = builtin_plans()
    if spec in plans:
        return plans[spec]
    if not os.path.isfile(spec):
        print(
            f"accelerate-tpu chaos: plan {spec!r} is neither a file nor a builtin "
            f"({', '.join(sorted(plans))})",
            file=sys.stderr,
        )
        raise SystemExit(2)
    try:
        return FaultPlan.load(spec)
    except (ValueError, KeyError, OSError) as exc:
        print(f"accelerate-tpu chaos: bad plan file {spec}: {exc}", file=sys.stderr)
        raise SystemExit(2)


def _infer_workload(plan) -> str:
    if getattr(plan, "workload", None):
        return plan.workload
    if any(ev.kind.startswith(("fleet.", "net.")) for ev in plan.events):
        return "fleet"
    if any(ev.kind.startswith("router.") for ev in plan.events):
        return "router"
    return "serve" if any(ev.kind.startswith("serve.") for ev in plan.events) else "train"


def chaos_run_command(args):
    import contextlib

    from ..chaos import ChaosRunner

    plan = _load_plan(args.plan)
    workload = args.workload or _infer_workload(plan)
    trace_dir = args.trace_dir or os.environ.get("ACCELERATE_TPU_TRACE_DIR")
    runner = ChaosRunner(plan, trace_dir=trace_dir)
    if workload == "serve":
        report = runner.run_serve(num_requests=args.requests)
    elif workload == "router":
        report = runner.run_router(
            num_requests=args.requests, replicas=args.replicas or 3
        )
    elif workload == "fleet":
        transport = args.transport
        if transport is None:
            transport = "socket" if any(
                ev.kind.startswith("net.") for ev in plan.events
            ) else "pipe"
        report = runner.run_fleet(
            num_requests=args.requests, replicas=args.replicas or 2,
            transport=transport,
            reconnect_deadline_s=args.reconnect_deadline_s,
        )
    else:
        # Default scratch dirs are cleaned up after the report is assembled
        # (checkpoint trees add up across CI runs); an explicit --base-dir is
        # the user's to keep for post-mortems.
        with contextlib.ExitStack() as stack:
            base_dir = args.base_dir or stack.enter_context(
                tempfile.TemporaryDirectory(prefix="accelerate_tpu_chaos_")
            )
            if workload == "supervised-train":
                report = runner.run_supervised_train(base_dir, steps=args.steps)
            else:
                report = runner.run_train(
                    base_dir, steps=args.steps, async_save=(workload == "async-train")
                )
    if args.report_out:
        report.save(args.report_out)
    print(report.to_json() if args.as_json else report.render_text())
    raise SystemExit(0 if report.ok else 1)


def chaos_list_faults_command(args):
    from ..chaos import builtin_plans, catalog

    for kind, description in sorted(catalog().items()):
        print(f"{kind:<28} {description}")
    print()
    print("builtin plans (chaos run --plan NAME):")
    for name, plan in sorted(builtin_plans().items()):
        workload = plan.workload or "(inferred)"
        print(f"{name:<28} workload={workload:<16} {plan.notes}")
    raise SystemExit(0)


def chaos_report_command(args):
    from ..chaos import InvariantReport

    try:
        report = InvariantReport.load(args.report_file)
    except (OSError, ValueError, KeyError) as exc:
        print(f"accelerate-tpu chaos report: {exc}", file=sys.stderr)
        raise SystemExit(2)
    print(report.to_json() if args.as_json else report.render_text())
    raise SystemExit(0 if report.ok else 1)
